"""Benchmark: synchronous RBCD throughput on sphere2500 with 8 agents, r=5
(BASELINE.md north-star config #2).

Measures full RBCD rounds/sec — each round = public-pose exchange + one RTR
(truncated-CG) step for every agent — on the default JAX backend (TPU when
present), and the same problem on the CPU backend in float64 as the
stand-in for the reference's SuiteSparse/ROPTLIB CPU implementation (the
reference publishes no numbers and its ROPTLIB dependency is git-fetched at
configure time, unavailable offline — see BASELINE.md).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

DATASET = "/root/reference/data/sphere2500.g2o"
NUM_ROBOTS = 8
RANK = 5
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "200"))
CPU_ROUNDS = int(os.environ.get("BENCH_CPU_ROUNDS", "15"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(dtype):
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    if os.path.exists(DATASET):
        from dpgo_tpu.utils.g2o import read_g2o
        meas = read_g2o(DATASET)
    else:  # fall back to a same-order synthetic problem
        from dpgo_tpu.utils.synthetic import make_measurements
        meas, _ = make_measurements(np.random.default_rng(0), n=2500, d=3,
                                    num_lc=2449, rot_noise=0.01,
                                    trans_noise=0.01)
    params = AgentParams(d=3, r=RANK, num_robots=NUM_ROBOTS)
    part = partition_contiguous(meas, NUM_ROBOTS)
    graph, meta = rbcd.build_graph(part, RANK, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return state, graph, meta, params


def time_rounds(device, dtype, rounds):
    import jax
    from dpgo_tpu.models import rbcd

    state, graph, meta, params = build(dtype)
    state = jax.device_put(state, device)
    graph = jax.device_put(graph, device)

    # Fused stepping (rbcd.rbcd_steps): the whole trial runs as one on-device
    # fori_loop of full rounds — pose exchange + per-agent RTR each — so the
    # measurement excludes host/tunnel dispatch, which otherwise dominates.
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    t0 = time.perf_counter()
    state = steps(state, 1)
    _ = np.asarray(state.X)
    log(f"  [{device.platform}] compile+first round: "
        f"{time.perf_counter() - t0:.1f}s")
    # Steady-state warm-up: the first fused call after compile measures
    # consistently slower (device ramp / tunnel session warm-up) — an
    # accelerator effect, so skip the extra rounds on the CPU baseline.
    if device.platform != "cpu":
        _ = np.asarray(steps(state, min(50, rounds)).X)

    # Median of several trials: the tunneled TPU is a shared resource whose
    # effective throughput fluctuates across minutes; the median is robust
    # to interfered trials without reporting the lucky peak.
    rates = []
    state0 = state
    for _ in range(5 if device.platform != "cpu" else 3):
        t0 = time.perf_counter()
        state = steps(state0, rounds)
        # Device->host readback, NOT block_until_ready: on this image's
        # experimental tunneled TPU platform, block_until_ready empirically
        # returns before execution finishes (measured: 100 chained rounds
        # "complete" in 7 ms under block_until_ready vs 2.0 s with a
        # readback, against an 18 ms single-round execution) — so timing
        # must end with a transfer, which cannot complete early.
        Xh = np.asarray(state.X)
        dt = time.perf_counter() - t0
        assert bool(np.isfinite(Xh).all()), "non-finite state"
        assert int(state.iteration) == int(state0.iteration) + rounds
        rates.append(rounds / dt)
        log(f"  [{device.platform}] trial: {rounds / dt:.1f} rounds/s")
    return float(np.median(rates))


def kernel_parity_check(device) -> float:
    """On-device Pallas-vs-XLA drift guard (VERDICT r2 item 5): run ONE
    full RBCD round through the compiled Mosaic kernel and through the ELL
    formulation ON THE BENCH DEVICE and return the max-abs iterate
    difference.  The kernels are parity-tested in interpreter mode on CPU
    (tests/test_pallas_tcg.py); this closes the remaining hole — a Mosaic
    compile difference would otherwise surface only as silent perf or
    accuracy drift.  Caller asserts the bound and records the number."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd

    state, graph, meta, params = build(jnp.float32)
    state = jax.device_put(state, device)
    graph = jax.device_put(graph, device)
    params_ell = dataclasses.replace(
        params, solver=dataclasses.replace(params.solver, pallas_tcg=False))
    s_kernel = rbcd.rbcd_step(state, graph, meta, params,
                              update_weights=False, restart=False)
    s_ell = rbcd.rbcd_step(state, graph, meta, params_ell,
                           update_weights=False, restart=False)
    dx = np.abs(np.asarray(s_kernel.X) - np.asarray(s_ell.X)).max()
    dg = np.abs(np.asarray(s_kernel.rel_change)
                - np.asarray(s_ell.rel_change)).max()
    return float(max(dx, dg))


#: On-device kernel-vs-XLA bound for one RBCD round: both paths run the
#: same f32 math, so the difference is reduction order + the kernel's
#: Newton-Schulz (vs SVD) retraction — observed ~1e-6..1e-5 scale; 5e-4
#: flags a genuine Mosaic lowering change without tripping on noise.
KERNEL_PARITY_BOUND = 5e-4


def cpu_baseline_subprocess() -> float:
    """Measure the f64 CPU baseline in a clean subprocess (x64 must be on
    for a true double-precision run, but enabling it in the TPU process
    breaks the tunnel compiler)."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
               BENCH_MODE="cpu")
    out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                         env=env, capture_output=True, text=True, timeout=1800)
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        raise RuntimeError(f"cpu baseline failed:\n{out.stderr[-2000:]}")
    return float(out.stdout.strip().splitlines()[-1])


def main():
    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_MODE") == "cpu":
        # The env JAX_PLATFORMS=cpu alone is not enough: the image's
        # sitecustomize re-registers the TPU tunnel and overrides
        # jax_platforms, and a second process touching the tunnel would
        # deadlock on the single TPU grant — pin the backend in code, as
        # tests/conftest.py does.
        jax.config.update("jax_platforms", "cpu")
        cpu = jax.devices("cpu")[0]
        ips = time_rounds(cpu, jnp.float64, CPU_ROUNDS)
        log(f"  cpu baseline: {ips:.2f} rounds/s (float64)")
        print(ips)
        return

    dev = jax.devices()[0]
    log(f"benchmark device: {dev.platform} ({dev.device_kind})")
    bench_dtype = "float32" if dev.platform != "cpu" else "float64"
    if bench_dtype == "float64":
        # CPU-only host: actually enable double precision (safe here — no
        # TPU tunnel in this process; enabling x64 under the tunnel is what
        # breaks its compiler).
        jax.config.update("jax_enable_x64", True)

    parity = None
    if dev.platform != "cpu":
        # Drift guard BEFORE timing: the compiled Mosaic kernel must match
        # the XLA formulation on this device.
        parity = kernel_parity_check(dev)
        log(f"  on-device kernel-vs-XLA parity: max-abs-diff {parity:.2e} "
            f"(bound {KERNEL_PARITY_BOUND:.0e})")
        assert parity < KERNEL_PARITY_BOUND, (
            f"Mosaic kernel drifted from the XLA formulation: "
            f"{parity:.3e} >= {KERNEL_PARITY_BOUND}")

    ips = time_rounds(dev, getattr(jnp, bench_dtype), ROUNDS)
    log(f"  {ips:.2f} RBCD rounds/s ({bench_dtype})")

    if dev.platform == "cpu":
        cpu_ips = ips
    else:
        cpu_ips = cpu_baseline_subprocess()

    out = {
        "metric": "rbcd_rounds_per_sec_sphere2500_8agents_r5",
        "value": round(ips, 3),
        "unit": "rounds/s",
        "vs_baseline": round(ips / cpu_ips, 3),
    }
    if parity is not None:
        out["kernel_parity_max_abs_diff"] = parity
    print(json.dumps(out))


if __name__ == "__main__":
    main()
