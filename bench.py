"""Benchmark: synchronous RBCD throughput on sphere2500 with 8 agents, r=5
(BASELINE.md north-star config #2).

Measures full RBCD rounds/sec — each round = public-pose exchange + one RTR
(truncated-CG) step for every agent — on the default JAX backend (TPU when
present), and the same problem on the CPU backend in float64 as the
stand-in for the reference's SuiteSparse/ROPTLIB CPU implementation (the
reference publishes no numbers and its ROPTLIB dependency is git-fetched at
configure time, unavailable offline — see BASELINE.md).

Since round 6 the accelerator arm times the PRODUCTION solve loop — the
device-resident verdict-word driver (``run_rbcd(verdict_every=K)``): all
rounds, the fused eval program, and termination run on device, and the
host reads one packed word per K rounds.  The raw fused-segment loop (the
pre-round-6 measurement: one trailing readback per trial) is still
measured and recorded as ``fused_rounds_per_s`` for cross-round
continuity.  Host syncs during the timed verdict trials are COUNTED via a
shim on the driver's one sanctioned fetch seam (``rbcd._host_fetch`` —
the same patch-the-seam technique as the zero-overhead telemetry smoke)
and reported as ``host_syncs_per_100_rounds``; the CPU f64 arm's
methodology (fused loop, spaced windows, contention guard) is unchanged.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

DATASET = "/root/reference/data/sphere2500.g2o"
NUM_ROBOTS = 8
RANK = 5
#: Rounds per verdict-loop trial (the headline arm).  Large enough that
#: the per-K-round word fetches and the one-per-solve epilogue amortize:
#: at ~0.3-0.5 ms/round on the TPU the loop is device-bound, not
#: RTT-bound.
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "2048"))
#: Verdict cadence K for the headline arm (one word readback per K
#: rounds; host_syncs_per_100_rounds = 100/K).
VERDICT_K = int(os.environ.get("BENCH_VERDICT_K", "512"))
#: Rounds per raw fused-loop trial (the pre-round-6 continuity arm).
FUSED_ROUNDS = int(os.environ.get("BENCH_FUSED_ROUNDS", "200"))
# 25 rounds/trial: the 1-core host's scheduling variance dominates short
# trials (observed 22.6-33.4 rounds/s across runs at 15), and ~1 s
# trials steady the median at negligible total cost.
CPU_ROUNDS = int(os.environ.get("BENCH_CPU_ROUNDS", "25"))
# Kernel selection-matmul mode for the TPU arm: bf16x3 (3-pass hi/mid/lo
# split; covers the full 24-bit f32 mantissa, so accuracy is f32-grade —
# per-round kernel-vs-XLA drift ~3e-5 vs the HIGHEST path's ~8e-6, both far
# inside the 5e-4 parity bound asserted below) at ~1.2x the HIGHEST-
# emulation round rate on this shape.  Recorded in the output JSON.
SEL_MODE = os.environ.get("BENCH_SEL_MODE", "bf16x3")
# CPU f64 arm: number of time-spaced measurement windows and their spacing.
# The 1-core host's effective f64 throughput swings up to 2x across thermal
# / scheduling windows (BASELINE.md round-4 caveat), so a single window can
# silently cherry-pick the headline; >=3 spaced windows give a min/median/
# max band and vs_baseline is computed from the MEDIAN (VERDICT r4 item 7).
CPU_WINDOWS = int(os.environ.get("BENCH_CPU_WINDOWS", "3"))
CPU_WINDOW_SPACING_S = float(os.environ.get("BENCH_CPU_SPACING_S", "45"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(dtype, never_terminate: bool = False):
    from dpgo_tpu.config import AgentParams, SolverParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    if os.path.exists(DATASET):
        from dpgo_tpu.utils.g2o import read_g2o
        meas = read_g2o(DATASET)
    else:  # fall back to a same-order synthetic problem
        from dpgo_tpu.utils.synthetic import make_measurements
        meas, _ = make_measurements(np.random.default_rng(0), n=2500, d=3,
                                    num_lc=2449, rot_noise=0.01,
                                    trans_noise=0.01)
    # never_terminate (verdict-loop arm): zero the consensus tolerance so
    # the on-device termination test can never cut a timed trial short —
    # every trial runs exactly its configured round count.
    params = AgentParams(d=3, r=RANK, num_robots=NUM_ROBOTS,
                         solver=SolverParams(pallas_sel_mode=SEL_MODE),
                         rel_change_tol=0.0 if never_terminate else 5e-3)
    part = partition_contiguous(meas, NUM_ROBOTS)
    graph, meta = rbcd.build_graph(part, RANK, dtype, sel_mode=SEL_MODE)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return state, graph, meta, params, part


def time_rounds(device, dtype, rounds):
    import jax
    from dpgo_tpu.models import rbcd

    state, graph, meta, params, _part = build(dtype)
    state = jax.device_put(state, device)
    graph = jax.device_put(graph, device)

    # Fused stepping (rbcd.rbcd_steps): the whole trial runs as one on-device
    # fori_loop of full rounds — pose exchange + per-agent RTR each — so the
    # measurement excludes host/tunnel dispatch, which otherwise dominates.
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    t0 = time.perf_counter()
    state = steps(state, 1)
    _ = np.asarray(state.X)
    log(f"  [{device.platform}] compile+first round: "
        f"{time.perf_counter() - t0:.1f}s")
    # Steady-state warm-up: the first fused call after compile measures
    # consistently slower (device ramp / tunnel session warm-up) — an
    # accelerator effect, so skip the extra rounds on the CPU baseline.
    if device.platform != "cpu":
        _ = np.asarray(steps(state, min(50, rounds)).X)

    # Median of several trials: the tunneled TPU is a shared resource whose
    # effective throughput fluctuates across minutes; the median is robust
    # to interfered trials without reporting the lucky peak.
    rates = []
    state0 = state
    for _ in range(5 if device.platform != "cpu" else 3):
        t0 = time.perf_counter()
        state = steps(state0, rounds)
        # Device->host readback, NOT block_until_ready: on this image's
        # experimental tunneled TPU platform, block_until_ready empirically
        # returns before execution finishes (measured: 100 chained rounds
        # "complete" in 7 ms under block_until_ready vs 2.0 s with a
        # readback, against an 18 ms single-round execution) — so timing
        # must end with a transfer, which cannot complete early.
        Xh = np.asarray(state.X)
        dt = time.perf_counter() - t0
        assert bool(np.isfinite(Xh).all()), "non-finite state"
        assert int(state.iteration) == int(state0.iteration) + rounds
        rates.append(rounds / dt)
        log(f"  [{device.platform}] trial: {rounds / dt:.1f} rounds/s")
    return float(np.median(rates))


def profile_fused_rounds(device, dtype, profile_dir, rounds=8):
    """Device-time attribution of the fused single-device loop (ISSUE
    16, opt-in via ``BENCH_DEVPROF=<dir>``): one traced segment run
    AFTER the timed trials — tracing slows the loop, so it must never
    touch a measured window.  Returns the attribution dict (or None when
    the profiler produced no trace)."""
    import jax
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.obs import devprof

    state, graph, meta, params, _part = build(dtype)
    state = jax.device_put(state, device)
    graph = jax.device_put(graph, device)
    steps = lambda s, k: rbcd.rbcd_steps(s, graph, k, meta, params)
    _ = np.asarray(steps(state, 1).X)  # compile outside the window
    win = devprof.DeviceTraceWindow(profile_dir, plane="solve").start()
    _ = np.asarray(steps(state, rounds).X)
    att = win.stop(num_rounds=rounds, label="fused_loop")
    if att is not None:
        pr = att["per_round"]
        log(f"  [devprof] fused loop: {pr['compute_s'] * 1e3:.2f} ms "
            f"compute + {pr['collective_s'] * 1e3:.2f} ms collective + "
            f"{pr['idle_s'] * 1e3:.2f} ms idle per round "
            f"({att['lanes']} lanes; trace in {profile_dir})")
    return att


def time_verdict_loop(device, dtype, rounds, k):
    """Time the production device-resident solve loop: ``run_rbcd`` in
    verdict mode — schedule segments + fused eval/verdict program on
    device, ONE packed-word readback per ``k`` rounds, tolerances zeroed
    so every trial executes exactly ``rounds`` rounds.  Host syncs are
    counted through the ``rbcd._host_fetch`` seam; the per-solve terminal
    epilogue (history + latched-index fetch, 2 calls) is excluded from
    the recurring rate, matching the driver's own metric accounting.

    Returns ``(rounds_per_s_median, syncs_per_100_rounds, fetches)``."""
    import jax
    from dpgo_tpu.models import rbcd

    state0, graph, meta, params, part = build(dtype, never_terminate=True)
    state0 = jax.device_put(state0, device)
    graph = jax.device_put(graph, device)
    step = lambda s, uw, rs: rbcd.rbcd_step(s, graph, meta, params,
                                            update_weights=uw, restart=rs)
    seg = lambda s, kk, uw, rs: rbcd.rbcd_segment(s, graph, kk, meta,
                                                  params,
                                                  first_update_weights=uw,
                                                  first_restart=rs)

    def drive(n_rounds):
        return rbcd.run_rbcd(state0, graph, meta, step, part, n_rounds,
                             grad_norm_tol=0.0, eval_every=k, dtype=dtype,
                             params=params, segment=seg, verdict_every=k)

    # Warm-up compiles the segment, verdict, and finalize programs with
    # the exact call pattern of the timed trials (a structurally
    # different warm-up re-traces inside the clock — verify SKILL.md).
    t0 = time.perf_counter()
    res = drive(k)
    assert res.iterations == k
    log(f"  [{device.platform}] verdict loop compile+first block: "
        f"{time.perf_counter() - t0:.1f}s")
    drive(min(2 * k, rounds))

    counted = [0]
    orig_fetch = rbcd._host_fetch

    def counting_fetch(x):
        counted[0] += 1
        return orig_fetch(x)

    rates, sync_rates = [], []
    fetches = 0
    rbcd._host_fetch = counting_fetch
    try:
        for _ in range(3 if device.platform != "cpu" else 2):
            counted[0] = 0
            t0 = time.perf_counter()
            res = drive(rounds)
            dt = time.perf_counter() - t0
            assert res.iterations == rounds, res.iterations
            assert res.terminated_by == "max_iters", res.terminated_by
            assert all(np.isfinite(c) for c in res.cost_history), \
                "non-finite cost in verdict history"
            fetches = counted[0]
            # The single fused terminal-epilogue fetch is once-per-solve,
            # like _finalize — excluded from the rate.
            sync_rates.append(100.0 * max(fetches - 1, 0) / rounds)
            rates.append(rounds / dt)
            log(f"  [{device.platform}] verdict trial: "
                f"{rounds / dt:.1f} rounds/s, {fetches} host fetches")
    finally:
        rbcd._host_fetch = orig_fetch
    return (float(np.median(rates)), float(np.median(sync_rates)),
            int(fetches))


def kernel_parity_check(device) -> float:
    """On-device Pallas-vs-XLA drift guard (VERDICT r2 item 5): run ONE
    full RBCD round through the compiled Mosaic kernel and through the ELL
    formulation ON THE BENCH DEVICE and return the max-abs iterate
    difference.  The kernels are parity-tested in interpreter mode on CPU
    (tests/test_pallas_tcg.py); this closes the remaining hole — a Mosaic
    compile difference would otherwise surface only as silent perf or
    accuracy drift.  Caller asserts the bound and records the number."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from dpgo_tpu.models import rbcd

    state, graph, meta, params, _part = build(jnp.float32)
    state = jax.device_put(state, device)
    graph = jax.device_put(graph, device)
    params_ell = dataclasses.replace(
        params, solver=dataclasses.replace(params.solver, pallas_tcg=False))
    s_kernel = rbcd.rbcd_step(state, graph, meta, params,
                              update_weights=False, restart=False)
    s_ell = rbcd.rbcd_step(state, graph, meta, params_ell,
                           update_weights=False, restart=False)
    dx = np.abs(np.asarray(s_kernel.X) - np.asarray(s_ell.X)).max()
    dg = np.abs(np.asarray(s_kernel.rel_change)
                - np.asarray(s_ell.rel_change)).max()
    return float(max(dx, dg))


#: On-device kernel-vs-XLA bound for one RBCD round: both paths run the
#: same f32 math, so the difference is reduction order + the kernel's
#: Newton-Schulz (vs SVD) retraction — observed ~1e-6..1e-5 scale; 5e-4
#: flags a genuine Mosaic lowering change without tripping on noise.
KERNEL_PARITY_BOUND = 5e-4


def _busy_core_seconds() -> float:
    """System-wide non-idle CPU time in core-seconds (all cores summed)."""
    with open("/proc/stat") as f:
        vals = [int(x) for x in f.readline().split()[1:]]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
    # guest/guest_nice (fields 9-10) are already counted in user/nice.
    guest = sum(vals[8:10]) if len(vals) > 9 else 0
    return (sum(vals) - idle - guest) / os.sysconf("SC_CLK_TCK")


def other_cpu_during(fn):
    """Run ``fn()`` and return ``(result, other_busy)`` where ``other_busy``
    is the CPU time used by OTHER processes during the call, in core-seconds
    per wall-second (system-wide ``/proc/stat`` busy delta minus this
    process's own ``os.times`` delta).

    The CPU f64 arm under-measures when anything else loads the host (a
    concurrent pytest run halved it once — which would silently DOUBLE the
    reported speedup), so contention is measured over the TIMED WINDOW
    ITSELF — pre/post sampling misses a competitor that lives exactly as
    long as the trial, and instantaneous runnable-count sampling misses
    bursty ones (measured: a competing f64 solve dropped the arm
    28.5 -> 22 rounds/s while 5 runnable-count samples all read 0).
    Core-seconds-per-second is core-count independent: one compute-bound
    competitor reads ~1.0 on any machine."""
    try:
        b0 = _busy_core_seconds()
    except (OSError, ValueError, IndexError):  # non-Linux: no guard
        return fn(), 0.0
    s0 = sum(os.times()[:4])  # self user+sys, incl. reaped children
    t0 = time.perf_counter()
    result = fn()
    dt = max(time.perf_counter() - t0, 1e-9)
    other = max(0.0, (_busy_core_seconds() - b0) - (sum(os.times()[:4]) - s0))
    return result, other / dt


#: Other-process core-seconds/s above which the f64 CPU arm is considered
#: contended: a clean host reads ~0, a single compute-bound competitor ~1.
CONTENTION_OTHER_CORES = 0.2


def cpu_baseline_subprocess() -> dict:
    """Measure the f64 CPU baseline in a clean subprocess (x64 must be on
    for a true double-precision run, but enabling it in the TPU process
    breaks the tunnel compiler).  Returns {"ips", "contended", ...}."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
               BENCH_MODE="cpu")
    out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                         env=env, capture_output=True, text=True, timeout=1800)
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        raise RuntimeError(f"cpu baseline failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main():
    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_MODE") == "cpu":
        # The env JAX_PLATFORMS=cpu alone is not enough: the image's
        # sitecustomize re-registers the TPU tunnel and overrides
        # jax_platforms, and a second process touching the tunnel would
        # deadlock on the single TPU grant — pin the backend in code, as
        # tests/conftest.py does.
        jax.config.update("jax_platforms", "cpu")
        cpu = jax.devices("cpu")[0]
        # Pre-check (this process sleeps, so all measured busy is others'):
        # wait once for a clean window before paying for the trials.
        _, pre = other_cpu_during(lambda: time.sleep(1.0))
        if pre > CONTENTION_OTHER_CORES:
            log(f"  [cpu] host contended ({pre:.2f} other core-s/s) — "
                f"waiting 20 s for a clean window")
            time.sleep(20.0)
        # The guard that counts is measured over the timed window itself.
        ips, other = other_cpu_during(
            lambda: time_rounds(cpu, jnp.float64, CPU_ROUNDS))
        try:
            with open("/proc/loadavg") as f:
                load1 = float(f.read().split()[0])
        except (OSError, ValueError):
            load1 = 0.0
        log(f"  cpu baseline: {ips:.2f} rounds/s (float64); "
            f"other-process CPU during trials {other:.2f} core-s/s, "
            f"load1 {load1:.2f}")
        print(json.dumps({"ips": ips,
                          "contended": other > CONTENTION_OTHER_CORES,
                          "other_busy_cores": round(other, 3),
                          "load1": load1}))
        return

    dev = jax.devices()[0]
    log(f"benchmark device: {dev.platform} ({dev.device_kind})")
    bench_dtype = "float32" if dev.platform != "cpu" else "float64"
    if bench_dtype == "float64":
        # CPU-only host: actually enable double precision (safe here — no
        # TPU tunnel in this process; enabling x64 under the tunnel is what
        # breaks its compiler).
        jax.config.update("jax_enable_x64", True)

    parity = None
    if dev.platform != "cpu":
        # Drift guard BEFORE timing: the compiled Mosaic kernel must match
        # the XLA formulation on this device.
        parity = kernel_parity_check(dev)
        log(f"  on-device kernel-vs-XLA parity: max-abs-diff {parity:.2e} "
            f"(bound {KERNEL_PARITY_BOUND:.0e})")
        assert parity < KERNEL_PARITY_BOUND, (
            f"Mosaic kernel drifted from the XLA formulation: "
            f"{parity:.3e} >= {KERNEL_PARITY_BOUND}")

    if dev.platform == "cpu":
        # CPU-only fallback: the raw fused loop, as in every prior round.
        ips = time_rounds(dev, getattr(jnp, bench_dtype), FUSED_ROUNDS)
        fused_ips, syncs, fetches = ips, None, None
        log(f"  {ips:.2f} RBCD rounds/s ({bench_dtype}, fused loop)")
    else:
        # Continuity arm first (the pre-round-6 measurement), then the
        # headline: the device-resident verdict-word solve loop.
        fused_ips = time_rounds(dev, getattr(jnp, bench_dtype),
                                FUSED_ROUNDS)
        log(f"  {fused_ips:.2f} RBCD rounds/s ({bench_dtype}, fused loop)")
        ips, syncs, fetches = time_verdict_loop(
            dev, getattr(jnp, bench_dtype), ROUNDS, VERDICT_K)
        log(f"  {ips:.2f} RBCD rounds/s ({bench_dtype}, verdict loop "
            f"K={VERDICT_K}; {syncs:.3g} host syncs/100 rounds)")

    # Optional device-time attribution of the fused loop (ISSUE 16):
    # a separate traced segment AFTER the timed arms above, so the
    # profiler overhead never contaminates the measured rates.
    attribution = None
    if os.environ.get("BENCH_DEVPROF"):
        attribution = profile_fused_rounds(
            dev, getattr(jnp, bench_dtype), os.environ["BENCH_DEVPROF"])

    if dev.platform == "cpu":
        windows = [{"ips": ips, "contended": False}]
    else:
        # >=3 time-spaced windows of the f64 arm (VERDICT r4 item 7): the
        # band makes the 2x thermal swing visible instead of letting one
        # lucky window set the headline.
        windows = []
        for wi in range(max(CPU_WINDOWS, 1)):
            if wi:
                log(f"  [cpu] window spacing: sleeping "
                    f"{CPU_WINDOW_SPACING_S:.0f}s")
                time.sleep(CPU_WINDOW_SPACING_S)
            windows.append(cpu_baseline_subprocess())
            log(f"  [cpu] window {wi + 1}/{CPU_WINDOWS}: "
                f"{windows[-1]['ips']:.2f} rounds/s"
                + (" (CONTENDED)" if windows[-1].get("contended") else ""))
    rates_all = [w["ips"] for w in windows]
    # Contended windows under-measure the arm (inflating vs_baseline), so
    # the band prefers clean windows and falls back to all only when no
    # clean window exists — in which case the output is flagged.
    clean = [w["ips"] for w in windows if not w.get("contended")] or rates_all
    cpu_med = float(np.median(clean))

    # The final line goes through the obs event schema (same leading
    # metric/value/unit keys as BENCH_r0*.json and the telemetry stream's
    # metric events), so bench records and run telemetry parse with one
    # reader (dpgo_tpu.obs.events.metric_record).
    from dpgo_tpu.obs.events import metric_record

    out = metric_record(
        "rbcd_rounds_per_sec_sphere2500_8agents_r5",
        round(ips, 3),
        "rounds/s",
        vs_baseline=round(ips / cpu_med, 3),
        sel_mode=SEL_MODE,
        cpu_arm_band={"min": round(min(rates_all), 2),
                      "median": round(cpu_med, 2),
                      "max": round(max(rates_all), 2),
                      "windows": [round(r, 2) for r in rates_all],
                      "spacing_s": CPU_WINDOW_SPACING_S},
        vs_baseline_band={"min": round(ips / max(rates_all), 2),
                          "max": round(ips / min(rates_all), 2)},
        loop="fused" if dev.platform == "cpu" else "verdict_word",
        fused_rounds_per_s=round(fused_ips, 3),
    )
    if syncs is not None:
        out["verdict_every"] = VERDICT_K
        out["verdict_rounds_per_trial"] = ROUNDS
        out["host_syncs_per_100_rounds"] = round(syncs, 4)
        out["host_fetches_per_trial"] = fetches
    if parity is not None:
        out["kernel_parity_max_abs_diff"] = parity
    if attribution is not None:
        out["device_attribution"] = {
            k: attribution[k]
            for k in ("lanes", "window_s", "compute_s", "collective_s",
                      "idle_s", "overlap_efficiency_measured")}
    if any(w.get("contended") for w in windows):
        # At least one f64 window ran on a loaded host; if ALL were
        # contended the median itself is inflated — flag loudest then.
        out["cpu_arm_contended_windows"] = sum(
            1 for w in windows if w.get("contended"))
        out["cpu_arm_all_contended"] = all(
            w.get("contended") for w in windows)
        out["cpu_arm_other_busy_cores"] = max(
            w.get("other_busy_cores") or 0.0 for w in windows)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
