"""Exporters: Prometheus text exposition and optional TensorBoard scalars.

Both read from the registry / event stream without touching devices — the
instrumentation layer already did its phase-boundary readbacks; exporters
are pure host-side formatting.
"""

from __future__ import annotations

import math
import os

from .events import nonfinite_str


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    parts = []
    for k, v in sorted(merged.items()):
        # Text exposition format escapes: backslash first, then newline
        # and quote — a raw newline in a label value splits the sample
        # line and corrupts the whole scrape.
        v = (str(v).replace("\\", "\\\\").replace("\n", "\\n")
             .replace('"', '\\"'))
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt_value(v: float) -> str:
    # Non-finite spelling shared with the snapshot/event serialization
    # (events.nonfinite_str) — one convention across the whole stack.
    if not math.isfinite(v):
        return nonfinite_str(v)
    return repr(float(v))


def _escape_help(s: str) -> str:
    # HELP text escapes only backslash and newline (the label escaping
    # above additionally covers quotes; HELP is unquoted).
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


#: Declared-unit spellings -> the canonical Prometheus name suffix.
_UNIT_SUFFIX = {"s": "seconds", "sec": "seconds", "seconds": "seconds",
                "B": "bytes", "bytes": "bytes"}


def exposition_name(name: str, unit: str = "") -> str:
    """The family's name on the wire: Prometheus naming wants the base
    unit as a name suffix (``_seconds``, ``_bytes``) so scrapes validate
    cleanly.  Families that declared a unit but don't carry its token in
    the name get the suffix appended (before a trailing ``_total``);
    names already mentioning the unit anywhere — ``comms_bytes_sent``,
    ``round_latency_seconds`` — pass through untouched, so pre-existing
    dashboards keep their series."""
    suffix = _UNIT_SUFFIX.get(unit or "")
    if suffix is None or suffix in name.split("_"):
        return name
    if name.endswith("_total"):
        return name[:-len("_total")] + f"_{suffix}_total"
    return f"{name}_{suffix}"


def to_prometheus_text(registry) -> str:
    """Prometheus text exposition (format version 0.0.4) of a
    ``MetricsRegistry``: ``# HELP`` / ``# TYPE`` headers per family
    (HELP text escaped per the format spec, falling back to the family
    name so every family is documented), unit-suffixed exposition names
    (``exposition_name``), histogram families expanded to
    ``_bucket``/``_sum``/``_count`` with cumulative ``le`` buckets."""
    lines = []
    for fam in registry.families():
        name = exposition_name(fam.name, fam.unit)
        lines.append(f"# HELP {name} {_escape_help(fam.help or fam.name)}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for key, val in sorted(fam.series().items()):
            labels = dict(key)
            if fam.kind == "histogram":
                cum = 0
                for bound, n in zip(fam.buckets, val["counts"]):
                    cum += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(bound)})}"
                        f" {cum}")
                cum += val["counts"][-1]
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, {'le': '+Inf'})}"
                    f" {cum}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)}"
                    f" {_fmt_value(val['sum'])}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {val['count']}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(val)}")
    return "\n".join(lines) + "\n"


_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"


def _split_sample_line(line: str):
    """``(name, labels_text_or_None, rest)`` of one exposition sample
    line, or None when the line does not parse as a sample."""
    import re

    m = re.match(rf"^({_NAME_RE})(\{{.*\}})?\s+(\S+)(\s+-?\d+)?\s*$",
                 line)
    if m is None:
        return None
    end = m.end(2) if m.group(2) else m.end(1)
    return m.group(1), m.group(2), line[end:]


def validate_prometheus_text(text: str) -> dict:
    """Line-validate a text exposition (format 0.0.4): every line must be
    a ``# HELP``/``# TYPE``/comment line, blank, or a well-formed sample
    with a finite/±Inf/NaN value.  Raises ``ValueError`` naming the first
    offending line; returns ``{"families": n, "samples": n}`` — the check
    the fleet-obs CI smoke runs on the aggregated scrape."""
    families: set = set()
    samples = 0
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                families.add(parts[2])
            continue
        parsed = _split_sample_line(line)
        if parsed is None:
            raise ValueError(f"malformed exposition line {ln}: {line!r}")
        value = parsed[2].split()[0]
        if value not in ("+Inf", "-Inf", "NaN"):
            try:
                float(value)
            except ValueError:
                raise ValueError(
                    f"non-numeric sample value on line {ln}: {line!r}")
        samples += 1
    return {"families": len(families), "samples": samples}


def relabel_prometheus_text(text: str, extra: dict) -> str:
    """Inject ``extra`` labels into every sample line of an exposition
    (comment/blank lines pass through) — how a fleet aggregator tags each
    child replica's scrape with ``replica="rN"`` before merging."""
    inject = _fmt_labels(extra)
    out = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            out.append(line)
            continue
        parsed = _split_sample_line(line)
        if parsed is None:
            out.append(line)   # pass through; validation flags it
            continue
        name, labels, rest = parsed
        if labels:
            merged = _fmt_labels(
                _parse_labels(labels), extra)
            out.append(f"{name}{merged}{rest}")
        else:
            out.append(f"{name}{inject}{rest}")
    return "\n".join(out)


def _parse_labels(labels_text: str) -> dict:
    """Parse ``{a="b",c="d"}`` back into a dict (escapes unwound) — only
    used to merge aggregator labels into already-rendered lines."""
    import re

    out = {}
    for m in re.finditer(rf'({_NAME_RE})="((?:\\.|[^"\\])*)"',
                         labels_text):
        v = (m.group(2).replace('\\"', '"').replace("\\n", "\n")
             .replace("\\\\", "\\"))
        out[m.group(1)] = v
    return out


def merge_prometheus_texts(parts: dict, label: str = "replica") -> str:
    """One exposition from many: each value of ``parts`` (keyed by
    replica id) is relabeled with ``label="<id>"`` and merged grouped by
    family — one ``# HELP``/``# TYPE`` header per family (first writer
    wins; the format forbids duplicates) followed by every contributor's
    samples, so strict scrapers see no interleaved families.  A falsy
    key ("" — the aggregator's own registry) passes through unlabeled:
    its samples already carry whatever identity they need."""
    order: list[str] = []
    headers: dict = {}
    samples: dict = {}
    for rid in sorted(parts):
        text = relabel_prometheus_text(parts[rid], {label: rid}) \
            if rid else parts[rid]
        fam = ""
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                toks = line.split(None, 3)
                if len(toks) >= 3 and toks[1] in ("HELP", "TYPE"):
                    fam = toks[2]
                    if fam not in headers:
                        headers[fam] = []
                        samples[fam] = []
                        order.append(fam)
                    if toks[1] not in {h.split(None, 3)[1]
                                       for h in headers[fam]}:
                        headers[fam].append(line)
                continue
            if fam not in samples:
                headers[fam] = []
                samples[fam] = []
                order.append(fam)
            samples[fam].append(line)
    out: list[str] = []
    for fam in order:
        out.extend(headers[fam])
        out.extend(samples[fam])
    return "\n".join(out) + ("\n" if out else "")


def write_tensorboard_scalars(run_dir: str, events: list[dict],
                              logdir: str | None = None) -> str | None:
    """Export the stream's ``metric`` events as TensorBoard scalars.

    Optional: uses whichever summary writer the environment already has
    (``tensorboardX`` or TensorFlow's), returns None — without raising —
    when neither is importable, so the core subsystem carries no
    TensorBoard dependency.  Scalars are keyed by metric name, stepped by
    the event's ``iteration`` field when present (else its sequence
    number), and stamped with the event's wall time.
    """
    writer_cls = None
    try:
        from tensorboardX import SummaryWriter as writer_cls  # noqa: N813
    except ImportError:
        try:
            from tensorflow.summary import create_file_writer  # noqa: F401
            import tensorflow as tf
        except ImportError:
            return None
        logdir = logdir or os.path.join(run_dir, "tensorboard")
        w = tf.summary.create_file_writer(logdir)
        with w.as_default():
            for ev in events:
                if ev.get("event") != "metric":
                    continue
                v = ev.get("value")
                if not isinstance(v, (int, float)):
                    continue
                step = int(ev.get("iteration", ev.get("seq", 0)))
                tf.summary.scalar(ev["metric"], v, step=step)
        w.flush()
        return logdir
    logdir = logdir or os.path.join(run_dir, "tensorboard")
    w = writer_cls(logdir)
    try:
        for ev in events:
            if ev.get("event") != "metric":
                continue
            v = ev.get("value")
            if not isinstance(v, (int, float)):
                continue
            step = int(ev.get("iteration", ev.get("seq", 0)))
            w.add_scalar(ev["metric"], v, global_step=step,
                         walltime=ev.get("t_wall"))
    finally:
        w.close()
    return logdir
