"""Exporters: Prometheus text exposition and optional TensorBoard scalars.

Both read from the registry / event stream without touching devices — the
instrumentation layer already did its phase-boundary readbacks; exporters
are pure host-side formatting.
"""

from __future__ import annotations

import math
import os

from .events import nonfinite_str


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    parts = []
    for k, v in sorted(merged.items()):
        # Text exposition format escapes: backslash first, then newline
        # and quote — a raw newline in a label value splits the sample
        # line and corrupts the whole scrape.
        v = (str(v).replace("\\", "\\\\").replace("\n", "\\n")
             .replace('"', '\\"'))
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt_value(v: float) -> str:
    # Non-finite spelling shared with the snapshot/event serialization
    # (events.nonfinite_str) — one convention across the whole stack.
    if not math.isfinite(v):
        return nonfinite_str(v)
    return repr(float(v))


def _escape_help(s: str) -> str:
    # HELP text escapes only backslash and newline (the label escaping
    # above additionally covers quotes; HELP is unquoted).
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


#: Declared-unit spellings -> the canonical Prometheus name suffix.
_UNIT_SUFFIX = {"s": "seconds", "sec": "seconds", "seconds": "seconds",
                "B": "bytes", "bytes": "bytes"}


def exposition_name(name: str, unit: str = "") -> str:
    """The family's name on the wire: Prometheus naming wants the base
    unit as a name suffix (``_seconds``, ``_bytes``) so scrapes validate
    cleanly.  Families that declared a unit but don't carry its token in
    the name get the suffix appended (before a trailing ``_total``);
    names already mentioning the unit anywhere — ``comms_bytes_sent``,
    ``round_latency_seconds`` — pass through untouched, so pre-existing
    dashboards keep their series."""
    suffix = _UNIT_SUFFIX.get(unit or "")
    if suffix is None or suffix in name.split("_"):
        return name
    if name.endswith("_total"):
        return name[:-len("_total")] + f"_{suffix}_total"
    return f"{name}_{suffix}"


def to_prometheus_text(registry) -> str:
    """Prometheus text exposition (format version 0.0.4) of a
    ``MetricsRegistry``: ``# HELP`` / ``# TYPE`` headers per family
    (HELP text escaped per the format spec, falling back to the family
    name so every family is documented), unit-suffixed exposition names
    (``exposition_name``), histogram families expanded to
    ``_bucket``/``_sum``/``_count`` with cumulative ``le`` buckets."""
    lines = []
    for fam in registry.families():
        name = exposition_name(fam.name, fam.unit)
        lines.append(f"# HELP {name} {_escape_help(fam.help or fam.name)}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for key, val in sorted(fam.series().items()):
            labels = dict(key)
            if fam.kind == "histogram":
                cum = 0
                for bound, n in zip(fam.buckets, val["counts"]):
                    cum += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(bound)})}"
                        f" {cum}")
                cum += val["counts"][-1]
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, {'le': '+Inf'})}"
                    f" {cum}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)}"
                    f" {_fmt_value(val['sum'])}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {val['count']}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(val)}")
    return "\n".join(lines) + "\n"


def write_tensorboard_scalars(run_dir: str, events: list[dict],
                              logdir: str | None = None) -> str | None:
    """Export the stream's ``metric`` events as TensorBoard scalars.

    Optional: uses whichever summary writer the environment already has
    (``tensorboardX`` or TensorFlow's), returns None — without raising —
    when neither is importable, so the core subsystem carries no
    TensorBoard dependency.  Scalars are keyed by metric name, stepped by
    the event's ``iteration`` field when present (else its sequence
    number), and stamped with the event's wall time.
    """
    writer_cls = None
    try:
        from tensorboardX import SummaryWriter as writer_cls  # noqa: N813
    except ImportError:
        try:
            from tensorflow.summary import create_file_writer  # noqa: F401
            import tensorflow as tf
        except ImportError:
            return None
        logdir = logdir or os.path.join(run_dir, "tensorboard")
        w = tf.summary.create_file_writer(logdir)
        with w.as_default():
            for ev in events:
                if ev.get("event") != "metric":
                    continue
                v = ev.get("value")
                if not isinstance(v, (int, float)):
                    continue
                step = int(ev.get("iteration", ev.get("seq", 0)))
                tf.summary.scalar(ev["metric"], v, step=step)
        w.flush()
        return logdir
    logdir = logdir or os.path.join(run_dir, "tensorboard")
    w = writer_cls(logdir)
    try:
        for ev in events:
            if ev.get("event") != "metric":
                continue
            v = ev.get("value")
            if not isinstance(v, (int, float)):
                continue
            step = int(ev.get("iteration", ev.get("seq", 0)))
            w.add_scalar(ev["metric"], v, global_step=step,
                         walltime=ev.get("t_wall"))
    finally:
        w.close()
    return logdir
