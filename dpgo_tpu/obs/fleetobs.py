"""Fleet-wide observability: cross-process harvest, merged generation
timelines, aggregated live endpoints, crash forensics (ISSUE 20).

Everything in ``obs`` before this module is per-process: one
``TelemetryRun`` per OS process, dark at the process boundary.  After
PR 17 the execution is genuinely multi-process (multihost ranks,
out-of-process replicas, real ``kill -9`` recovery), so this module
makes the observability stack match:

* **Generation-scoped run directories + harvest.**  ``launch_world``
  and ``ProcServer`` hand each rank/child its own run directory
  (``--telemetry-dir``); after each generation (or on replica death) the
  parent's fail-open harvester reads every rank's ``events.jsonl`` tail
  (tail-tolerant: a SIGKILLed writer leaves a torn last line), the last
  published verdict word, and any ``blackbox.npz``, and folds them into
  one structured ``generation_postmortem`` event on the parent's run —
  the victim's forensics survive the victim.

* **Merged generation timeline.**  Workers/children stamp
  ``clock_sample`` pairs on the coordination-service barrier
  round-trips and the procs heartbeat poll (``comms.protocol
  .attach_clock``/``pop_clock`` — telemetry off means no stamp and a
  byte-identical wire), each process identifies itself with a
  fleet-plane actor id (``mh_rank_actor`` / ``proc_replica_actor``),
  and ``write_fleet_trace`` merges launcher + ranks + replicas into ONE
  Perfetto-loadable Chrome trace: barrier-wait spans, generation /
  respawn instants, and the kill as a ``process_lost`` instant on the
  victim's own track.

* **Aggregated live endpoints + resource sampling.**  ``FleetSidecar``
  serves fleet-level ``/metrics`` (the parent registry merged with each
  child sidecar's scrape, per-replica labels) and ``/statusz`` (per-
  replica status with unreachable replicas *marked*, never fatal —
  ``report --live --fleet`` renders the partial view).
  ``ResourceSampler`` is a slow-cadence stdlib-only thread (RSS, open
  fds, thread count, queue depth) whose series feed ``regress.py``'s
  flat-memory soak gate.

Zero-overhead fence: every constructor here is DPG002-registered and
only reachable through the ``start_resource_sampler`` /
``attach_fleet_sidecar`` seams, which return ``None`` without a live
run — telemetry off spawns no sampler, no harvester work, no HTTP
threads, and stamps no wire entries.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from .events import read_events_meta
from .run import EVENTS_FILE, get_run

#: Default sampler cadence: slow — the point is soak trends over
#: minutes/hours, not per-request attribution.
DEFAULT_SAMPLE_INTERVAL_S = 5.0

#: Postmortem tail length: the victim's last N events, by name/time.
POSTMORTEM_TAIL = 8


# ---------------------------------------------------------------------------
# Resource sampling (stdlib only: no psutil in the image)
# ---------------------------------------------------------------------------

def sample_resources() -> dict:
    """One stdlib-only resource snapshot of THIS process: RSS bytes
    (``/proc/self/status`` VmRSS, falling back to ``ru_maxrss``), open
    fd count, and live thread count.  Fields are None where the platform
    offers no cheap reading."""
    rss = None
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    if rss is None:
        try:
            import resource

            # Linux reports ru_maxrss in KiB (peak, not current — still
            # monotone evidence for a leak gate).
            rss = int(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss) * 1024
        except Exception:
            rss = None
    fds = None
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    return {"rss_bytes": rss, "open_fds": fds,
            "threads": threading.active_count()}


class ResourceSampler:
    """Slow-cadence per-process resource sampler thread.

    Emits ``process_rss_bytes`` / ``process_open_fds`` /
    ``process_threads`` (and, with a ``queue_depth`` callable,
    ``serve_queue_depth``) both as labeled gauges on the run's registry
    (the fleet ``/metrics`` surface) and as ``metric`` events (the soak
    trend series ``regress.py --soak`` gates).  Construct only through
    ``start_resource_sampler`` — the telemetry fence (DPG002)."""

    def __init__(self, run, interval_s: float = DEFAULT_SAMPLE_INTERVAL_S,
                 queue_depth=None, **labels):
        self.run = run
        self.interval_s = float(interval_s)
        self._queue_depth = queue_depth
        self._labels = {k: str(v) for k, v in labels.items()
                        if v is not None}
        self._stop = threading.Event()
        self._g_rss = run.gauge("process_rss_bytes",
                                "resident set size of this process",
                                unit="B")
        self._g_fds = run.gauge("process_open_fds",
                                "open file descriptors of this process")
        self._g_thr = run.gauge("process_threads",
                                "live threads in this process")
        self._g_q = run.gauge("serve_queue_depth_sampled",
                              "sampled admission queue depth")
        self.samples = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dpgo-resource-sampler")
        self._thread.start()

    def sample_once(self) -> dict:
        s = sample_resources()
        if self._queue_depth is not None:
            try:
                s["queue_depth"] = int(self._queue_depth())
            except Exception:
                s["queue_depth"] = None
        if s["rss_bytes"] is not None:
            self._g_rss.set(float(s["rss_bytes"]), **self._labels)
            self.run.metric("process_rss_bytes", s["rss_bytes"], "B",
                            phase="fleet", **self._labels)
        if s["open_fds"] is not None:
            self._g_fds.set(float(s["open_fds"]), **self._labels)
            self.run.metric("process_open_fds", s["open_fds"],
                            phase="fleet", **self._labels)
        self._g_thr.set(float(s["threads"]), **self._labels)
        self.run.metric("process_threads", s["threads"], phase="fleet",
                        **self._labels)
        if s.get("queue_depth") is not None:
            self._g_q.set(float(s["queue_depth"]), **self._labels)
            self.run.metric("serve_queue_depth_sampled", s["queue_depth"],
                            phase="fleet", **self._labels)
        self.samples += 1
        return s

    def _loop(self) -> None:
        # First sample immediately: short-lived processes (one child per
        # generation) still leave at least one point in the series.
        while True:
            try:
                self.sample_once()
            except Exception:
                pass  # fail-open: sampling must never take the host down
            if self._stop.wait(self.interval_s):
                return

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "ResourceSampler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_resource_sampler(interval_s: float = DEFAULT_SAMPLE_INTERVAL_S,
                           queue_depth=None, run=None,
                           **labels) -> ResourceSampler | None:
    """The sampler's telemetry fence: None (and no thread) without a
    live run."""
    run = run if run is not None else get_run()
    if run is None:
        return None
    return ResourceSampler(run, interval_s=interval_s,
                           queue_depth=queue_depth, **labels)


# ---------------------------------------------------------------------------
# Cross-process harvest + crash forensics
# ---------------------------------------------------------------------------

def generation_run_dir(root, generation: int, rank) -> str:
    """The generation-scoped run directory layout one harvest pass
    globs: ``<root>/g<generation>-r<rank>`` (rank may be a replica id)."""
    return os.path.join(str(root), f"g{int(generation)}-r{rank}")


def harvest_run_dir(run_dir: str, tail: int = POSTMORTEM_TAIL) -> dict:
    """Fail-open post-mortem of one (possibly killed) process's run dir.

    Tail-tolerant: ``read_events_meta`` drops a torn final JSONL line (a
    SIGKILL mid-write) and reports ``truncated``.  Returns the event
    tally, the last ``tail`` events (name + stamps), the last published
    verdict word decoded (``rbcd.unpack_verdict``), and the blackbox
    pointer when the flight recorder dumped one.  Never raises."""
    out: dict = {"run_dir": str(run_dir), "events": 0, "truncated": False,
                 "tail": [], "last_verdict": None, "blackbox": None}
    try:
        events, truncated = read_events_meta(
            os.path.join(run_dir, EVENTS_FILE))
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out
    out["events"] = len(events)
    out["truncated"] = bool(truncated)
    out["tail"] = [
        {k: e[k] for k in ("event", "t_mono", "t_wall", "iteration",
                           "seq", "phase") if k in e}
        for e in events[-tail:]]
    for e in reversed(events):
        if e.get("event") == "verdict_publish":
            entry = {"seq": e.get("seq_boundary"),
                     "iteration": e.get("iteration"),
                     "word": e.get("word"), "key": e.get("key")}
            try:
                from ..models.rbcd import unpack_verdict

                entry["decoded"] = unpack_verdict(int(e["word"]))
            except Exception:
                pass
            out["last_verdict"] = entry
            break
    try:
        from .recorder import BLACKBOX_NPZ

        bb = os.path.join(run_dir, BLACKBOX_NPZ)
        if os.path.exists(bb):
            info: dict = {"path": bb}
            try:
                from .recorder import load_blackbox

                context, arrays = load_blackbox(bb)
                info["context"] = {
                    k: context[k] for k in ("reason", "iteration", "rank")
                    if isinstance(context, dict) and k in context}
                info["arrays"] = sorted(arrays) \
                    if hasattr(arrays, "__iter__") else None
            except Exception:
                pass
            out["blackbox"] = info
    except Exception:
        pass
    return out


def harvest_generation(run, generation: int, rank_dirs: dict,
                       outcomes: dict | None = None,
                       records: dict | None = None,
                       plane: str = "multihost",
                       lost_actor=None) -> dict | None:
    """Collect every rank's telemetry after one generation and emit the
    ``generation_postmortem`` event on the parent's run.

    ``rank_dirs`` maps rank/replica-id -> run dir; ``outcomes`` carries
    the launcher's ``_classify`` verdict per rank and ``records`` the
    per-rank result/fault JSON.  Dead ranks (``signal:*`` / ``crash:*``
    outcomes) additionally get a ``process_lost`` instant on their own
    timeline track (``lost_actor(rank) -> actor id``).  Entirely
    fail-open; returns the postmortem dict (None without a run)."""
    if run is None:
        return None
    from .trace import emit_span

    t0_mono, t0_wall = time.monotonic(), time.time()
    outcomes = outcomes or {}
    records = records or {}
    ranks: dict = {}
    for rank, d in sorted(rank_dirs.items(), key=lambda kv: str(kv[0])):
        entry = harvest_run_dir(d)
        entry["outcome"] = outcomes.get(rank)
        rec = records.get(rank)
        if isinstance(rec, dict):
            entry["record"] = {
                k: rec[k] for k in ("ok", "kind", "phase", "boundaries",
                                    "iterations", "final_cost",
                                    "host_syncs_per_100_rounds", "error")
                if k in rec}
            # The rank stamped its record at write time: the reverse
            # (rank -> parent) clock sample, paired with the spawn stamp
            # the worker recorded, makes the launcher<->rank offset
            # bidirectional.
            if "t_record_mono" in rec and lost_actor is not None:
                try:
                    from ..comms.protocol import ORIGIN_FLEET_PARENT

                    run.event("clock_sample", phase="comms",
                              src=int(lost_actor(rank)),
                              dst=ORIGIN_FLEET_PARENT,
                              channel="harvest", kind="record",
                              t_send_mono=float(rec["t_record_mono"]),
                              t_send_wall=float(rec.get("t_record_wall",
                                                        0.0)))
                except Exception:
                    pass
        lost = str(entry["outcome"] or "").startswith(("signal:", "crash:"))
        if lost and lost_actor is not None:
            try:
                last = entry["tail"][-1] if entry["tail"] else {}
                run.event("process_lost", phase="comms",
                          robot=int(lost_actor(rank)), rank=rank,
                          generation=int(generation),
                          outcome=entry["outcome"], plane=plane,
                          last_event=last.get("event"),
                          last_event_t_wall=last.get("t_wall"))
            except Exception:
                pass
        ranks[str(rank)] = entry
    post = {"generation": int(generation), "plane": plane, "ranks": ranks}
    try:
        run.event("generation_postmortem", phase="fleet", **post)
        # The harvest span doubles as the launcher stream's identity
        # anchor (its actor id homes the stream for the track mapper).
        from ..comms.protocol import ORIGIN_FLEET_PARENT

        emit_span(run, "harvest_generation", t0_mono, t0_wall,
                  time.monotonic() - t0_mono, phase="fleet",
                  robot=ORIGIN_FLEET_PARENT, generation=int(generation))
    except Exception:
        pass
    return post


def write_fleet_trace(paths: list, out_path: str) -> dict:
    """Merge launcher + rank/replica run dirs into ONE validated Chrome
    trace at ``out_path``; returns the validation counts plus the clock
    report.  Raises only on an invalid merged trace — missing streams
    are skipped (fail-open harvest of a partially-written fleet)."""
    from . import timeline

    live = [p for p in paths
            if os.path.exists(timeline._events_path(str(p)))]
    tl = timeline.merge([str(p) for p in live])
    timeline.write_chrome_trace(out_path, tl)
    counts = timeline.validate_chrome_trace(out_path)
    return {"trace": out_path, "streams": len(live), **counts,
            "clock": tl.offsets}


# ---------------------------------------------------------------------------
# Aggregated fleet endpoints
# ---------------------------------------------------------------------------

def _scrape(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", "replace")


class ReplicaFleetSource:
    """Snapshot provider over a ``ReplicaManager`` (anything with
    ``replicas()`` + ``status()``): per-replica status from the parent's
    own heartbeat surface plus each child sidecar's ``/metrics`` URL."""

    def __init__(self, manager):
        self.manager = manager

    def snapshot(self) -> dict:
        try:
            fleet = self.manager.status()
        except Exception as e:
            fleet = {"error": f"{type(e).__name__}: {e}"}
        replicas: dict = {}
        try:
            live = list(self.manager.replicas())
        except Exception:
            live = []
        for rep in live:
            server = getattr(rep, "server", rep)
            rid = str(getattr(rep, "replica_id",
                              getattr(server, "replica_id", None)))
            entry: dict = {"status": None,
                           "metrics_url": getattr(server, "metrics_url",
                                                  None)}
            try:
                entry["status"] = server.status()
            except Exception as e:
                entry["error"] = f"{type(e).__name__}: {e}"
            replicas[rid] = entry
        return {"fleet": fleet, "replicas": replicas}


class ServersFleetSource(ReplicaFleetSource):
    """Same surface over a plain list of servers (tests, ad-hoc CLI)."""

    def __init__(self, servers):
        self.servers = list(servers)

    def status(self):
        return {"replicas": len(self.servers)}

    def replicas(self):
        return self.servers

    @property
    def manager(self):
        return self

    @manager.setter
    def manager(self, _):
        pass


class FleetSidecar:
    """Fleet-level ``/metrics`` + ``/statusz`` on the launcher/manager.

    ``/metrics`` merges the parent run's registry (which already carries
    the per-replica heartbeat gauges) with each reachable child
    sidecar's scrape, every child sample tagged ``replica="<id>"``.
    ``/statusz`` is the per-replica status map with unreachable/dead
    replicas MARKED (``reachable: false``) instead of failing the whole
    payload — the contract ``report --live --fleet`` renders a partial
    fleet view from.  Construct only through ``attach_fleet_sidecar``
    (DPG002 fence)."""

    def __init__(self, source, run, host: str = "127.0.0.1",
                 port: int = 0, scrape_timeout_s: float = 2.0):
        from ..serve.statusz import MetricsSidecar  # route table reuse
        from ..obs.events import _jsonable
        from .exporters import merge_prometheus_texts, to_prometheus_text
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.source = source
        self.run = run
        self.scrape_timeout_s = float(scrape_timeout_s)
        sidecar = self
        del MetricsSidecar  # shape reference only; routes differ

        def metrics_body():
            snap = sidecar.source.snapshot()
            parts = {"": to_prometheus_text(sidecar.run.registry)}
            for rid, entry in snap.get("replicas", {}).items():
                url = entry.get("metrics_url")
                if not url:
                    continue
                try:
                    parts[rid] = _scrape(url, sidecar.scrape_timeout_s)
                except Exception:
                    # A replica dying mid-scrape must not fail the
                    # aggregate; its absence IS the signal (statusz
                    # marks it unreachable).
                    continue
            return merge_prometheus_texts(parts)

        def statusz_body():
            snap = sidecar.source.snapshot()
            replicas = {}
            for rid, entry in snap.get("replicas", {}).items():
                st = entry.get("status")
                reachable = bool(st) and not st.get("closed", False) \
                    and st.get("child_alive", True) is not False
                replicas[rid] = {"reachable": reachable, "status": st,
                                 **({"error": entry["error"]}
                                    if entry.get("error") else {})}
            return {"fleet": snap.get("fleet", {}),
                    "replicas": replicas,
                    "run": sidecar.run.run_id}

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                from ..serve.statusz import PROMETHEUS_CONTENT_TYPE

                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = metrics_body().encode("utf-8")
                        ctype, code = PROMETHEUS_CONTENT_TYPE, 200
                    elif path in ("/statusz", "/healthz"):
                        body = json.dumps(
                            _jsonable(statusz_body())).encode("utf-8")
                        ctype, code = "application/json", 200
                    else:
                        body = json.dumps(
                            {"error": f"unknown path {path!r}",
                             "paths": ["/metrics", "/statusz",
                                       "/healthz"]}).encode("utf-8")
                        ctype, code = "application/json", 404
                except Exception as e:  # never take the scrape loop down
                    body = json.dumps({"error": repr(e)}).encode("utf-8")
                    ctype, code = "application/json", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        try:
            self._httpd.daemon_threads = True
            self.host, self.port = self._httpd.server_address[:2]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="dpgo-fleet-metrics")
            self._thread.start()
        except BaseException:
            # Never strand the bound socket on a failed start
            # (leakcheck-enforced contract, same as MetricsSidecar).
            self._httpd.server_close()
            raise

    def close(self) -> None:
        try:
            self._httpd.shutdown()
        finally:
            self._httpd.server_close()
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "FleetSidecar":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def attach_fleet_sidecar(source, host: str = "127.0.0.1", port: int = 0,
                         run=None, **kw) -> FleetSidecar | None:
    """The fleet sidecar's telemetry fence: None (no HTTP thread, no
    socket) without a live run."""
    run = run if run is not None else get_run()
    if run is None:
        return None
    return FleetSidecar(source, run, host=host, port=port, **kw)
