"""In-band numerical-health anomaly detection.

The PR-1 metrics and PR-4 tracing *time* the solver; this module *judges*
it.  A ``HealthMonitor`` consumes the scalars the driver already reads back
per eval (``run_rbcd``'s stacked readback — zero extra device transfers)
and the per-robot signals of the deployment plane, and turns numerical
failure modes into structured ``anomaly`` events:

* ``non_finite`` — NaN/Inf sentinel on cost / gradient norm / per-agent
  relative change (the silent-divergence case: a NaN'd run otherwise looks
  identical to a healthy one until the final cost).
* ``cost_spike`` — non-monotone centralized cost beyond a per-GNC-stage
  tolerance.  GNC mu updates legitimately jump the cost (the objective
  being minimized changes), so the monotonicity baseline resets on every
  stage transition (``robust.gnc_stage_index``) instead of flagging the
  anneal schedule itself.
* ``grad_explosion`` — gradient norm blowing past the stage's running
  minimum by a large factor (trust-region rejection storms, bad
  preconditioner shifts).
* ``stall`` — no relative cost improvement over a window of evals while
  the solve keeps burning rounds (plateau detection; fired once per GNC
  stage).
* ``inlier_collapse`` — GNC inlier fraction dropping below an absolute
  floor or falling hard from its running maximum (the correlated-
  corruption breakdown mode of docs/NEXT.md item 4).
* ``cert_refuse_loop`` — consecutive undecidable certification verdicts
  (``certify_solution`` / ``certify_sharded`` REFUSE streaks).

Every anomaly emits one ``anomaly`` event (kind, severity, iteration,
GNC stage, numeric context), increments the ``anomalies_total`` counter,
invokes registered callbacks, optionally triggers a flight-recorder dump
(``obs.recorder``, when one is attached to the run), and — per the
configured abort policy — raises ``SolverHealthError`` so a doomed run
stops burning device hours.

Zero-overhead fence: a monitor only exists attached to a live
``TelemetryRun`` (``monitor_for`` returns None with telemetry off), so
``tests/test_obs.py``'s telemetry-off test patches
``HealthMonitor.__init__`` to throw and proves no detector is ever
constructed on the off path.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque

from .run import get_run

__all__ = [
    "HealthConfig",
    "HealthMonitor",
    "SolverHealthError",
    "monitor_for",
    "SEVERITIES",
]

#: Severity order, mild to fatal.
SEVERITIES = ("warning", "critical")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


class SolverHealthError(RuntimeError):
    """Raised by the abort policy: the run is numerically doomed.

    ``anomalies`` holds the anomaly record(s) that tripped the policy —
    the same dicts emitted as ``anomaly`` events."""

    def __init__(self, anomalies: list[dict]):
        self.anomalies = list(anomalies)
        kinds = ", ".join(a["kind"] for a in self.anomalies)
        super().__init__(f"solver health abort: {kinds}")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Detector thresholds and policies.

    Defaults are deliberately loose — the detectors must stay silent on
    every healthy run in the test suite and flag only genuinely broken
    numerics; tighten per-run for gating."""

    # Non-monotone cost tolerance within one GNC stage: flag when the cost
    # exceeds the stage's best by more than rtol (relative) + atol.
    cost_spike_rtol: float = 0.5
    cost_spike_atol: float = 1e-9
    # Gradient norm explosion: flag when gn > factor * max(stage min, floor).
    grad_explosion_factor: float = 1e4
    grad_floor: float = 1e-9
    # Stall: over `stall_window` consecutive evals the cost improved by
    # less than stall_rtol (relative) — fired once per GNC stage, and only
    # after the window fills.  <= 1 disables.
    stall_window: int = 12
    stall_rtol: float = 1e-5
    # GNC inlier-fraction collapse: below the absolute floor, or a drop of
    # more than `inlier_collapse_drop` from the running maximum.
    inlier_collapse_frac: float = 0.02
    inlier_collapse_drop: float = 0.6
    # Certification REFUSE loop: this many consecutive undecidable verdicts.
    cert_refuse_streak: int = 3
    # Abort policy: anomaly kinds (e.g. "non_finite") and/or severities
    # (e.g. "critical") that raise SolverHealthError.  Empty = never abort.
    abort_on: frozenset = frozenset()
    # Minimum severity that triggers a flight-recorder dump when a recorder
    # is attached to the run ("warning" | "critical" | "never").
    dump_on: str = "critical"


class HealthMonitor:
    """Per-run anomaly detector state.  Not thread-safe per call — the
    solver driver observes from one thread; the deployment plane's
    ``anomaly()`` reports are independent events and take no shared
    detector state."""

    def __init__(self, run, config: HealthConfig | None = None):
        self.run = run
        self.config = config or HealthConfig()
        self.anomalies: list[dict] = []
        self._callbacks: list = []
        # Per-GNC-stage baselines.
        self._stage = 0
        self._last_mu: float | None = None
        self._best_cost: float | None = None
        self._min_gn: float | None = None
        self._cost_window: deque = deque(maxlen=max(self.config.stall_window, 1))
        self._stalled_stage = False
        self._collapsed_stage = False
        self._max_inlier: float | None = None
        self._cert_refusals = 0
        self._cert_loop_flagged = False

    # -- plumbing -----------------------------------------------------------

    def on_anomaly(self, callback) -> None:
        """Register ``callback(record: dict)`` invoked on every anomaly."""
        self._callbacks.append(callback)

    def _record(self, kind: str, severity: str, iteration=None,
                **fields) -> dict:
        rec = {"kind": kind, "severity": severity, "stage": self._stage}
        if iteration is not None:
            rec["iteration"] = int(iteration)
        rec.update(fields)
        self.anomalies.append(rec)
        self.run.event("anomaly", phase="health", **rec)
        labels = {"kind": kind, "severity": severity}
        if "robot" in rec:
            labels["robot"] = rec["robot"]
        self.run.counter("anomalies_total",
                         "numerical-health anomalies detected").inc(1, **labels)
        for cb in self._callbacks:
            cb(rec)
        cfg = self.config
        if cfg.dump_on != "never" and \
                _SEV_RANK[severity] >= _SEV_RANK.get(cfg.dump_on, 99):
            rec_dump = getattr(self.run, "recorder", None)
            if rec_dump is not None:
                rec_dump.dump(f"anomaly:{kind}")
        return rec

    def _maybe_abort(self, fired: list[dict]) -> None:
        ab = self.config.abort_on
        if not ab:
            return
        trip = [a for a in fired if a["kind"] in ab or a["severity"] in ab]
        if trip:
            raise SolverHealthError(trip)

    # -- the solver path (run_rbcd eval scalars) ----------------------------

    def _new_stage(self) -> None:
        self._stage += 1
        self._best_cost = None
        self._min_gn = None
        self._cost_window.clear()
        self._stalled_stage = False
        self._collapsed_stage = False

    def observe_solver(self, iteration: int, cost: float, grad_norm: float,
                       mu: float | None = None,
                       inlier_frac: float | None = None,
                       rel_change=None, stage: int | None = None) -> list[dict]:
        """Judge one eval's scalars; returns the anomalies fired (possibly
        raising per the abort policy).  ``rel_change`` may be a per-agent
        array (already host-side — the caller's readback materialized it).
        ``stage`` overrides the mu-transition stage counter when the caller
        knows the GNC stage index (``robust.gnc_stage_index``)."""
        cfg = self.config
        fired: list[dict] = []
        if mu is not None:
            if self._last_mu is not None and mu != self._last_mu:
                self._new_stage()
            self._last_mu = float(mu)
        if stage is not None:
            if stage != self._stage:
                self._new_stage()
            self._stage = int(stage)

        bad = []
        if not math.isfinite(cost):
            bad.append(("cost", cost))
        if not math.isfinite(grad_norm):
            bad.append(("grad_norm", grad_norm))
        rel_bad = []
        if rel_change is not None:
            for a, v in enumerate(rel_change):
                if not math.isfinite(float(v)):
                    rel_bad.append(a)
        if bad or rel_bad:
            rec = self._record(
                "non_finite", "critical", iteration,
                signals=[k for k, _ in bad],
                agents=rel_bad or None,
                cost=cost, grad_norm=grad_norm)
            fired.append(rec)
            self._maybe_abort(fired)
            return fired

        # Cost monotonicity within the stage.
        if self._best_cost is not None and \
                cost > self._best_cost * (1.0 + cfg.cost_spike_rtol) \
                + cfg.cost_spike_atol:
            fired.append(self._record(
                "cost_spike", "warning", iteration, cost=cost,
                stage_best=self._best_cost,
                ratio=cost / self._best_cost if self._best_cost else None))
        self._best_cost = cost if self._best_cost is None \
            else min(self._best_cost, cost)

        # Gradient-norm explosion vs the stage's running minimum.
        if self._min_gn is not None:
            ref = max(self._min_gn, cfg.grad_floor)
            if grad_norm > cfg.grad_explosion_factor * ref:
                fired.append(self._record(
                    "grad_explosion", "critical", iteration,
                    grad_norm=grad_norm, stage_min=self._min_gn,
                    factor=grad_norm / ref))
        self._min_gn = grad_norm if self._min_gn is None \
            else min(self._min_gn, grad_norm)

        # Stall / plateau.
        if cfg.stall_window > 1:
            self._cost_window.append(cost)
            if (len(self._cost_window) == cfg.stall_window
                    and not self._stalled_stage):
                first, last = self._cost_window[0], self._cost_window[-1]
                if first - last <= cfg.stall_rtol * abs(first):
                    self._stalled_stage = True
                    fired.append(self._record(
                        "stall", "warning", iteration, cost=cost,
                        window=cfg.stall_window,
                        improvement=first - last))

        # GNC inlier-fraction collapse.
        if inlier_frac is not None:
            f = float(inlier_frac)
            if (self._max_inlier is not None and not self._collapsed_stage
                    and (f < cfg.inlier_collapse_frac
                         or f < self._max_inlier - cfg.inlier_collapse_drop)):
                self._collapsed_stage = True
                fired.append(self._record(
                    "inlier_collapse", "critical", iteration,
                    inlier_fraction=f, running_max=self._max_inlier))
            self._max_inlier = f if self._max_inlier is None \
                else max(self._max_inlier, f)

        self._maybe_abort(fired)
        return fired

    # -- certification verdict timeline -------------------------------------

    def observe_certificate(self, certified: bool, decidable: bool,
                            lambda_min: float | None = None,
                            **fields) -> list[dict]:
        """Track the certification outcome stream; flags a REFUSE loop
        (consecutive undecidable verdicts) once per streak."""
        fired: list[dict] = []
        if decidable:
            self._cert_refusals = 0
            self._cert_loop_flagged = False
        else:
            self._cert_refusals += 1
            if (self._cert_refusals >= self.config.cert_refuse_streak
                    and not self._cert_loop_flagged):
                self._cert_loop_flagged = True
                fired.append(self._record(
                    "cert_refuse_loop", "warning",
                    refusals=self._cert_refusals,
                    lambda_min=lambda_min, **fields))
        self._maybe_abort(fired)
        return fired

    # -- deployment plane (per-robot ad-hoc reports) ------------------------

    def anomaly(self, kind: str, severity: str = "warning",
                iteration=None, **fields) -> dict:
        """Report one externally-detected anomaly (the per-agent NaN
        sentinels of ``agent.PGOAgent`` land here).  Applies the dump and
        abort policies like the built-in detectors."""
        rec = self._record(kind, severity, iteration, **fields)
        self._maybe_abort([rec])
        return rec


def monitor_for(run=None, config: HealthConfig | None = None) -> HealthMonitor | None:
    """The run's health monitor (created on first use), or None with
    telemetry off — the zero-overhead fence.  Pass ``config`` on the
    first call (before any instrumented solve observes) to set policy;
    a later call with a config replaces the monitor."""
    run = get_run() if run is None else run
    if run is None:
        return None
    mon = getattr(run, "_health_monitor", None)
    if mon is None or config is not None:
        mon = run._health_monitor = HealthMonitor(run, config)
    return mon
