"""Solver flight recorder: bounded in-memory black box + deterministic replay.

A ``FlightRecorder`` rides a ``TelemetryRun`` (attach with
``FlightRecorder.attach(run)``) and records, at every ``run_rbcd`` eval
boundary, the scalars the driver already read back (cost, gradient norm,
GNC mu, inlier fraction, per-agent relative change) into a bounded ring
buffer, plus a time-down-sampled **exact** solver-state snapshot every
``snapshot_every`` evals (X, GNC weights, RNG keys, Nesterov aux state,
mu — everything ``RBCDState`` carries except the recomputable
preconditioner factors).  On an anomaly (``obs.health`` dump policy) or a
crash (``run_rbcd``'s driver loop) the recorder dumps:

* ``blackbox.npz`` — the replayable payload: ring columns, the retained
  snapshots, and (when the solve registered its problem) the full global
  measurement set, so the black box is self-contained;
* ``blackbox.jsonl`` — one context line (config fingerprint, encoded
  ``AgentParams``, RNG/seed bookkeeping, dump reason, snapshot index)
  followed by one line per retained ring record — greppable without numpy.

``python -m dpgo_tpu.obs.recorder --replay <blackbox.npz>`` rebuilds the
problem from the stored measurements, resumes from the last *healthy*
snapshot, re-runs the exact same fused schedule segments
(``models.rbcd.schedule_bounds`` + ``rbcd_segment`` — the same jitted
programs the original driver dispatched), re-applies any recorded fault
injection (``inject_nan``), and checks the recomputed eval trajectory
against the recorded one bit-for-bit (NaNs compare positionally).  On the
deterministic CPU backend this reproduces the failure exactly; exit code
0 = reproduced, 1 = diverged, 2 = not replayable.

Zero-overhead fence: a recorder only ever exists attached to a live run
(telemetry off ⇒ ``run_rbcd`` never resolves one), and every device value
it persists goes through ``obs.materialize`` — the telemetry-off test
patches both ``FlightRecorder.__init__`` and ``materialize`` to throw.
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import math
import os
import sys
import time
from collections import deque

import numpy as np

from .events import _jsonable, restore_nonfinite
from .run import get_run, materialize

BLACKBOX_NPZ = "blackbox.npz"
BLACKBOX_JSONL = "blackbox.jsonl"

#: Measurement array fields persisted into / restored from the npz.
_MEAS_FIELDS = ("r1", "p1", "r2", "p2", "R", "t", "kappa", "tau",
                "weight", "is_known_inlier")
#: RBCDState array fields captured per snapshot (None-able ones optional).
_STATE_FIELDS = ("X", "weights", "key", "rel_change", "ready",
                 "gamma", "alpha", "mu")
_STATE_OPTIONAL = ("V", "X_init")


# ---------------------------------------------------------------------------
# Config (AgentParams) <-> JSON: generic frozen-dataclass / enum codec
# ---------------------------------------------------------------------------

def encode_config(obj):
    """JSON-encode a config object (nested frozen dataclasses + enums +
    scalars) so the black box can rebuild the exact ``AgentParams``."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {"__dataclass__": type(obj).__name__,
                "fields": {f.name: encode_config(getattr(obj, f.name))
                           for f in dataclasses.fields(obj)}}
    if isinstance(obj, enum.Enum):
        return {"__enum__": type(obj).__name__, "name": obj.name}
    if isinstance(obj, tuple):
        return {"__tuple__": [encode_config(x) for x in obj]}
    if isinstance(obj, list):
        return [encode_config(x) for x in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    raise TypeError(f"cannot encode config value of type {type(obj).__name__}")


def decode_config(data):
    """Inverse of ``encode_config``; resolves types from ``dpgo_tpu.config``."""
    from .. import config as config_mod

    if isinstance(data, dict) and "__dataclass__" in data:
        cls = getattr(config_mod, data["__dataclass__"])
        return cls(**{k: decode_config(v)
                      for k, v in data["fields"].items()})
    if isinstance(data, dict) and "__enum__" in data:
        return getattr(config_mod, data["__enum__"])[data["name"]]
    if isinstance(data, dict) and "__tuple__" in data:
        return tuple(decode_config(x) for x in data["__tuple__"])
    if isinstance(data, list):
        return [decode_config(x) for x in data]
    return data


def inject_nan(state, agent: int, pose: int):
    """The canonical NaN fault: corrupt one agent's pose block (the frame
    its neighbors consume on the next exchange).  Shared by the seeded
    fault-injection tests and ``replay`` so a recorded fault re-applies
    identically."""
    import jax.numpy as jnp

    return state._replace(
        X=state.X.at[int(agent), int(pose)].set(jnp.nan))


# ---------------------------------------------------------------------------
# The recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded black box for one telemetry run (attach before solving)."""

    def __init__(self, run, capacity: int = 512, snapshot_every: int = 4,
                 max_snapshots: int = 4):
        self.run = run
        self.capacity = int(capacity)
        self.snapshot_every = max(int(snapshot_every), 1)
        self.ring: deque = deque(maxlen=self.capacity)
        self.snapshots: deque = deque(maxlen=max(int(max_snapshots), 1))
        self.context: dict = {}
        self._evals_since_snap: int | None = None  # None = no snapshot yet
        self._problem: dict | None = None
        self._dumped: str | None = None

    @classmethod
    def attach(cls, run=None, **kwargs) -> "FlightRecorder | None":
        """Create a recorder and install it as ``run.recorder`` (the handle
        ``run_rbcd`` and the health dump policy resolve).  Returns None with
        telemetry off."""
        run = get_run() if run is None else run
        if run is None:
            return None
        rec = cls(run, **kwargs)
        run.recorder = rec
        return rec

    # -- context / problem registration -------------------------------------

    def set_context(self, **fields) -> None:
        """Merge free-form context (fault specs, dataset names, seeds) into
        the black box's context line."""
        self.context.update({k: _jsonable(v) for k, v in fields.items()})

    def set_problem(self, part, meta, params, dtype, eval_every: int,
                    grad_norm_tol: float, max_iters: int) -> None:
        """Register the solve's problem so the dump is self-contained and
        replayable.  Called by ``run_rbcd`` when a recorder is attached;
        requires explicit ``params`` (a param-less solve is recorded but
        not replayable)."""
        meas = part.meas_global
        arrays = {f"meas_{f}": np.asarray(getattr(meas, f))
                  for f in _MEAS_FIELDS}
        arrays["part_n"] = np.asarray(part.n)
        self._problem = {
            "arrays": arrays,
            "meta": {
                "d": int(meas.d), "num_poses": int(meas.num_poses),
                "num_robots": int(part.num_robots),
                "dtype": str(np.dtype(dtype)),
                "eval_every": int(eval_every),
                "grad_norm_tol": float(grad_norm_tol),
                "max_iters": int(max_iters),
                "params": encode_config(params) if params is not None else None,
                "replayable": params is not None,
            },
        }

    # -- recording -----------------------------------------------------------

    def record_eval(self, iteration: int, scalars: dict, state=None,
                    num_weight_updates: int = 0) -> None:
        """Append one eval-boundary record; snapshot the state on cadence.
        ``scalars`` values must already be host-side (the driver's existing
        readback) — only the optional state snapshot touches the device,
        through the ``materialize`` fence."""
        healthy = True
        rec = {"iteration": int(iteration)}
        for k, v in scalars.items():
            a = np.asarray(v)
            rec[k] = a if a.ndim else (float(a) if a.dtype.kind == "f"
                                       else a.item())
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                healthy = False
        rec["healthy"] = healthy
        self.ring.append(rec)
        if state is None:
            return
        if self._evals_since_snap is None \
                or self._evals_since_snap + 1 >= self.snapshot_every:
            self._snapshot(iteration, state, num_weight_updates, healthy)
            self._evals_since_snap = 0
        else:
            self._evals_since_snap += 1

    def snapshot_state(self, iteration: int, state, num_weight_updates: int,
                       healthy: bool = True) -> None:
        """Take one exact-state snapshot outside ``record_eval``'s
        cadence — the verdict-loop driver (``models.rbcd``'s
        ``verdict_every`` mode) snapshots at its K-round fetch boundaries,
        where the live state is on hand, while the per-eval scalar rows
        arrive separately through ``record_eval(state=None)`` from the
        lazily-fetched device history.  ``iteration`` must be an eval
        boundary present in the ring for the replay to align."""
        self._snapshot(iteration, state, num_weight_updates, bool(healthy))
        self._evals_since_snap = 0

    def _snapshot(self, iteration: int, state, num_weight_updates: int,
                  healthy: bool) -> None:
        arrays = {}
        for f in _STATE_FIELDS + _STATE_OPTIONAL:
            v = getattr(state, f)
            if v is None:
                continue
            arrays[f] = materialize(v)
        self.snapshots.append({
            "iteration": int(iteration),
            "num_weight_updates": int(num_weight_updates),
            "healthy": bool(healthy),
            "arrays": arrays,
        })

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str, force: bool = False) -> str | None:
        """Write ``blackbox.npz`` + ``blackbox.jsonl`` under the run dir.
        First dump wins (an anomaly dump is not overwritten by the
        subsequent crash dump) unless ``force``."""
        if self._dumped is not None and not force:
            return os.path.join(self.run.run_dir, BLACKBOX_NPZ)
        arrays: dict = {}
        ring = list(self.ring)
        if ring:
            keys = sorted({k for r in ring for k in r} - {"healthy"})
            for k in keys:
                col = [r.get(k, np.nan) for r in ring]
                try:
                    arrays[f"ring_{k}"] = np.asarray(col)
                except ValueError:  # ragged (shape changed mid-run): skip
                    pass
            arrays["ring_healthy"] = np.asarray(
                [r["healthy"] for r in ring], bool)
        snap_meta = []
        for i, snap in enumerate(self.snapshots):
            snap_meta.append({k: snap[k] for k in
                              ("iteration", "num_weight_updates", "healthy")})
            for f, v in snap["arrays"].items():
                arrays[f"snap{i}_{f}"] = v
        problem_meta = None
        if self._problem is not None:
            arrays.update(self._problem["arrays"])
            problem_meta = self._problem["meta"]
        context = dict(self.context)
        context.update({
            "kind": "context",
            "run": self.run.run_id,
            "reason": str(reason),
            "t_wall": time.time(),
            "fingerprint": getattr(self.run, "fingerprint", {}),
            "snapshots": snap_meta,
            "problem": problem_meta,
            "replayable": bool(problem_meta and problem_meta["replayable"]),
        })
        npz_path = os.path.join(self.run.run_dir, BLACKBOX_NPZ)
        jsonl_path = os.path.join(self.run.run_dir, BLACKBOX_JSONL)
        with open(npz_path, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        with open(jsonl_path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(_jsonable(context)) + "\n")
            for r in ring:
                fh.write(json.dumps(_jsonable(
                    dict(r, kind="round"))) + "\n")
        self._dumped = str(reason)
        self.run.event("blackbox_dump", phase="health", reason=str(reason),
                       path=npz_path,
                       rounds_recorded=len(ring),
                       snapshots=len(snap_meta))
        return npz_path


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReplayResult:
    snapshot_iteration: int
    iterations: list
    cost: list
    grad_norm: list
    recorded_cost: list
    recorded_grad_norm: list
    match: bool
    mismatches: list


def load_blackbox(npz_path: str) -> tuple[dict, dict]:
    """``(context, arrays)`` for a dumped black box.  The context comes
    from the sibling ``blackbox.jsonl`` (non-finite strings restored to
    floats)."""
    arrays = dict(np.load(npz_path, allow_pickle=False))
    jsonl = os.path.join(os.path.dirname(os.path.abspath(npz_path)),
                         BLACKBOX_JSONL)
    context = {}
    if os.path.exists(jsonl):
        with open(jsonl, encoding="utf-8") as fh:
            first = fh.readline().strip()
        if first:
            context = restore_nonfinite(json.loads(first))
    return context, arrays


def _bits_equal(a: float, b: float) -> bool:
    return (a == b) or (math.isnan(a) and math.isnan(b))


def replay(npz_path: str, snapshot: int | None = None,
           log=None) -> ReplayResult:
    """Resume from the black box's last healthy snapshot and recompute the
    recorded eval trajectory with the original jitted schedule segments.

    Raises ``ValueError`` when the black box is not replayable (no problem
    registered / custom partition / missing snapshot)."""
    import jax
    import jax.numpy as jnp

    context, arrays = load_blackbox(npz_path)
    if not context.get("replayable"):
        raise ValueError(
            f"{npz_path} is not replayable: the recorded solve did not "
            "register its problem (run with an attached FlightRecorder and "
            "explicit AgentParams)")
    prob = context["problem"]
    dtype = np.dtype(prob["dtype"])
    if dtype == np.float64 and not jax.config.read("jax_enable_x64"):
        jax.config.update("jax_enable_x64", True)

    from ..models import rbcd
    from ..models.rbcd import RBCDState, build_graph, refresh_problem
    from ..types import Measurements, edge_set_from_measurements
    from ..utils.partition import partition_contiguous

    params = decode_config(prob["params"])
    meas = Measurements(
        d=prob["d"], num_poses=prob["num_poses"],
        **{f: arrays[f"meas_{f}"] for f in _MEAS_FIELDS})
    part = partition_contiguous(meas, prob["num_robots"])
    if not np.array_equal(np.asarray(part.n), arrays["part_n"]):
        raise ValueError(
            "recorded partition does not match partition_contiguous — "
            "custom partitions are not replayable")
    graph, meta = build_graph(part, params.r, jnp.dtype(dtype),
                              sel_mode=rbcd.resolved_sel_mode(params))

    snaps = context.get("snapshots") or []
    if not snaps:
        raise ValueError("black box holds no state snapshot")
    ring_it_all = arrays.get("ring_iteration")
    last_eval = int(np.asarray(ring_it_all).max()) \
        if ring_it_all is not None and np.asarray(ring_it_all).size else -1
    if snapshot is None:
        # Last GOOD snapshot that still has recorded evals after it — the
        # one the failure replays from.
        healthy = [i for i, s in enumerate(snaps)
                   if s["healthy"] and s["iteration"] < last_eval]
        snapshot = healthy[-1] if healthy else 0
    snap_meta = snaps[snapshot]
    sd = {f: arrays[f"snap{snapshot}_{f}"]
          for f in _STATE_FIELDS + _STATE_OPTIONAL
          if f"snap{snapshot}_{f}" in arrays}
    it0 = int(snap_meta["iteration"])
    nwu = int(snap_meta["num_weight_updates"])
    state = RBCDState(
        X=jnp.asarray(sd["X"]), weights=jnp.asarray(sd["weights"]),
        iteration=jnp.asarray(it0, jnp.int32),
        key=jnp.asarray(sd["key"]),
        rel_change=jnp.asarray(sd["rel_change"]),
        ready=jnp.asarray(sd["ready"]),
        V=jnp.asarray(sd["V"]) if "V" in sd else None,
        gamma=jnp.asarray(sd["gamma"]), alpha=jnp.asarray(sd["alpha"]),
        mu=jnp.asarray(sd["mu"]),
        X_init=jnp.asarray(sd["X_init"]) if "X_init" in sd else None,
        chol=None, Qbuf=None)
    # Factors recompute exactly: the carried Cholesky is always the factor
    # of the live weights at the last refresh, which are the snapshot's
    # weights (see models.rbcd._rbcd_round's refresh schedule).
    state = refresh_problem(state, graph, meta, params)

    n_total = part.meas_global.num_poses
    num_meas = len(part.meas_global)
    edges_g = edge_set_from_measurements(part.meas_global,
                                         dtype=jnp.dtype(dtype))
    central = rbcd._make_central_metrics(graph, edges_g, n_total, num_meas,
                                         telemetry=True)

    from ..config import RobustCostType

    robust_on = params.robust.cost_type != RobustCostType.L2
    fault = context.get("fault")
    fault_applied = False
    targets_i, rec_cost, rec_gn = [], [], []
    ring_it = arrays.get("ring_iteration")
    if ring_it is not None:
        for j, ri in enumerate(np.asarray(ring_it).tolist()):
            if ri > it0:
                targets_i.append(int(ri))
                rec_cost.append(float(arrays["ring_cost"][j]))
                rec_gn.append(float(arrays["ring_grad_norm"][j]))
    if not targets_i:
        raise ValueError(
            f"no recorded evals after snapshot iteration {it0} to replay")

    it = it0
    out_cost, out_gn, mismatches = [], [], []
    for target, rc, rg in zip(targets_i, rec_cost, rec_gn):
        while it < target:
            uw, rs, end = rbcd.schedule_bounds(
                it, nwu, max_iters=prob["max_iters"],
                eval_every=prob["eval_every"], params=params,
                robust_on=robust_on, accel_on=params.acceleration)
            nwu += int(uw)
            state = rbcd.rbcd_segment(state, graph, end - it, meta, params,
                                      first_update_weights=uw,
                                      first_restart=rs)
            it = end
            if fault is not None and not fault_applied \
                    and it >= int(fault["iteration"]):
                state = inject_nan(state, fault["agent"], fault["pose"])
                fault_applied = True
        vec = np.asarray(central(state.X, state.weights, state.ready,
                                 state.mu, state.rel_change))
        f, gn = float(vec[0]), float(vec[1])
        out_cost.append(f)
        out_gn.append(gn)
        if not (_bits_equal(f, rc) and _bits_equal(gn, rg)):
            mismatches.append({"iteration": it, "cost": f,
                               "recorded_cost": rc, "grad_norm": gn,
                               "recorded_grad_norm": rg})
        if log is not None:
            log(f"  iter {it}: cost {f!r} (recorded {rc!r}) "
                f"gn {gn!r} (recorded {rg!r})")
    return ReplayResult(
        snapshot_iteration=it0, iterations=targets_i,
        cost=out_cost, grad_norm=out_gn,
        recorded_cost=rec_cost, recorded_grad_norm=rec_gn,
        match=not mismatches, mismatches=mismatches)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpgo_tpu.obs.recorder",
        description="Replay a solver black box (blackbox.npz) from its "
                    "last healthy snapshot and verify the recorded "
                    "trajectory reproduces bit-for-bit.")
    ap.add_argument("--replay", metavar="BLACKBOX_NPZ", required=True,
                    help="path to a dumped blackbox.npz (blackbox.jsonl "
                         "must sit beside it)")
    ap.add_argument("--snapshot", type=int, default=None,
                    help="snapshot index to resume from (default: last "
                         "healthy)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result")
    args = ap.parse_args(argv)
    try:
        res = replay(args.replay, snapshot=args.snapshot,
                     log=None if args.json else
                     (lambda m: print(m, file=sys.stderr)))
    except (ValueError, OSError, KeyError) as e:
        print(f"replay failed: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(_jsonable(dataclasses.asdict(res))))
    else:
        verdict = "REPRODUCED bit-for-bit" if res.match else "DIVERGED"
        print(f"replay of {len(res.iterations)} evals from snapshot at "
              f"iteration {res.snapshot_iteration}: {verdict}")
        for m in res.mismatches[:5]:
            print(f"  iter {m['iteration']}: cost {m['cost']!r} != "
                  f"recorded {m['recorded_cost']!r}")
    return 0 if res.match else 1


if __name__ == "__main__":
    sys.exit(main())
