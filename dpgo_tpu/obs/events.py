"""Structured JSONL event stream.

One event per line.  Every line carries the correlation fields up front —
``run`` (run id), ``seq`` (per-stream sequence number), ``t_wall`` (Unix
epoch seconds), ``t_mono`` (monotonic seconds, for intra-run latency math
immune to clock steps), ``event`` (kind), and ``phase`` (solver phase the
event belongs to: ``exchange`` / ``solve`` / ``eval`` / ``certify`` / ...)
— followed by the event's own payload fields.

``metric_record`` is the shared scalar-metric schema: the same
``metric`` / ``value`` / ``unit`` leading keys as the repo's
``BENCH_r0*.json`` records, so ``bench.py``'s final line and in-stream
``metric`` events parse with one reader.
"""

from __future__ import annotations

import json
import math
import threading
import time
import warnings


#: The one non-finite float convention of the whole obs stack: JSON has no
#: literal for them, so they serialize as the Prometheus text-exposition
#: strings and ``read_events`` restores them to floats on load — the
#: snapshot (``metrics.py``), the exporters, and the event stream all
#: round-trip through this single table.
NONFINITE_STR = {"NaN": float("nan"), "+Inf": float("inf"),
                 "-Inf": float("-inf")}
#: Legacy spellings from pre-unification streams, restored on read only.
_NONFINITE_LEGACY = {"nan": float("nan"), "inf": float("inf"),
                     "-inf": float("-inf")}


def nonfinite_str(v: float) -> str:
    """Canonical string for a non-finite float (Prometheus convention)."""
    if math.isnan(v):
        return "NaN"
    return "+Inf" if v > 0 else "-Inf"


def restore_nonfinite(v):
    """Inverse of the serialization convention: recursively convert the
    canonical (and legacy) non-finite strings back to floats.  Applied by
    ``read_events`` so a round-tripped stream yields real float NaN/Inf —
    string payloads that happen to spell exactly ``"NaN"``/``"+Inf"``/
    ``"-Inf"`` are, by convention, numbers."""
    if isinstance(v, str):
        if v in NONFINITE_STR:
            return NONFINITE_STR[v]
        if v in _NONFINITE_LEGACY:
            return _NONFINITE_LEGACY[v]
        return v
    if isinstance(v, dict):
        return {k: restore_nonfinite(x) for k, x in v.items()}
    if isinstance(v, list):
        return [restore_nonfinite(x) for x in v]
    return v


def _jsonable(v):
    """Coerce payload values to JSON-safe types (numpy scalars/arrays from
    phase-boundary readbacks arrive here routinely; non-finite floats have
    no JSON literal, so they become the canonical strings rather than
    invalid output)."""
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else nonfinite_str(v)
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return _jsonable(v.item())
    if hasattr(v, "tolist"):
        return _jsonable(v.tolist())
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


def metric_record(metric: str, value, unit: str | None = None,
                  **extra) -> dict:
    """The canonical scalar-metric record: ``metric``/``value``/``unit``
    first (the ``BENCH_r0*.json`` key set), extras after."""
    rec = {"metric": str(metric), "value": _jsonable(value)}
    if unit is not None:
        rec["unit"] = str(unit)
    for k, v in extra.items():
        rec[k] = _jsonable(v)
    return rec


class EventStream:
    """Append-only JSONL writer for one run.

    Thread-safe: one lock serializes sequence assignment and the write, so
    lines from the agent's optimization thread and a transport thread
    interleave whole, never torn.  Lines are flushed per event — an event
    stream that loses its tail on a crash is the one that mattered.
    """

    def __init__(self, path: str, run_id: str):
        self.path = path
        self.run_id = run_id
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = open(path, "a", encoding="utf-8")
        self._closed = False

    def emit(self, event: str, phase: str | None = None, **fields) -> dict:
        rec = {"run": self.run_id, "seq": 0,
               "t_wall": time.time(), "t_mono": time.monotonic(),
               "event": str(event)}
        if phase is not None:
            rec["phase"] = str(phase)
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        line = None
        with self._lock:
            if self._closed:
                return rec
            rec["seq"] = self._seq
            self._seq += 1
            line = json.dumps(rec)
            self._fh.write(line + "\n")
            self._fh.flush()
        return rec

    def metric(self, metric: str, value, unit: str | None = None,
               phase: str | None = None, **extra) -> dict:
        """Emit one scalar-metric event in the shared schema."""
        return self.emit("metric", phase=phase,
                         **metric_record(metric, value, unit, **extra))

    @property
    def num_emitted(self) -> int:
        with self._lock:
            return self._seq

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._fh.close()


def read_events(path: str) -> list[dict]:
    """Load a JSONL event file; skips blank lines.

    A corrupt line in the MIDDLE of the file raises ``ValueError`` (the
    stream is damaged, not merely cut short).  An unparseable FINAL line
    is tolerated with a ``RuntimeWarning`` — a robot killed mid-write
    (exactly the ``tests/test_chaos.py`` scenarios) truncates its last
    line, and the events before it are intact and wanted.  Use
    ``read_events_meta`` to get the truncation flag programmatically.

    Non-finite floats round-trip: values the writer serialized as the
    canonical ``"NaN"``/``"+Inf"``/``"-Inf"`` strings (``_jsonable``) come
    back as real floats (``restore_nonfinite``)."""
    events, _truncated = read_events_meta(path)
    return events


def read_events_meta(path: str) -> tuple[list[dict], bool]:
    """``(events, truncated)``: like ``read_events`` but returns whether
    the file ended in a truncated (unparseable) final line."""
    out = []
    with open(path, encoding="utf-8") as fh:
        lines = fh.readlines()
    last = max((i for i, ln in enumerate(lines) if ln.strip()), default=-1)
    for ln, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(restore_nonfinite(json.loads(line)))
        except json.JSONDecodeError as e:
            if ln == last:
                warnings.warn(
                    f"{path}:{ln + 1}: truncated final event line "
                    "(writer killed mid-write?) — dropped",
                    RuntimeWarning, stacklevel=2)
                return out, True
            raise ValueError(f"{path}:{ln + 1}: corrupt event line") from e
    return out, False
