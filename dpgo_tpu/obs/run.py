"""Run scoping: one ``TelemetryRun`` = one registry + one event stream
bound to a run directory, installable as the process-ambient run.

Instrumented hot paths resolve the ambient run with ``get_run()`` and take
a no-telemetry early exit when it is ``None`` — that early exit IS the
zero-overhead path the acceptance criteria require: no events, no registry
calls, and no added device->host transfers, because every device readback
the instrumentation performs goes through ``materialize`` below, which is
only reachable behind the ``get_run() is not None`` guard
(``tests/test_obs.py`` patches ``materialize`` to count and asserts zero
with telemetry off).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid

import numpy as np

from .events import EventStream
from .exporters import to_prometheus_text
from .metrics import MetricsRegistry

EVENTS_FILE = "events.jsonl"
METRICS_FILE = "metrics.json"
PROMETHEUS_FILE = "metrics.prom"
META_FILE = "run.json"


def materialize(x) -> np.ndarray:
    """The obs-owned device->host fence: every readback the telemetry layer
    performs funnels through here, so 'telemetry off adds no transfers' is
    a testable property instead of a code-review promise.  Same fence
    semantics as ``RoundTimer.stop(sync=...)`` — on the tunneled-TPU
    platform a transfer is the only trustworthy materialization."""
    return np.asarray(x)


class TelemetryRun:
    """Metrics + events for one run, persisted under ``run_dir``.

    ``close()`` (or the ``run_scope`` context) writes the metrics snapshot
    (``metrics.json``), the Prometheus exposition (``metrics.prom``), and
    closes the event stream; the report CLI reads those artifacts.
    """

    def __init__(self, run_dir: str, run_id: str | None = None):
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.registry = MetricsRegistry()
        self.events = EventStream(
            os.path.join(self.run_dir, EVENTS_FILE), self.run_id)
        #: Config fingerprint (dataset, ranks, wire format, ... — whatever
        #: the instrumented layers register via ``set_fingerprint``); the
        #: regression gate (``obs.regress``) refuses apples-to-oranges
        #: comparisons on it.
        self.fingerprint: dict = {}
        #: Optional attached ``obs.recorder.FlightRecorder``.
        self.recorder = None
        self._closed = False
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        with open(os.path.join(self.run_dir, META_FILE), "w") as fh:
            json.dump({"run": self.run_id, "t_start_wall": self._t0_wall,
                       "t_start_mono": self._t0_mono}, fh)
        self.events.emit("run_start")

    # -- convenience forwarding --------------------------------------------

    def event(self, event: str, phase: str | None = None, **fields) -> dict:
        return self.events.emit(event, phase=phase, **fields)

    def metric(self, metric: str, value, unit: str | None = None,
               phase: str | None = None, **extra) -> dict:
        return self.events.metric(metric, value, unit, phase=phase, **extra)

    def counter(self, name, help="", unit=""):
        return self.registry.counter(name, help, unit)

    def gauge(self, name, help="", unit=""):
        return self.registry.gauge(name, help, unit)

    def histogram(self, name, help="", unit="", **kw):
        return self.registry.histogram(name, help, unit, **kw)

    def set_fingerprint(self, **fields) -> dict:
        """Merge config-identity fields (dataset, num_robots, rank,
        sel_mode, wire format, package version, ...) into the run's
        fingerprint and emit it as a ``run_summary`` event with
        ``channel="config"`` — the record ``report --compare`` keys its
        apples-to-oranges refusal on.  The merged fingerprint also lands
        in ``run.json`` at close.  Fields set to None are dropped; later
        calls override earlier keys (the most specific caller wins)."""
        from .events import _jsonable

        for k, v in fields.items():
            if v is not None:
                self.fingerprint[k] = _jsonable(v)
        self.events.emit("run_summary", phase="config", channel="config",
                         fingerprint=dict(self.fingerprint))
        return dict(self.fingerprint)

    # -- persistence --------------------------------------------------------

    def write_snapshot(self) -> str:
        path = os.path.join(self.run_dir, METRICS_FILE)
        snap = {"run": self.run_id, "t_wall": time.time(),
                "t_mono": time.monotonic(),
                "metrics": self.registry.snapshot()}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(snap, fh, indent=1)
        os.replace(tmp, path)
        prom = os.path.join(self.run_dir, PROMETHEUS_FILE)
        with open(prom + ".tmp", "w") as fh:
            fh.write(to_prometheus_text(self.registry))
        os.replace(prom + ".tmp", prom)
        return path

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.events.emit("run_end",
                         duration_s=time.monotonic() - self._t0_mono)
        self.write_snapshot()
        self.events.close()
        if self.fingerprint:
            # Persist the final fingerprint into run.json so comparisons
            # need not scan the event stream.
            meta_path = os.path.join(self.run_dir, META_FILE)
            try:
                with open(meta_path) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError):
                meta = {"run": self.run_id}
            meta["fingerprint"] = self.fingerprint
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(meta, fh)
            os.replace(tmp, meta_path)

    @property
    def closed(self) -> bool:
        return self._closed


# -- ambient run -------------------------------------------------------------

_lock = threading.Lock()
_current: TelemetryRun | None = None


def get_run() -> TelemetryRun | None:
    """The ambient run, or None (the zero-overhead telemetry-off path).

    Deliberately lock-free: a plain global read, so the hot-path guard
    ``if obs.get_run() is not None`` costs one attribute lookup.  Python's
    GIL makes the read atomic; installation/removal takes the lock."""
    return _current


def start_run(run_dir: str, run_id: str | None = None) -> TelemetryRun:
    """Create a run under ``run_dir`` and install it as the ambient run.

    Refuses to silently replace a live ambient run — two overlapping runs
    would interleave their instrumentation; scope with ``run_scope`` or
    ``end_run()`` first."""
    global _current
    run = TelemetryRun(run_dir, run_id)
    with _lock:
        if _current is not None and not _current.closed:
            run.events.close()
            raise RuntimeError(
                f"a telemetry run is already active ({_current.run_id}); "
                "end it before starting another")
        _current = run
    return run


def end_run() -> None:
    """Close and uninstall the ambient run (no-op when none is active)."""
    global _current
    with _lock:
        run, _current = _current, None
    if run is not None:
        run.close()


@contextlib.contextmanager
def run_scope(run_dir: str, run_id: str | None = None):
    """``with obs.run_scope(dir) as run: solve(...)`` — telemetry on inside,
    artifacts written and the ambient run cleared on exit (exceptions
    included)."""
    run = start_run(run_dir, run_id)
    try:
        yield run
    finally:
        global _current
        with _lock:
            if _current is run:
                _current = None
        run.close()
