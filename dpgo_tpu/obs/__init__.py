"""Run-scoped telemetry: metrics registry, JSONL event stream, exporters.

The reference's only observability is ``ROPTResult`` wall-clock bookkeeping
plus verbose printouts; both source papers evaluate convergence through
per-iteration cost/gradient trajectories and per-agent status — signals the
solvers here already compute but (before this subsystem) never collected,
correlated, or exported.  This package is the substrate every perf and
robustness change reports through:

* ``MetricsRegistry`` (``metrics.py``) — thread-safe counters / gauges /
  histograms with labels, safe to call from the agent's background
  optimization thread (``agent.start_optimization_loop``).
* ``EventStream`` (``events.py``) — structured JSONL: every line carries the
  run id, wall + monotonic timestamps, a sequence number, and the solver
  phase.  ``metric_record`` is the shared ``metric``/``value``/``unit``
  record schema (``bench.py`` emits its final line through it, so bench and
  telemetry records parse identically).
* ``TelemetryRun`` (``run.py``) — one registry + one event stream scoped to
  a run directory, installed as the process-ambient run (``start_run`` /
  ``get_run`` / ``run_scope``).  Instrumented hot paths resolve the ambient
  run and take a no-telemetry early exit when none is installed: with
  telemetry off there are zero events, zero registry calls, and — by
  construction — zero added device->host transfers (every device readback
  the instrumentation performs goes through ``materialize``, which is only
  reached behind a ``get_run() is not None`` guard; see
  ``tests/test_obs.py::test_telemetry_off_is_zero_overhead``).
* Exporters (``exporters.py``) — Prometheus text exposition, optional
  TensorBoard scalars (gated on an available writer), and the JSON metrics
  snapshot.  ``python -m dpgo_tpu.obs.report <run_dir>`` renders a
  human-readable report from the persisted artifacts (``--json`` for
  machine-readable output).
* Distributed tracing (``trace.py`` / ``timeline.py``) — lightweight
  spans emitted through the event stream behind the same telemetry-off
  fence; trace context propagates across processes as optional wire
  entries, and ``python -m dpgo_tpu.obs.timeline <run_dir>...`` merges
  per-robot streams (pairwise clock-offset estimation from the
  send/receive stamps riding heartbeats and traced frames) into a
  Perfetto-loadable Chrome trace with cross-robot flow arrows.
* Numerical health (``health.py``) — in-band anomaly detectors fed by
  the scalars the driver already reads back (NaN/Inf sentinel,
  per-GNC-stage cost monotonicity, gradient-norm explosion, stall,
  inlier-fraction collapse, certification REFUSE loops), emitting
  structured ``anomaly`` events with optional callback/abort policy.
* Flight recorder (``recorder.py``) — bounded ring buffer of recent
  eval scalars + exact state snapshots, dumped as a self-contained
  ``blackbox.npz`` + context JSONL on anomaly or crash;
  ``python -m dpgo_tpu.obs.recorder --replay`` resumes from the last
  healthy snapshot and reproduces the recorded trajectory bit-for-bit
  on the deterministic CPU backend.
* Convergence regression gate (``regress.py``) — ``report --compare
  runA runB`` checks run B's convergence against run A's tail noise
  bands, refuses apples-to-oranges comparisons on the config
  fingerprint (``TelemetryRun.set_fingerprint``), and exits non-zero on
  regression — CI's convergence analog of the perf smoke.

Instrumentation discipline on accelerator hot paths: never add a host sync
inside jitted code.  The solvers extend their *existing* phase-boundary
readbacks (the ``run_rbcd`` eval fetch, ``PGOAgent.iterate``'s host-side
state update) with telemetry scalars stacked into the same transfer, so a
telemetry-on run costs one slightly-larger readback per phase boundary and
a telemetry-off run is byte-identical to the uninstrumented driver.
"""

from __future__ import annotations

from .events import (
    EventStream,
    metric_record,
    nonfinite_str,
    read_events,
    read_events_meta,
    restore_nonfinite,
)
from .exporters import to_prometheus_text, write_tensorboard_scalars
from .health import HealthConfig, HealthMonitor, SolverHealthError, monitor_for
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .recorder import FlightRecorder
from .run import (
    TelemetryRun,
    end_run,
    get_run,
    materialize,
    run_scope,
    start_run,
)
from . import profile  # noqa: E402  (serving compile/device profiling)
from . import trace  # noqa: E402  (span API: trace.span / trace.start_span)

__all__ = [
    "profile",
    "Counter",
    "EventStream",
    "FlightRecorder",
    "Gauge",
    "HealthConfig",
    "HealthMonitor",
    "Histogram",
    "MetricsRegistry",
    "SolverHealthError",
    "TelemetryRun",
    "end_run",
    "get_run",
    "materialize",
    "metric_record",
    "monitor_for",
    "nonfinite_str",
    "read_events",
    "read_events_meta",
    "restore_nonfinite",
    "run_scope",
    "start_run",
    "to_prometheus_text",
    "trace",
    "write_tensorboard_scalars",
]
