"""Human-readable run report CLI.

Usage::

    python -m dpgo_tpu.obs.report <run_dir> [<run_dir>...] [--json]
    python -m dpgo_tpu.obs.report --compare <run_a> <run_b> [--json]
    python -m dpgo_tpu.obs.report --live <host>:<port> [--json]

``--live`` is the one mode that doesn't read artifacts: it scrapes a
running serve sidecar's ``/statusz`` endpoint
(``SolveServer(metrics_port=...)``) and renders queue depth, per-tenant
in-flight vs. quota, cache compile/hit tallies, last-batch occupancy,
and SLO burn rates while the server is still up.

Reads the artifacts a ``TelemetryRun`` persisted (``events.jsonl``,
``metrics.json``) and prints the run's story: event volume, per-iteration
cost/gradient-norm trajectory, GNC mu annealing, round latency, per-phase
wall-clock, communication volume, and — when the run carries ``span``
events — the fleet timeline: per-robot busy/wait breakdown, per-round
critical path, straggler ranking, and overlap efficiency.  Runs that hit
numerical-health anomalies (``obs.health``) get a "numerical health"
section and a pointer to the flight-recorder black box.  ``--json``
emits the same content machine-readably (one JSON document per run dir).
``--compare`` invokes the convergence regression gate (``obs.regress``):
exit 0 = no regression, 2 = regression or refused (mismatched
fingerprints).  Pure host-side formatting — no devices are touched, so
it runs anywhere the run directory is visible.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter as _TallyCounter

from .events import read_events_meta
from .run import EVENTS_FILE, META_FILE, METRICS_FILE
from .timeline import fleet_timeline_stats


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _trajectory_lines(events: list[dict], metric: str) -> list[str]:
    pts = [(ev.get("iteration", ev["seq"]), ev["value"]) for ev in events
           if ev.get("event") == "metric" and ev.get("metric") == metric
           and isinstance(ev.get("value"), (int, float))]
    if not pts:
        return []
    vals = [v for _, v in pts]
    head = (f"  {metric}: {len(pts)} points, first {_fmt(vals[0])}, "
            f"last {_fmt(vals[-1])}, min {_fmt(min(vals))}, "
            f"max {_fmt(max(vals))}")
    shown = pts if len(pts) <= 8 else pts[:4] + [None] + pts[-3:]
    rows = []
    for p in shown:
        rows.append("      ..." if p is None
                    else f"      iter {p[0]:>6}: {_fmt(p[1])}")
    return [head] + rows


def _histogram_summary(name: str, fam: dict) -> list[str]:
    out = []
    bounds = fam.get("buckets", [])
    for s in fam.get("series", []):
        labels = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
        n = s.get("count", 0)
        if not n:
            continue
        mean = s["sum"] / n
        # Approximate median from the cumulative buckets.
        cum, med = 0, "inf"
        for bound, c in zip(bounds, s["counts"]):
            cum += c
            if cum >= n / 2:
                med = _fmt(bound)
                break
        lab = f"{{{labels}}}" if labels else ""
        out.append(f"  {name}{lab}: n={n} mean={_fmt(mean)} p50<={med}")
    return out


def _health_lines(events: list[dict]) -> list[str]:
    """Render the numerical-health section: anomaly events (solver +
    per-robot), fleet-wide peer anomaly sightings, and black-box dumps."""
    anomalies = [ev for ev in events if ev.get("event") == "anomaly"]
    peer = [ev for ev in events if ev.get("event") == "peer_anomaly"]
    dumps = [ev for ev in events if ev.get("event") == "blackbox_dump"]
    if not (anomalies or peer or dumps):
        return []
    crit = sum(1 for ev in anomalies if ev.get("severity") == "critical")
    lines = [f"numerical health: {len(anomalies)} anomalies"
             + (f" ({crit} critical)" if crit else "")]
    for ev in anomalies[:10]:
        where = f" robot {ev['robot']}" if "robot" in ev else ""
        it = f" iter {ev['iteration']}" if "iteration" in ev else ""
        lines.append(f"  [{ev.get('severity')}]{it}{where} "
                     f"{ev.get('kind')} (stage {ev.get('stage', 0)})")
    if len(anomalies) > 10:
        lines.append(f"  ... {len(anomalies) - 10} more")
    if peer:
        tally = _TallyCounter(ev.get("peer") for ev in peer)
        lines.append("  fleet: anomalies seen from "
                     + ", ".join(f"robot {p} x{n}"
                                 for p, n in sorted(tally.items())))
    for ev in dumps:
        lines.append(f"  blackbox: {ev.get('path')} (reason "
                     f"{ev.get('reason')}, {ev.get('rounds_recorded')} "
                     f"rounds, {ev.get('snapshots')} snapshots)")
    return lines


def serving_stats(events: list[dict]) -> dict | None:
    """Per-tenant serving SLOs from the serve plane's event schema
    (``serve_request`` / ``serve_batch`` / ``serve_shed`` — the same
    records ``bench_serving.py`` writes), shared by the text report, the
    ``--json`` payload, and the bench's assertions.

    Per tenant: request count, QPS over the tenant's request window,
    queue-wait p50, and solve-latency p50/p99 (exact percentiles from the
    per-request events, not histogram-bucket approximations).  Fleet-wide:
    batch count, mean batch occupancy/size, shed tallies by tenant and
    reason, and SLO burn alerts (``slo_burn`` anomalies).

    A run whose serve plane saw no completed request (server stood up,
    everything shed or nothing arrived) reports ``no_traffic=True`` with
    empty tenant stats — there is no submit->complete window to divide
    by, and the report renders an explicit "no traffic" line instead of
    exploding."""
    reqs = [ev for ev in events if ev.get("event") == "serve_request"]
    batches = [ev for ev in events if ev.get("event") == "serve_batch"]
    sheds = [ev for ev in events if ev.get("event") == "serve_shed"]
    serve_seen = any(ev.get("phase") == "serve" for ev in events)
    if not (reqs or batches or sheds or serve_seen):
        return None

    def _pct(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        k = min(len(vals) - 1, max(0, int(round(q / 100.0 * (len(vals) - 1)))))
        return vals[k]

    tenants: dict = {}
    for ev in reqs:
        tenants.setdefault(ev.get("tenant", "?"), []).append(ev)
    out_t = {}
    for tenant, evs in sorted(tenants.items()):
        lats = [ev["latency_s"] for ev in evs
                if isinstance(ev.get("latency_s"), (int, float))]
        waits = [ev["queue_wait_s"] for ev in evs
                 if isinstance(ev.get("queue_wait_s"), (int, float))]
        # Completion events of one batch land within microseconds of each
        # other, so the serving window runs from the first request's
        # SUBMIT (its completion stamp minus its latency) to the last
        # completion.
        first_submit = evs[0]["t_mono"] - (evs[0].get("latency_s") or 0.0)
        window = evs[-1]["t_mono"] - first_submit
        out_t[tenant] = {
            "requests": len(evs),
            "qps": len(evs) / window if window > 0 else None,
            "queue_wait_p50_s": _pct(waits, 50),
            "latency_p50_s": _pct(lats, 50),
            "latency_p99_s": _pct(lats, 99),
        }
    occ = [ev["occupancy"] for ev in batches
           if isinstance(ev.get("occupancy"), (int, float))]
    sizes = [ev["size"] for ev in batches
             if isinstance(ev.get("size"), (int, float))]
    shed_tally = dict(_TallyCounter(
        (ev.get("tenant", "?"), ev.get("reason", "?")) for ev in sheds))
    # SLO burn alerts: the serve plane's slo_burn anomalies + recoveries.
    burns = [ev for ev in events if ev.get("event") == "anomaly"
             and ev.get("kind") == "slo_burn"]
    slo = None
    if burns:
        slo = {}
        for ev in burns:
            row = slo.setdefault(
                ev.get("tenant", "?"),
                {"alerts": 0, "max_burn": 0.0, "worst_severity": None,
                 "slos": set()})
            row["alerts"] += 1
            rate = ev.get("burn_rate")
            if isinstance(rate, (int, float)):
                row["max_burn"] = max(row["max_burn"], float(rate))
            if ev.get("severity") == "critical" or \
                    row["worst_severity"] is None:
                row["worst_severity"] = ev.get("severity")
            row["slos"].add(ev.get("slo", "?"))
        for row in slo.values():
            row["slos"] = sorted(row["slos"])
    return {
        "no_traffic": not reqs,
        "tenants": out_t,
        "batches": {
            "count": len(batches),
            "mean_occupancy": sum(occ) / len(occ) if occ else None,
            "mean_size": sum(sizes) / len(sizes) if sizes else None,
        },
        "shed": [{"tenant": t, "reason": r, "count": n}
                 for (t, r), n in sorted(shed_tally.items())],
        "slo": slo,
    }


def _serving_lines(stats: dict | None) -> list[str]:
    """Render the serving section (serve-plane events present)."""
    if not stats:
        return []
    lines = ["serving:"]
    if stats.get("no_traffic"):
        lines.append("  no completed requests (no traffic)")
    for tenant, row in stats["tenants"].items():
        parts = [f"{row['requests']} requests"]
        if row["qps"] is not None:
            parts.append(f"{row['qps']:.2f} req/s")
        if row["queue_wait_p50_s"] is not None:
            parts.append(f"queue wait p50 {row['queue_wait_p50_s'] * 1e3:.1f}ms")
        if row["latency_p50_s"] is not None:
            parts.append(f"latency p50 {row['latency_p50_s']:.3f}s"
                         + (f" / p99 {row['latency_p99_s']:.3f}s"
                            if row["latency_p99_s"] is not None else ""))
        lines.append(f"  tenant {tenant}: " + ", ".join(parts))
    b = stats["batches"]
    if b["count"] and b["mean_occupancy"] is not None:
        lines.append(
            f"  batches: {b['count']} dispatched, mean occupancy "
            f"{b['mean_occupancy'] * 100:.0f}%, mean size "
            f"{b['mean_size']:.1f}")
    for s in stats["shed"]:
        lines.append(f"  shed: tenant {s['tenant']} x{s['count']} "
                     f"({s['reason']})")
    for tenant, row in sorted((stats.get("slo") or {}).items()):
        lines.append(
            f"  slo burn: tenant {tenant} {row['alerts']} alert(s) "
            f"[{row['worst_severity']}] on {'/'.join(row['slos'])}, "
            f"max burn {row['max_burn']:.1f}x")
    return lines


def sharded_stats(events: list[dict]) -> dict | None:
    """Mesh-path facts from the event stream (``solve_rbcd_sharded`` /
    ``bench_sharded.py`` schemas), shared by the text report, ``--json``,
    and the bench's assertions: mesh layout + exchange backend + halo
    overlap flag (``sharded_solve`` setup events), modeled vs measured
    interconnect bytes per round (``sharded_comm_bytes_measured`` metric,
    measured = parsed from the compiled program's collectives), halo
    overlap efficiency (``sharded_overlap_efficiency`` metric, 1 -
    t_overlap/t_lockstep), the verdict sync rate, the sharded GN-CG
    tail summary (``gn_tail`` events with ``sharded=True``), and the
    pod-scale resilience story (``mesh_checkpoint`` / ``mesh_fault`` /
    ``mesh_rewind`` events from ``parallel.resilience``)."""
    setup = [ev for ev in events if ev.get("event") == "sharded_solve"]
    overlap = [ev for ev in events if ev.get("event") == "metric"
               and ev.get("metric") == "sharded_overlap_efficiency"]
    comm = [ev for ev in events if ev.get("event") == "metric"
            and ev.get("metric") == "sharded_comm_bytes_measured"]
    tails = [ev for ev in events if ev.get("event") == "gn_tail"
             and ev.get("sharded")]
    checkpoints = [ev for ev in events
                   if ev.get("event") == "mesh_checkpoint"]
    faults = [ev for ev in events if ev.get("event") == "mesh_fault"]
    rewinds = [ev for ev in events if ev.get("event") == "mesh_rewind"]
    if not (setup or overlap or comm or tails or checkpoints or faults
            or rewinds):
        return None
    out: dict = {"solves": [], "gn_tails": []}
    syncs = [ev for ev in events if ev.get("event") == "metric"
             and ev.get("metric") == "host_syncs_per_100_rounds"]
    for ev in setup:
        out["solves"].append({
            "mesh_size": ev.get("mesh_size"),
            "mesh_axes": ev.get("mesh_axes"),
            "agents_per_shard": ev.get("agents_per_shard"),
            "exchange": ev.get("exchange"),
            "overlap": ev.get("overlap"),
            "verdict_every": ev.get("verdict_every"),
            "comm_bytes_per_round": ev.get("comm_bytes_per_round"),
        })
    if syncs:
        out["host_syncs_per_100_rounds"] = syncs[-1].get("value")
    if overlap:
        ev = overlap[-1]
        out["overlap"] = {"efficiency": ev.get("value"),
                          "overlap_rounds_per_s": ev.get("overlap_rounds_per_s"),
                          "lockstep_rounds_per_s": ev.get("lockstep_rounds_per_s")}
    if comm:
        ev = comm[-1]
        out["comm_measured"] = {"measured": ev.get("value"),
                                "modeled": ev.get("modeled")}
    for ev in tails:
        out["gn_tails"].append({
            "terminated_by": ev.get("terminated_by"),
            "outer_iterations": ev.get("outer_iterations"),
            "cg_iterations": ev.get("cg_iterations"),
            "cost": ev.get("cost"), "grad_norm": ev.get("grad_norm")})
    if checkpoints or rewinds or faults:
        overhead = [ev for ev in events if ev.get("event") == "metric"
                    and ev.get("metric") == "mesh_recovery_overhead_s"]
        out["resilience"] = {
            "checkpoints": len(checkpoints),
            "last_checkpoint_iteration":
                checkpoints[-1].get("iteration") if checkpoints else None,
            "faults": [{"kind": ev.get("kind"),
                        "phase": ev.get("fault_phase"),
                        "device": ev.get("device")} for ev in faults],
            "rewinds": [{"kind": ev.get("kind"),
                         "mesh_from": ev.get("mesh_from"),
                         "mesh_to": ev.get("mesh_to"),
                         "resume_iteration": ev.get("resume_iteration"),
                         "cold": ev.get("cold")} for ev in rewinds],
            "recovery_overhead_s":
                overhead[-1].get("value") if overhead else None,
        }
    return out


def _sharded_lines(stats: dict | None) -> list[str]:
    """Render the sharded section (mesh-path events present)."""
    if not stats:
        return []
    lines = ["sharded:"]
    for s in stats["solves"]:
        axes = "x".join(str(a) for a in (s.get("mesh_axes") or []))
        parts = [f"mesh {s['mesh_size']} devices ({axes})",
                 f"{s['agents_per_shard']} agents/shard",
                 f"exchange {s['exchange']}",
                 f"halo overlap {'on' if s.get('overlap') else 'off'}"]
        if s.get("verdict_every"):
            parts.append(f"verdict loop K={s['verdict_every']}")
        lines.append("  " + ", ".join(parts))
        if s.get("comm_bytes_per_round") is not None:
            lines.append("  interconnect (modeled): "
                         f"{_fmt_bytes(s['comm_bytes_per_round'])}/round"
                         "/device")
    cm = stats.get("comm_measured")
    if cm and cm.get("measured") is not None:
        ratio = ""
        if cm.get("modeled"):
            ratio = f" ({cm['measured'] / cm['modeled']:.2f}x model)"
        lines.append(f"  interconnect (compiled collectives): "
                     f"{_fmt_bytes(cm['measured'])}/round/device{ratio}")
    if stats.get("host_syncs_per_100_rounds") is not None:
        lines.append("  verdict sync rate: "
                     f"{_fmt(stats['host_syncs_per_100_rounds'])} host "
                     "fetches / 100 rounds")
    ov = stats.get("overlap")
    if ov and ov.get("efficiency") is not None:
        detail = ""
        if ov.get("overlap_rounds_per_s") and ov.get("lockstep_rounds_per_s"):
            detail = (f" ({ov['overlap_rounds_per_s']:.1f} vs "
                      f"{ov['lockstep_rounds_per_s']:.1f} rounds/s)")
        lines.append(
            f"  halo overlap efficiency: {ov['efficiency'] * 100:.1f}%"
            + detail)
        if ov["efficiency"] < 0:
            lines.append(
                "  WARNING: overlap not paying (negative efficiency — "
                "gate it off with overlap=\"auto\" or profile with "
                "devprof)")
    for t in stats["gn_tails"]:
        lines.append(
            f"  gn tail: {t['terminated_by']} after "
            f"{t['outer_iterations']} outer / {t['cg_iterations']} CG "
            f"iters, cost {_fmt(t.get('cost'))}, "
            f"gn {_fmt(t.get('grad_norm'))}")
    rz = stats.get("resilience")
    if rz:
        head = f"  resilience: {rz['checkpoints']} checkpoint(s)"
        if rz.get("last_checkpoint_iteration") is not None:
            head += f" (last at round {rz['last_checkpoint_iteration']})"
        if rz.get("recovery_overhead_s") is not None:
            head += f", recovery overhead {rz['recovery_overhead_s']:.2f}s"
        lines.append(head)
        for f in rz["faults"]:
            dev = f" device {f['device']}" if f.get("device") is not None \
                else ""
            lines.append(f"  mesh fault: {f['kind']} in phase "
                         f"{f['phase']}{dev}")
        for r in rz["rewinds"]:
            dest = "cold restart" if r.get("cold") \
                else f"round {r['resume_iteration']}"
            lines.append(
                f"  rewind [{r['kind']}]: mesh {r['mesh_from']} -> "
                f"{r['mesh_to']} devices, resumed from {dest}")
    return lines


def devprof_stats(events: list[dict]) -> dict | None:
    """Device-time attribution facts (ISSUE 16): ``devprof``'s
    ``device_attribution`` windows (compute/collective/idle split +
    measured overlap efficiency), the adaptive gate's
    ``overlap_decision`` records, and the solver planes'
    ``compile_profile`` rooflines.  Serve-plane compiles keep rendering
    in the fleet section (``fleet_serve_stats``); this section owns
    ``phase in ("solve", "sharded")``."""
    attrs = [ev for ev in events
             if ev.get("event") == "device_attribution"]
    decisions = [ev for ev in events
                 if ev.get("event") == "overlap_decision"]
    compiles = [ev for ev in events if ev.get("event") == "compile_profile"
                and ev.get("phase") in ("solve", "sharded")]
    errors = [ev for ev in events if ev.get("event") == "profiler_error"
              and ev.get("phase") in ("solve", "sharded")]
    if not (attrs or decisions or compiles):
        return None
    out: dict = {"windows": [], "decisions": [], "compiles": [],
                 "profiler_errors": len(errors)}
    for ev in attrs:
        out["windows"].append({k: ev.get(k) for k in (
            "label", "phase", "lanes", "num_rounds", "window_s",
            "compute_s", "collective_s", "idle_s", "per_round",
            "collective_hidden_s", "overlap_efficiency_measured",
            "top_ops", "trace_files", "profile_dir")})
    for ev in decisions:
        out["decisions"].append({k: ev.get(k) for k in (
            "overlap", "efficiency", "threshold", "reason", "mesh_size",
            "exchange", "calib_rounds",
            "lockstep_seconds", "overlapped_seconds",
            "lockstep_rounds_per_s", "overlapped_rounds_per_s",
            "lockstep_overlap_efficiency_measured",
            "overlapped_overlap_efficiency_measured",
            "lockstep_collective_s_per_round",
            "overlapped_collective_s_per_round")})
    for ev in compiles:
        out["compiles"].append({k: ev.get(k) for k in (
            "label", "phase", "key", "static", "lower_s", "compile_s",
            "total_s", "flops", "bytes_accessed", "bytes_per_flop",
            "temp_bytes")})
    return out


def _devprof_lines(stats: dict | None) -> list[str]:
    """Render the device-profile section (devprof events present)."""
    if not stats:
        return []
    lines = ["device profile:"]
    for w in stats["windows"]:
        busy = (w.get("compute_s") or 0.0) + (w.get("collective_s") or 0.0)
        total = busy + (w.get("idle_s") or 0.0)
        pct = (lambda v: f"{100.0 * v / total:.0f}%") if total > 0 \
            else (lambda v: "-")
        lines.append(
            f"  window [{w.get('label')}] ({w.get('phase')}): "
            f"{w.get('lanes')} lanes x {_fmt(w.get('window_s'))}s, "
            f"{w.get('num_rounds')} rounds — compute "
            f"{pct(w.get('compute_s') or 0.0)}, collective "
            f"{pct(w.get('collective_s') or 0.0)}, idle "
            f"{pct(w.get('idle_s') or 0.0)}")
        eff = w.get("overlap_efficiency_measured")
        if eff is not None:
            lines.append(
                f"    measured overlap: {eff * 100:.1f}% of collective "
                f"time hidden behind compute "
                f"({_fmt(w.get('collective_hidden_s'))}s of "
                f"{_fmt(w.get('collective_s'))}s)")
        for op in (w.get("top_ops") or [])[:3]:
            lines.append(
                f"    top op: {op.get('op')} [{op.get('kind')}] "
                f"{_fmt(op.get('total_s'))}s x{op.get('count')}")
    for d in stats["decisions"]:
        verdict = "ON" if d.get("overlap") else "OFF"
        if d.get("reason"):
            lines.append(f"  overlap gate: {verdict} ({d['reason']})")
            continue
        lines.append(
            f"  overlap gate: {verdict} — A/B efficiency "
            f"{(d.get('efficiency') or 0.0) * 100:.1f}% vs threshold "
            f"{(d.get('threshold') or 0.0) * 100:.0f}% "
            f"({_fmt(d.get('overlapped_rounds_per_s'))} vs "
            f"{_fmt(d.get('lockstep_rounds_per_s'))} rounds/s over "
            f"{d.get('calib_rounds')} calib rounds)")
        for arm in ("lockstep", "overlapped"):
            m = d.get(f"{arm}_overlap_efficiency_measured")
            if m is not None:
                lines.append(
                    f"    {arm} arm: measured overlap {m * 100:.1f}%, "
                    f"collective "
                    f"{_fmt(d.get(f'{arm}_collective_s_per_round'))}s"
                    "/round")
    for c in stats["compiles"]:
        static = ""
        if c.get("static"):
            static = " {" + ", ".join(
                f"{k}={v}" for k, v in sorted(c["static"].items())) + "}"
        roof = ""
        if c.get("bytes_per_flop") is not None:
            roof = f", {c['bytes_per_flop']:.2f} bytes/flop"
        flops = ""
        if c.get("flops") is not None:
            flops = f", {c['flops']:.3g} flops"
        lines.append(
            f"  compile [{c.get('label')}]{static} ({c.get('phase')}): "
            f"{_fmt(c.get('total_s'))}s{flops}{roof}")
    if stats.get("profiler_errors"):
        lines.append(f"  profiler errors: {stats['profiler_errors']} "
                     "(window(s) degraded, solve unaffected)")
    return lines


def cert_stats(events: list[dict]) -> dict | None:
    """Certificate-decision tallies (ISSUE 16 satellite): ACCEPT / FAIL /
    REFUSE counts over the run's ``certificate`` events, by source, plus
    the host-f64 REFUSE-band fallback wall — the denominator data for
    the f32 ACCEPT-band sweep."""
    evs = [ev for ev in events if ev.get("event") == "certificate"]
    if not evs:
        return None
    tally = {"accept": 0, "fail": 0, "refuse": 0}
    sources: dict = {}
    f64_s = 0.0
    for ev in evs:
        status = "accept" if ev.get("certified") else \
            ("fail" if ev.get("decidable") else "refuse")
        tally[status] += 1
        src = ev.get("source") or \
            ("certify_sharded" if ev.get("sharded") else "device_epilogue")
        sources[src] = sources.get(src, 0) + 1
        if isinstance(ev.get("f64_fallback_s"), (int, float)):
            f64_s += ev["f64_fallback_s"]
    return {"tally": tally, "sources": sources, "total": len(evs),
            "f64_fallback_s": f64_s}


def _cert_lines(stats: dict | None) -> list[str]:
    if not stats:
        return []
    t = stats["tally"]
    line = (f"  certificates: {t['accept']} accept / {t['fail']} fail / "
            f"{t['refuse']} refuse ("
            + ", ".join(f"{k} x{n}"
                        for k, n in sorted(stats["sources"].items()))
            + ")")
    lines = [line]
    if stats["f64_fallback_s"]:
        lines.append(f"  f64 fallback: {stats['f64_fallback_s']:.3f}s "
                     "wall in host eigensolves (REFUSE band)")
    return lines


def render_statusz(status: dict) -> str:
    """Human rendering of a live ``/statusz`` payload (the JSON
    ``serve.statusz.MetricsSidecar`` serves and ``SolveServer.status()``
    produces) — the ``--live`` mode's output."""
    lines = ["== live server status =="]
    lines.append(
        f"uptime {status.get('uptime_s', 0.0):.1f}s"
        + (", CLOSED" if status.get("closed") else ""))
    lines.append(
        f"queue: {status.get('queue_depth', 0)}/{status.get('max_queue', '?')}"
        f" pending, max batch {status.get('max_batch', '?')}, "
        f"quantum {status.get('quantum', '?')}")
    lines.append(
        f"lifetime: {status.get('requests_served', 0)} served / "
        f"{status.get('requests_shed', 0)} shed over "
        f"{status.get('batches_dispatched', 0)} batches")
    for tenant, row in (status.get("tenants") or {}).items():
        quota = row.get("quota")
        lines.append(f"  tenant {tenant}: {row.get('in_flight', 0)} in flight"
                     + (f" / quota {quota}" if quota is not None else ""))
    lb = status.get("last_batch")
    if lb:
        lines.append(
            f"last batch: {lb.get('size')}/{lb.get('batch')} slots "
            f"({(lb.get('occupancy') or 0) * 100:.0f}% occupancy), "
            f"{lb.get('rounds')} rounds in {lb.get('duration_s', 0):.3f}s")
    cache = status.get("cache")
    if cache:
        lines.append(
            f"executable cache: {cache.get('entries', 0)} entries, "
            f"{cache.get('compiles', 0)} compiles, "
            f"{cache.get('hits', 0)} hits")
    for tenant, row in (status.get("slo") or {}).items():
        level = row.get("level")
        lines.append(
            f"  slo {tenant}: latency burn {row.get('latency_burn', 0):.2f}x,"
            f" shed burn {row.get('shed_burn', 0):.2f}x"
            f" ({row.get('requests', 0)} req / {row.get('slow', 0)} slow / "
            f"{row.get('shed', 0)} shed in {row.get('window_s', 0):.0f}s)"
            + (f" ALERT {level}" if level else ""))
    return "\n".join(lines)


def render_fleet_statusz(payload: dict) -> str:
    """Human rendering of a fleet-level ``/statusz`` payload (the JSON
    ``obs.fleetobs.FleetSidecar`` serves): one line per replica with
    unreachable/dead replicas MARKED — a partial fleet is still a
    report, never an error."""
    lines = ["== live fleet status =="]
    replicas = payload.get("replicas") or {}
    up = sum(1 for e in replicas.values() if e.get("reachable"))
    lines.append(f"replicas: {up}/{len(replicas)} reachable")
    for rid, entry in sorted(replicas.items()):
        st = entry.get("status") or {}
        if not entry.get("reachable"):
            why = entry.get("error") or (
                "closed" if st.get("closed") else "no status")
            lines.append(f"  replica {rid}: ** UNREACHABLE ** ({why})")
            continue
        bits = [f"queue {st.get('queue_depth', 0)}"]
        if st.get("draining"):
            bits.append("DRAINING")
        if not st.get("accepting", True):
            bits.append("not accepting")
        if "heartbeat_misses" in st and st["heartbeat_misses"]:
            bits.append(f"{st['heartbeat_misses']} missed heartbeats")
        bits.append(f"{st.get('requests_served', 0)} served")
        lines.append(f"  replica {rid}: " + ", ".join(str(b)
                                                      for b in bits))
    fleet = payload.get("fleet") or {}
    for k in ("error",):
        if fleet.get(k):
            lines.append(f"  fleet {k}: {fleet[k]}")
    return "\n".join(lines)


def live_report(target: str, json_out: bool = False, timeout: float = 5.0,
                out=None, fleet: bool = False) -> int:
    """``--live HOST:PORT``: scrape a running server's ``/statusz``
    sidecar and render it.  rc 0 on success, 2 on unreachable/garbage
    (same contract as the run-dir error paths).

    With ``fleet=True`` (or a payload that is recognizably fleet-level)
    the target is an aggregated ``FleetSidecar`` endpoint: replicas that
    died or dropped mid-scrape render MARKED inside a partial fleet
    view with rc 0 — only the aggregate endpoint itself being
    unreachable is rc 2."""
    import urllib.error
    import urllib.request

    out = out or sys.stdout
    if "://" not in target:
        target = f"http://{target}"
    url = target.rstrip("/") + "/statusz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            status = json.load(resp)
    except (urllib.error.URLError, OSError, ValueError) as e:
        # An HTTPError carries the open response body: close it on the
        # error path too, the success path's `with` never ran
        # (leakcheck-enforced contract).
        if hasattr(e, "close"):
            e.close()
        print(f"cannot scrape {url}: {e}", file=sys.stderr)
        return 2
    is_fleet = fleet or ("replicas" in status and "fleet" in status)
    if json_out:
        print(json.dumps(status), file=out)
    elif is_fleet:
        print(render_fleet_statusz(status), file=out)
    else:
        print(render_statusz(status), file=out)
    return 0


def _fleet_lines(stats: dict | None) -> list[str]:
    """Render the fleet-timeline section (tracing spans present)."""
    if not stats:
        return []
    lines = [f"fleet timeline: {stats['num_spans']} spans over "
             f"{stats['window_s']:.2f}s, "
             f"{stats['num_flow_links']} cross-robot frame links"]
    for r, row in sorted(stats["robots"].items()):
        who = "bus" if int(r) < 0 else f"robot {r}"
        parts = [f"busy {row['busy_s']:.3f}s"]
        if row["wait_s"]:
            parts.append(f"wait {row['wait_s']:.3f}s")
        if row["wire_s"]:
            parts.append(f"wire {row['wire_s']:.3f}s")
        if row["iterations"]:
            parts.append(f"{row['iterations']} iterates @ "
                         f"{(row['mean_iterate_s'] or 0) * 1e3:.2f}ms")
        if row["overlap_efficiency"] is not None:
            parts.append(
                f"overlap eff {row['overlap_efficiency'] * 100:.0f}%")
        lines.append(f"  {who}: " + ", ".join(parts))
    rc = stats.get("round_critical_path")
    if rc:
        crit = ", ".join(f"robot {r} x{n}"
                         for r, n in rc["critical_path_counts"].items())
        lines.append(
            f"  critical path over {rc['rounds']} rounds: makespan "
            f"mean {rc['mean_makespan_s'] * 1e3:.2f}ms / p95 "
            f"{rc['p95_makespan_s'] * 1e3:.2f}ms; ends on {crit}")
    strag = stats.get("straggler_ranking")
    if strag:
        lines.append("  stragglers (mean iterate, slowest first): "
                     + ", ".join(f"robot {s['robot']} "
                                 f"{s['mean_iterate_s'] * 1e3:.2f}ms"
                                 for s in strag[:5]))
    return lines


def fleet_serve_stats(events: list[dict]) -> dict | None:
    """Fleet-of-replicas serving stats from ``serve.fleet``'s event
    schema (``replica_spawn``/``replica_death``/``fleet_scale``/
    ``session_migrated`` plus the AOT disk tier's ``compile_profile``/
    ``aot_entry_quarantined``/``aot_store_failed``), shared by the text
    report and the ``--json`` payload (``out["fleet"]``).

    Distinct from :func:`~dpgo_tpu.obs.timeline.fleet_timeline_stats`,
    which reconstructs the *robot* fleet's span timeline — this section
    is about the *replica* fleet: lifecycle churn, live migrations by
    kind, autoscaler decisions, and the persistent-cache disk-hit vs.
    compile split that proves a warm restart skipped XLA."""
    spawns = [ev for ev in events if ev.get("event") == "replica_spawn"]
    deaths = [ev for ev in events if ev.get("event") == "replica_death"]
    scales = [ev for ev in events if ev.get("event") == "fleet_scale"]
    migs = [ev for ev in events if ev.get("event") == "session_migrated"]
    quarantined = [ev for ev in events
                   if ev.get("event") == "aot_entry_quarantined"]
    store_fails = [ev for ev in events
                   if ev.get("event") == "aot_store_failed"]
    fleet_seen = any(ev.get("phase") == "fleet" for ev in events)
    if not (fleet_seen or quarantined or store_fails):
        return None
    profiles = [ev for ev in events if ev.get("event") == "compile_profile"]
    disk_hits = [ev for ev in profiles if ev.get("disk_hit")]
    compiles = [ev for ev in profiles if not ev.get("disk_hit")]
    cold = [ev for ev in events if ev.get("event") == "metric"
            and ev.get("metric") == "serve_cold_start_seconds"]
    out: dict = {
        "replicas": {
            "spawned": len(spawns),
            "spawn_reasons": dict(_TallyCounter(
                ev.get("reason", "?") for ev in spawns)),
            "deaths": len(deaths),
            "pool_end": ([ev.get("pool") for ev in spawns + deaths
                          + scales] or [None])[-1],
        },
        "migrations": {
            "count": len(migs),
            "by_kind": dict(_TallyCounter(
                ev.get("kind", "?") for ev in migs)),
            "failed": sum(1 for ev in migs if not ev.get("ok")),
            "sessions": sorted({ev["session"] for ev in migs
                                if ev.get("session")}),
        },
        "scale": {
            "events": len(scales),
            "by_direction": dict(_TallyCounter(
                ev.get("direction", "?") for ev in scales)),
            "last_burn": scales[-1].get("burn") if scales else None,
        },
        "aot": {
            "disk_hits": len(disk_hits),
            "compiles": len(compiles),
            "quarantined": len(quarantined),
            "store_failures": len(store_fails),
        } if (profiles or quarantined or store_fails) else None,
        "cold_start": [
            {"arm": ev.get("arm", "?"),
             "first_solve_s": ev.get("value"),
             "compile_seconds_total": ev.get("compile_seconds_total"),
             "disk_hits": ev.get("disk_hits")}
            for ev in cold] or None,
    }
    return out


def _fleet_serve_lines(stats: dict | None) -> list[str]:
    """Render the replica-fleet section (fleet-phase events present)."""
    if not stats:
        return []
    rep = stats["replicas"]
    reasons = ", ".join(f"{k} {n}" for k, n
                        in sorted(rep["spawn_reasons"].items()))
    lines = [f"fleet: {rep['spawned']} replicas spawned"
             + (f" ({reasons})" if reasons else "")
             + f", {rep['deaths']} deaths"
             + (f", pool {rep['pool_end']} at end"
                if rep["pool_end"] is not None else "")]
    mig = stats["migrations"]
    if mig["count"]:
        kinds = ", ".join(f"{k} {n}" for k, n
                          in sorted(mig["by_kind"].items()))
        line = f"  migrations: {mig['count']} ({kinds})"
        if mig["failed"]:
            line += f", {mig['failed']} FAILED"
        if mig["sessions"]:
            line += " — sessions " + ", ".join(mig["sessions"][:6])
            if len(mig["sessions"]) > 6:
                line += f" (+{len(mig['sessions']) - 6} more)"
        lines.append(line)
    sc = stats["scale"]
    if sc["events"]:
        dirs = ", ".join(f"{k} {n}" for k, n
                         in sorted(sc["by_direction"].items()))
        line = f"  autoscale: {sc['events']} decisions ({dirs})"
        if sc["last_burn"] is not None:
            line += f", last burn {sc['last_burn']:.3g}"
        lines.append(line)
    aot = stats["aot"]
    if aot:
        line = (f"  aot cache: {aot['disk_hits']} disk hits / "
                f"{aot['compiles']} compiles")
        if aot["quarantined"]:
            line += f", {aot['quarantined']} QUARANTINED"
        if aot["store_failures"]:
            line += f", {aot['store_failures']} store failures"
        lines.append(line)
    for row in stats["cold_start"] or []:
        parts = []
        if row["first_solve_s"] is not None:
            parts.append(f"first solve {row['first_solve_s']:.3f}s")
        if row["compile_seconds_total"] is not None:
            parts.append(f"compile {row['compile_seconds_total']:.3f}s")
        if row["disk_hits"] is not None:
            parts.append(f"{row['disk_hits']} disk hits")
        lines.append(f"  cold start [{row['arm']}]: " + ", ".join(parts))
    return lines


def render_report(run_dir: str) -> str:
    lines = [f"== telemetry report: {run_dir} =="]
    meta_path = os.path.join(run_dir, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            meta = json.load(fh)
        lines.append(f"run id: {meta.get('run')}")

    ev_path = os.path.join(run_dir, EVENTS_FILE)
    events, truncated = read_events_meta(ev_path) \
        if os.path.exists(ev_path) else ([], False)
    if truncated:
        lines.append("WARNING: event stream ends mid-line (writer killed "
                     "mid-write?) — final event dropped")
    if events:
        dur = events[-1]["t_mono"] - events[0]["t_mono"]
        lines.append(f"events: {len(events)} over {dur:.2f}s")
        tally = _TallyCounter(ev.get("event", "?") for ev in events)
        kinds = ", ".join(f"{k} x{n}" for k, n in sorted(tally.items()))
        lines.append(f"  kinds: {kinds}")

        for ev in events:
            if ev.get("event") == "solve_end":
                verdict = ""
                if ev.get("verdict_every"):
                    v = ev.get("verdict") or {}
                    verdict = (f" [verdict loop K={ev['verdict_every']}"
                               + (f", anomaly={v['anomaly']}"
                                  if v.get("anomaly") else "") + "]")
                lines.append(
                    f"solve: {ev.get('iterations')} iterations, "
                    f"terminated by {ev.get('terminated_by')} "
                    f"in {_fmt(ev.get('duration_s'))}s" + verdict)
        # The readback-kill measurement (one metric event per solve).
        for ev in events:
            if ev.get("event") == "metric" \
                    and ev.get("metric") == "host_syncs_per_100_rounds":
                lines.append(
                    f"host syncs: {_fmt(ev.get('value'))} per 100 rounds "
                    f"({ev.get('fetches')} fetches / "
                    f"{ev.get('rounds')} rounds)")

        lines.append("trajectories:")
        metric_names = sorted({ev.get("metric") for ev in events
                               if ev.get("event") == "metric"
                               and ev.get("metric")})
        any_traj = False
        # Convergence signals first, everything else after.
        front = [m for m in ("solver_cost", "solver_grad_norm", "gnc_mu",
                             "gnc_inlier_fraction") if m in metric_names]
        for m in front + [m for m in metric_names if m not in front]:
            t = _trajectory_lines(events, m)
            any_traj = any_traj or bool(t)
            lines.extend(t)
        if not any_traj:
            lines.append("  (no metric events)")

        # Config fingerprint (run_summary channel="config" events, merged
        # in stream order — what report --compare keys on).
        fp: dict = {}
        for ev in events:
            if ev.get("event") == "run_summary" \
                    and ev.get("channel") == "config":
                fp.update(ev.get("fingerprint") or {})
        if fp:
            lines.append("config fingerprint: "
                         + ", ".join(f"{k}={fp[k]}" for k in sorted(fp)))

        # Network health: the comms layer's terminal run_summary events
        # (one per channel, plus the bus's aggregate) and peer-loss story.
        summaries = [ev for ev in events if ev.get("event") == "run_summary"
                     and ev.get("channel") != "config"]
        if summaries:
            lines.append("network health (comms):")
            for ev in summaries:
                parts = [f"{ev.get('messages_received', 0)} in / "
                         f"{ev.get('messages_sent', 0)} out"]
                if ev.get("bytes_sent") or ev.get("bytes_received"):
                    parts.append(
                        f"{_fmt_bytes(ev.get('bytes_received', 0))} in / "
                        f"{_fmt_bytes(ev.get('bytes_sent', 0))} out wire")
                for key, label in (("retries", "retries"),
                                   ("timeouts", "timeouts"),
                                   ("stale_dropped", "stale"),
                                   ("corrupt_dropped", "corrupt")):
                    if ev.get(key):
                        parts.append(f"{ev[key]} {label}")
                if ev.get("peers_lost"):
                    parts.append(f"peers lost {ev['peers_lost']}")
                lines.append(f"  {ev.get('channel', '?')}: "
                             + ", ".join(parts))
        # Deployment fast-path numbers (bench_deployment.py metric events).
        deploy = [ev for ev in events if ev.get("event") == "metric"
                  and str(ev.get("metric", "")).startswith(
                      "deployment_rounds_per_sec")]
        for ev in deploy:
            extras = []
            if ev.get("speedup_vs_legacy") is not None:
                extras.append(f"{ev['speedup_vs_legacy']}x vs legacy wire")
            if ev.get("staleness") is not None:
                extras.append(f"staleness {ev['staleness']}")
            lines.append(
                f"deployment bench: {_fmt(ev.get('value'))} "
                f"{ev.get('unit', '')}".rstrip()
                + (f" ({', '.join(extras)})" if extras else ""))
        losses = [ev for ev in events if ev.get("event") == "peer_lost"]
        if losses:
            for ev in losses:
                where = (f"robot {ev['robot']}" if "robot" in ev else "bus")
                why = f" ({ev['reason']})" if ev.get("reason") else ""
                lines.append(f"  peer_lost: {where} lost peer "
                             f"{ev.get('peer')}{why}")

        timers = [ev for ev in events if ev.get("event") == "phase_timings"]
        if timers:
            lines.append("phase timings (last snapshot):")
            for phase, row in sorted(
                    timers[-1].get("timings", {}).items(),
                    key=lambda kv: -kv[1].get("total_s", 0.0)):
                lines.append(
                    f"  {phase}: {row.get('total_s', 0.0):.4f}s "
                    f"/ {row.get('count', 0)} "
                    f"({row.get('avg_ms', 0.0):.2f} ms avg)")

        sharded_sec = _sharded_lines(sharded_stats(events))
        serving_sec = _serving_lines(serving_stats(events))
        certs = _cert_lines(cert_stats(events))
        if certs:
            # The tallies belong to whichever plane solved: sharded
            # section first, serving next, standalone for a plain solve.
            if sharded_sec:
                sharded_sec.extend(certs)
            elif serving_sec:
                serving_sec.extend(certs)
            else:
                sharded_sec = ["certificates:"] + certs
        lines.extend(sharded_sec)
        lines.extend(_devprof_lines(devprof_stats(events)))
        lines.extend(serving_sec)
        lines.extend(_health_lines(events))
        lines.extend(_fleet_lines(fleet_timeline_stats(events)))
        lines.extend(_fleet_serve_lines(fleet_serve_stats(events)))
    else:
        lines.append("events: none")

    m_path = os.path.join(run_dir, METRICS_FILE)
    if os.path.exists(m_path):
        with open(m_path) as fh:
            snap = json.load(fh)
        metrics = snap.get("metrics", {})
        lines.append("metrics snapshot:")
        for name, fam in sorted(metrics.items()):
            if fam["kind"] == "histogram":
                lines.extend(_histogram_summary(name, fam))
                continue
            for s in fam.get("series", []):
                labels = ",".join(f"{k}={v}"
                                  for k, v in sorted(s["labels"].items()))
                lab = f"{{{labels}}}" if labels else ""
                unit = f" {fam['unit']}" if fam.get("unit") else ""
                lines.append(f"  {name}{lab}: {_fmt(s.get('value'))}{unit}")
    else:
        lines.append("metrics snapshot: none (run not closed?)")
    return "\n".join(lines)


def report_data(run_dir: str) -> dict:
    """Machine-readable report for one run dir (the ``--json`` payload)."""
    out: dict = {"run_dir": run_dir}
    meta_path = os.path.join(run_dir, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            out["run"] = json.load(fh).get("run")
    ev_path = os.path.join(run_dir, EVENTS_FILE)
    events, truncated = read_events_meta(ev_path) \
        if os.path.exists(ev_path) else ([], False)
    out["truncated"] = truncated
    out["num_events"] = len(events)
    if events:
        out["duration_s"] = events[-1]["t_mono"] - events[0]["t_mono"]
        out["event_kinds"] = dict(_TallyCounter(
            ev.get("event", "?") for ev in events))
        out["metric_events"] = [
            ev for ev in events if ev.get("event") == "metric"]
        out["network"] = [ev for ev in events
                          if ev.get("event") == "run_summary"
                          and ev.get("channel") != "config"]
        fp: dict = {}
        for ev in events:
            if ev.get("event") == "run_summary" \
                    and ev.get("channel") == "config":
                fp.update(ev.get("fingerprint") or {})
        out["fingerprint"] = fp
        out["anomalies"] = [ev for ev in events
                            if ev.get("event") in ("anomaly",
                                                   "peer_anomaly",
                                                   "blackbox_dump")]
        out["sharded"] = sharded_stats(events)
        out["serving"] = serving_stats(events)
        out["devprof"] = devprof_stats(events)
        out["certificates"] = cert_stats(events)
        out["fleet_timeline"] = fleet_timeline_stats(events)
        out["fleet"] = fleet_serve_stats(events)
    m_path = os.path.join(run_dir, METRICS_FILE)
    if os.path.exists(m_path):
        with open(m_path) as fh:
            out["metrics"] = json.load(fh).get("metrics", {})
    return out


def _run_dir_error(rd: str) -> str | None:
    """Reject a missing or empty run dir with a clean message."""
    if not os.path.isdir(rd):
        return f"not a run directory: {rd}"
    if not any(os.path.exists(os.path.join(rd, f))
               for f in (EVENTS_FILE, METRICS_FILE, META_FILE)):
        return f"empty run directory (no telemetry artifacts): {rd}"
    return None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpgo_tpu.obs.report", description=__doc__)
    ap.add_argument("run_dir", nargs="*",
                    help="telemetry run directory (holds events.jsonl)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON document per "
                         "run dir) instead of the text report")
    ap.add_argument("--compare", nargs=2, metavar=("RUN_A", "RUN_B"),
                    help="convergence regression gate: compare two runs, "
                         "exit 2 on regression or incomparable configs")
    ap.add_argument("--rtol", type=float, default=0.05,
                    help="--compare: relative tolerance over run A's tail "
                         "noise band (default 0.05)")
    ap.add_argument("--allow-mismatch", action="store_true",
                    help="--compare: proceed despite fingerprint mismatches")
    ap.add_argument("--live", metavar="HOST:PORT",
                    help="scrape a running serve sidecar's /statusz "
                         "(--metrics-port) and render the live status")
    ap.add_argument("--fleet", action="store_true",
                    help="with --live: the target is a fleet-level "
                         "aggregated /statusz (obs.fleetobs."
                         "FleetSidecar); unreachable replicas render "
                         "marked in a partial view, rc 0")
    ap.add_argument("--ledger", nargs="?", const=".", metavar="ROOT",
                    help="render the cross-round perf ledger over the "
                         "BENCH_r*/MULTICHIP_r*/FLEET_r* records under "
                         "ROOT (default: cwd); --json emits the LEDGER "
                         "record tools/check_bench_floor.py validates")
    args = ap.parse_args(argv)
    if args.live:
        return live_report(args.live, json_out=args.json,
                           fleet=args.fleet)
    if args.ledger is not None:
        from .ledger import load_ledger

        ledger = load_ledger(args.ledger)
        if not ledger.rows:
            print(f"no bench records found under {args.ledger}",
                  file=sys.stderr)
            return 2
        print(json.dumps(ledger.to_json()) if args.json
              else ledger.render())
        return 0
    if args.compare:
        from .regress import run_compare

        return run_compare(args.compare[0], args.compare[1],
                           rtol=args.rtol, json_out=args.json,
                           allow_mismatch=args.allow_mismatch)
    if not args.run_dir:
        ap.error("at least one run_dir is required (or --compare A B, "
                 "or --ledger [ROOT])")
    rc = 0
    try:
        for rd in args.run_dir:
            err = _run_dir_error(rd)
            if err is not None:
                print(err, file=sys.stderr)
                rc = 2
                continue
            if args.json:
                print(json.dumps(report_data(rd)))
            else:
                print(render_report(rd))
    except BrokenPipeError:
        # Downstream pager/head closed the pipe — normal CLI etiquette.
        try:
            sys.stdout.close()
        except OSError:
            pass
    return rc


if __name__ == "__main__":
    sys.exit(main())
