"""Lightweight distributed spans for the deployment plane.

A *span* is one timed unit of work — an ``iterate`` step, a ``publish``,
the blocking part of an overlapped exchange — emitted through the run's
existing ``EventStream`` as a single ``span`` event at close:

``{"event": "span", "name", "phase", "robot", "trace", "span",
"parent"?, "t0_mono", "t0_wall", "dur_s", "link_*"?, **counters}``

Ids are random 63-bit integers rendered as 16-hex-digit strings.  Spans
nest through a thread-local stack (``with span(...)``): a span opened
inside another on the same thread inherits its trace id and records it as
``parent`` — the overlap worker's ``wire_round`` span parents the
``publish``/``collect`` it drives, and the per-thread stacks keep an
agent's optimization thread and its comms thread from cross-linking.

Cross-process causality does NOT ride the thread-local state: a publish
span's context (trace id, span id, sender robot, send time) is packed
into the outgoing frame as an optional wire entry
(``comms.protocol.pack_trace_entries``), survives the bus rebroadcast
under the sender's ``r{id}|`` namespace, and lands on the receiver's
``scatter`` span as ``link_*`` fields.  ``obs.timeline`` turns those
links into Chrome trace *flow* arrows from the sender's publish to the
receiver's ingest — a round's publish→exchange→scatter→step chain becomes
one causal edge set across robots.

Zero-overhead fence: every entry point resolves ``get_run()`` first and
returns the no-op ``NULL_SPAN`` (or emits nothing) when telemetry is off
— the same contract as the rest of ``dpgo_tpu.obs``
(``tests/test_obs.py::test_telemetry_off_is_zero_overhead`` patches
``Span.__init__`` and ``emit_span`` to throw and drives the instrumented
paths with telemetry off).
"""

from __future__ import annotations

import os
import struct
import threading
import time

from .run import get_run

__all__ = [
    "NULL_SPAN",
    "Span",
    "current_span",
    "emit_span",
    "link_fields",
    "new_id",
    "span",
    "start_span",
]


def new_id() -> int:
    """A random non-zero 63-bit id (fits int64 on the wire)."""
    (v,) = struct.unpack("<Q", os.urandom(8))
    return (v >> 1) or 1


def _hex(i: int) -> str:
    return f"{int(i):016x}"


_tls = threading.local()


def current_span() -> "Span | None":
    """The innermost ``with span(...)`` on THIS thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def link_fields(ctx) -> dict:
    """``link_*`` span fields from a wire trace context tuple
    ``(trace_id, span_id, robot, t_mono, t_wall)`` (the shape
    ``comms.protocol.unpack_trace_entries`` returns)."""
    trace_id, span_id, robot, t_mono, t_wall = ctx
    return {"link_trace": _hex(trace_id), "link_span": _hex(span_id),
            "link_robot": int(robot), "link_t_mono": float(t_mono),
            "link_t_wall": float(t_wall)}


class Span:
    """One open span; emits its ``span`` event exactly once on ``end()``.

    Constructed ONLY behind a ``get_run() is not None`` guard (use
    ``span()`` / ``start_span()``) — construction is the telemetry-on
    path by definition, which is what makes the zero-overhead test's
    ``Span.__init__``-throws patch a complete fence."""

    __slots__ = ("run", "name", "phase", "robot", "trace_id", "span_id",
                 "parent_id", "t0_mono", "t0_wall", "_counters", "_link",
                 "_ended")

    def __init__(self, run, name: str, phase: str | None = None,
                 robot: int | None = None, trace_id: int | None = None,
                 parent_id: int | None = None, link=None):
        self.run = run
        self.name = str(name)
        self.phase = phase
        self.robot = robot
        self.span_id = new_id()
        parent = current_span()
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else new_id()
        if parent_id is None and parent is not None:
            parent_id = parent.span_id
        self.trace_id = int(trace_id)
        self.parent_id = parent_id
        self._link = link
        self._counters: dict = {}
        self._ended = False
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()

    def add(self, **counters) -> "Span":
        """Attach counters; they ride the close event."""
        self._counters.update(counters)
        return self

    def end(self, **counters) -> None:
        if self._ended:
            return
        self._ended = True
        if counters:
            self._counters.update(counters)
        fields = {"name": self.name, "trace": _hex(self.trace_id),
                  "span": _hex(self.span_id), "t0_mono": self.t0_mono,
                  "t0_wall": self.t0_wall,
                  "dur_s": time.monotonic() - self.t0_mono}
        if self.robot is not None:
            fields["robot"] = int(self.robot)
        if self.parent_id:
            fields["parent"] = _hex(self.parent_id)
        if self._link is not None:
            fields.update(link_fields(self._link))
        fields.update(self._counters)
        self.run.events.emit("span", phase=self.phase, **fields)

    # -- context manager (pushes onto the thread-local parent stack) --------

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        self.end(error=repr(exc)) if exc is not None else self.end()
        return False


class _NullSpan:
    """The telemetry-off span: every operation is a no-op."""

    __slots__ = ()
    trace_id = 0
    span_id = 0

    def add(self, **counters):
        return self

    def end(self, **counters):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_SPAN = _NullSpan()


def start_span(name: str, phase: str | None = None,
               robot: int | None = None, link=None, run=None):
    """Open a span (NOT pushed on the parent stack), or None with
    telemetry off.  Callers that need the ids (wire stamping) use the
    None return as their fence."""
    run = get_run() if run is None else run
    if run is None:
        return None
    return Span(run, name, phase=phase, robot=robot, link=link)


def span(name: str, phase: str | None = None, robot: int | None = None,
         link=None, **counters):
    """``with span("publish", phase="comms", robot=2): ...`` — a no-op
    context manager with telemetry off, a parent-stack-participating
    ``Span`` otherwise."""
    run = get_run()
    if run is None:
        return NULL_SPAN
    sp = Span(run, name, phase=phase, robot=robot, link=link)
    if counters:
        sp.add(**counters)
    return sp


def emit_span(run, name: str, t0_mono: float, t0_wall: float, dur_s: float,
              phase: str | None = None, robot: int | None = None,
              link=None, trace_id: int | None = None,
              parent_id: int | None = None, **counters) -> None:
    """Emit a complete span from already-measured times — for hot paths
    (``PGOAgent.iterate``, the eval readback) that time themselves and
    must not pay a second clock read.  ``run`` is the caller's
    already-resolved ambient run (the caller's guard IS the fence).

    ``trace_id``/``parent_id`` pin the span into an explicit trace instead
    of the thread-local one — the serving plane's worker thread emits
    per-request spans (queue wait, reply) into each request's trace this
    way, because the request's trace lives on the submitter's thread, not
    the worker's."""
    parent = current_span()
    if trace_id is None:
        trace_id = parent.trace_id if parent is not None else new_id()
    if parent_id is None and parent is not None:
        parent_id = parent.span_id
    fields = {"name": str(name), "t0_mono": float(t0_mono),
              "t0_wall": float(t0_wall), "dur_s": float(dur_s),
              "span": _hex(new_id()), "trace": _hex(trace_id)}
    if parent_id:
        fields["parent"] = _hex(parent_id)
    if robot is not None:
        fields["robot"] = int(robot)
    if link is not None:
        fields.update(link_fields(link))
    fields.update(counters)
    run.events.emit("span", phase=phase, **fields)
