"""Compile & device profiling for the serving plane.

A batched Burer–Monteiro RBCD service has two dominant costs the flat
serving events never showed: XLA compiles filling the
``serve.cache.ExecutableCache`` (seconds per bucket on CPU, tens of
seconds on TPU) and device time/HBM per padded bucket.  This module makes
both observable without touching the solver math:

* ``ProfiledExecutable`` wraps a jitted program from the executable
  cache.  With telemetry on, each distinct static-argument combination is
  lowered and AOT-compiled exactly once, the compile wall-time split into
  trace/lower vs. XLA compile, and the compiled executable's
  ``cost_analysis()`` / ``memory_analysis()`` (flops, bytes accessed,
  temp/argument/output HBM) recorded as one ``compile_profile`` event per
  fingerprint key plus ``serve_compile_seconds_total`` /
  ``serve_compile_flops`` metrics.  The AOT-compiled executable is then
  what every later dispatch calls, so the profiled path compiles each
  program once — same count as the unprofiled jit path.  With telemetry
  off the wrapper is never constructed (the cache stores the bare jit
  wrapper), so the fence stays airtight: no ``lower()``/``cost_analysis``
  calls exist on the off path for the zero-overhead boom test to trip.

* ``ProfilerWindow`` is the opt-in ``jax.profiler`` trace window: started
  before the first batch dispatch, stopped after the first K, writing a
  TensorBoard-loadable device profile under ``profile_dir``.  Constructed
  only behind the telemetry fence (``SolveServer`` refuses to build one
  with telemetry off, even when ``--profile-dir`` is set).

Analysis extraction is defensive throughout: backends differ in what
``cost_analysis``/``memory_analysis`` expose (dict vs. list-of-dict vs.
unimplemented), and profiling must never break a solve — every probe
degrades to "field absent", never to an exception on the dispatch path.
"""

from __future__ import annotations

import threading
import time

from .run import get_run

__all__ = [
    "ProfiledExecutable",
    "ProfilerWindow",
    "aot_compile_profile",
]

#: memory_analysis attributes worth recording, exported under these keys.
_MEMORY_FIELDS = (
    ("temp_size_in_bytes", "temp_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("generated_code_size_in_bytes", "code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)

#: cost_analysis keys worth recording, exported under these names.
_COST_FIELDS = (
    ("flops", "flops"),
    ("transcendentals", "transcendentals"),
    ("bytes accessed", "bytes_accessed"),
)


def _cost_fields(compiled) -> dict:
    """Flatten ``compiled.cost_analysis()`` to the stable field subset.
    Older jax returns a list with one dict per device program; newer
    returns the dict directly; some backends raise."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for key, name in _COST_FIELDS:
        v = cost.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _memory_fields(compiled) -> dict:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for attr, name in _MEMORY_FIELDS:
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def aot_compile_profile(run, jitfn, args, kwargs, key: str, label: str,
                        phase: str = "serve", metric_prefix: str = "serve",
                        **extra):
    """Lower + AOT-compile ``jitfn`` for these arguments, recording the
    compile profile under fingerprint ``key``; returns the compiled
    executable (the thing to dispatch from now on).

    One ``compile_profile`` event carries: the fingerprint key, the
    program label (segment/metrics/finalize), trace/lower vs. XLA compile
    wall seconds, whatever cost/memory analysis the backend exposes, and
    — when both flops and bytes-accessed are known — the bytes-per-flop
    roofline ratio (arithmetic intensity's reciprocal: how memory-bound
    the program is).  ``phase``/``metric_prefix`` scope the event and
    metric names to the emitting plane (``serve`` for the executable
    cache, ``solve``/``sharded`` via ``devprof.profiled_program``).
    ``run`` is the caller's already-resolved ambient run — the caller's
    fence, like ``emit_span``."""
    t0 = time.monotonic()
    lowered = jitfn.lower(*args, **kwargs)
    t_lower = time.monotonic()
    compiled = lowered.compile()
    t_done = time.monotonic()
    fields = {"key": key, "label": label,
              "lower_s": t_lower - t0, "compile_s": t_done - t_lower,
              "total_s": t_done - t0}
    fields.update(_cost_fields(compiled))
    fields.update(_memory_fields(compiled))
    if fields.get("flops", 0) > 0 and "bytes_accessed" in fields:
        fields["bytes_per_flop"] = fields["bytes_accessed"] / fields["flops"]
    fields.update(extra)
    run.event("compile_profile", phase=phase, **fields)
    run.counter(f"{metric_prefix}_compile_seconds_total",
                "wall-clock spent in XLA compiles of profiled executables",
                unit="s").inc(t_done - t0, label=label)
    if "flops" in fields:
        run.gauge(f"{metric_prefix}_compile_flops",
                  "XLA cost-analysis flops of the last compiled "
                  "executable").set(fields["flops"], label=label)
    if "temp_bytes" in fields:
        run.gauge(f"{metric_prefix}_compile_temp_bytes",
                  "XLA memory-analysis temp allocation of the last "
                  "compiled executable",
                  unit="bytes").set(fields["temp_bytes"], label=label)
    if "bytes_per_flop" in fields:
        run.gauge(f"{metric_prefix}_bytes_per_flop",
                  "roofline ratio (bytes accessed / flop) of the last "
                  "compiled executable").set(fields["bytes_per_flop"],
                                             label=label)
    return compiled


class ProfiledExecutable:
    """A cache entry that profiles its compiles.

    Wraps the jitted program the executable cache would otherwise store
    directly.  Each distinct static-argument combination (``uw``/``rs``
    for RBCD segments) is AOT-compiled exactly once through
    ``aot_compile_profile``; later calls dispatch the compiled executable
    with the static kwargs stripped (they are baked into the program).
    If telemetry vanished since construction, falls back to the plain jit
    wrapper — correctness never depends on the run outliving the cache.
    """

    def __init__(self, jitfn, key: str, label: str,
                 static_names: tuple = (), **extra):
        self._jitfn = jitfn
        self._key = str(key)
        self._label = str(label)
        self._static = tuple(static_names)
        self._extra = dict(extra)
        self._compiled: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        run = get_run()
        if run is None:
            return self._jitfn(*args, **kwargs)
        combo = tuple(sorted(
            (k, kwargs[k]) for k in self._static if k in kwargs))
        with self._lock:
            compiled = self._compiled.get(combo)
        if compiled is None:
            compiled = aot_compile_profile(
                run, self._jitfn, args, kwargs, self._key, self._label,
                static=dict(combo) or None, **self._extra)
            with self._lock:
                self._compiled.setdefault(combo, compiled)
        dyn = {k: v for k, v in kwargs.items() if k not in self._static}
        return compiled(*args, **dyn)


class ProfilerWindow:
    """Opt-in ``jax.profiler`` capture of the first K batch dispatches.

    ``batch_begin()`` starts the trace before the first profiled batch;
    ``batch_end()`` counts it down and stops the trace after the K-th —
    one contiguous window covering exactly the cold-start batches where
    compiles and first dispatches happen.  Start/stop failures disable
    the window (profiling must never take the server down) and are
    reported as a ``profiler_error`` event when a run is live."""

    def __init__(self, profile_dir: str, num_batches: int = 3):
        self.profile_dir = str(profile_dir)
        self.remaining = max(1, int(num_batches))
        self._active = False
        self._dead = False
        self._lock = threading.Lock()

    def batch_begin(self) -> None:
        with self._lock:
            if self._dead or self._active or self.remaining <= 0:
                return
            try:
                import jax

                jax.profiler.start_trace(self.profile_dir)
                self._active = True
            except Exception as e:
                self._dead = True
                run = get_run()
                if run is not None:
                    run.event("profiler_error", phase="serve",
                              error=repr(e))

    def batch_end(self) -> None:
        with self._lock:
            if not self._active:
                return
            self.remaining -= 1
            if self.remaining > 0:
                return
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                self._dead = True
                run = get_run()
                if run is not None:
                    run.event("profiler_error", phase="serve",
                              error=repr(e))
            finally:
                self._active = False
                run = get_run()
                if run is not None and not self._dead:
                    run.event("profiler_window", phase="serve",
                              profile_dir=self.profile_dir)

    def close(self) -> None:
        """Stop a still-open window (server shutting down mid-capture)."""
        with self._lock:
            if self._active:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._active = False
