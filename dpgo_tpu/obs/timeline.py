"""Fleet timeline: merge per-robot event streams, align clocks, export a
Perfetto-loadable Chrome trace.

Each robot process writes its own ``events.jsonl`` with its own monotonic
clock — an island.  This module joins the islands:

1. **Clock alignment.**  Every stamped frame (heartbeats included) the
   comms layer receives with telemetry on produced a ``clock_sample``
   event: the sender's clock at send (``t_send_mono``) next to the
   receiver's clock at receipt (the event's own ``t_mono``).  A one-way
   delta ``recv - send`` equals ``offset + latency``; with samples in
   both directions the latency cancels in
   ``(median(a->b) - median(b->a)) / 2`` and the remainder is the
   pairwise clock offset, reported with an uncertainty of half the
   median round-trip plus the sample spread (MAD).  Offsets propagate
   from a reference stream (the bus hub when present) over the sample
   graph, so robots that never exchanged directly still land on one
   timeline through the hub.  One-direction-only pairs cannot separate
   offset from latency — they are used with the latency bias left in and
   flagged ``bidirectional: false`` with a wider uncertainty.

2. **Span merge.**  All events are rebased onto the reference clock
   (``t_mono``, ``t0_mono``, and ``link_t_mono`` fields shifted by the
   stream offset) and tagged with their source stream.

3. **Chrome trace export.**  ``to_chrome_trace`` renders one process per
   robot (the bus hub is its own track), threads split by phase
   (compute / comms / solver), spans as complete (``X``) events, select
   events (``peer_lost``, solve lifecycle) as instants, and every
   cross-robot ``link_*`` span edge as a flow arrow (``s``/``f``) from
   the sender's publish to the receiver's scatter.  Load the file in
   https://ui.perfetto.dev or ``chrome://tracing``.

CLI::

    python -m dpgo_tpu.obs.timeline RUN_DIR [RUN_DIR...] \
        [-o trace.json] [--report]

Pure host-side: reads JSONL, writes JSON, touches no devices.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
from collections import defaultdict

import numpy as np

from .events import read_events_meta
from .run import EVENTS_FILE

#: Span names that are blocking waits on the wire (the robot is idle).
WAIT_SPANS = ("collect", "exchange_wait", "drain")
#: Span names that measure wire work (hidden under compute in overlap
#: mode when the worker thread runs them).
WIRE_SPANS = ("publish", "collect", "wire_round", "bus_round")


# ---------------------------------------------------------------------------
# Streams
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Stream:
    """One event file = one clock domain."""

    path: str
    events: list
    truncated: bool
    robots: set                      # robot ids whose spans live here
    home: int | None = None          # the stream's own robot (-1 = bus)
    offset: float = 0.0              # seconds; subtract to rebase
    uncertainty: float | None = None
    aligned: bool = True             # False: no sample path to reference


def _events_path(path: str) -> str:
    """Accept a run dir (holding ``events.jsonl``) or a jsonl file."""
    if os.path.isdir(path):
        return os.path.join(path, EVENTS_FILE)
    return path


def load_stream(path: str) -> Stream:
    ev_path = _events_path(path)
    events, truncated = read_events_meta(ev_path)
    robots = set()
    tally: dict = defaultdict(int)
    for e in events:
        if e.get("event") == "span" and "robot" in e:
            robots.add(int(e["robot"]))
            tally[int(e["robot"])] += 1
    # Home preference: a fleet-plane actor (multihost rank <= -100 /
    # procs replica <= -200 / launcher -5 — comms.protocol's bands)
    # identifies the PROCESS that wrote this stream, so it wins over the
    # solver's per-agent robot ids even when agent spans outnumber the
    # plane's barrier/boot spans.
    plane = {r: n for r, n in tally.items() if r <= -100 or r == -5}
    if plane:
        home = max(plane, key=plane.get)
    else:
        home = max(tally, key=tally.get) if tally else None
    return Stream(path=path, events=events, truncated=truncated,
                  robots=robots, home=home)


def robot_stream_map(streams: list[Stream]) -> dict:
    """robot id -> index of the stream that owns its spans (first wins)."""
    out: dict = {}
    for i, s in enumerate(streams):
        for r in sorted(s.robots):
            out.setdefault(r, i)
    return out


# ---------------------------------------------------------------------------
# Clock-offset estimation
# ---------------------------------------------------------------------------

def _median(xs):
    return float(np.median(np.asarray(xs, np.float64)))


def _mad(xs):
    a = np.asarray(xs, np.float64)
    return float(1.4826 * np.median(np.abs(a - np.median(a))))


def pairwise_deltas(streams: list[Stream],
                    robot_of: dict) -> dict:
    """``{(sender_stream, receiver_stream): [recv_mono - send_mono]}``
    from every ``clock_sample`` event; same-stream samples (loopback:
    identical clock) are dropped."""
    deltas: dict = defaultdict(list)
    for j, s in enumerate(streams):
        for e in s.events:
            if e.get("event") != "clock_sample":
                continue
            src = e.get("src")
            if src is None or src == -2:
                continue
            i = robot_of.get(int(src))
            if i is None or i == j:
                continue
            try:
                deltas[(i, j)].append(
                    float(e["t_mono"]) - float(e["t_send_mono"]))
            except (KeyError, TypeError, ValueError):
                continue
    return dict(deltas)


def estimate_offsets(streams: list[Stream]) -> dict:
    """Estimate per-stream clock offsets relative to a reference stream
    and write them onto the ``Stream`` objects.

    Reference choice: the stream owning the bus hub (robot -1) when
    present — every robot exchanges with the hub, so it is the natural
    center of the sample graph — else the fleet launcher/manager
    (actor -5: it exchanges spawn/harvest/heartbeat samples with every
    rank and replica), else the stream owning robot 0, else stream 0.
    Returns a report dict (per-stream offset, uncertainty, sample
    counts, pair diagnostics)."""
    robot_of = robot_stream_map(streams)
    ref = robot_of.get(-1, robot_of.get(-5, robot_of.get(0, 0)))
    deltas = pairwise_deltas(streams, robot_of)

    # Symmetric pair estimates: offset o[j] - o[i] for each sampled pair.
    pair_est: dict = {}
    seen = set()
    for (i, j) in deltas:
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        a, b = key
        d_ab, d_ba = deltas.get((a, b)), deltas.get((b, a))
        if d_ab and d_ba:
            med_ab, med_ba = _median(d_ab), _median(d_ba)
            off = (med_ab - med_ba) / 2.0        # clock_b - clock_a
            half_rtt = max(0.0, (med_ab + med_ba) / 2.0)
            unc = half_rtt + max(_mad(d_ab), _mad(d_ba))
            pair_est[key] = {"offset": off, "uncertainty": unc,
                             "bidirectional": True,
                             "samples": len(d_ab) + len(d_ba)}
        else:
            d, sign = (d_ab, 1.0) if d_ab else (d_ba, -1.0)
            med = _median(d)
            # One-way: the (nonnegative) latency is inseparable from the
            # offset — keep the biased estimate, widen the uncertainty.
            pair_est[key] = {"offset": sign * med,
                             "uncertainty": abs(med) + _mad(d),
                             "bidirectional": False, "samples": len(d)}

    # Propagate from the reference over the pair graph (BFS).
    for s in streams:
        s.offset, s.uncertainty, s.aligned = 0.0, None, False
    streams[ref].offset, streams[ref].uncertainty = 0.0, 0.0
    streams[ref].aligned = True
    frontier = [ref]
    while frontier:
        i = frontier.pop()
        for (a, b), est in pair_est.items():
            for (src, dst, sign) in ((a, b, 1.0), (b, a, -1.0)):
                if src == i and not streams[dst].aligned:
                    streams[dst].offset = \
                        streams[i].offset + sign * est["offset"]
                    streams[dst].uncertainty = \
                        (streams[i].uncertainty or 0.0) + est["uncertainty"]
                    streams[dst].aligned = True
                    frontier.append(dst)

    return {
        "reference": streams[ref].path,
        "streams": [{
            "path": s.path, "home": s.home,
            "offset_s": round(s.offset, 6),
            "uncertainty_s": (None if s.uncertainty is None
                              else round(s.uncertainty, 6)),
            "aligned": s.aligned, "truncated": s.truncated,
        } for s in streams],
        "pairs": [{
            "streams": [streams[a].path, streams[b].path],
            "offset_s": round(est["offset"], 6),
            "uncertainty_s": round(est["uncertainty"], 6),
            "bidirectional": est["bidirectional"],
            "samples": est["samples"],
        } for (a, b), est in sorted(pair_est.items())],
    }


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Timeline:
    """Merged, clock-rebased view over N streams."""

    streams: list
    events: list            # rebased copies, sorted by t_mono, + _stream
    offsets: dict           # the estimate_offsets report
    robot_of: dict          # robot id -> stream index


_REBASE_FIELDS = ("t_mono", "t0_mono")


def merge(paths: list[str]) -> Timeline:
    """Load, align, and rebase the given run dirs / event files onto the
    reference clock."""
    streams = [load_stream(p) for p in paths]
    report = estimate_offsets(streams)
    robot_of = robot_stream_map(streams)
    merged = []
    for i, s in enumerate(streams):
        for e in s.events:
            e2 = dict(e)
            for f in _REBASE_FIELDS:
                if f in e2 and isinstance(e2[f], (int, float)):
                    e2[f] = float(e2[f]) - s.offset
            # link_t_mono is on the SENDER's clock — rebase by the
            # sender's stream offset, not the receiver's.
            if "link_t_mono" in e2 and "link_robot" in e2:
                li = robot_of.get(int(e2["link_robot"]))
                off = streams[li].offset if li is not None else s.offset
                e2["link_t_mono"] = float(e2["link_t_mono"]) - off
            e2["_stream"] = i
            merged.append(e2)
    merged.sort(key=lambda e: e.get("t_mono", 0.0))
    return Timeline(streams=streams, events=merged, offsets=report,
                    robot_of=robot_of)


# ---------------------------------------------------------------------------
# Fleet statistics (the report CLI's "fleet timeline" section)
# ---------------------------------------------------------------------------

def fleet_timeline_stats(events: list[dict]) -> dict | None:
    """Busy/wait/wire breakdown per robot, per-round critical path,
    straggler ranking, and overlap efficiency from ``span`` events (raw
    or merged).  None when the stream carries no spans."""
    spans = [e for e in events if e.get("event") == "span"]
    if not spans:
        return None
    per = defaultdict(lambda: {"busy_s": 0.0, "wait_s": 0.0, "wire_s": 0.0,
                               "hidden_wire_s": 0.0, "iterations": 0,
                               "iter_durs": []})
    rounds: dict = defaultdict(list)   # iteration -> [(t0, t1, robot)]
    flows = 0
    t_lo, t_hi = math.inf, -math.inf
    for e in spans:
        dur = float(e.get("dur_s", 0.0))
        t0 = float(e.get("t0_mono", 0.0))
        t_lo, t_hi = min(t_lo, t0), max(t_hi, t0 + dur)
        if "link_span" in e:
            flows += 1
        r = e.get("robot")
        if r is None:
            continue
        row = per[int(r)]
        name = e.get("name", "")
        if e.get("phase") == "compute":
            row["busy_s"] += dur
            if name == "iterate":
                row["iterations"] += 1
                row["iter_durs"].append(dur)
                if "iteration" in e:
                    rounds[int(e["iteration"])].append(
                        (t0, t0 + dur, int(r)))
        elif name in WAIT_SPANS:
            row["wait_s"] += dur
        if name == "wire_round":
            row["hidden_wire_s"] += dur
        if name in WIRE_SPANS:
            row["wire_s"] += dur

    robots = {}
    for r, row in sorted(per.items()):
        durs = row.pop("iter_durs")
        mean_it = float(np.mean(durs)) if durs else None
        hidden = row["hidden_wire_s"]
        eff = None
        if hidden > 0:
            # Overlap efficiency: the worker's wire time that did NOT
            # resurface as caller-side blocking (exchange_wait + drain).
            eff = max(0.0, min(1.0, 1.0 - row["wait_s"] / hidden))
        robots[r] = {**{k: round(v, 6) for k, v in row.items()},
                     "mean_iterate_s": (None if mean_it is None
                                        else round(mean_it, 6)),
                     "overlap_efficiency": (None if eff is None
                                            else round(eff, 4))}

    crit = defaultdict(int)
    makespans = []
    for it, rows in rounds.items():
        if len(rows) < 2:
            continue
        start = min(t0 for t0, _, _ in rows)
        end, crit_robot = max((t1, r) for _, t1, r in rows)
        makespans.append(end - start)
        crit[crit_robot] += 1
    round_stats = None
    if makespans:
        round_stats = {
            "rounds": len(makespans),
            "mean_makespan_s": round(float(np.mean(makespans)), 6),
            "p95_makespan_s": round(float(np.percentile(makespans, 95)), 6),
            "critical_path_counts": dict(sorted(
                crit.items(), key=lambda kv: -kv[1])),
        }

    stragglers = sorted(
        ((r, row["mean_iterate_s"]) for r, row in robots.items()
         if row["mean_iterate_s"] is not None and r >= 0),
        key=lambda kv: -(kv[1] or 0.0))
    return {
        "window_s": round(t_hi - t_lo, 6) if t_hi > t_lo else 0.0,
        "num_spans": len(spans),
        "num_flow_links": flows,
        "robots": robots,
        "round_critical_path": round_stats,
        "straggler_ranking": [
            {"robot": r, "mean_iterate_s": round(d, 6)}
            for r, d in stragglers],
    }


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

#: phase -> thread id inside each robot's process track.
_PHASE_TID = {"compute": 0, "comms": 1, "solve": 2, "eval": 2, "serve": 4}
_TID_NAMES = {0: "compute", 1: "comms", 2: "solver", 3: "events",
              4: "serving"}

#: Events rendered as instants on the timeline.  The fleet plane
#: (ISSUE 20) adds process/generation lifecycle instants — a kill -9
#: renders as ``process_lost`` on the victim's own track.
_INSTANT_EVENTS = ("peer_lost", "solve_start", "solve_end", "run_start",
                   "run_end", "agent_state", "overlap_decision",
                   "process_lost", "generation_start", "generation_end",
                   "generation_postmortem", "replica_postmortem",
                   "verdict_publish")

#: The device-attribution track (ISSUE 16): ``device_attribution``
#: events carry window-relative XLA op slices; they render as their own
#: process with one thread per device lane, far above the robot pids.
_PID_DEVICE = 1000

#: Fleet-plane track bands (ISSUE 20), mirroring the actor-id bands in
#: ``comms.protocol``: the launcher/manager (actor -5) gets its own
#: track, multihost rank r (actor -100-r) the 300 band, out-of-process
#: replica i (actor -200-i) the 500 band — all visually separated from
#: robots (2+) and below/around the device track.
_PID_LAUNCHER = 200
_PID_RANK_BASE = 300
_PID_REPLICA_BASE = 500


def _pid(robot) -> int:
    """Track id: 0 = host/driver, 1 = bus hub, 2+r = robot r, plus the
    fleet bands above.  The serving-plane origin sentinels (-3/-4,
    ``comms.protocol.ORIGIN_SERVE_*``) map onto the host track — serve
    spans carry no robot, so their flow arrows must start where the
    spans render."""
    if robot is None:
        return 0
    robot = int(robot)
    if robot <= -200:
        return _PID_REPLICA_BASE + (-robot - 200)
    if robot <= -100:
        return _PID_RANK_BASE + (-robot - 100)
    if robot == -5:
        return _PID_LAUNCHER
    if robot <= -3:
        return 0
    return 1 if robot < 0 else 2 + robot


def _pid_name(pid: int) -> str:
    if pid == 0:
        return "host"
    if pid == 1:
        return "bus"
    if pid == _PID_LAUNCHER:
        return "launcher"
    if _PID_RANK_BASE <= pid < _PID_REPLICA_BASE:
        return f"rank {pid - _PID_RANK_BASE}"
    if pid >= _PID_REPLICA_BASE:
        return f"replica {pid - _PID_REPLICA_BASE}"
    return f"robot {pid - 2}"


def to_chrome_trace(timeline: Timeline) -> dict:
    """Chrome trace-event JSON (dict) from a merged timeline."""
    evs = timeline.events
    t_base = min((e["t0_mono"] for e in evs
                  if e.get("event") == "span" and "t0_mono" in e),
                 default=min((e.get("t_mono", 0.0) for e in evs),
                             default=0.0))

    def us(t):
        return round((float(t) - t_base) * 1e6, 3)

    out = []
    pids_used: dict = {}
    tids_used: set = set()

    def track(robot, stream_idx):
        if robot is None:
            s = timeline.streams[stream_idx]
            robot = s.home
        pid = _pid(robot)
        pids_used[pid] = _pid_name(pid)
        return pid

    flow_seq = 0
    for e in evs:
        kind = e.get("event")
        if kind == "span":
            pid = track(e.get("robot"), e["_stream"])
            tid = _PHASE_TID.get(e.get("phase"), 3)
            tids_used.add((pid, tid))
            args = {k: v for k, v in e.items()
                    if k not in ("event", "name", "phase", "seq", "run",
                                 "t_wall", "t_mono", "t0_mono", "t0_wall",
                                 "dur_s", "_stream")}
            rec = {"name": e.get("name", "span"),
                   "cat": e.get("phase") or "span", "ph": "X",
                   "ts": us(e["t0_mono"]),
                   "dur": max(round(float(e.get("dur_s", 0.0)) * 1e6, 3),
                              0.001),
                   "pid": pid, "tid": tid, "args": args}
            out.append(rec)
            if "link_span" in e and "link_t_mono" in e:
                # Flow arrow: sender publish -> this span.  One unique id
                # per edge (a publish fans out to many receivers; each
                # edge is its own s/f pair so every arrow renders).
                flow_seq += 1
                fid = f"{e['link_span']}.{flow_seq}"
                spid = _pid(e.get("link_robot"))
                pids_used[spid] = _pid_name(spid)
                tids_used.add((spid, 1))
                s_ts = us(e["link_t_mono"])
                f_ts = max(rec["ts"], s_ts)  # clamp: offset noise must
                out.append({"name": "frame", "cat": "frame", "ph": "s",
                            "id": fid, "pid": spid, "tid": 1, "ts": s_ts})
                out.append({"name": "frame", "cat": "frame", "ph": "f",
                            "bp": "e", "id": fid, "pid": pid, "tid": tid,
                            "ts": f_ts})  # not break s<=f ordering
        elif kind == "device_attribution":
            # Device track: the window's XLA op slices, anchored so the
            # window ENDS at the event's (rebased) emission stamp — the
            # slices' t0_s are window-relative.  Alignment to host spans
            # is as good as the stop-to-emit latency (attribution parse
            # time), which is fine for a visual correlation track.
            window_s = float(e.get("window_s") or 0.0)
            anchor = float(e.get("t_mono", t_base)) - window_s
            pids_used[_PID_DEVICE] = "device"
            for sl in e.get("slices") or []:
                tid = int(sl.get("lane", 0))
                tids_used.add((_PID_DEVICE, tid))
                out.append({
                    "name": str(sl.get("op", "op")),
                    "cat": str(sl.get("kind", "compute")), "ph": "X",
                    "ts": us(anchor + float(sl.get("t0_s", 0.0))),
                    "dur": max(round(float(sl.get("dur_s", 0.0)) * 1e6, 3),
                               0.001),
                    "pid": _PID_DEVICE, "tid": tid,
                    "args": {"kind": sl.get("kind"),
                             "label": e.get("label"),
                             "plane": e.get("phase")}})
        elif kind in _INSTANT_EVENTS:
            pid = track(e.get("robot"), e["_stream"])
            tids_used.add((pid, 3))
            args = {k: v for k, v in e.items()
                    if k not in ("event", "seq", "run", "t_wall", "t_mono",
                                 "_stream")}
            out.append({"name": kind, "cat": "event", "ph": "i",
                        "s": "p", "ts": us(e.get("t_mono", t_base)),
                        "pid": pid, "tid": 3, "args": args})

    meta = []
    for pid, name in sorted(pids_used.items()):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": name}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "args": {"sort_index": pid}})
    for pid, tid in sorted(tids_used):
        tname = f"device lane {tid}" if pid == _PID_DEVICE \
            else _TID_NAMES.get(tid, "events")
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": tname}})

    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"clock_alignment": timeline.offsets}}


def write_chrome_trace(path: str, timeline: Timeline) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(to_chrome_trace(timeline), fh)
    os.replace(tmp, path)
    return path


def validate_chrome_trace(obj) -> dict:
    """Structural validation of an exported trace (dict or file path).
    Raises ``ValueError`` on schema violations; returns summary counts —
    the round-trip check the CI smoke runs on the exported file."""
    if isinstance(obj, str):
        with open(obj) as fh:
            obj = json.load(fh)
    if not isinstance(obj, dict) or \
            not isinstance(obj.get("traceEvents"), list):
        raise ValueError("not a Chrome trace: missing traceEvents list")
    spans = 0
    flow_s: dict = {}
    flow_f: dict = {}
    pids = set()
    for e in obj["traceEvents"]:
        ph = e.get("ph")
        if ph is None or "pid" not in e:
            raise ValueError(f"trace event missing ph/pid: {e}")
        pids.add(e["pid"])
        if ph == "M":
            continue
        if not isinstance(e.get("ts"), (int, float)):
            raise ValueError(f"trace event missing numeric ts: {e}")
        if ph == "X":
            spans += 1
            if not isinstance(e.get("dur"), (int, float)) or e["dur"] < 0:
                raise ValueError(f"X event missing/negative dur: {e}")
        elif ph == "s":
            if e.get("id") in flow_s:
                raise ValueError(f"duplicate flow start id {e.get('id')}")
            flow_s[e["id"]] = e
        elif ph == "f":
            if e.get("id") in flow_f:
                raise ValueError(f"duplicate flow finish id {e.get('id')}")
            flow_f[e["id"]] = e
    if set(flow_s) != set(flow_f):
        raise ValueError(
            f"unbalanced flow events: {len(flow_s)} starts vs "
            f"{len(flow_f)} finishes")
    for fid, s in flow_s.items():
        if flow_f[fid]["ts"] < s["ts"]:
            raise ValueError(f"flow {fid} finishes before it starts")
    cross = sum(1 for fid, s in flow_s.items()
                if flow_f[fid]["pid"] != s["pid"])
    return {"spans": spans, "flows": len(flow_s),
            "cross_robot_flows": cross, "pids": len(pids)}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpgo_tpu.obs.timeline", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("inputs", nargs="+",
                    help="run directories (holding events.jsonl) or "
                         "event files, one per robot/process")
    ap.add_argument("-o", "--out", default=None,
                    help="Chrome trace output path (default: trace.json "
                         "next to the first input)")
    ap.add_argument("--report", action="store_true",
                    help="also print the fleet timeline statistics")
    args = ap.parse_args(argv)

    missing = [p for p in args.inputs
               if not os.path.exists(_events_path(p))]
    if missing:
        print(f"no events found under: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    tl = merge(args.inputs)
    if not tl.events:
        print("no events in any input stream", file=sys.stderr)
        return 2
    out = args.out
    if out is None:
        base = args.inputs[0]
        base_dir = base if os.path.isdir(base) else os.path.dirname(base)
        out = os.path.join(base_dir, "trace.json")
    write_chrome_trace(out, tl)
    counts = validate_chrome_trace(out)
    print(f"wrote {out}: {counts['spans']} spans, {counts['flows']} flow "
          f"edges ({counts['cross_robot_flows']} cross-robot) over "
          f"{counts['pids']} tracks — load in https://ui.perfetto.dev")
    for s in tl.offsets["streams"]:
        unc = ("?" if s["uncertainty_s"] is None
               else f"±{s['uncertainty_s'] * 1e3:.3f}ms")
        tag = "" if s["aligned"] else "  [UNALIGNED: no sample path]"
        tag += "  [truncated tail]" if s["truncated"] else ""
        print(f"  clock {s['path']}: offset {s['offset_s'] * 1e3:+.3f}ms "
              f"{unc}{tag}")
    if args.report:
        stats = fleet_timeline_stats(tl.events)
        print(json.dumps({"fleet_timeline": stats}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
