"""Cross-round perf ledger: every checked-in bench record, one table.

The repo accumulates one bench record per growth round —
``BENCH_r*.json`` (single-device kernel arm), ``MULTICHIP_r*.json``
(sharded mesh arm), ``FLEET_r*.json`` (serve fleet arm) — but until now
nothing read them *together*: the regress gate compares exactly two
telemetry runs, and ``check_bench_floor.py`` validates exactly one
record.  A perf question that spans rounds ("did rounds/s ever dip?",
"has overlap efficiency always been negative on this mesh?") meant
opening files by hand.

``PerfLedger`` ingests every record into a round-indexed table of
normalized rows::

    {"family": "BENCH" | "MULTICHIP" | "FLEET",
     "round":  int,            # NN from the _rNN filename
     "file":   str,            # basename, for provenance
     "ok":     bool,           # rc == 0 / record's own ok flag
     "metric": str | None,     # headline metric name (None: placeholder)
     "value":  float | None,
     "unit":   str | None,
     "extras": dict}           # trend-worthy scalars (vs_baseline,
                               # overlap_efficiency, host syncs, ...)

Early rounds are kept as honest placeholders: MULTICHIP r01–r05 predate
the sharded solver's metric record (r01 is a genuine failed run,
``ok=false``) and still appear as rows — the ledger's coverage claim is
"every round is accounted for", not "every round produced a number".

Consumers:

* ``report --ledger`` renders the trend table (``--json`` for the
  machine form, which ``tools/check_bench_floor.py`` schema-validates).
* ``regress.trend_gate`` turns a ledger into a cross-round gate: for
  each directioned trend series, the newest reading must not regress
  beyond tolerance against the best previous round.

The ledger is offline tooling over static JSON — it never rides the
solve path, and the ``PerfLedger`` constructor sits behind the same
DPG002 fence discipline as every other obs object (constructed only in
this module, via ``load_ledger``).
"""

from __future__ import annotations

import glob
import json
import math
import os
import re

__all__ = ["PerfLedger", "load_ledger", "discover_records"]

#: filename pattern -> record family.
_FAMILY_PATTERNS = (
    ("BENCH", re.compile(r"^BENCH_r(\d+)\.json$")),
    ("MULTICHIP", re.compile(r"^MULTICHIP_r(\d+)\.json$")),
    ("FLEET", re.compile(r"^FLEET_r(\d+)\.json$")),
)

#: extras lifted into trend series when present on a row, in render order.
TREND_EXTRAS = ("vs_baseline", "kernel_parity_max_abs_diff",
                "host_syncs_per_100_rounds", "overlap_efficiency",
                "scaling_1_to_2")


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def discover_records(root: str) -> list[tuple[str, int, str]]:
    """All ``(family, round, path)`` bench records under ``root``,
    sorted by family then round."""
    found = []
    for path in sorted(glob.glob(os.path.join(root, "*.json"))):
        base = os.path.basename(path)
        for family, pat in _FAMILY_PATTERNS:
            m = pat.match(base)
            if m:
                found.append((family, int(m.group(1)), path))
                break
    found.sort(key=lambda t: (t[0], t[1]))
    return found


def _normalize_bench(rec: dict) -> dict:
    """``bench.py`` driver record: {n, cmd, rc, tail, parsed:{...}}."""
    parsed = rec.get("parsed") if isinstance(rec.get("parsed"), dict) else {}
    extras = {}
    for key in ("vs_baseline", "kernel_parity_max_abs_diff", "sel_mode"):
        if key in parsed:
            extras[key] = parsed[key]
    band = parsed.get("cpu_arm_band")
    if isinstance(band, dict) and _num(band.get("min")) \
            and _num(band.get("max")):
        extras["band_min"], extras["band_max"] = band["min"], band["max"]
    return {"ok": rec.get("rc") == 0,
            "metric": parsed.get("metric"),
            "value": parsed["value"] if _num(parsed.get("value")) else None,
            "unit": parsed.get("unit"),
            "extras": extras}


def _normalize_multichip(rec: dict) -> dict:
    """Placeholder rounds carry only {n_devices, rc, ok, skipped, tail};
    the full MULTICHIP record (record=="MULTICHIP") has the metric."""
    extras = {}
    if _num(rec.get("n_devices")):
        extras["n_devices"] = rec["n_devices"]
    if rec.get("skipped"):
        extras["skipped"] = True
    if rec.get("record") != "MULTICHIP":
        return {"ok": bool(rec.get("ok")), "metric": None, "value": None,
                "unit": None, "extras": extras}
    for key in ("verdict_every", "host_syncs_per_100_rounds"):
        if _num(rec.get(key)):
            extras[key] = rec[key]
    ov = rec.get("overlap")
    if isinstance(ov, dict) and _num(ov.get("efficiency")):
        extras["overlap_efficiency"] = ov["efficiency"]
    scale = rec.get("scale_test")
    if isinstance(scale, dict) and "cert_status" in scale:
        extras["cert_status"] = scale["cert_status"]
    return {"ok": bool(rec.get("ok")),
            "metric": rec.get("metric"),
            "value": rec["value"] if _num(rec.get("value")) else None,
            "unit": rec.get("unit"),
            "extras": extras}


def _normalize_fleet(rec: dict) -> dict:
    """FLEET record: headline value = QPS of the widest replica arm."""
    extras = {}
    qps = rec.get("qps")
    value = None
    if isinstance(qps, list) and qps:
        widest = max((a for a in qps if _num(a.get("qps"))),
                     key=lambda a: a.get("replicas", 0), default=None)
        if widest is not None:
            value = widest["qps"]
            extras["replicas"] = widest.get("replicas")
    if _num(rec.get("scaling_1_to_2")):
        extras["scaling_1_to_2"] = rec["scaling_1_to_2"]
    cold = rec.get("cold_start")
    if isinstance(cold, dict) and _num(cold.get("compile_seconds_total")):
        extras["cold_compile_s"] = cold["compile_seconds_total"]
    return {"ok": bool(rec.get("ok")), "metric": "fleet_qps",
            "value": value, "unit": "problems/s", "extras": extras}


_NORMALIZERS = {"BENCH": _normalize_bench,
                "MULTICHIP": _normalize_multichip,
                "FLEET": _normalize_fleet}


class PerfLedger:
    """The round-indexed trend table (see module docstring).

    Rows are immutable once loaded; accessors slice them into per-family
    trend series for the report renderer and the regress trend gate.
    """

    def __init__(self, rows: list[dict], root: str = "."):
        self.rows = list(rows)
        self.root = str(root)

    # -- accessors ---------------------------------------------------

    def families(self) -> list[str]:
        return sorted({r["family"] for r in self.rows})

    def family_rows(self, family: str) -> list[dict]:
        return [r for r in self.rows if r["family"] == family]

    def series(self, family: str, key: str = "value") -> list[tuple]:
        """``(round, value)`` trend for a family; ``key`` is ``"value"``
        (the headline metric) or an extras key.  Placeholder rounds
        (no reading) are skipped."""
        out = []
        for r in self.family_rows(family):
            v = r["value"] if key == "value" else r["extras"].get(key)
            if _num(v):
                out.append((r["round"], float(v)))
        return out

    # -- serialization ----------------------------------------------

    def to_json(self) -> dict:
        """The machine form ``check_bench_floor.py`` validates."""
        return {"record": "LEDGER", "root": self.root,
                "rounds": len(self.rows), "families": self.families(),
                "rows": self.rows}

    def render(self) -> str:
        lines = [f"== perf ledger: {len(self.rows)} rounds across "
                 f"{len(self.families())} families =="]
        for family in self.families():
            rows = self.family_rows(family)
            lines.append(f"[{family}] ({len(rows)} rounds)")
            lines.append(f"  {'round':>5} {'ok':<4} {'value':>12} "
                         f"{'unit':<12} extras")
            for r in rows:
                val = f"{r['value']:.6g}" if _num(r["value"]) else "-"
                unit = r["unit"] or "-"
                extras = ", ".join(
                    f"{k}={r['extras'][k]:.4g}"
                    if _num(r["extras"][k]) else f"{k}={r['extras'][k]}"
                    for k in TREND_EXTRAS + ("sel_mode", "cert_status",
                                             "n_devices", "skipped")
                    if k in r["extras"])
                ok = "ok" if r["ok"] else "FAIL"
                lines.append(f"  r{r['round']:>04d} {ok:<4} {val:>12} "
                             f"{unit:<12} {extras}")
            # Trend summary per directioned series (delta last vs first).
            for key in ("value",) + TREND_EXTRAS:
                pts = self.series(family, key)
                if len(pts) >= 2:
                    (r0, v0), (r1, v1) = pts[0], pts[-1]
                    name = "value" if key == "value" else key
                    delta = f"{100.0 * (v1 - v0) / abs(v0):+.1f}%" \
                        if abs(v0) > 0 else f"{v1 - v0:+.4g}"
                    lines.append(f"  trend {name}: r{r0:02d} {v0:.6g} -> "
                                 f"r{r1:02d} {v1:.6g} ({delta} over "
                                 f"{len(pts)} readings)")
        return "\n".join(lines)


def load_ledger(root: str = ".") -> PerfLedger:
    """Ingest every bench record under ``root`` into a ``PerfLedger``.

    Unreadable files become ``ok=false`` placeholder rows rather than
    raising — a corrupt round is a finding the ledger should show, not
    an excuse to hide the other rounds."""
    rows = []
    for family, rnd, path in discover_records(root):
        base = os.path.basename(path)
        try:
            with open(path) as fh:
                rec = json.load(fh)
            if not isinstance(rec, dict):
                raise ValueError("record is not a JSON object")
        except (OSError, ValueError) as e:
            rows.append({"family": family, "round": rnd, "file": base,
                         "ok": False, "metric": None, "value": None,
                         "unit": None, "extras": {"error": str(e)}})
            continue
        row = _NORMALIZERS[family](rec)
        row.update({"family": family, "round": rnd, "file": base})
        rows.append(row)
    return PerfLedger(rows, root=root)
