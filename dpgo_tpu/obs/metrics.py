"""Thread-safe metrics primitives: counters, gauges, histograms with labels.

The shapes follow the Prometheus data model (a *family* per name, one time
series per label set) because that is what the text exposition exports, but
the implementation is a host-side dict under one lock — metric calls happen
at phase boundaries (per round / per eval / per message), thousands per
second at most, so a single ``threading.Lock`` per registry is simpler and
plenty.  Safe from the agent's background optimization thread
(``agent.start_optimization_loop``) concurrently with a transport thread.

Values are plain floats; histograms keep cumulative bucket counts plus
sum/count (Prometheus ``_bucket``/``_sum``/``_count`` semantics).
"""

from __future__ import annotations

import math
import threading

from .events import nonfinite_str

# Default histogram buckets: geometric, spanning 100 us .. ~100 s — sized
# for round/iterate latencies, the dominant histogram use.
DEFAULT_BUCKETS = tuple(1e-4 * (10 ** (k / 3.0)) for k in range(19))


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """Base: one named metric family holding per-label-set series."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str = "",
                 unit: str = ""):
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.unit = unit
        self._series: dict[tuple, object] = {}  # guarded-by: _lock

    def _zero(self):
        return 0.0

    def _get(self, labels: dict):  # holds: _lock
        key = _label_key(labels)
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = self._zero()
        return key, series

    def series(self) -> dict[tuple, object]:
        with self._lock:
            return dict(self._series)


class Counter(_Family):
    """Monotonically increasing value (``inc`` only)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        with self._lock:
            key, cur = self._get(labels)
            self._series[key] = cur + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Family):
    """Point-in-time value (``set``/``inc``)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            key, _ = self._get(labels)
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            key, cur = self._get(labels)
            self._series[key] = cur + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus semantics).

    ``observe_many`` takes any value iterable (a numpy array included) and
    bins it in one pass — the GNC weight vector is observed per update
    round, and a Python-level per-element loop there would cost more than
    the weight computation itself.
    """

    kind = "histogram"

    def __init__(self, registry, name, help="", unit="",
                 buckets=DEFAULT_BUCKETS):
        super().__init__(registry, name, help, unit)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = tuple(b)

    def _zero(self):
        return {"counts": [0] * (len(self.buckets) + 1),  # +inf tail
                "sum": 0.0, "count": 0}

    def observe(self, value: float, **labels) -> None:
        self.observe_many((value,), **labels)

    def observe_many(self, values, **labels) -> None:
        vals = [float(v) for v in values]
        if not vals:
            return
        binned = [0] * (len(self.buckets) + 1)
        total = 0.0
        for v in vals:
            total += v
            for bi, bound in enumerate(self.buckets):
                if v <= bound:
                    binned[bi] += 1
                    break
            else:
                binned[-1] += 1
        with self._lock:
            key, series = self._get(labels)
            for bi, n in enumerate(binned):
                series["counts"][bi] += n
            series["sum"] += total
            series["count"] += len(vals)

    def snapshot_series(self, **labels) -> dict | None:
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return None
            return {"counts": list(s["counts"]), "sum": s["sum"],
                    "count": s["count"]}


class MetricsRegistry:
    """A run's metric families, keyed by name.

    Re-requesting a name returns the existing family (so call-site helpers
    need no caching), but re-requesting with a different kind raises — a
    silent kind change would corrupt the exposition.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}  # guarded-by: _lock

    def _family(self, cls, name: str, help: str, unit: str, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            new = cls(self, name, help, unit, **kw)
            with self._lock:
                fam = self._families.setdefault(name, new)
        if type(fam) is not cls:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}")
        return fam

    def counter(self, name: str, help: str = "", unit: str = "") -> Counter:
        return self._family(Counter, name, help, unit)

    def gauge(self, name: str, help: str = "", unit: str = "") -> Gauge:
        return self._family(Gauge, name, help, unit)

    def histogram(self, name: str, help: str = "", unit: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._family(Histogram, name, help, unit, buckets=buckets)

    def families(self) -> list[_Family]:
        with self._lock:
            return list(self._families.values())

    def snapshot(self) -> dict:
        """JSON-serializable view of every series of every family."""
        out = {}
        for fam in self.families():
            series = []
            for key, val in sorted(fam.series().items()):
                entry = {"labels": dict(key)}
                if isinstance(val, dict):
                    entry.update(val)
                else:
                    # One non-finite convention across the stack: the same
                    # canonical strings the Prometheus exposition and the
                    # event stream use (events.nonfinite_str), restored to
                    # floats by read_events.
                    entry["value"] = val if math.isfinite(val) \
                        else nonfinite_str(val)
                series.append(entry)
            out[fam.name] = {"kind": fam.kind, "help": fam.help,
                             "unit": fam.unit, "series": series}
            if fam.kind == "histogram":
                out[fam.name]["buckets"] = list(fam.buckets)
        return out
