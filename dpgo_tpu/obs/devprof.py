"""Device-time attribution from ``jax.profiler`` traces (solver planes).

PR-7's ``ProfilerWindow`` captures a device profile for the serving plane
but never *reads* it — the trace goes to TensorBoard and the obs stack
stays blind to what happens inside a compiled program.  That blindness is
exactly ROADMAP item 3's soft spot: the halo/compute overlap A/B showed a
*negative* efficiency on the CPU mesh (MULTICHIP_r06) and nothing could
say where the time went.  This module closes the loop:

* ``DeviceTraceWindow`` — a fence-constructed ``jax.profiler`` trace
  window over a short calibration segment (a few fused rounds of the
  sharded verdict loop, or of the single-device fused loop).  Stopping
  the window parses the emitted Chrome-format trace itself.
* ``attribute_trace`` / ``attribute_profile_dir`` — pure parsers that
  split per-device-lane XLA op time into **collective** (all-gather /
  all-reduce / collective-permute / reduce-scatter / ... matched by the
  op-name pattern table) vs **compute** vs **idle**, normalized per
  round, plus a *measured* overlap efficiency: the fraction of
  collective wall time during which some other lane was computing —
  i.e. how much of the exchange actually hid behind compute.  (On the
  CPU host-platform mesh the lanes share physical cores, so "hidden"
  concurrency still contends for cycles — which is precisely why
  overlap does not pay there; the A/B wall-clock in
  ``decide_overlap`` stays the decision authority and the attribution
  is the evidence.)
* ``decide_overlap`` — the adaptive overlap gate's arbiter: given the
  timed lockstep/overlapped arms (and their attributions when captured)
  it picks the winner and shapes the ``overlap_decision`` evidence.
* ``profiled_program`` — extends the serve cache's
  ``ProfiledExecutable``-style compile accounting (cost/memory analysis
  with the bytes-per-flop roofline ratio) to solver-plane programs,
  defensively: a failed AOT probe falls back to the plain jit callable.

Everything here is constructed and invoked strictly behind the PR-1
zero-overhead telemetry fence; the trace-parsing helpers are pure
functions usable offline (tests, ``report``).  XLA op events are
recognized by the ``args.hlo_op`` marker the profiler attaches to device
ops (host-side Python spans lack it), with one executor thread per
device lane — verified against jax 0.4.x CPU traces.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time

from .run import get_run

__all__ = [
    "COLLECTIVE_OP_PREFIXES",
    "DeviceTraceWindow",
    "attribute_profile_dir",
    "attribute_trace",
    "classify_op",
    "decide_overlap",
    "find_trace_files",
    "load_trace_events",
    "profiled_program",
]

#: Op-name prefixes that mark an XLA op as a cross-device collective.
#: Matched against ``args.hlo_op`` (HLO instruction names: the HLO op
#: kind plus a numeric suffix, e.g. ``all-gather.3``).  ``psum`` /
#: ``ppermute`` are the jax-level spellings that surface on some
#: backends' op metadata; ``send``/``recv`` are the point-to-point pair
#: ppermute lowers to on real interconnects.
COLLECTIVE_OP_PREFIXES = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-broadcast",
    "collective-permute",
    "reduce-scatter",
    "psum",
    "ppermute",
    "send",
    "recv",
)

#: Keep at most this many slices in a ``device_attribution`` event (the
#: longest ones) — enough for the timeline device track without letting
#: a long window bloat events.jsonl.
MAX_SLICES = 200

#: And at most this many distinct ops in the ``top_ops`` table.
MAX_TOP_OPS = 12


def classify_op(op_name: str) -> str:
    """``"collective"`` or ``"compute"`` for one HLO op name."""
    name = op_name.lower()
    for prefix in COLLECTIVE_OP_PREFIXES:
        if name.startswith(prefix):
            return "collective"
    return "compute"


def find_trace_files(profile_dir: str) -> list:
    """Chrome-format trace files under a ``jax.profiler`` output dir.

    jax writes ``<dir>/plugins/profile/<timestamp>/<host>.trace.json.gz``;
    accept the uncompressed spelling too and, as a last resort, any
    ``*.trace.json[.gz]`` anywhere below ``profile_dir``."""
    pats = [
        os.path.join(profile_dir, "plugins", "profile", "*",
                     "*.trace.json.gz"),
        os.path.join(profile_dir, "plugins", "profile", "*",
                     "*.trace.json"),
        os.path.join(profile_dir, "**", "*.trace.json.gz"),
        os.path.join(profile_dir, "**", "*.trace.json"),
    ]
    for pat in pats:
        found = sorted(glob.glob(pat, recursive=True))
        if found:
            return found
    return []


def load_trace_events(path: str) -> list:
    """The ``traceEvents`` list of one Chrome-format trace file."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    return [e for e in events if isinstance(e, dict)]


def _merge(intervals: list) -> list:
    """Union of [t0, t1) intervals, sorted and coalesced."""
    out = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _subtract(merged_a: list, merged_b: list) -> list:
    """Parts of merged union ``a`` not covered by merged union ``b``."""
    out = []
    j = 0
    for t0, t1 in merged_a:
        cur = t0
        while j < len(merged_b) and merged_b[j][1] <= cur:
            j += 1
        k = j
        while k < len(merged_b) and merged_b[k][0] < t1:
            if merged_b[k][0] > cur:
                out.append((cur, merged_b[k][0]))
            cur = max(cur, merged_b[k][1])
            k += 1
        if cur < t1:
            out.append((cur, t1))
    return out


def _leaf_flags(ops: list) -> list:
    """``True`` per op that contains no other op on the same lane.

    XLA traces nest: the fused-rounds ``while`` slice encloses every op
    of its body, so summing raw durations double-counts and the container
    drowns the real op mix.  Ops here are ``(t0, t1, op)`` tuples;
    ordering by (start, -duration) makes any enclosing op precede its
    children, so one stack pass marks the parents."""
    order = sorted(range(len(ops)),
                   key=lambda i: (ops[i][0], ops[i][0] - ops[i][1]))
    leaf = [True] * len(ops)
    stack: list = []
    for i in order:
        t0, t1 = ops[i][0], ops[i][1]
        while stack and ops[stack[-1]][1] <= t0:
            stack.pop()
        if stack:
            leaf[stack[-1]] = False
        stack.append(i)
    return leaf

def _overlap_len(intervals: list, merged: list) -> float:
    """Total length of ``intervals`` covered by the merged union."""
    total = 0.0
    j = 0
    for t0, t1 in sorted(intervals):
        while j > 0 and merged[j - 1][1] > t0:
            j -= 1
        k = j
        while k < len(merged) and merged[k][0] < t1:
            total += max(0.0, min(t1, merged[k][1]) - max(t0, merged[k][0]))
            k += 1
        j = max(k - 1, 0)
    return total


def attribute_trace(events: list, num_rounds: int = 1,
                    module_filter: str | None = None) -> dict:
    """Per-round device-time attribution of one trace's XLA op events.

    Device ops are the ``ph == "X"`` slices whose ``args`` carry the
    ``hlo_op`` marker; one (pid, tid) pair per device lane.  Per lane,
    collective time is the merged union of its collective-op intervals
    and compute time is the lane's busy union minus that — interval
    algebra, not duration sums, so nested slices (the fused-rounds
    ``while`` container encloses its whole body) never double-count and
    container self-time still lands in compute.  Idle is the rest of the
    window.  Returns the split (totals and per-round), the measured
    overlap efficiency (fraction of collective time concurrent with
    compute on another lane — how much of the exchange was actually
    hidden), a leaf-op ``top_ops`` table, and the longest leaf
    ``slices`` (window-relative seconds) for the timeline device track.
    """
    num_rounds = max(1, int(num_rounds))
    lanes: dict = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue
        if module_filter and module_filter not in str(
                args.get("hlo_module", "")):
            continue
        try:
            t0 = float(e["ts"])
            dur = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        op = str(args.get("hlo_op") or e.get("name", ""))
        lane = (e.get("pid", 0), e.get("tid", 0))
        lanes.setdefault(lane, []).append((t0, t0 + max(dur, 0.0), op))

    if not lanes:
        return {"lanes": 0, "num_rounds": num_rounds, "window_s": 0.0,
                "compute_s": 0.0, "collective_s": 0.0, "idle_s": 0.0,
                "per_round": {"compute_s": 0.0, "collective_s": 0.0,
                              "idle_s": 0.0},
                "collective_hidden_s": 0.0,
                "overlap_efficiency_measured": 0.0,
                "top_ops": [], "slices": []}

    t_min = min(t0 for ops in lanes.values() for t0, _t1, _op in ops)
    t_max = max(t1 for ops in lanes.values() for _t0, t1, _op in ops)
    window_us = max(t_max - t_min, 0.0)

    lane_ids = {lane: i for i, lane in enumerate(sorted(lanes))}
    compute_us = collective_us = busy_us = 0.0
    per_lane_compute: dict = {}
    per_lane_collective: dict = {}
    op_totals: dict = {}
    all_slices = []
    for lane, ops in lanes.items():
        leaf = _leaf_flags(ops)
        coll_raw = []
        for is_leaf, (t0, t1, op) in zip(leaf, ops):
            kind = classify_op(op)
            if kind == "collective":
                coll_raw.append((t0, t1))
            if is_leaf:
                base = op.rsplit(".", 1)[0] or op
                tot = op_totals.setdefault(base, [kind, 0.0, 0])
                tot[1] += t1 - t0
                tot[2] += 1
                all_slices.append((t1 - t0, lane_ids[lane], op, kind, t0))
        coll = _merge(coll_raw)
        busy = _merge([(t0, t1) for t0, t1, _op in ops])
        comp = _subtract(busy, coll)
        compute_us += sum(t1 - t0 for t0, t1 in comp)
        collective_us += sum(t1 - t0 for t0, t1 in coll)
        busy_us += sum(t1 - t0 for t0, t1 in busy)
        per_lane_compute[lane] = comp
        per_lane_collective[lane] = coll

    # Hidden collective time: per lane, its collective intervals that are
    # concurrent with compute on ANY OTHER lane (same-lane overlap cannot
    # happen on a serialized executor; on async-collective backends the
    # same-device compute stream shows up as its own lane/tid anyway).
    hidden_us = 0.0
    for lane, coll in per_lane_collective.items():
        if not coll:
            continue
        others = _merge([iv for other, comp in per_lane_compute.items()
                         if other != lane for iv in comp])
        if others:
            hidden_us += _overlap_len(coll, others)

    n_lanes = len(lanes)
    idle_us = max(n_lanes * window_us - busy_us, 0.0)
    to_s = 1e-6
    top = sorted(op_totals.items(), key=lambda kv: -kv[1][1])[:MAX_TOP_OPS]
    all_slices.sort(reverse=True)
    slices = [{"lane": lane_i, "op": op, "kind": kind,
               "t0_s": round((t0 - t_min) * to_s, 9),
               "dur_s": round(dur * to_s, 9)}
              for dur, lane_i, op, kind, t0 in all_slices[:MAX_SLICES]]
    slices.sort(key=lambda s: (s["lane"], s["t0_s"]))
    return {
        "lanes": n_lanes,
        "num_rounds": num_rounds,
        "window_s": window_us * to_s,
        "compute_s": compute_us * to_s,
        "collective_s": collective_us * to_s,
        "idle_s": idle_us * to_s,
        "per_round": {
            "compute_s": compute_us * to_s / num_rounds,
            "collective_s": collective_us * to_s / num_rounds,
            "idle_s": idle_us * to_s / num_rounds,
        },
        "collective_hidden_s": hidden_us * to_s,
        "overlap_efficiency_measured":
            (hidden_us / collective_us) if collective_us > 0 else 0.0,
        "top_ops": [{"op": op, "kind": kind, "total_s": tot * to_s,
                     "count": count}
                    for op, (kind, tot, count) in top],
        "slices": slices,
    }


def attribute_profile_dir(profile_dir: str, num_rounds: int = 1,
                          module_filter: str | None = None) -> dict | None:
    """Attribution over every trace file a profiler window emitted
    (normally one per host); ``None`` when no trace was found."""
    files = find_trace_files(profile_dir)
    if not files:
        return None
    events = []
    for path in files:
        try:
            events.extend(load_trace_events(path))
        except (OSError, ValueError):
            continue
    out = attribute_trace(events, num_rounds=num_rounds,
                          module_filter=module_filter)
    out["trace_files"] = len(files)
    return out


class DeviceTraceWindow:
    """One fence-constructed profiler capture + attribution window.

    ``start()`` opens a ``jax.profiler`` trace into ``profile_dir``;
    ``stop(num_rounds=K)`` closes it, attributes the emitted trace, and
    (when a run is still live) emits one ``device_attribution`` event
    carrying the split, the measured overlap efficiency, the top-ops
    table, and the timeline slices.  Like the serving plane's
    ``ProfilerWindow``, every failure path degrades to "no attribution"
    (plus a ``profiler_error`` event) — profiling must never take a
    solve down, and a window is only ever constructed behind
    ``get_run() is not None`` (DPG002)."""

    def __init__(self, profile_dir: str, plane: str = "sharded"):
        self.profile_dir = str(profile_dir)
        self.plane = str(plane)
        self._active = False
        self._dead = False
        self._lock = threading.Lock()

    def start(self) -> "DeviceTraceWindow":
        with self._lock:
            if self._dead or self._active:
                return self
            try:
                import jax

                jax.profiler.start_trace(self.profile_dir)
                self._active = True
            except Exception as e:
                self._dead = True
                run = get_run()
                if run is not None:
                    run.event("profiler_error", phase=self.plane,
                              error=repr(e))
        return self

    def stop(self, num_rounds: int = 1, label: str = "calibration",
             module_filter: str | None = None, **extra) -> dict | None:
        with self._lock:
            if not self._active:
                return None
            self._active = False
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                self._dead = True
                run = get_run()
                if run is not None:
                    run.event("profiler_error", phase=self.plane,
                              error=repr(e))
                return None
        try:
            attribution = attribute_profile_dir(
                self.profile_dir, num_rounds=num_rounds,
                module_filter=module_filter)
        except Exception as e:
            attribution = None
            run = get_run()
            if run is not None:
                run.event("profiler_error", phase=self.plane,
                          error=repr(e))
        run = get_run()
        if run is not None and attribution is not None:
            run.event("device_attribution", phase=self.plane, label=label,
                      profile_dir=self.profile_dir, **attribution, **extra)
            run.gauge(
                "device_overlap_efficiency_measured",
                "measured fraction of collective device time hidden "
                "behind compute (profiler attribution)").set(
                    attribution["overlap_efficiency_measured"], label=label)
        return attribution

    def close(self) -> None:
        """Abandon a still-open window without attribution."""
        with self._lock:
            if self._active:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._active = False


def decide_overlap(arms: dict, threshold: float = 0.0) -> dict:
    """The adaptive gate's arbiter: pick overlapped vs lockstep.

    ``arms`` maps ``"lockstep"``/``"overlapped"`` to dicts with at least
    ``seconds`` and ``rounds`` (plus optional ``attribution``).  The A/B
    efficiency is ``1 - t_overlapped / t_lockstep`` (positive = overlap
    pays); overlap wins when it clears ``threshold``.  Returns the
    decision record that becomes the ``overlap_decision`` event body."""
    lock = arms["lockstep"]
    over = arms["overlapped"]
    t_lock = max(float(lock["seconds"]), 1e-12)
    t_over = max(float(over["seconds"]), 1e-12)
    efficiency = 1.0 - t_over / t_lock
    chosen = efficiency > float(threshold)
    record = {
        "overlap": chosen,
        "efficiency": efficiency,
        "threshold": float(threshold),
        "lockstep_seconds": float(lock["seconds"]),
        "overlapped_seconds": float(over["seconds"]),
        "lockstep_rounds_per_s": float(lock["rounds"]) / t_lock,
        "overlapped_rounds_per_s": float(over["rounds"]) / t_over,
        "calib_rounds": int(lock["rounds"]),
    }
    for name, arm in (("lockstep", lock), ("overlapped", over)):
        attribution = arm.get("attribution")
        if attribution:
            record[f"{name}_overlap_efficiency_measured"] = \
                attribution["overlap_efficiency_measured"]
            record[f"{name}_collective_s_per_round"] = \
                attribution["per_round"]["collective_s"]
            record[f"{name}_compute_s_per_round"] = \
                attribution["per_round"]["compute_s"]
    return record


def profiled_program(run, jitfn, key: str, label: str, plane: str,
                     static_names: tuple = (), **extra):
    """Solver-plane compile accounting: a defensive, roofline-reporting
    cousin of the serve cache's ``ProfiledExecutable``.

    Returns a callable that AOT-compiles ``jitfn`` once per static-kwarg
    combination through ``profile.aot_compile_profile`` (recording
    lower/compile walls, cost/memory analysis, and the bytes-per-flop
    roofline ratio under ``phase=plane``) and dispatches the compiled
    executable from then on — the same compile count as the plain jit
    path.  Any AOT failure (an exotic arg pytree, a backend without AOT
    support) falls back permanently to the plain jit callable: compile
    accounting must never change solver behavior.  ``run`` is the
    caller's already-resolved fence, like ``aot_compile_profile``."""
    from . import profile as profile_mod

    compiled: dict = {}
    dead: list = []
    lock = threading.Lock()

    def call(*args, **kwargs):
        if dead or get_run() is None:
            return jitfn(*args, **kwargs)
        combo = tuple(sorted(
            (k, kwargs[k]) for k in static_names if k in kwargs))
        with lock:
            exe = compiled.get(combo)
        if exe is None:
            try:
                exe = profile_mod.aot_compile_profile(
                    run, jitfn, args, kwargs, key, label, phase=plane,
                    metric_prefix=plane, static=dict(combo) or None,
                    **extra)
            except Exception as e:
                dead.append(True)
                run.event("profiler_error", phase=plane, label=label,
                          error=repr(e))
                return jitfn(*args, **kwargs)
            with lock:
                compiled.setdefault(combo, exe)
        dyn = {k: v for k, v in kwargs.items() if k not in static_names}
        try:
            return exe(*args, **dyn)
        except Exception:
            # AOT dispatch rejected the call (e.g. sharding/layout drift
            # after a mesh rewind) — permanent fallback, correctness first.
            dead.append(True)
            return jitfn(*args, **kwargs)

    return call


def time_arm(fn, *args) -> float:
    """Wall seconds for one fully-materialized call of ``fn`` — the plain
    A/B timer the auto gate uses with telemetry OFF (no obs machinery:
    ``jax.block_until_ready`` is the fence)."""
    import jax

    t0 = time.monotonic()
    jax.block_until_ready(fn(*args))
    return time.monotonic() - t0
