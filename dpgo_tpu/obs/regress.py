"""Convergence regression gate: ``report --compare runA runB``.

Compares two telemetry run directories' convergence trajectories and
terminal metrics and exits non-zero on regression — the convergence
analog of the CI perf smoke.  The comparison:

* **Fingerprint gate.**  Both runs' config fingerprints (``run_summary``
  ``channel="config"`` events / ``run.json``) must agree on every shared
  identity key (dataset, num_robots, rank, schedule, wire format, ...);
  an apples-to-oranges comparison is refused with a clear message rather
  than producing a meaningless delta table.  Package version is recorded
  but never gates — comparing across versions is the point of the gate.
* **Terminal metrics with noise bands.**  For each gated metric run B's
  final value is checked against run A's tail *noise band* (min/median/
  max over the last ``tail`` evals — the ``cpu_arm_band`` schema of
  ``bench.py``'s metric_record) widened by ``rtol``.  ``GATED_METRICS``
  declares each metric's improvement direction: lower-is-better metrics
  (``solver_cost``, ...) regress when B's final exceeds A's band max
  beyond tolerance; higher-is-better metrics (``fleet_qps``) regress
  when B's final drops below A's band min.  Either way a non-finite B
  where A was finite regresses.
* **Trajectory deltas.**  Per-iteration aligned relative deviation over
  the common eval grid, reported per metric (informational).
* **Anomaly gate.**  Run B showing critical ``anomaly`` events where run
  A had none is a regression regardless of the final numbers — a NaN'd
  run that happens to dump a small last cost must not pass.

Exit codes: 0 = no regression, 2 = regression or refused comparison.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

from .events import read_events_meta
from .run import EVENTS_FILE, META_FILE

#: Gated metrics and their improvement direction.  The host-sync rate is
#: the readback-kill gate (ISSUE 9): a change that silently reintroduces
#: per-eval device->host fetches into the driver loop regresses here even
#: when the convergence numbers are untouched.  Sharded records gate the
#: same lower-is-better way (ISSUE 11) — the mesh identity rides the run
#: fingerprint (solver=solve_rbcd_sharded, mesh_size, exchange), so a
#: sharded run only ever compares against a same-mesh baseline and a
#: reopened readback on the mesh path fails here too
#: (tests/test_sharded_verdict.py pins it).
#: Fleet records (ISSUE 13) gate both ways: throughput must not drop
#: (``fleet_qps`` — the first higher-is-better metric, mirrored band
#: check against A's tail MIN) and a warm restart must not get slower
#: (``serve_cold_start_seconds``).
#: Resilience records (ISSUE 14) gate the rewind tax: a change that
#: makes a mesh recovery (checkpoint restore + re-shard + recompile)
#: slower regresses ``mesh_recovery_overhead_s`` even when the solve
#: itself is untouched.  Absent on fault-free runs, so only chaos-arm
#: baselines ever compare it.
#: Overlap efficiency (ISSUE 16) gates lower-bounded (higher is better):
#: a change that drops the halo/compute overlap win below the baseline
#: band — in particular a regression from positive to negative — fails
#: the compare even when throughput metrics stay inside tolerance.
GATED_METRICS = {"solver_cost": "lower", "solver_grad_norm": "lower",
                 "host_syncs_per_100_rounds": "lower",
                 "fleet_qps": "higher",
                 "serve_cold_start_seconds": "lower",
                 "mesh_recovery_overhead_s": "lower",
                 "sharded_overlap_efficiency": "higher",
                 "device_overlap_efficiency_measured": "higher"}
#: Fingerprint keys that never gate (recorded for the report only).
NON_GATING_KEYS = {"version"}


def tail_band(values: list[float], k: int = 5) -> dict:
    """Noise band over the trailing ``k`` values — the ``cpu_arm_band``
    key schema (min/median/max + the window itself) from ``bench.py``."""
    window = [float(v) for v in values[-max(k, 1):]]
    finite = [v for v in window if math.isfinite(v)]
    ref = sorted(finite)
    med = (ref[len(ref) // 2] if len(ref) % 2 else
           0.5 * (ref[len(ref) // 2 - 1] + ref[len(ref) // 2])) \
        if ref else float("nan")
    return {"min": min(window) if finite else float("nan"),
            "median": med,
            "max": max(window) if finite else float("nan"),
            "windows": window}


def _trajectory(events: list[dict], metric: str) -> list[tuple]:
    return [(ev.get("iteration", ev.get("seq", 0)), float(ev["value"]))
            for ev in events
            if ev.get("event") == "metric" and ev.get("metric") == metric
            and isinstance(ev.get("value"), (int, float))]


def load_run(run_dir: str) -> dict:
    """Events + merged fingerprint for one run dir; raises ValueError on a
    dir with no event stream."""
    ev_path = os.path.join(run_dir, EVENTS_FILE)
    if not os.path.exists(ev_path):
        raise ValueError(f"not a telemetry run directory (no {EVENTS_FILE}): "
                         f"{run_dir}")
    events, _trunc = read_events_meta(ev_path)
    fingerprint: dict = {}
    for ev in events:
        if ev.get("event") == "run_summary" \
                and ev.get("channel") == "config":
            fingerprint.update(ev.get("fingerprint") or {})
    meta_path = os.path.join(run_dir, META_FILE)
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as fh:
                fingerprint.update(json.load(fh).get("fingerprint") or {})
        except (OSError, ValueError):
            pass
    return {"run_dir": run_dir, "events": events, "fingerprint": fingerprint}


def _critical_anomalies(events: list[dict]) -> int:
    return sum(1 for ev in events if ev.get("event") == "anomaly"
               and ev.get("severity") == "critical")


def compare_runs(dir_a: str, dir_b: str, rtol: float = 0.05,
                 atol: float = 1e-9, tail: int = 5,
                 allow_mismatch: bool = False) -> dict:
    """Full comparison record (see module docstring for the semantics)."""
    a, b = load_run(dir_a), load_run(dir_b)
    shared = set(a["fingerprint"]) & set(b["fingerprint"]) - NON_GATING_KEYS
    mismatches = {k: [a["fingerprint"][k], b["fingerprint"][k]]
                  for k in sorted(shared)
                  if a["fingerprint"][k] != b["fingerprint"][k]}
    out: dict = {
        "run_a": dir_a, "run_b": dir_b,
        "fingerprint_a": a["fingerprint"], "fingerprint_b": b["fingerprint"],
        "fingerprint_mismatches": mismatches,
        "compatible": not mismatches or allow_mismatch,
        "metrics": {}, "regressions": [],
    }
    if mismatches and not allow_mismatch:
        out["rc"] = 2
        return out

    names = sorted({ev.get("metric") for r in (a, b) for ev in r["events"]
                    if ev.get("event") == "metric" and ev.get("metric")})
    for name in names:
        ta, tb = _trajectory(a["events"], name), _trajectory(b["events"], name)
        if not ta or not tb:
            continue
        va, vb = [v for _, v in ta], [v for _, v in tb]
        band_a, band_b = tail_band(va, tail), tail_band(vb, tail)
        a_final, b_final = va[-1], vb[-1]
        direction = GATED_METRICS.get(name)
        # Aligned per-iteration relative deviation (informational).
        da, db = dict(ta), dict(tb)
        common = sorted(set(da) & set(db))
        max_dev = max((abs(db[i] - da[i]) / max(abs(da[i]), atol)
                       for i in common
                       if math.isfinite(da[i]) and math.isfinite(db[i])),
                      default=None)
        regressed = False
        why = None
        if direction == "lower":
            if not math.isfinite(b_final) and math.isfinite(a_final):
                regressed, why = True, "non-finite final value"
            elif math.isfinite(b_final) and math.isfinite(band_a["max"]):
                bound = band_a["max"] * (1.0 + rtol) + atol \
                    if band_a["max"] >= 0 \
                    else band_a["max"] * (1.0 - rtol) + atol
                if b_final > bound:
                    regressed = True
                    why = (f"final {b_final:.6g} above band max "
                           f"{band_a['max']:.6g} (+{rtol * 100:.0f}%)")
        elif direction == "higher":
            if not math.isfinite(b_final) and math.isfinite(a_final):
                regressed, why = True, "non-finite final value"
            elif math.isfinite(b_final) and math.isfinite(band_a["min"]):
                bound = band_a["min"] * (1.0 - rtol) - atol \
                    if band_a["min"] >= 0 \
                    else band_a["min"] * (1.0 + rtol) - atol
                if b_final < bound:
                    regressed = True
                    why = (f"final {b_final:.6g} below band min "
                           f"{band_a['min']:.6g} (-{rtol * 100:.0f}%)")
        entry = {"a_final": a_final, "b_final": b_final,
                 "delta": b_final - a_final
                 if math.isfinite(b_final) and math.isfinite(a_final)
                 else None,
                 "a_band": band_a, "b_band": band_b,
                 "points": [len(ta), len(tb)],
                 "max_rel_deviation": max_dev,
                 "direction": direction, "regressed": regressed,
                 "reason": why}
        out["metrics"][name] = entry
        if regressed:
            out["regressions"].append(name)

    crit_a = _critical_anomalies(a["events"])
    crit_b = _critical_anomalies(b["events"])
    out["critical_anomalies"] = [crit_a, crit_b]
    if crit_b > crit_a:
        out["regressions"].append("anomalies")
        out["metrics"]["anomalies"] = {
            "a_final": crit_a, "b_final": crit_b, "direction": "lower",
            "regressed": True,
            "reason": f"{crit_b} critical anomalies vs {crit_a}"}
    out["rc"] = 2 if out["regressions"] else 0
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_compare(cmp: dict) -> str:
    lines = [f"== convergence compare: {cmp['run_a']} vs {cmp['run_b']} =="]
    mism = cmp["fingerprint_mismatches"]
    if mism and not cmp["compatible"]:
        lines.append("REFUSED: runs are not comparable — config "
                     "fingerprints disagree:")
        for k, (va, vb) in sorted(mism.items()):
            lines.append(f"  {k}: {va!r} vs {vb!r}")
        lines.append("(re-run with matching configs, or pass "
                     "--allow-mismatch to compare anyway)")
        return "\n".join(lines)
    if mism:
        lines.append("fingerprint mismatches (overridden by "
                     "--allow-mismatch): " + ", ".join(sorted(mism)))
    else:
        nkeys = len(set(cmp["fingerprint_a"]) & set(cmp["fingerprint_b"]))
        lines.append(f"fingerprint: compatible ({nkeys} shared keys)")
    header = (f"  {'metric':<28} {'A final':>12} {'B final':>12} "
              f"{'delta':>11} {'A tail band':>26}  verdict")
    lines.append(header)
    for name, m in sorted(cmp["metrics"].items()):
        band = m.get("a_band")
        band_s = f"[{_fmt(band['min'])}, {_fmt(band['max'])}]" if band else "-"
        delta = m.get("delta")
        if delta is not None and math.isfinite(m["a_final"]) \
                and abs(m["a_final"]) > 0:
            delta_s = f"{100.0 * delta / abs(m['a_final']):+.2f}%"
        else:
            delta_s = _fmt(delta)
        verdict = "REGRESSED" if m["regressed"] else (
            "ok" if m.get("direction") else "info")
        lines.append(f"  {name:<28} {_fmt(m['a_final']):>12} "
                     f"{_fmt(m['b_final']):>12} {delta_s:>11} "
                     f"{band_s:>26}  {verdict}")
        if m.get("reason"):
            lines.append(f"    ^ {m['reason']}")
    if cmp["regressions"]:
        lines.append(f"RESULT: REGRESSION in {', '.join(cmp['regressions'])}")
    else:
        lines.append("RESULT: no regression")
    return "\n".join(lines)


#: Cross-round ledger trends and their improvement direction (ISSUE 16).
#: Keys are ``(family, series)`` into ``ledger.PerfLedger.series``:
#: ``"value"`` is the family's headline metric, anything else an extras
#: key.  The newest round gates against the noise band of all previous
#: readings, the same sign-aware bound arithmetic as the pairwise gate —
#: so the trend gate catches a slide the pairwise compare never sees
#: (each round individually within tolerance of its predecessor).
LEDGER_TRENDS = {
    ("BENCH", "value"): "higher",
    ("BENCH", "vs_baseline"): "higher",
    ("BENCH", "kernel_parity_max_abs_diff"): "lower",
    ("MULTICHIP", "value"): "higher",
    ("MULTICHIP", "host_syncs_per_100_rounds"): "lower",
    ("MULTICHIP", "overlap_efficiency"): "higher",
    ("FLEET", "value"): "higher",
    ("FLEET", "scaling_1_to_2"): "higher",
}


def _band_bound(band_edge: float, direction: str, rtol: float,
                atol: float = 1e-9) -> float:
    """Sign-aware tolerance widening of a band edge (shared with the
    pairwise gate's inline arithmetic)."""
    if direction == "lower":
        return band_edge * (1.0 + rtol) + atol if band_edge >= 0 \
            else band_edge * (1.0 - rtol) + atol
    return band_edge * (1.0 - rtol) - atol if band_edge >= 0 \
        else band_edge * (1.0 + rtol) - atol


def trend_gate(ledger, rtol: float = 0.10, tail: int = 5) -> dict:
    """Cross-round regression gate over a ``PerfLedger``.

    For every declared trend series with >= 2 readings, the newest
    round's value must stay inside the noise band (``tail_band`` over
    the trailing ``tail`` previous readings) widened by ``rtol`` in the
    series' improvement direction.  A latest-round record with
    ``ok=false`` in any family regresses outright — a round that failed
    to produce its record must not pass on the strength of old numbers.
    Returns the comparison record (``rc`` 0/2), mirroring
    ``compare_runs``."""
    out: dict = {"root": ledger.root, "trends": {}, "regressions": [],
                 "families": ledger.families()}
    for family in ledger.families():
        rows = ledger.family_rows(family)
        if rows and not rows[-1]["ok"]:
            name = f"{family}:ok"
            out["trends"][name] = {
                "latest_round": rows[-1]["round"], "regressed": True,
                "reason": f"latest round r{rows[-1]['round']:02d} "
                          f"({rows[-1]['file']}) reports ok=false"}
            out["regressions"].append(name)
    for (family, key), direction in sorted(LEDGER_TRENDS.items()):
        pts = ledger.series(family, key)
        if len(pts) < 2:
            continue
        rounds = [r for r, _ in pts]
        values = [v for _, v in pts]
        band = tail_band(values[:-1], tail)
        latest_r, latest = rounds[-1], values[-1]
        regressed, why = False, None
        if direction == "lower":
            bound = _band_bound(band["max"], "lower", rtol)
            if math.isfinite(bound) and latest > bound:
                regressed = True
                why = (f"r{latest_r:02d} value {latest:.6g} above prior "
                       f"band max {band['max']:.6g} (+{rtol * 100:.0f}%)")
        else:
            bound = _band_bound(band["min"], "higher", rtol)
            if math.isfinite(bound) and latest < bound:
                regressed = True
                why = (f"r{latest_r:02d} value {latest:.6g} below prior "
                       f"band min {band['min']:.6g} (-{rtol * 100:.0f}%)")
        name = f"{family}:{key}"
        out["trends"][name] = {
            "direction": direction, "rounds": rounds, "values": values,
            "band": band, "latest_round": latest_r, "latest": latest,
            "regressed": regressed, "reason": why}
        if regressed:
            out["regressions"].append(name)
    out["rc"] = 2 if out["regressions"] else 0
    return out


def render_trend(gate: dict) -> str:
    lines = [f"== ledger trend gate: {gate['root']} "
             f"({', '.join(gate['families']) or 'no records'}) =="]
    for name, t in sorted(gate["trends"].items()):
        if "values" not in t:
            lines.append(f"  {name:<38} REGRESSED")
            lines.append(f"    ^ {t['reason']}")
            continue
        span = (f"r{t['rounds'][0]:02d}..r{t['latest_round']:02d} "
                f"({len(t['values'])} readings)")
        verdict = "REGRESSED" if t["regressed"] else "ok"
        lines.append(f"  {name:<38} {span:<26} "
                     f"latest {_fmt(t['latest']):>12}  {verdict}")
        if t.get("reason"):
            lines.append(f"    ^ {t['reason']}")
    if gate["regressions"]:
        lines.append("RESULT: TREND REGRESSION in "
                     + ", ".join(gate["regressions"]))
    else:
        lines.append("RESULT: no trend regression")
    return "\n".join(lines)


def run_trend(root: str, rtol: float = 0.10,
              json_out: bool = False) -> int:
    """CLI body for ``--ledger``: load, gate, print, return exit code."""
    from .ledger import load_ledger

    ledger = load_ledger(root)
    if not ledger.rows:
        print(f"no bench records found under {root}", file=sys.stderr)
        return 2
    gate = trend_gate(ledger, rtol=rtol)
    if json_out:
        print(json.dumps(gate))
    else:
        print(render_trend(gate))
    return int(gate["rc"])


#: Flat-memory soak gate defaults (ISSUE 20): the head/tail medians of a
#: soak window's ``process_rss_bytes`` series must agree within
#: ``SOAK_RSS_RTOL`` plus an absolute slack — allocator warmup and JIT
#: cache growth land in the slack; an unbounded leak does not.
SOAK_RSS_RTOL = 0.15
SOAK_RSS_SLACK_BYTES = 64 << 20
SOAK_MIN_SAMPLES = 8


def soak_memory_gate(run_dir: str, metric: str = "process_rss_bytes",
                     rtol: float = SOAK_RSS_RTOL,
                     slack: float = SOAK_RSS_SLACK_BYTES,
                     window: int = 4,
                     min_samples: int = SOAK_MIN_SAMPLES) -> dict:
    """Flat-memory trend check over one soak run's ``ResourceSampler``
    series (ROADMAP item 5's "memory held flat" acceptance, made
    checkable).

    The series' trailing-``window`` median must stay within
    ``head_median * (1 + rtol) + slack`` of its leading-``window``
    median.  Multiple labeled series (one per replica/rank) gate
    independently — any replica leaking fails the run.  Too few samples
    is a SKIP (ok, flagged), not a pass pretending to be evidence."""
    run = load_run(run_dir)
    series: dict = {}
    for ev in run["events"]:
        if ev.get("event") != "metric" or ev.get("metric") != metric:
            continue
        v = ev.get("value")
        if not isinstance(v, (int, float)) or not math.isfinite(v):
            continue
        who = str(ev.get("replica", ev.get("rank", "self")))
        series.setdefault(who, []).append(float(v))
    out: dict = {"run_dir": run_dir, "metric": metric, "rtol": rtol,
                 "slack_bytes": slack, "series": {}, "regressions": []}
    for who, vals in sorted(series.items()):
        if len(vals) < min_samples:
            out["series"][who] = {"samples": len(vals), "skipped": True,
                                  "reason": f"only {len(vals)} samples "
                                            f"(< {min_samples})"}
            continue
        head = tail_band(vals[:window], window)["median"]
        tail = tail_band(vals, window)["median"]
        bound = head * (1.0 + rtol) + slack
        regressed = tail > bound
        out["series"][who] = {
            "samples": len(vals), "skipped": False,
            "head_median": head, "tail_median": tail, "bound": bound,
            "growth_bytes": tail - head, "regressed": regressed}
        if regressed:
            out["regressions"].append(who)
    if not series:
        out["skipped"] = True
        out["reason"] = f"no {metric!r} samples in {run_dir} " \
                        "(sampler off or telemetry-off run)"
    out["rc"] = 2 if out["regressions"] else 0
    return out


def render_soak(gate: dict) -> str:
    lines = [f"== flat-memory soak gate: {gate['run_dir']} "
             f"({gate['metric']}) =="]
    if gate.get("skipped"):
        lines.append(f"SKIPPED: {gate['reason']}")
        return "\n".join(lines)
    for who, s in sorted(gate["series"].items()):
        if s.get("skipped"):
            lines.append(f"  {who:<16} SKIPPED ({s['reason']})")
            continue
        mb = 1.0 / (1 << 20)
        verdict = "LEAKING" if s["regressed"] else "flat"
        lines.append(
            f"  {who:<16} {s['samples']:>4} samples  "
            f"head {s['head_median'] * mb:8.1f}MiB -> "
            f"tail {s['tail_median'] * mb:8.1f}MiB "
            f"({s['growth_bytes'] * mb:+8.1f}MiB)  {verdict}")
    if gate["regressions"]:
        lines.append("RESULT: MEMORY NOT FLAT in "
                     + ", ".join(gate["regressions"]))
    else:
        lines.append("RESULT: memory held flat")
    return "\n".join(lines)


def run_soak(run_dir: str, rtol: float | None = None,
             json_out: bool = False) -> int:
    """CLI body for ``--soak``: gate, print, return exit code."""
    try:
        gate = soak_memory_gate(
            run_dir, rtol=SOAK_RSS_RTOL if rtol is None else rtol)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 2
    if json_out:
        print(json.dumps(gate))
    else:
        print(render_soak(gate))
    return int(gate["rc"])


def run_compare(dir_a: str, dir_b: str, rtol: float = 0.05,
                json_out: bool = False, allow_mismatch: bool = False) -> int:
    """CLI body shared by ``report --compare`` and ``python -m
    dpgo_tpu.obs.regress``; prints and returns the exit code."""
    try:
        cmp = compare_runs(dir_a, dir_b, rtol=rtol,
                           allow_mismatch=allow_mismatch)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 2
    if json_out:
        print(json.dumps(cmp))
    else:
        print(render_compare(cmp))
    return int(cmp["rc"])


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dpgo_tpu.obs.regress", description=__doc__)
    ap.add_argument("run_a", nargs="?")
    ap.add_argument("run_b", nargs="?")
    ap.add_argument("--rtol", type=float, default=None,
                    help="relative tolerance over the baseline band "
                         "(default 0.05 pairwise, 0.10 for --ledger)")
    ap.add_argument("--allow-mismatch", action="store_true",
                    help="compare despite fingerprint mismatches")
    ap.add_argument("--ledger", metavar="ROOT",
                    help="cross-round trend gate over the BENCH_r*/"
                         "MULTICHIP_r*/FLEET_r* records under ROOT "
                         "instead of a pairwise run compare")
    ap.add_argument("--soak", metavar="RUN_DIR",
                    help="flat-memory gate over one soak run's "
                         "ResourceSampler series (process_rss_bytes "
                         "head vs tail median; exit 2 on growth)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.soak is not None:
        if args.run_a or args.run_b:
            ap.error("--soak takes no extra run directories")
        return run_soak(args.soak, rtol=args.rtol, json_out=args.json)
    if args.ledger is not None:
        if args.run_a or args.run_b:
            ap.error("--ledger takes no run directories")
        return run_trend(args.ledger,
                         rtol=0.10 if args.rtol is None else args.rtol,
                         json_out=args.json)
    if not (args.run_a and args.run_b):
        ap.error("need two run directories (or --ledger ROOT)")
    return run_compare(args.run_a, args.run_b,
                       rtol=0.05 if args.rtol is None else args.rtol,
                       json_out=args.json,
                       allow_mismatch=args.allow_mismatch)


if __name__ == "__main__":
    sys.exit(main())
