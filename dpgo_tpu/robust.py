"""Robust cost weight functions.

TPU-native equivalent of reference ``src/DPGO_robust.cpp:23-103``
(``RobustCost``).  The reference wraps mutable state (GNC ``mu`` and
iteration counter) in a class; here the weight functions are pure and
batched — ``mu`` lives in the optimizer state pytree and is advanced
functionally (``gnc_update_mu``), so the whole GNC outer loop stays inside
jitted code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .config import RobustCostParams, RobustCostType


def weight(r: jax.Array, params: RobustCostParams, mu: jax.Array | float = 0.0) -> jax.Array:
    """Weight w(r) in [0, 1] for residual norm ``r`` (elementwise).

    Matches reference ``RobustCost::weight`` (``DPGO_robust.cpp:23-67``) for
    every supported cost type.  ``mu`` is the GNC control parameter (only
    used by GNC_TLS).
    """
    ct = params.cost_type
    if ct == RobustCostType.L2:
        return jnp.ones_like(r)
    if ct == RobustCostType.L1:
        return 1.0 / r
    if ct == RobustCostType.Huber:
        return jnp.where(r < params.huber_threshold, 1.0, params.huber_threshold / r)
    if ct == RobustCostType.TLS:
        return jnp.where(r < params.tls_threshold, 1.0, 0.0)
    if ct == RobustCostType.GM:
        a = 1.0 + r * r
        return 1.0 / (a * a)
    if ct == RobustCostType.GNC_TLS:
        # The reference keeps mu as managed internal state so it is always
        # positive; here it is explicit, so reject a forgotten/zero mu (with
        # mu=0 every residual would silently map to weight 0).
        if isinstance(mu, (int, float)) and mu <= 0:
            raise ValueError("GNC_TLS requires a positive mu (e.g. params.gnc_init_mu)")
        return gnc_tls_weight(r, mu, params.gnc_barc)
    raise NotImplementedError(f"weight function for {ct} is not implemented")


def gnc_tls_weight(r: jax.Array, mu: jax.Array | float, barc: float) -> jax.Array:
    """GNC-TLS weight, eq. (14) of the GNC paper (reference ``DPGO_robust.cpp:49-62``).

    w = 0                              if r^2 >= (mu+1)/mu * barc^2
      = 1                              if r^2 <= mu/(mu+1) * barc^2
      = sqrt(barc^2 mu (mu+1) / r^2) - mu   otherwise
    """
    barc_sq = barc * barc
    r_sq = r * r
    upper = (mu + 1.0) / mu * barc_sq
    lower = mu / (mu + 1.0) * barc_sq
    # Guard the sqrt against r = 0 in the (unused) middle branch.
    safe_r_sq = jnp.maximum(r_sq, 1e-30)
    mid = jnp.sqrt(barc_sq * mu * (mu + 1.0) / safe_r_sq) - mu
    w = jnp.where(r_sq >= upper, 0.0, jnp.where(r_sq <= lower, 1.0, mid))
    return jnp.clip(w, 0.0, 1.0)


def gnc_update_mu(mu: jax.Array, params: RobustCostParams) -> jax.Array:
    """One GNC annealing step: mu <- mu_step * mu, capped after
    ``gnc_max_iters`` steps (reference ``RobustCost::update``,
    ``DPGO_robust.cpp:85-103``, stops annealing after ``GNCMaxNumIters`` —
    weight recomputation continues at the frozen mu)."""
    mu_max = params.gnc_init_mu * params.gnc_mu_step ** params.gnc_max_iters
    return jnp.minimum(mu * params.gnc_mu_step, mu_max)


def gnc_init_mu(params: RobustCostParams) -> float:
    return params.gnc_init_mu


def gnc_stage_index(mu, params: RobustCostParams) -> int:
    """Host-side GNC stage label: the number of annealing steps taken to
    reach ``mu`` from ``gnc_init_mu`` (0 before the first update, capped at
    ``gnc_max_iters`` like ``gnc_update_mu``'s schedule).

    The observability layer (``obs.health``) keys its per-stage baselines
    — cost monotonicity, gradient-norm floor, stall windows — on this
    index: within one stage the GNC objective is fixed and the cost should
    be monotone; across stages it legitimately jumps.  Pure float math on
    an already-read-back scalar, never called inside jitted code."""
    import math

    mu = float(mu)
    mu0 = float(params.gnc_init_mu)
    step = float(params.gnc_mu_step)
    if mu <= 0 or mu0 <= 0 or step <= 1.0 or mu <= mu0:
        return 0
    k = round(math.log(mu / mu0) / math.log(step))
    return max(0, min(int(k), int(params.gnc_max_iters)))


def is_weight_converged(w: jax.Array, tol: float = 1e-4) -> jax.Array:
    """Elementwise: has this edge's GNC weight converged to {0, 1}?

    Reference ``PGOAgent::computeConvergedLoopClosureRatio`` counts weights
    exactly equal to 0 or 1 (``PGOAgent.cpp:1247-1289``); since the GNC-TLS
    outer branches return exact constants this tolerance check is equivalent
    while also being robust to float rounding.
    """
    return (w < tol) | (w > 1.0 - tol)
