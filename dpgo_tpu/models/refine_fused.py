"""Single-readback certified refinement: the recenter and the gap oracle
ON the device, in double-f32.

Round 4 measured the certified-1e-6 pipeline's floor at two fixed ~90 ms
tunnel round-trips (~47% of the 0.40-0.49 s wall, BASELINE.md): one
device->host readback to hand the descent iterate to the HOST f64
recenter (``models.refine.recenter``), and one to verify the refined gap
in f64.  Both existed only because f64 lived on the host.  This module
moves that work on-device using ``ops.df32`` (double-f32, ~49 mantissa
bits, measured 1e-13-relative on the TPU):

* ``_project_polar_df``   — f64-grade manifold projection (Newton-Schulz
  on the Gram matrix, unrolled d x d df32 matmuls);
* ``recenter_device``     — the full recenter: reference residuals,
  Euclidean gradient via a GLOBAL incidence gather (no scatter-add —
  df32 accumulation is a pairwise fold over the incidence slots),
  ``S0``/``g0``, the reference cost ``f_ref``, the block-Jacobi
  preconditioner, and the Pallas-kernel tile layouts — everything
  ``models.refine.recenter`` builds on the host, built in one device
  program;
* ``refine_until``        — accelerated re-centered rounds whose STOP
  decision is an on-device gap oracle: f(R + D) = f_ref + delta(D) with
  ``delta`` exact-to-f32 (the ambient cost is quadratic, so the delta
  carries no large-term cancellation), checked every few rounds inside
  one ``lax.while_loop``.

The only host round-trip left is the final readback of ``(R, D, stats)``
— which doubles as the wall-clock fence the tunneled platform needs —
followed by a host f64 VERIFY of the claimed gap (``refine.global_cost``)
so the reported number never rests on device arithmetic alone.

Precision budget (sphere2500 scale, f ~ 8.4e2, target gap 1e-6):
``f_ref`` df32 error ~1e-13 rel; ``delta`` f32 eval error ~1e-7 * |delta|
with |delta| <= 1e-3 * f at the handoff, i.e. <=1e-10 * f; the oracle
stops at 0.3x the requested gap, leaving a ~3x margin that the host
verify then confirms.  Reference counterpart: none — the reference runs
f64 end-to-end on CPU (``QuadraticProblem.cpp``); this is the TPU-native
equivalent of simply "being in f64" for the terminal decimals.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AgentParams
from ..ops import df32
from ..ops.df32 import DF
from ..types import EdgeSet
from . import rbcd
from .refine import RefineConstants


class GlobalProblemDF(NamedTuple):
    """Global (one-entry-per-measurement) edge data in df32 + incidence.

    Built once per problem on the host (``build_global_df``) OUTSIDE any
    timed section; shapes: E measurements, N poses, K = max pose degree.
    """

    i: jax.Array        # [E] int32 global endpoint i
    j: jax.Array        # [E] int32 global endpoint j
    Rm: DF              # [E, d, d] measurement rotations
    tm: DF              # [E, d]    measurement translations
    kap: DF             # [E]
    tau: DF             # [E]
    w: jax.Array        # [E] f32 weight * mask
    inc_slot: jax.Array  # [N, K] int32 into the [gi | gj] concatenation
    inc_mask: jax.Array  # [N, K] f32
    edges32: EdgeSet    # f32 global EdgeSet (hi parts) for the delta oracle


def build_global_df(meas_global, weights=None) -> GlobalProblemDF:
    """Host-side build of the df32 global problem (f64 measurement data
    split exactly into hi/lo pairs; numpy incidence pass over E edges).

    ``weights [M]``: optional per-measurement robust weights to fold into
    ``w`` (must match the weights the refined solve ran under)."""
    from ..types import edge_set_from_measurements

    e64 = edge_set_from_measurements(meas_global, dtype=np.float64,
                                     as_numpy=True)
    E = len(np.asarray(e64.i))
    N = meas_global.num_poses
    i_np = np.asarray(e64.i, np.int64)
    j_np = np.asarray(e64.j, np.int64)

    inc: list[list[int]] = [[] for _ in range(N)]
    for e in range(E):
        inc[i_np[e]].append(e)
        inc[j_np[e]].append(E + e)
    K = max(1, max(len(s) for s in inc))
    inc_slot = np.zeros((N, K), np.int32)
    inc_mask = np.zeros((N, K), np.float32)
    for v in range(N):
        for c, slot in enumerate(inc[v]):
            inc_slot[v, c] = slot
            inc_mask[v, c] = 1.0

    w = np.asarray(e64.mask, np.float64) * np.asarray(e64.weight, np.float64)
    if weights is not None:
        w = w * np.asarray(weights, np.float64)

    edges32 = edge_set_from_measurements(meas_global, dtype=jnp.float32)
    edges32 = edges32._replace(weight=jnp.asarray(w, jnp.float32),
                               mask=jnp.ones(E, jnp.float32))
    return GlobalProblemDF(
        i=jnp.asarray(i_np, jnp.int32), j=jnp.asarray(j_np, jnp.int32),
        Rm=df32.from_f64(np.asarray(e64.R)),
        tm=df32.from_f64(np.asarray(e64.t)),
        kap=df32.from_f64(np.asarray(e64.kappa)),
        tau=df32.from_f64(np.asarray(e64.tau)),
        w=jnp.asarray(w, jnp.float32),
        inc_slot=jnp.asarray(inc_slot), inc_mask=jnp.asarray(inc_mask),
        edges32=edges32)


# ---------------------------------------------------------------------------
# df32 building blocks (all unrolled over the small static dims r, d)
# ---------------------------------------------------------------------------

def _matvec_small(M: DF, v: DF) -> DF:
    """[..., m, k] @ [..., k] -> [..., m], unrolled over k."""
    k = M.hi.shape[-1]
    acc = None
    for t in range(k):
        term = df32.mul(DF(M.hi[..., :, t], M.lo[..., :, t]),
                        DF(v.hi[..., t, None], v.lo[..., t, None]))
        acc = term if acc is None else df32.add(acc, term)
    return acc


def _project_polar_df(Xg: jax.Array, d: int, iters: int = 3) -> DF:
    """df32 manifold projection of a NEAR-orthonormal f32 iterate.

    Per pose, the polar factor of Y [r, d] is Y (Y^T Y)^{-1/2}; the
    descent retracts every round, so Y^T Y = I + O(f32 eps) and the
    Newton-Schulz iteration Z <- Z (3I - B Z^2)/2 (B = Y^T Y, Z0 = I)
    converges quadratically: 3 df32 iterations land at the df32 floor
    (~1e-13; counterpart of the host SVD in refine._np_project_manifold).
    """
    Y = df32.from_f32(Xg[..., :d])               # [N, r, d]
    B = df32.matmul_small(df32.transpose(Y, (0, 2, 1)), Y)  # [N, d, d]
    eye = df32.from_f32(jnp.broadcast_to(jnp.eye(d, dtype=jnp.float32),
                                         B.hi.shape))
    Z = eye
    three_eye = df32.scale(eye, 3.0)
    for _ in range(iters):
        BZ2 = df32.matmul_small(B, df32.matmul_small(Z, Z))
        Z = df32.scale(df32.matmul_small(
            Z, df32.add(three_eye, df32.neg(BZ2))), 0.5)
    RY = df32.matmul_small(Y, Z)
    T = df32.from_f32(Xg[..., d:])
    return DF(jnp.concatenate([RY.hi, T.hi], axis=-1),
              jnp.concatenate([RY.lo, T.lo], axis=-1))


def _edge_residuals_df(R: DF, gp: GlobalProblemDF, d: int):
    """Per-edge residuals at the df32 reference point:
    rR = Yj - Yi Rm [E, r, d], rt = pj - pi - Yi tm [E, r]."""
    Xi = df32.index(R, gp.i)          # [E, r, d+1]
    Xj = df32.index(R, gp.j)
    Yi = DF(Xi.hi[..., :d], Xi.lo[..., :d])
    Yj = DF(Xj.hi[..., :d], Xj.lo[..., :d])
    pi = DF(Xi.hi[..., d], Xi.lo[..., d])
    pj = DF(Xj.hi[..., d], Xj.lo[..., d])
    rR = df32.add(Yj, df32.neg(df32.matmul_small(Yi, gp.Rm)))
    rt = df32.add(pj, df32.neg(df32.add(pi, _matvec_small(Yi, gp.tm))))
    return rR, rt


def _sumsq_df(x: DF) -> DF:
    """Sum of squares over all trailing axes (flattened), per leading row."""
    hi = x.hi.reshape(x.hi.shape[0], -1)
    lo = x.lo.reshape(x.lo.shape[0], -1)
    sq = df32.mul(DF(hi, lo), DF(hi, lo))
    return df32.fold_sum(sq, axis=-1)


def recenter_device(Xg: jax.Array, gp: GlobalProblemDF, graph, meta,
                    params: AgentParams, n_total: int):
    """The full re-centering in one device program (df32): the on-device
    equivalent of ``models.refine.recenter`` + ``global_cost``.

    Returns ``(R, f_ref, consts, rho32)`` where ``R: DF [N, r, d+1]`` is
    the projected reference, ``f_ref: DF []`` the global cost at R,
    ``consts`` the per-agent ``RefineConstants`` (f32 hi-parts — the same
    truncation the host path applies when shipping), and
    ``rho32 = (rR, rt)`` f32 global residuals for the delta oracle.
    """
    d = meta.d
    r = meta.rank

    R = _project_polar_df(Xg, d)                         # [N, r, k] df32
    rR, rt = _edge_residuals_df(R, gp, d)                # [E, ...] df32

    # Per-edge gradient contributions (df32 mirror of
    # quadratic._edge_grad_terms, global layout).
    wk = df32.mul_f(gp.kap, gp.w)                        # [E]
    wt = df32.mul_f(gp.tau, gp.w)
    wk3 = DF(wk.hi[:, None, None], wk.lo[:, None, None])
    wt2 = DF(wt.hi[:, None], wt.lo[:, None])
    wkrR = df32.mul(wk3, rR)                             # [E, r, d]
    wtrt = df32.mul(wt2, rt)                             # [E, r]
    gj = DF(jnp.concatenate([wkrR.hi, wtrt.hi[..., None]], axis=-1),
            jnp.concatenate([wkrR.lo, wtrt.lo[..., None]], axis=-1))
    giY = df32.add(
        df32.neg(df32.matmul_small(wkrR, df32.transpose(gp.Rm, (0, 2, 1)))),
        df32.neg(df32.mul(DF(wtrt.hi[..., None], wtrt.lo[..., None]),
                          DF(gp.tm.hi[:, None, :], gp.tm.lo[:, None, :]))))
    gi = DF(jnp.concatenate([giY.hi, -wtrt.hi[..., None]], axis=-1),
            jnp.concatenate([giY.lo, -wtrt.lo[..., None]], axis=-1))

    # Global Euclidean gradient: gather-only incidence sum (pairwise df32
    # fold over the K slots; scatter-add cannot accumulate in df32).
    g_both = DF(jnp.concatenate([gi.hi, gj.hi], axis=0),
                jnp.concatenate([gi.lo, gj.lo], axis=0))  # [2E, r, k]
    contrib = df32.index(g_both, gp.inc_slot)             # [N, K, r, k]
    m = gp.inc_mask[:, :, None, None]
    contrib = DF(contrib.hi * m, contrib.lo * m)
    G = df32.fold_sum(df32.transpose(contrib, (0, 2, 3, 1)), axis=-1)
    # -> [N, r, k]

    # S0 = sym(R_Y^T G_Y), g0 = G - [R_Y S0 | 0].
    RY = DF(R.hi[..., :d], R.lo[..., :d])
    GY = DF(G.hi[..., :d], G.lo[..., :d])
    S0 = df32.sym(df32.matmul_small(df32.transpose(RY, (0, 2, 1)), GY))
    RS = df32.matmul_small(RY, S0)
    g0Y = df32.add(GY, df32.neg(RS))
    g0 = DF(jnp.concatenate([g0Y.hi, G.hi[..., d:]], axis=-1),
            jnp.concatenate([g0Y.lo, G.lo[..., d:]], axis=-1))

    # f_ref = 0.5 sum_e w (kappa ||rR||^2 + tau ||rt||^2), df32 throughout.
    ssR = _sumsq_df(rR)                                   # [E]
    sst = _sumsq_df(rt)
    per_edge = df32.add(df32.mul(gp.kap, ssR), df32.mul(gp.tau, sst))
    per_edge = df32.mul_f(per_edge, gp.w)
    f_ref = df32.scale(df32.fold_sum(per_edge, axis=-1), 0.5)

    # ---- distribute to the per-agent layout (exact gathers of hi parts;
    # the host path ships f32 to the device, so hi-part truncation is the
    # SAME approximation — errors enter only multiplied by |D|).
    gi_idx = graph.global_index                           # [A, n]
    pm = graph.pose_mask[..., None, None]
    # R is shipped UNMASKED (padded slots alias pose 0, matching the host
    # recenter's plain gather — harmless: padded D rows stay zero); the
    # gradient-family constants are masked because the host builds them
    # by scatter into zero-initialized per-agent buffers.
    R_loc = R.hi[gi_idx]
    G_loc = G.hi[gi_idx] * pm
    g0_loc = g0.hi[gi_idx] * pm
    S0_loc = S0.hi[gi_idx] * graph.pose_mask[..., None, None]
    Rz = rbcd.neighbor_buffer(rbcd.public_table(R_loc, graph), graph)

    # Per-agent residual tiles from the global residuals (meas_id keeps
    # the measurement orientation in every agent's copy).
    rho_R32 = rR.hi[graph.meas_id] * graph.edges.mask[..., None, None]
    rho_t32 = rt.hi[graph.meas_id] * graph.edges.mask[..., None]

    chol = rbcd.precond_chol(graph.edges, meta.n_max, meta.s_max, params)

    fields = dict(R=R_loc, Rz=Rz, G_ref=G_loc, g0=g0_loc, S0=S0_loc,
                  chol=chol)
    if graph.eidx_i is not None:
        A, nt, _, T = graph.eidx_i.shape
        E_a = graph.edges.kappa.shape[1]
        pad = nt * T - E_a
        k = d + 1

        def tile_cm(arr, rows):   # [A, E_a, ...] -> [A, nt, rows, T]
            flat = arr.reshape(A, E_a, rows).transpose(0, 2, 1)
            flat = jnp.pad(flat, ((0, 0), (0, 0), (0, pad)))
            return flat.reshape(A, rows, nt, T).transpose(0, 2, 1, 3)

        def wtile(vals):          # [A, E_a] -> [A, nt, 1, T]
            return jnp.pad(vals, ((0, 0), (0, pad))).reshape(A, nt, 1, T)

        def cm(arr):              # [A, n, r, k] -> [A, r*k, n]
            return arr.transpose(0, 2, 3, 1).reshape(A, -1, meta.n_max)

        w_a = graph.edges.mask * graph.edges.weight
        fields.update(
            rho_rot_t=tile_cm(rho_R32, r * d),
            rho_trn_t=tile_cm(rho_t32, r),
            Rc=cm(R_loc),
            wk_t=wtile(w_a * graph.edges.kappa),
            wt_t=wtile(w_a * graph.edges.tau),
            g0_c=cm(g0_loc),
            Gref_c=cm(G_loc),
            S0_c=S0_loc.transpose(0, 2, 3, 1).reshape(A, d * d, meta.n_max),
            Lc=jnp.transpose(chol, (0, 2, 3, 1)).reshape(A, k * k,
                                                         meta.n_max),
        )
    consts = RefineConstants(**fields)
    return R, f_ref, consts, (rR.hi, rt.hi)


def _delta_global(D, graph, gp: GlobalProblemDF, rho32, n_total: int):
    """f(R + D) - f(R) on the GLOBAL edge set, f32: linear cross term
    against the reference residuals + exact quadratic term (the ambient
    cost is quadratic — mirror of ``refine._delta_cost`` at global
    scope, so the oracle sees each measurement exactly once)."""
    from ..ops import quadratic

    Dg = rbcd.gather_to_global(D, graph, n_total)
    LR, Lt = quadratic._edge_terms(Dg, gp.edges32)
    rho_R, rho_t = rho32
    cross = gp.edges32.kappa * jnp.sum(rho_R * LR, axis=(-2, -1)) \
        + gp.edges32.tau * jnp.sum(rho_t * Lt, axis=-1)
    quad = gp.edges32.kappa * jnp.sum(LR * LR, axis=(-2, -1)) \
        + gp.edges32.tau * jnp.sum(Lt * Lt, axis=-1)
    return jnp.sum(gp.w * (cross + 0.5 * quad))


def refine_until(D0, consts: RefineConstants, graph, meta,
                 params: AgentParams, gp: GlobalProblemDF, rho32,
                 thr: jax.Array, n_total: int, max_rounds: int,
                 check_every: int = 8):
    """Accelerated re-centered rounds until the ON-DEVICE oracle says
    f_ref + delta(D) <= target (``thr = target - f_ref`` precomputed in
    df32), in one ``lax.while_loop`` — no host sync.

    Momentum/restart mirror ``refine.refine_rounds_accel`` (adaptive
    x-scheme restart); the oracle runs every ``check_every`` rounds (its
    edge pass costs a fraction of a round).  Returns (D, rounds_used,
    last_delta).
    """
    from .refine import accel_round_carry

    def one_round(carry):
        return accel_round_carry(carry, consts, graph, meta, params)

    def cond(state):
        _, rounds, done = state
        return (~done) & (rounds < max_rounds)

    def body(state):
        carry, rounds, _ = state
        carry = jax.lax.fori_loop(0, check_every,
                                  lambda _, c: one_round(c), carry)
        delta = _delta_global(carry[0], graph, gp, rho32, n_total)
        return carry, rounds + check_every, delta <= thr

    init_carry = (D0, D0, jnp.zeros((), D0.dtype), jnp.asarray(False))
    # delta(D0) == 0 for the zero correction, so the loop starts already
    # done when the recenter landed at/below target (second-cycle case).
    done0 = jnp.asarray(0.0, jnp.float32) <= thr
    (D, _, _, _), rounds, done = jax.lax.while_loop(
        cond, body, (init_carry, jnp.asarray(0, jnp.int32), done0))
    delta = _delta_global(D, graph, gp, rho32, n_total)
    return D, rounds, delta


class FusedCycleResult(NamedTuple):
    R_hi: jax.Array     # [N, r, k] reference point, hi part
    R_lo: jax.Array     # [N, r, k] reference point, lo part
    D: jax.Array        # [A, n, r, k] refined correction
    f_ref_hi: jax.Array
    f_ref_lo: jax.Array
    delta: jax.Array    # last oracle delta (f(R+D) ~= f_ref + delta)
    rounds: jax.Array   # refine rounds used


def next_iterate(res: FusedCycleResult, graph, n_total: int) -> jax.Array:
    """f32 global iterate R + D for chaining a second fused cycle
    (rounding here perturbs the cost by O(eps^2 * curvature) — far below
    the oracle margin)."""
    Dg = rbcd.gather_to_global(res.D, graph, n_total)
    return res.R_hi + (res.R_lo + Dg)


def assemble_f64(res: FusedCycleResult, graph) -> np.ndarray:
    """HOST: exact f64 iterate R + D from a readback of the result."""
    from .refine import scatter_owned
    Xg = np.asarray(res.R_hi, np.float64) + np.asarray(res.R_lo, np.float64)
    return scatter_owned(Xg, res.D, graph)


def pack_result(res: FusedCycleResult) -> jax.Array:
    """Flatten a cycle result into ONE f32 vector so the final readback
    is a single transfer (the tunnel charges ~90 ms per transfer
    regardless of size; a per-field readback would pay 7x)."""
    parts = [res.R_hi.ravel(), res.R_lo.ravel(), res.D.ravel(),
             res.f_ref_hi.reshape(1), res.f_ref_lo.reshape(1),
             res.delta.reshape(1),
             res.rounds.astype(jnp.float32).reshape(1)]
    return jnp.concatenate(parts)


def unpack_result_host(flat: np.ndarray, n_total: int, r: int, k: int,
                       d_shape) -> FusedCycleResult:
    """Host-side inverse of ``pack_result`` (``d_shape = (A, n, r, k)``)."""
    flat = np.asarray(flat)
    nrk = n_total * r * k
    dsz = int(np.prod(d_shape))
    off = 0
    R_hi = flat[off:off + nrk].reshape(n_total, r, k); off += nrk
    R_lo = flat[off:off + nrk].reshape(n_total, r, k); off += nrk
    D = flat[off:off + dsz].reshape(d_shape); off += dsz
    f_ref_hi, f_ref_lo, delta, rounds = flat[off:off + 4]
    return FusedCycleResult(R_hi, R_lo, D, f_ref_hi, f_ref_lo, delta,
                            int(rounds))


class FusedFns(NamedTuple):
    """Jitted pieces of the single-readback pipeline.  ``recenter`` and
    ``refine`` are SEPARATE dispatches (both async — chaining them costs
    no host round-trip) so that only the df32-heavy recenter pays the
    CPU opt-0 workaround of ``ops.df32.precise_jit``; on TPU both are
    ordinary fully-optimized programs."""

    recenter: object   # (Xg, gp, graph, target: DF) -> (R, f_ref, consts,
    #                     rho32, thr)
    refine: object     # (consts, graph, gp, rho32, thr) -> (D, rounds,
    #                     delta)
    nxt: object        # (res: FusedCycleResult, graph) -> Xg'
    pack: object       # (res: FusedCycleResult) -> flat f32 [L]


def make_fused_fns(meta, params: AgentParams, n_total: int,
                   max_rounds: int = 256, check_every: int = 8) -> FusedFns:
    def _recenter(Xg, gp, graph, target: DF):
        R, f_ref, consts, rho32 = recenter_device(Xg, gp, graph, meta,
                                                  params, n_total)
        thr = df32.add(target, df32.neg(f_ref)).hi
        return R, f_ref, consts, rho32, thr

    def _refine(consts, graph, gp, rho32, thr):
        D0 = jnp.zeros(consts.R.shape, jnp.float32)
        return refine_until(D0, consts, graph, meta, params, gp, rho32,
                            thr, n_total, max_rounds, check_every)

    return FusedFns(
        recenter=df32.precise_jit(_recenter),
        refine=jax.jit(_refine),
        nxt=jax.jit(lambda res, graph: next_iterate(res, graph, n_total)),
        pack=jax.jit(pack_result))


def run_fused_cycles(fns: FusedFns, Xg0, gp: GlobalProblemDF, graph,
                     target: DF, cycles: int = 2) -> FusedCycleResult:
    """Chain ``cycles`` recenter+refine cycles with NO host round-trip:
    every call is an async dispatch on device-resident values.  A cycle
    whose predecessor already hit the oracle target exits its refine
    while_loop at round 0, but still pays its RECENTER (the most
    expensive single program here: one extra cycle measured +0.046 s on
    the sphere bench) — provision cycles for the problem, not 'just in
    case'.
    Returns the LAST cycle's result (read it back ONCE, then
    ``assemble_f64`` + ``refine.global_cost`` for the f64 verify)."""
    Xg = Xg0
    res = None
    for _ in range(cycles):
        R, f_ref, consts, rho32, thr = fns.recenter(Xg, gp, graph, target)
        D, rounds, delta = fns.refine(consts, graph, gp, rho32, thr)
        res = FusedCycleResult(R.hi, R.lo, D, f_ref.hi, f_ref.lo, delta,
                               rounds)
        Xg = fns.nxt(res, graph)
    return res
