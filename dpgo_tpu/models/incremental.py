"""Live sessions: streaming edges and warm restarts over a fixed pose set.

Everything upstream of this module is cold-solve: a new measurement means
rebuilding the problem (``prepare_problem``) and re-initializing from the
centralized chordal solve.  The RBCD formulation makes that unnecessary —
new edges only ADD rows to the connection Laplacian ``Q`` and the linear
term ``G`` (T-RO 2021, eq. 14: both are sums over edges), and the
async-RBCD theory (RA-L 2020) tolerates resuming descent from any feasible
iterate.  ``LiveProblem`` exploits both:

* **Delta apply** (``apply_edges``): a streamed edge batch lands as pure
  masked appends into the *padded* per-agent layout of the serving plane
  (``serve.bucketing``): new edge rows occupy previously-masked rows of the
  padded ``EdgeSet``, new neighbor slots / public poses occupy masked rows
  of their tables, and the ELL incidence rows of the endpoint poses grow in
  place.  Every padded dimension is unchanged, so the bucket shape — and
  with it the config fingerprint and every compiled executable keyed on it
  (the fused segment program above all) — is REUSED.  When an append would
  overflow the padding, the problem re-pads (same bucket: still no
  recompile) or re-buckets (grown shape: one honest recompile), explicitly
  reported in the returned ``EdgeDelta``.

* **Warm restart** (``warm_dispatch``): resume ``dispatch_prepared`` from
  an exact ``RBCDState`` snapshot — the terminal state of the previous
  solve (``RBCDResult.state``), a flight-recorder snapshot, or a serving
  session snapshot (``serve.session``) — instead of the chordal init.  The
  carried GNC weights are remapped onto the (possibly reordered) edge rows
  through the global measurement ids, the convergence bookkeeping
  (``ready``/``rel_change``) resets because the problem changed, and the
  preconditioner factors are recomputed from the live weights
  (``refresh_problem``).

The pose set is FIXED for the life of a ``LiveProblem``: streaming
measurements between existing poses (loop closures, re-observations,
cross-robot matches) is the supported surface; a measurement referencing a
new pose raises, because ``partition_contiguous`` re-derives the
pose-to-robot map from the total count and a grown count would silently
reassign every pose.  Growing the *fleet* mid-solve is the deployment
plane's job (``comms.bus`` join handshake + ``PGOAgent.admit_neighbor``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..config import AgentParams, Schedule
from ..types import EdgeSet, Measurements
from ..utils.partition import partition_contiguous
from .rbcd import (MultiAgentGraph, PreparedProblem, RBCDResult, RBCDState,
                   dispatch_prepared, prepare_problem, refresh_problem)


class EdgeDelta(NamedTuple):
    """Outcome of one ``apply_edges`` call.

    ``mode`` is ``"delta"`` (masked appends in place — executables reused),
    ``"repad"`` (rebuilt, but re-padded to the SAME bucket shape — compiled
    programs still reused), or ``"rebucket"`` (the padding overflowed: the
    bucket grew and the next dispatch compiles)."""

    mode: str
    num_edges: int
    shape: "tuple"
    recompiles: bool


class LiveProblem:
    """A prepared problem that absorbs streamed edges and warm restarts.

    Holds the accumulated measurement set, the current padded problem at
    its bucket shape, and numpy mirrors of the padded per-agent arrays the
    delta path appends into.  ``prob`` exposes the dispatch view (a
    ``PreparedProblem`` whose graph/meta are the PADDED ones, so repeated
    dispatches across deltas hit the jit cache on one segment program).
    """

    def __init__(self, meas: Measurements, num_robots: int,
                 params: AgentParams | None = None, dtype=jnp.float64,
                 quantum: int = 32, init: str = "chordal",
                 headroom: int = 1):
        self.num_robots = int(num_robots)
        self.params = params or AgentParams(d=meas.d, r=5,
                                            num_robots=num_robots)
        self.dtype = dtype
        self.quantum = int(quantum)
        self.init_policy = init
        #: Extra quanta of padding reserved in every streamable dimension
        #: (edges, slots, public poses, ELL degree, measurement count) so a
        #: stream has room to append before its first forced re-bucket.
        #: 0 = the serving plane's exact bucket.
        self.headroom = int(headroom)
        self._meas = meas
        self.deltas_applied = 0
        #: The most recent ``apply_edges`` outcome (None before the first).
        self.last_delta: EdgeDelta | None = None
        self._rebuild(meas, prefer_shape=None)

    # -- dispatch views ------------------------------------------------------

    @property
    def prob(self) -> PreparedProblem:
        """Dispatch-ready view at the padded bucket shape."""
        p = self.padded
        return PreparedProblem(part=self.part, graph=p.graph, meta=p.meta,
                               params=self.params, dtype=self.dtype,
                               X0=p.X0)

    @property
    def num_meas(self) -> int:
        return len(self._meas)

    @property
    def meas(self) -> Measurements:
        return self._meas

    def solve(self, **dispatch_kw) -> RBCDResult:
        """Cold dispatch of the current problem (chordal-initialized).  The
        returned result's ``.state`` is the warm-restart handle for the
        next ``warm_dispatch``."""
        return dispatch_prepared(self.prob, **dispatch_kw)

    # -- rebuild path --------------------------------------------------------

    def _rebuild(self, meas: Measurements, prefer_shape) -> str:
        """Full rebuild: re-prepare, re-pad (to ``prefer_shape`` when the
        new problem still fits it — executable reuse), reload mirrors."""
        from ..serve.bucketing import bucket_shape_of, pad_problem

        part = partition_contiguous(meas, self.num_robots)
        raw = prepare_problem(meas, self.num_robots, params=self.params,
                              dtype=self.dtype, part=part, init=None,
                              pallas_sel=False)
        want = bucket_shape_of(raw, quantum=self.quantum)
        if self.headroom > 0:
            # The pose set is fixed (n_max/n_total never grow); every
            # edge-driven dimension reserves stream room.
            q, sq = self.headroom * self.quantum, self.headroom * 8
            want = want._replace(
                e_max=want.e_max + q, s_max=want.s_max + sq,
                p_max=want.p_max + sq, k_inc=want.k_inc + sq,
                num_meas=want.num_meas + q)
        if prefer_shape is not None and all(
                w <= s for w, s in zip(want, prefer_shape)):
            shape, mode = prefer_shape, "repad"
        else:
            shape, mode = want, "rebucket"
        self.padded = pad_problem(raw, shape, init=self.init_policy)
        self.shape = shape
        self.part = part
        self._meas = meas
        self._load_mirrors()
        return mode

    def _load_mirrors(self) -> None:
        """Host-side numpy mirrors of the padded arrays the delta path
        mutates, plus the occupancy bookkeeping (valid counts per padded
        table) and the key->row dictionaries the append staging needs."""
        g = self.padded.graph
        e = g.edges
        m = self.padded.meta
        self._np = {
            "ei": np.asarray(e.i).copy(), "ej": np.asarray(e.j).copy(),
            "R": np.asarray(e.R).copy(), "t": np.asarray(e.t).copy(),
            "kappa": np.asarray(e.kappa).copy(),
            "tau": np.asarray(e.tau).copy(),
            "weight": np.asarray(e.weight).copy(),
            "mask": np.asarray(e.mask).copy(),
            "is_lc": np.asarray(e.is_lc).copy(),
            "fixed": np.asarray(e.fixed_weight).copy(),
            "meas_id": np.asarray(g.meas_id).copy(),
            "pub_idx": np.asarray(g.pub_idx).copy(),
            "pub_mask": np.asarray(g.pub_mask).copy(),
            "nbr_robot": np.asarray(g.nbr_robot).copy(),
            "nbr_pub": np.asarray(g.nbr_pub).copy(),
            "nbr_mask": np.asarray(g.nbr_mask).copy(),
            "inc_slot": np.asarray(g.inc_slot).copy(),
            "inc_mask": np.asarray(g.inc_mask).copy(),
        }
        eg = self.padded.edges_g
        self._g = {f: np.asarray(getattr(eg, f)).copy()
                   for f in ("i", "j", "R", "t", "kappa", "tau", "weight",
                             "mask", "is_lc", "fixed_weight")}
        A = m.num_robots
        self._e_used = self._np["mask"].sum(axis=1).astype(int)
        self._p_used = self._np["pub_mask"].sum(axis=1).astype(int)
        self._s_used = self._np["nbr_mask"].sum(axis=1).astype(int)
        self._inc_used = self._np["inc_mask"].sum(axis=2).astype(int)
        # (local pose -> pub row) per agent, and ((robot, pose) -> slot).
        self._pub_row = [
            {int(self._np["pub_idx"][a, r]): r
             for r in range(self._p_used[a])} for a in range(A)]
        self._slot_of = []
        for a in range(A):
            d = {}
            for s in range(self._s_used[a]):
                b = int(self._np["nbr_robot"][a, s])
                q = int(self._np["pub_idx"][b, int(self._np["nbr_pub"][a, s])])
                d[(b, q)] = s
            self._slot_of.append(d)

    # -- the delta path ------------------------------------------------------

    def _robot_of(self, p: np.ndarray):
        """The contiguous partition's pose->robot map (must agree with
        ``partition_contiguous`` exactly — same arithmetic)."""
        npr = self._meas.num_poses // self.num_robots
        robot = np.minimum(p // npr, self.num_robots - 1)
        return robot.astype(np.int64), (p - robot * npr).astype(np.int64)

    def apply_edges(self, new_meas: Measurements) -> EdgeDelta:
        """Absorb a batch of streamed measurements between EXISTING poses.

        Fast path: stage masked appends against copies of the occupancy
        counters; commit only when every padded table has room.  Any
        overflow (or the COLORED schedule, whose agent coloring a new
        shared edge can invalidate) falls back to a full rebuild —
        re-padded to the same bucket when it still fits (``"repad"``, no
        recompile), else grown (``"rebucket"``)."""
        if new_meas.d != self._meas.d:
            raise ValueError(f"dimension mismatch: d={new_meas.d} vs "
                             f"{self._meas.d}")
        if len(new_meas) == 0:
            return EdgeDelta("delta", 0, tuple(self.shape), False)
        if np.any(np.asarray(new_meas.r1) != 0) or \
                np.any(np.asarray(new_meas.r2) != 0):
            raise ValueError("apply_edges expects globally-indexed "
                             "measurements (r1 == r2 == 0)")
        p1 = np.asarray(new_meas.p1, np.int64)
        p2 = np.asarray(new_meas.p2, np.int64)
        n_total = self._meas.num_poses
        if new_meas.num_poses > n_total or max(p1.max(), p2.max()) >= n_total:
            raise ValueError(
                "streamed measurements reference poses beyond the live "
                "problem's fixed pose set — streaming NEW poses is not "
                "supported (the contiguous partition would reassign every "
                "pose); build a fresh LiveProblem instead")

        cat = Measurements.concatenate([self._meas, new_meas])
        mode = None
        if self.params.schedule != Schedule.COLORED:
            mode = self._try_delta(new_meas, cat)
        if mode is None:
            mode = self._rebuild(cat, prefer_shape=self.shape)
        self.deltas_applied += 1
        delta = EdgeDelta(mode, len(new_meas), tuple(self.shape),
                          mode == "rebucket")
        self.last_delta = delta
        run = obs.get_run()
        if run is not None:
            run.event("live_delta", phase="live", mode=mode,
                      num_edges=len(new_meas),
                      num_meas=len(self._meas),
                      delta_index=self.deltas_applied)
            run.counter("live_edges_streamed_total",
                        "measurements absorbed by live deltas").inc(
                len(new_meas), mode=mode)
        return delta

    def _try_delta(self, new_meas: Measurements, cat: Measurements):
        """Stage + commit the masked appends; None when any table lacks
        room (the caller rebuilds)."""
        shape = self.shape
        m = self.padded.meta
        n_pad = m.n_max
        e_pad = m.e_max
        A = m.num_robots
        m_used = len(self._meas)
        if m_used + len(new_meas) > shape.num_meas:
            return None

        p1 = np.asarray(new_meas.p1, np.int64)
        p2 = np.asarray(new_meas.p2, np.int64)
        ra, la = self._robot_of(p1)
        rb, lb = self._robot_of(p2)

        # Staged copies: committed only if everything fits.
        e_used = self._e_used.copy()
        p_used = self._p_used.copy()
        s_used = self._s_used.copy()
        inc_used = self._inc_used.copy()
        pub_row = [dict(d) for d in self._pub_row]
        slot_of = [dict(d) for d in self._slot_of]
        new_pub: list[tuple[int, int, int]] = []    # (agent, pose, row)
        new_slot: list[tuple[int, int, int, int]] = []  # (agent, s, robot, row)
        # (agent, row, ti, hi, k) per edge copy; k indexes new_meas.
        rows: list[tuple[int, int, int, int, int]] = []

        def ensure_pub(a: int, pose: int):
            r = pub_row[a].get(pose)
            if r is not None:
                return r
            if p_used[a] >= shape.p_max:
                return None
            r = int(p_used[a])
            p_used[a] += 1
            pub_row[a][pose] = r
            new_pub.append((a, pose, r))
            return r

        def ensure_slot(a: int, b: int, q: int):
            s = slot_of[a].get((b, q))
            if s is not None:
                return s
            r = ensure_pub(b, q)
            if r is None or s_used[a] >= shape.s_max:
                return None
            s = int(s_used[a])
            s_used[a] += 1
            slot_of[a][(b, q)] = s
            new_slot.append((a, s, b, r))
            return s

        stage_inc: list[tuple[int, int, int]] = []

        def stage_row(a: int, ti: int, hi: int, k: int) -> bool:
            if e_used[a] >= e_pad:
                return False
            row = int(e_used[a])
            # ELL incidence for local endpoints: slot ``row`` for the tail
            # half, ``e_pad + row`` for the head half (the [gi | gj]
            # concatenation egrad_ell gathers).  Slot endpoints get no
            # incidence entry — gradients only accumulate on local poses.
            if ti < n_pad and inc_used[a, ti] >= shape.k_inc:
                return False
            if hi < n_pad and inc_used[a, hi] >= shape.k_inc:
                return False
            e_used[a] += 1
            if ti < n_pad:
                stage_inc.append((a, ti, row))
                inc_used[a, ti] += 1
            if hi < n_pad:
                stage_inc.append((a, hi, e_pad + row))
                inc_used[a, hi] += 1
            rows.append((a, row, ti, hi, k))
            return True
        for k in range(len(new_meas)):
            a, b = int(ra[k]), int(rb[k])
            pa, pb = int(la[k]), int(lb[k])
            if a == b:
                if not stage_row(a, pa, pb, k):
                    return None
            else:
                # Both endpoint poses become public on their owners; each
                # owner holds a copy with the remote endpoint in a slot.
                if ensure_pub(a, pa) is None or ensure_pub(b, pb) is None:
                    return None
                sa = ensure_slot(a, b, pb)
                sb = ensure_slot(b, a, pa)
                if sa is None or sb is None:
                    return None
                if not stage_row(a, pa, n_pad + sa, k):
                    return None
                if not stage_row(b, n_pad + sb, pb, k):
                    return None

        # -- commit ----------------------------------------------------------
        npd = self._np
        for a, pose, r in new_pub:
            npd["pub_idx"][a, r] = pose
            npd["pub_mask"][a, r] = 1.0
        for a, s, b, r in new_slot:
            npd["nbr_robot"][a, s] = b
            npd["nbr_pub"][a, s] = r
            npd["nbr_mask"][a, s] = 1.0
        for a, pose, slot_val in stage_inc:
            col = int(self._inc_used[a, pose])
            # staged additions to one pose arrive in order; track the fill
            while col < shape.k_inc and npd["inc_mask"][a, pose, col] > 0:
                col += 1
            npd["inc_slot"][a, pose, col] = slot_val
            npd["inc_mask"][a, pose, col] = 1.0
        is_lc_f = (~((ra == rb) & (p1 + 1 == p2))).astype(np.float64)
        fixed_f = np.asarray(new_meas.is_known_inlier,
                             bool).astype(np.float64)
        R_new = np.asarray(new_meas.R)
        t_new = np.asarray(new_meas.t)
        for a, row, ti, hi, k in rows:
            npd["ei"][a, row] = ti
            npd["ej"][a, row] = hi
            npd["R"][a, row] = R_new[k]
            npd["t"][a, row] = t_new[k]
            npd["kappa"][a, row] = new_meas.kappa[k]
            npd["tau"][a, row] = new_meas.tau[k]
            npd["weight"][a, row] = new_meas.weight[k]
            npd["mask"][a, row] = 1.0
            npd["is_lc"][a, row] = is_lc_f[k]
            npd["fixed"][a, row] = fixed_f[k]
            npd["meas_id"][a, row] = m_used + k
        gm = self._g
        gids = m_used + np.arange(len(new_meas))
        gm["i"][gids] = p1
        gm["j"][gids] = p2
        gm["R"][gids] = R_new
        gm["t"][gids] = t_new
        gm["kappa"][gids] = new_meas.kappa
        gm["tau"][gids] = new_meas.tau
        gm["weight"][gids] = new_meas.weight
        gm["mask"][gids] = 1.0
        gm["is_lc"][gids] = is_lc_f
        gm["fixed_weight"][gids] = fixed_f

        self._e_used = e_used
        self._p_used = p_used
        self._s_used = s_used
        self._inc_used = self._np["inc_mask"].sum(axis=2).astype(int)
        self._pub_row = pub_row
        self._slot_of = slot_of
        self._meas = cat
        self.part = partition_contiguous(cat, self.num_robots)
        self._upload()
        return "delta"

    def _upload(self) -> None:
        """Rebuild the device-side padded graph / global edge set from the
        mirrors (array shapes unchanged — the compiled programs re-run on
        the fresh buffers without retracing)."""
        npd = self._np
        g_old = self.padded.graph
        fdt = npd["R"].dtype
        edges = EdgeSet(
            i=jnp.asarray(npd["ei"]), j=jnp.asarray(npd["ej"]),
            R=jnp.asarray(npd["R"], fdt), t=jnp.asarray(npd["t"], fdt),
            kappa=jnp.asarray(npd["kappa"], fdt),
            tau=jnp.asarray(npd["tau"], fdt),
            weight=jnp.asarray(npd["weight"], fdt),
            mask=jnp.asarray(npd["mask"], fdt),
            is_lc=jnp.asarray(npd["is_lc"], fdt),
            fixed_weight=jnp.asarray(npd["fixed"], fdt))
        graph = MultiAgentGraph(
            edges=edges,
            meas_id=jnp.asarray(npd["meas_id"].astype(np.int32)),
            n=g_old.n, pose_mask=g_old.pose_mask,
            pub_idx=jnp.asarray(npd["pub_idx"].astype(np.int32)),
            pub_mask=jnp.asarray(npd["pub_mask"], fdt),
            nbr_robot=jnp.asarray(npd["nbr_robot"]),
            nbr_pub=jnp.asarray(npd["nbr_pub"]),
            nbr_mask=jnp.asarray(npd["nbr_mask"], fdt),
            global_index=g_old.global_index,
            inc_slot=jnp.asarray(npd["inc_slot"]),
            inc_mask=jnp.asarray(npd["inc_mask"], fdt),
            color=g_old.color,
            eidx_i=None, eidx_j=None, rot_t=None, trn_t=None)
        gm = self._g
        edges_g = EdgeSet(
            i=jnp.asarray(gm["i"]), j=jnp.asarray(gm["j"]),
            R=jnp.asarray(gm["R"], fdt), t=jnp.asarray(gm["t"], fdt),
            kappa=jnp.asarray(gm["kappa"], fdt),
            tau=jnp.asarray(gm["tau"], fdt),
            weight=jnp.asarray(gm["weight"], fdt),
            mask=jnp.asarray(gm["mask"], fdt),
            is_lc=jnp.asarray(gm["is_lc"], fdt),
            fixed_weight=jnp.asarray(gm["fixed_weight"], fdt))
        prob_new = dataclasses.replace(self.padded.prob, part=self.part)
        self.padded = dataclasses.replace(self.padded, prob=prob_new,
                                          graph=graph, edges_g=edges_g)

    # -- warm restarts -------------------------------------------------------

    def warm_dispatch(self, state: "RBCDState | RBCDResult",
                      new_edges: Measurements | None = None,
                      max_iters: int | None = None,
                      grad_norm_tol: float = 0.1, eval_every: int = 1,
                      verdict_every: int | None = None) -> RBCDResult:
        """Resume solving from an exact snapshot after (optionally)
        absorbing ``new_edges`` — the streaming restart of ROADMAP item 3.

        ``state`` must correspond to the problem as it was BEFORE
        ``new_edges`` (a prior solve's ``RBCDResult`` — its ``.state`` is
        used — a ``serve.session`` snapshot, or a flight-recorder
        snapshot); the carried GNC weights are remapped to the new edge
        rows through the global measurement ids, so the delta path's
        in-place appends and a full rebuild's reordered rows resume
        identically."""
        if isinstance(state, RBCDResult):
            if state.state is None:
                raise ValueError("result carries no terminal state to "
                                 "resume from")
            state = state.state
        old_map = (self._np["meas_id"].copy(), self._np["mask"].copy(),
                   len(self._meas))
        if new_edges is not None and len(new_edges):
            self.apply_edges(new_edges)
        state = self._adapt_state(state, old_map)
        return dispatch_prepared(self.prob, max_iters=max_iters,
                                 grad_norm_tol=grad_norm_tol,
                                 eval_every=eval_every, state=state,
                                 verdict_every=verdict_every)

    def _adapt_state(self, state: RBCDState, old_map) -> RBCDState:
        """Map a snapshot onto the CURRENT padded layout: pad the iterate
        to a grown bucket, remap weights by measurement id, reset the
        convergence bookkeeping, and refresh the carried factors."""
        meta = self.padded.meta
        old_meas_id, old_mask, m_old = old_map
        X = np.asarray(state.X)
        A, n_old = X.shape[0], X.shape[1]
        if A != meta.num_robots:
            raise ValueError(f"snapshot has {A} agents, problem has "
                             f"{meta.num_robots}")
        dn = meta.n_max - n_old
        if dn < 0:
            raise ValueError("snapshot is wider than the live problem — "
                             "buckets only grow")

        def pad_poses(a):
            a = np.asarray(a)
            if dn == 0:
                return a
            return np.concatenate(
                [a, np.broadcast_to(a[:, :1], (A, dn) + a.shape[2:])], axis=1)

        # Weights: collapse the OLD per-agent rows to per-measurement
        # (shared copies are identical — masked mean is exact), then
        # scatter onto the new rows; rows for streamed measurements take
        # the build-time weight.
        w_old = np.asarray(state.weights)
        ids = old_meas_id.reshape(-1)
        msk = old_mask.reshape(-1)
        if w_old.size != ids.size:
            raise ValueError(
                "snapshot weights do not match the pre-delta edge layout — "
                "pass the state captured before these edges were applied")
        num = np.zeros(m_old)
        den = np.zeros(m_old)
        np.add.at(num, ids, w_old.reshape(-1) * msk)
        np.add.at(den, ids, msk)
        w_glob = np.where(den > 0, num / np.maximum(den, 1.0), 1.0)
        new_id = self._np["meas_id"]
        new_mask = self._np["mask"] > 0
        carried = new_mask & (new_id < m_old)
        w_new = self._np["weight"].copy()
        w_new[carried] = w_glob[new_id[carried]]

        dt = X.dtype
        accel = state.V is not None
        Xp = jnp.asarray(pad_poses(X))
        state = RBCDState(
            X=Xp,
            weights=jnp.asarray(w_new, w_old.dtype),
            iteration=jnp.array(0, jnp.int32),
            key=state.key,
            rel_change=jnp.full((A,), jnp.inf, dt),
            ready=jnp.zeros((A,), bool),
            # A changed problem restarts the Nesterov sequences (the same
            # collapse a weight-update round performs).
            V=Xp if accel else None,
            gamma=jnp.zeros((A,), dt),
            alpha=jnp.zeros((A,), dt),
            mu=state.mu,
            X_init=jnp.asarray(pad_poses(np.asarray(state.X_init)))
            if state.X_init is not None else None,
            chol=None, Qbuf=None)
        return refresh_problem(state, self.padded.graph, meta, self.params)


def state_from_arrays(arrays: dict) -> RBCDState:
    """Rebuild an ``RBCDState`` from the array dict the snapshot codecs
    persist (the flight recorder's ``snap*_`` fields, ``serve.session``
    files).  Factors (``chol``/``Qbuf``) recompute via
    ``refresh_problem``."""
    return RBCDState(
        X=jnp.asarray(arrays["X"]), weights=jnp.asarray(arrays["weights"]),
        iteration=jnp.asarray(arrays.get("iteration", 0), jnp.int32),
        key=jnp.asarray(arrays["key"]),
        rel_change=jnp.asarray(arrays["rel_change"]),
        ready=jnp.asarray(arrays["ready"]),
        V=jnp.asarray(arrays["V"]) if "V" in arrays else None,
        gamma=jnp.asarray(arrays["gamma"]),
        alpha=jnp.asarray(arrays["alpha"]),
        mu=jnp.asarray(arrays["mu"]),
        X_init=jnp.asarray(arrays["X_init"]) if "X_init" in arrays else None,
        chol=None, Qbuf=None)


def state_to_arrays(state: RBCDState) -> dict:
    """The inverse codec: every persistable ``RBCDState`` field as host
    arrays (the recomputable factors are dropped — ``refresh_problem``
    restores them bit-for-bit from the weights)."""
    out = {}
    for f in ("X", "weights", "iteration", "key", "rel_change", "ready",
              "gamma", "alpha", "mu", "V", "X_init"):
        v = getattr(state, f)
        if v is None:
            continue
        out[f] = np.asarray(v)
    return out
