"""Multi-agent Riemannian block-coordinate descent (RBCD) — the distributed
core of the framework.

Replaces the reference's per-robot ``PGOAgent`` object graph
(``src/PGOAgent.cpp``) and the in-process message loop of
``examples/MultiRobotExample.cpp`` with a TPU-native design (SURVEY.md
section 7): all agents' states live in one batched array ``X: [A, n_max, r,
d+1]``, a single jitted step function updates the selected/all blocks, and
"communication" is an array gather of the public-pose table (a collective in
the sharded path, ``dpgo_tpu.parallel``).

Mapping to the reference:

* measurement classification odometry / private LC / shared LC
  (``PGOAgent.cpp:197-248``)  ->  host-side graph builder, one padded
  ``EdgeSet`` per agent whose indices point into a per-agent buffer
  ``[local poses | neighbor slots]``.
* ``constructQMatrix`` / ``constructGMatrix`` (``PGOAgent.cpp:720-859``)
  ->  nothing to construct: the per-agent cost/gradient/Hessian evaluate
  edge-wise against the buffer (``ops.quadratic``); fixed neighbor slots
  reproduce Q's shared-edge diagonal blocks and the linear term G exactly.
* ``iterate(true)`` + ``QuadraticOptimizer`` (``PGOAgent.cpp:642-718``,
  ``1093-1145``)  ->  ``ops.solver.rtr_single_step`` vmapped over agents.
* greedy selection by block gradient norm
  (``MultiRobotExample.cpp:242-256``)  ->  GREEDY schedule (argmax of the
  per-agent Riemannian gradient norms, computed locally — no centralized
  oracle needed).  JACOBI updates all agents each round (the TPU-native
  default; Jacobi-style parallel RBCD is what the reference's async mode
  realizes in wall-clock).  ASYNC fires each agent with an independent
  Bernoulli clock per round (``PGOAgent.cpp:876-898`` semantics).
* termination status gossip (``PGOAgent.h:163-207``, ``shouldTerminate``,
  ``PGOAgent.cpp:1007-1031``)  ->  per-agent relative-change array reduced
  with ``all``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import (AgentParams, ROptAlg, RobustCostParams,
                      RobustCostType, Schedule)
from .. import obs
from ..obs import trace
from .. import robust
from ..types import EdgeSet, Measurements, edge_set_from_measurements
from ..utils.graph_plan import plan_topology
from ..utils.lie import lifting_matrix as _lifting_matrix
from ..utils.partition import Partition, partition_contiguous
from ..ops import chordal, manifold, quadratic, solver
from .local_pgo import lift, round_solution


@dataclasses.dataclass(frozen=True)
class GraphMeta:
    """Static shape metadata (hashable; a jit static argument)."""

    num_robots: int
    n_max: int
    e_max: int
    s_max: int  # neighbor slots per agent
    p_max: int  # public poses per agent
    d: int
    rank: int
    # Chromatic size of the greedy agent coloring (Schedule.COLORED fires
    # color class (iteration mod num_colors) each round).
    num_colors: int = 1


class MultiAgentGraph(NamedTuple):
    """Batched per-agent problem data (pytree of [A, ...] arrays)."""

    edges: EdgeSet  # fields [A, E_max]; i/j index into [n_max + S_max] buffer
    meas_id: jax.Array  # [A, E_max] global measurement id (weight consistency)
    n: jax.Array  # [A] pose counts
    pose_mask: jax.Array  # [A, n_max]
    pub_idx: jax.Array  # [A, P_max] local indices of public poses
    pub_mask: jax.Array  # [A, P_max]
    nbr_robot: jax.Array  # [A, S_max]
    nbr_pub: jax.Array  # [A, S_max] slot into that robot's public row
    nbr_mask: jax.Array  # [A, S_max]
    global_index: jax.Array  # [A, n_max] local -> global pose id (0 for pad)
    # ELL incidence of local poses (gather-only gradient/Hessian path,
    # ``ops.quadratic.egrad_ell``): slot e = endpoint i of edge e, slot
    # E_max + e = endpoint j.  K = max local pose degree over the partition.
    inc_slot: jax.Array  # [A, n_max, K] into the [gi | gj] concatenation
    inc_mask: jax.Array  # [A, n_max, K]
    # Tile-major edge data for the Pallas VMEM solver kernels
    # (``ops.pallas_tcg``): edges padded to nt * T and stored with the tile
    # axis leading so the kernel streams one [*, T] tile per ``fori_loop``
    # step, building each one-hot selection tile on the fly from the int32
    # endpoint indices (memory O(E), vs the O(E*n) resident one-hot
    # matrices of the first design).  Padded edges carry index
    # n_max + s_max, which one-hots to all-zero in both the local and the
    # neighbor range.  None when built with pallas_sel=False.
    eidx_i: jax.Array | None = None  # [A, nt, 1, T] int32 into [n+s] buffer
    eidx_j: jax.Array | None = None  # [A, nt, 1, T]
    rot_t: jax.Array | None = None   # [A, nt, d*d, T]
    trn_t: jax.Array | None = None   # [A, nt, d, T]
    # Greedy agent coloring (``utils.graph_plan.color_agents``): agents of
    # one color share no edge; Schedule.COLORED fires one class per round.
    color: jax.Array | None = None   # [A] int32


class RBCDState(NamedTuple):
    X: jax.Array  # [A, n_max, r, d+1]
    weights: jax.Array  # [A, E_max] robust (GNC) weights per edge
    iteration: jax.Array  # int32
    key: jax.Array  # [A, 2] per-agent PRNG keys (async schedule)
    rel_change: jax.Array  # [A]
    ready: jax.Array  # [A] bool
    # Nesterov acceleration (RA-L 2020; reference PGOAgent.cpp:1054-1091).
    # V is the auxiliary sequence (None when acceleration is off); gamma and
    # alpha are the per-agent momentum scalars.  Y is recomputed every round
    # from (X, V, alpha) and never carried across rounds (the reference's
    # stored Y is always refreshed by updateY before any use).
    V: jax.Array | None  # [A, n_max, r, d+1] or None
    gamma: jax.Array  # [A]
    alpha: jax.Array  # [A]
    # GNC control parameter (reference RobustCost::mu, DPGO_robust.cpp:85-103).
    mu: jax.Array  # scalar
    # Initial guess, kept only when the robust warm start is disabled: the
    # iterate resets to it on every weight update (PGOAgent.cpp:657-662).
    X_init: jax.Array | None  # [A, n_max, r, d+1] or None
    # Block-Jacobi preconditioner factors [A, n_max, d+1, d+1].  Q's diagonal
    # blocks depend only on the GNC weights, so the factorization is carried
    # across rounds and refreshed only on weight-update rounds — the same
    # schedule as the reference's CHOLMOD refactorization
    # (constructQMatrix inside updateX only in robust mode,
    # PGOAgent.cpp:1110-1112; QuadraticProblem::setQ factorizes, cpp:37-41).
    chol: jax.Array | None = None
    # Materialized per-agent connection Laplacian over the pose buffer,
    # [A, (d+1)(n_max+s_max), (d+1)(n_max+s_max)] (``quadratic.dense_q``) —
    # the dense-Q fast path; None when the buffers are too large to
    # materialize (``use_dense_q``).  Same refresh schedule as ``chol``.
    Qbuf: jax.Array | None = None


def build_graph(part: Partition, rank: int, dtype=jnp.float32,
                pallas_sel: bool | None = None, planner: str = "auto",
                wide_tiles: bool | None = None,
                sel_mode: str | None = None):
    """Assemble padded per-agent arrays from a partitioned measurement set.

    Each shared measurement appears in both endpoint agents' edge lists with
    the remote endpoint redirected to a neighbor slot — the same double
    bookkeeping as ``PGOAgent::addSharedLoopClosure`` (reference
    ``PGOAgent.cpp:228-248``), but as index arrays instead of dictionaries.
    Topology (edge rows, slot tables, ELL incidence) comes from the planner
    (``utils.graph_plan``: native C++ when available, Python fallback —
    identical output); the per-edge payload scatter here is vectorized
    numpy.
    """
    A = part.num_robots
    meas = part.meas
    d = meas.d
    n_max = part.n_max

    plan = plan_topology(meas.r1, meas.p1, meas.r2, meas.p2, A, n_max,
                         backend=planner)
    e_max, s_max, p_max = plan.e_max, plan.s_max, plan.p_max

    cls = part.classify()  # 0 odo, 1 private LC, 2 shared

    # Vectorized per-edge payload scatter over the planned rows.
    valid = plan.emask  # [A, e_max] bool
    kk = plan.meas_id[valid]  # global measurement id per valid (a, idx)
    eR = np.tile(np.eye(d), (A, e_max, 1, 1))
    et = np.zeros((A, e_max, d))
    ekap = np.zeros((A, e_max))
    etau = np.zeros((A, e_max))
    eis_lc = np.zeros((A, e_max))
    efix = np.zeros((A, e_max))
    eweight = np.ones((A, e_max))
    eR[valid] = meas.R[kk]
    et[valid] = meas.t[kk]
    ekap[valid] = meas.kappa[kk]
    etau[valid] = meas.tau[kk]
    eis_lc[valid] = (cls[kk] != 0).astype(np.float64)
    efix[valid] = np.asarray(meas.is_known_inlier, bool)[kk].astype(np.float64)
    eweight[valid] = meas.weight[kk]

    # Tile-major edge arrays for the Pallas tCG kernel (int32 endpoint
    # indices + edge transforms, padded to nt * T — O(E) memory, so no
    # budget gate is needed at build time).  Skipped entirely
    # (pallas_sel=None -> auto) off-TPU, where the kernel would only ever
    # run in interpreter mode — force with pallas_sel=True for
    # interpreter-mode testing.
    if pallas_sel is None:
        pallas_sel = jax.default_backend() == "tpu"
    if pallas_sel:
        # Wide (T=256) tiles are sound only for bf16 selection modes
        # (half-size one-hot transients; f32 aborts in Mosaic — see
        # _edge_tile_shape).  Derive from ``sel_mode`` (the kernel
        # selection mode this graph will run under, e.g.
        # ``resolved_sel_mode(params)``) unless explicitly overridden.
        if wide_tiles is None:
            wide_tiles = sel_mode is not None and sel_mode != "f32"
        T, nt = _edge_tile_shape(n_max, s_max, e_max, wide=wide_tiles)
        Ep = nt * T
        pad_idx = n_max + s_max  # one-hots to all-zero in both ranges
        idx_i = np.full((A, Ep), pad_idx, np.int32)
        idx_j = np.full((A, Ep), pad_idx, np.int32)
        idx_i[:, :e_max][valid] = plan.ei[valid]
        idx_j[:, :e_max][valid] = plan.ej[valid]
        rot_flat = np.zeros((A, d * d, Ep), np.float32)
        trn_flat = np.zeros((A, d, Ep), np.float32)
        rot_flat[:, :, :e_max] = eR.transpose(0, 2, 3, 1).reshape(
            A, d * d, e_max)
        trn_flat[:, :, :e_max] = et.transpose(0, 2, 1)
        pallas_fields = dict(
            eidx_i=jnp.asarray(idx_i.reshape(A, nt, 1, T)),
            eidx_j=jnp.asarray(idx_j.reshape(A, nt, 1, T)),
            rot_t=jnp.asarray(np.ascontiguousarray(
                rot_flat.reshape(A, d * d, nt, T).transpose(0, 2, 1, 3))),
            trn_t=jnp.asarray(np.ascontiguousarray(
                trn_flat.reshape(A, d, nt, T).transpose(0, 2, 1, 3))))
    else:
        pallas_fields = dict(eidx_i=None, eidx_j=None, rot_t=None, trn_t=None)

    pose_mask = (np.arange(n_max)[None, :] < part.n[:, None]).astype(np.float64)

    edges = EdgeSet(
        i=jnp.asarray(plan.ei), j=jnp.asarray(plan.ej),
        R=jnp.asarray(eR, dtype), t=jnp.asarray(et, dtype),
        kappa=jnp.asarray(ekap, dtype), tau=jnp.asarray(etau, dtype),
        weight=jnp.asarray(eweight, dtype),
        mask=jnp.asarray(valid.astype(np.float64), dtype),
        is_lc=jnp.asarray(eis_lc, dtype), fixed_weight=jnp.asarray(efix, dtype),
    )
    from ..utils.graph_plan import color_agents
    color, num_colors = color_agents(plan.nbr_robot, plan.nbr_mask, A)

    graph = MultiAgentGraph(
        edges=edges,
        meas_id=jnp.asarray(plan.meas_id.astype(np.int32)),
        n=jnp.asarray(part.n, jnp.int32),
        pose_mask=jnp.asarray(pose_mask, dtype),
        pub_idx=jnp.asarray(np.maximum(plan.pub_idx, 0), jnp.int32),
        pub_mask=jnp.asarray(plan.pub_mask.astype(np.float64), dtype),
        nbr_robot=jnp.asarray(plan.nbr_robot),
        nbr_pub=jnp.asarray(plan.nbr_pub),
        nbr_mask=jnp.asarray(plan.nbr_mask.astype(np.float64), dtype),
        global_index=jnp.asarray(np.maximum(part.global_index, 0), jnp.int32),
        inc_slot=jnp.asarray(plan.inc_slot),
        inc_mask=jnp.asarray(plan.inc_mask.astype(np.float64), dtype),
        color=jnp.asarray(color),
        **pallas_fields,
    )
    meta = GraphMeta(num_robots=A, n_max=n_max, e_max=e_max, s_max=s_max,
                     p_max=p_max, d=d, rank=rank, num_colors=num_colors)
    return graph, meta


# ---------------------------------------------------------------------------
# Global <-> per-agent layout
# ---------------------------------------------------------------------------

def with_weights(graph: MultiAgentGraph, weights) -> MultiAgentGraph:
    """Graph with ``edges.weight`` replaced by ``weights [A, E_max]`` —
    use to evaluate/refine/certify the objective a robust (GNC) solve
    actually minimized (``RBCDState.weights``), since weight updates live
    in the state, not the build-time graph."""
    return graph._replace(edges=graph.edges._replace(
        weight=jnp.asarray(weights, graph.edges.weight.dtype)))


def scatter_to_agents(Xg: jax.Array, graph: MultiAgentGraph) -> jax.Array:
    """Global pose array [N, ...] -> per-agent [A, n_max, ...]."""
    return Xg[graph.global_index]


def gather_to_global(Xa: jax.Array, graph: MultiAgentGraph, n_total: int) -> jax.Array:
    """Per-agent [A, n_max, ...] -> global [N, ...] (padding dropped)."""
    flat_idx = graph.global_index.reshape(-1)
    flat = Xa.reshape((-1,) + Xa.shape[2:])
    w = graph.pose_mask.reshape(-1)
    out = jnp.zeros((n_total,) + Xa.shape[2:], Xa.dtype)
    return out.at[flat_idx].add(flat * w.reshape((-1,) + (1,) * (Xa.ndim - 2)))


def public_table(X: jax.Array, graph: MultiAgentGraph) -> jax.Array:
    """Extract each agent's public poses: [A, P_max, r, d+1].

    This is the message payload of the framework — the analog of
    ``getSharedPoseDict`` (reference ``PGOAgent.cpp:95-105``).
    """
    return jax.vmap(lambda x, idx: x[idx])(X, graph.pub_idx)


def neighbor_buffer(Xpub: jax.Array, graph: MultiAgentGraph) -> jax.Array:
    """Resolve neighbor slots from the (gathered) public table:
    [A, S_max, r, d+1].  The analog of ``updateNeighborPoses``
    (reference ``PGOAgent.cpp:434-458``)."""
    Z = Xpub[graph.nbr_robot, graph.nbr_pub]
    return Z * graph.nbr_mask[:, :, None, None]


class PPermutePlan(NamedTuple):
    """Per-agent routing for the ppermute pose exchange (all arrays [A, S_max],
    sharded over agents like the rest of the graph).

    ``src`` indexes the stacked received tables: 0 = this device's own table,
    1 + i = the table received at ``shifts[i]``; ``lrobot`` is the neighbor
    robot's *local* index on its home device."""

    src: jax.Array
    lrobot: jax.Array


def plan_ppermute(graph: MultiAgentGraph, num_robots: int, n_dev: int):
    """Host-side routing plan for the shift-based neighbor exchange.

    The all_gather v1 moves every agent's public table to every device —
    on a ring that is ``n_dev - 1`` hops of the full table regardless of who
    actually needs what (SURVEY.md section 2.4).  Robot adjacency in SLAM
    partitions is sparse and mostly local (contiguous partitions put the
    odometry-crossing edges between consecutive robots), so the set of
    *device-to-device* shifts that carry any edge is small; one
    ``lax.ppermute`` per needed shift moves only those tables.  Returns
    ``(shifts, plan)``: ``shifts`` is the static tuple of nonzero ring
    offsets (compile-time; one collective each), ``plan`` the per-agent
    routing arrays.  ``num_robots`` must be a multiple of ``n_dev``, with
    agents laid out in contiguous blocks per device (``shard_problem``)."""
    if num_robots % n_dev != 0:
        raise ValueError(
            f"num_robots={num_robots} must be a multiple of n_dev={n_dev} "
            "(contiguous agent blocks per device, as shard_problem lays out)")
    A_loc = num_robots // n_dev
    nbr_robot = np.asarray(graph.nbr_robot)
    nbr_mask = np.asarray(graph.nbr_mask) > 0
    dev_of = np.arange(num_robots) // A_loc
    da = dev_of[:, None]
    db = dev_of[nbr_robot]
    s = np.where(nbr_mask, (da - db) % n_dev, 0)
    shifts = tuple(sorted(set(s[nbr_mask].astype(int).tolist()) - {0}))
    pos = {0: 0, **{sh: i + 1 for i, sh in enumerate(shifts)}}
    src = np.zeros_like(s)
    for sh, p in pos.items():
        src[s == sh] = p
    plan = PPermutePlan(src=jnp.asarray(src, jnp.int32),
                        lrobot=jnp.asarray(nbr_robot % A_loc, jnp.int32))
    return shifts, plan


def _ppermute_exchange(Xl: jax.Array, graph: MultiAgentGraph,
                       plan: PPermutePlan, shifts: tuple, axis_name: str,
                       n_dev: int) -> jax.Array:
    """Neighbor buffer via one ppermute per needed device shift (the
    optimized ICI path; bitwise-identical result to the all_gather form)."""
    T = public_table(Xl, graph)  # this shard's own public table
    parts = [T]
    for s in shifts:
        perm = [(i, (i + s) % n_dev) for i in range(n_dev)]
        parts.append(jax.lax.ppermute(T, axis_name, perm))
    stacked = jnp.stack(parts)  # [1 + len(shifts), A_loc, P_max, r, d+1]
    Z = stacked[plan.src, plan.lrobot, graph.nbr_pub]
    return Z * graph.nbr_mask[:, :, None, None]


#: Collective fault-injection hook (``parallel.resilience``): when set,
#: every exchange closure built below is passed through it before use, so
#: chaos tests can corrupt halo payloads at the seam itself.  Trace-time —
#: only programs compiled while the hook is installed are affected.
_exchange_wrap = None


def _exchange_for(graph: MultiAgentGraph, A_tot: int, axis_name,
                  plan: PPermutePlan | None, shifts: tuple):
    """The pose-exchange closure of a round: neighbor buffer resolved from
    the all-gathered public table (v1), or the shift-based ppermute route
    when a ``plan`` is given; plain gathers with ``axis_name=None``.

    Factored out of ``_rbcd_round`` so the overlapped fused loop
    (``_rbcd_rounds(overlap=True)``) can issue the NEXT round's exchange
    outside the round body — the halo/compute-overlap restructure of the
    sharded plane."""
    if axis_name is None:
        if plan is not None:
            raise ValueError("ppermute exchange requires a mesh axis_name")
        gather = lambda t: t
    else:
        gather = lambda t: jax.lax.all_gather(t, axis_name, axis=0,
                                              tiled=True)
    if plan is None:
        exchange = lambda Xl: neighbor_buffer(
            gather(public_table(Xl, graph)), graph)
    else:
        def exchange(Xl):
            n_dev = A_tot // Xl.shape[0]
            return _ppermute_exchange(Xl, graph, plan, shifts, axis_name,
                                      n_dev)

    if _exchange_wrap is not None:
        exchange = _exchange_wrap(exchange)
    return exchange


# ---------------------------------------------------------------------------
# The jitted step
# ---------------------------------------------------------------------------

def _agent_local_problem(z, edges, chol, n_max, inc=None, qbuf=None):
    """Solver closures for one agent given fixed neighbor buffer z.

    Three gradient/Hessian formulations, fastest applicable first:

    * ``qbuf`` (materialized dense connection Laplacian over the pose
      buffer, ``ops.quadratic.dense_q``): cost/gradient/Hessian-vector are
      single MXU matmuls against precomputed ``Q`` and the per-round linear
      term ``G = Z Q_nl`` — the reference's own ``f = 0.5 <Q, X^T X> +
      <X, G>`` form (``QuadraticProblem.cpp:50-73``), dense on TPU.  The
      RBCD default while per-agent buffers stay small enough to materialize.
    * ``inc = (inc_slot, inc_mask)``: gather-only ELL edge path
      (``ops.quadratic.egrad_ell``) — O(E) memory, any problem size.
    * neither: scatter-add edge path (single-agent fallback).
    """

    def buf(Xl):
        return jnp.concatenate([Xl, z], axis=0)

    n_buf = n_max + z.shape[0]
    if qbuf is not None:
        k = z.shape[-1]  # d + 1
        nl = n_max * k
        Qll = qbuf[:nl, :nl]
        Qnl = qbuf[nl:, :nl]
        Qnn = qbuf[nl:, nl:]
        Zm = quadratic.to_mat(z)
        G = Zm @ Qnl                       # [r, (d+1) n_max], fixed per round
        const = 0.5 * jnp.sum((Zm @ Qnn) * Zm)

        def cost_d(Xl):
            Xm = quadratic.to_mat(Xl)
            return 0.5 * jnp.sum((Xm @ Qll) * Xm) + jnp.sum(Xm * G) + const

        def egrad_d(Xl):
            Xm = quadratic.to_mat(Xl)
            return quadratic.from_mat(Xm @ Qll + G, n_max)

        def ehess_d(Xl, V):
            return quadratic.from_mat(quadratic.to_mat(V) @ Qll, n_max)

        return solver.Problem(
            cost=cost_d, egrad=egrad_d, ehess=ehess_d,
            precond=lambda Xl, V: quadratic.precond_apply(chol, V),
        )
    if inc is not None:
        inc_slot, inc_mask = inc
        return solver.Problem(
            cost=lambda Xl: quadratic.cost(buf(Xl), edges),
            egrad=lambda Xl: quadratic.egrad_ell(buf(Xl), edges,
                                                 inc_slot, inc_mask),
            ehess=lambda Xl, V: quadratic.hessvec_ell(V, edges, inc_slot,
                                                      inc_mask, n_buf=n_buf),
            precond=lambda Xl, V: quadratic.precond_apply(chol, V),
        )
    return solver.Problem(
        cost=lambda Xl: quadratic.cost(buf(Xl), edges),
        egrad=lambda Xl: quadratic.egrad(buf(Xl), edges, n_out=n_max),
        ehess=lambda Xl, V: quadratic.hessvec(V, edges, n_buf=n_buf),
        precond=lambda Xl, V: quadratic.precond_apply(chol, V),
    )


def precond_chol(graph_edges: EdgeSet, n_max: int, s_max: int,
                 params: AgentParams) -> jax.Array:
    """Block-Jacobi preconditioner factors for all agents [A, n_max, k, k]."""

    def one(e):
        blocks = quadratic.diag_blocks(e, n_max + s_max, n_out=n_max)
        return quadratic.precond_factors(blocks, params.solver.precond_shift)

    return jax.vmap(one)(graph_edges)


#: Jitted ``precond_chol`` for HOST-side callers (init_state /
#: refresh_problem) — eager, the vmapped block build dispatches hundreds of
#: individual ops, ~90 ms each on a tunneled TPU.  ``_rbcd_round`` calls the
#: plain function (it already traces under jit).
precond_chol_jit = jax.jit(precond_chol,
                           static_argnames=("n_max", "s_max", "params"))


#: Dense-Q memory budget: the [A, K, K] buffer Laplacians (K = (d+1)
#: (n_max + s_max)) must fit comfortably beside the rest of the problem.
#: 1 GiB covers sphere2500/8 (51 MB f32) through city10000/8 (~900 MB f32
#: at the margin).
DENSE_Q_BUDGET_BYTES = 1 << 30


def use_dense_q(meta: GraphMeta, params: AgentParams | None,
                itemsize: int) -> bool:
    """Whether the (opt-in) materialized dense-Q formulation applies:
    requested via ``SolverParams.dense_quadratic`` and within the memory
    budget at the problem's actual ``itemsize`` (4 for float32 graphs, 8
    for float64 — required so the predicate always agrees with what the
    solver will actually dispatch)."""
    if params is None or not params.solver.dense_quadratic:
        return False
    K = (meta.d + 1) * (meta.n_max + meta.s_max)
    return meta.num_robots * K * K * itemsize <= DENSE_Q_BUDGET_BYTES


#: Per-agent VMEM the Pallas tCG kernel may stage (loop vectors, tiled edge
#: payloads, and the per-tile transient one-hots must fit beside
#: double-buffering headroom on a ~16 MiB VMEM core).
PALLAS_TCG_VMEM_BUDGET_BYTES = 10 << 20


def _edge_tile_shape(n_max: int, s_max: int, e_max: int,
                     wide: bool = False) -> tuple[int, int]:
    """(T, nt) of the kernel's tile-major edge layout.  Adaptive tile: the
    kernel's transient one-hots are [n, T]; halve the tile for large pose
    buffers to keep them inside VMEM.

    ``wide``: the caller runs a bf16 selection mode, whose one-hot
    transients are HALF size — T stays at 256 up to ~3000-pose buffers.
    Measured round 5 at 100k/64 (buffer 2288): bf16x3 T=128 -> 256 is
    50.1 -> 58.5 rounds/s (fewer, wider dot issues); the SAME widening
    in f32 mode aborts in Mosaic (scoped VMEM 17.8M > 16M), which is why
    this is mode-gated rather than unconditional."""
    from ..ops.pallas_tcg import TILE

    if wide and (n_max + s_max) <= 3000:
        T = TILE
    else:
        T = TILE if (n_max + s_max) <= 1024 else TILE // 2
    T = _ab_tile_override(T)
    return T, max(1, -(-e_max // T))


def _ab_tile_override(T: int) -> int:
    """The round-5 ``PALLAS_TILE`` A/B override, scoped OUT of the
    production path: it only applies when ``DPGO_AB=1`` is also set, the
    value must be a positive lane multiple (128), and an active override
    is logged — a PALLAS_TILE leaked into a normal shell previously
    retiled every solve silently and could reproduce the Mosaic VMEM
    abort the adaptive tile exists to avoid."""
    import os
    import sys

    raw = os.environ.get("PALLAS_TILE")
    if raw is None:
        return T
    if os.environ.get("DPGO_AB") != "1":
        return T  # experiments only opt in explicitly
    try:
        t = int(raw)
    except ValueError:
        raise ValueError(f"PALLAS_TILE={raw!r} is not an integer") from None
    if t <= 0 or t % 128 != 0:
        raise ValueError(
            f"PALLAS_TILE={t} invalid: must be a positive multiple of the "
            "128-lane tile width")
    if t != T:
        print(f"[dpgo_tpu] DPGO_AB: PALLAS_TILE override {T} -> {t}",
              file=sys.stderr)
    return t


def pallas_vmem_ok(n_max: int, s_max: int, rank: int, d: int, T: int,
                   nt: int, bf16: bool = False) -> bool:
    """Scalar-shape form of ``_pallas_vmem_ok`` — also the gate for the
    per-robot deployment surface (``agent.PGOAgent``), which has no
    GraphMeta/MultiAgentGraph."""
    from ..ops.pallas_tcg import hoist_scratch_bytes, should_hoist

    rk = rank * (d + 1)
    sel_item = 2 if bf16 else 4  # bf16 one-hot tiles are half-size
    edge_tiles_b = nt * T * (d * d + d + 4) * 4
    onehots = 4 * T * (n_max + s_max) * sel_item
    vecs = 12 * rk * n_max * 4
    hoist = hoist_scratch_bytes(nt, T, n_max, sel_item) \
        if should_hoist(nt, T, n_max, sel_item) else 0
    return edge_tiles_b + onehots + vecs + hoist \
        <= PALLAS_TCG_VMEM_BUDGET_BYTES


def agent_edge_tiles(i, j, R, t, n: int, s: int, wide: bool = False):
    """Tile-major edge arrays for ONE agent's buffer-indexed edge list —
    the single-agent equivalent of ``build_graph``'s batched Pallas layout
    (``eidx_i/eidx_j [nt, 1, T]``, ``rot_t [nt, d*d, T]``,
    ``trn_t [nt, d, T]``; padding gets index ``n + s``, which one-hots to
    all-zero in both the local and neighbor ranges).  Used by the
    deployment surface (``agent.PGOAgent``) so per-robot iterates run the
    same VMEM kernel as the batched core.  ``wide`` mirrors
    ``build_graph``'s bf16-selection-mode tile widening."""
    i = np.asarray(i, np.int32)
    j = np.asarray(j, np.int32)
    R = np.asarray(R, np.float32)
    t = np.asarray(t, np.float32)
    e = i.shape[0]
    d = R.shape[-1]
    T, nt = _edge_tile_shape(n, s, e, wide=wide)
    Ep = nt * T
    pad = n + s
    ii = np.full((Ep,), pad, np.int32)
    jj = np.full((Ep,), pad, np.int32)
    ii[:e] = i
    jj[:e] = j
    rot = np.zeros((d * d, Ep), np.float32)
    trn = np.zeros((d, Ep), np.float32)
    rot[:, :e] = R.transpose(1, 2, 0).reshape(d * d, e)
    trn[:, :e] = t.T
    return (jnp.asarray(ii.reshape(nt, 1, T)),
            jnp.asarray(jj.reshape(nt, 1, T)),
            jnp.asarray(np.ascontiguousarray(
                rot.reshape(d * d, nt, T).transpose(1, 0, 2))),
            jnp.asarray(np.ascontiguousarray(
                trn.reshape(d, nt, T).transpose(1, 0, 2))))


def _pallas_vmem_ok(meta: GraphMeta, graph, bf16: bool = False) -> bool:
    """Whether the kernel's per-agent working set fits in VMEM.

    With the tile-streaming kernel the resident set is ~12 [r(d+1), n]
    loop vectors, the O(E) tiled edge payload, and the transient per-tile
    one-hot selection tiles (4 x [n or s, T] live at the cost evaluation).
    This is a budget check, not an edge-count gate — the old one-hot
    design's ~765-edge Mosaic compile ceiling is gone (e_max 1906 /
    n_max 1000 verified compiling and running on v5e); the remaining
    ceiling tracks real VMEM pressure (e_max 3793 / n_max 2000 at T=256
    crashes the compile helper, consistent with this estimate).  The
    hoisted one-hot scratch (``pallas_tcg.should_hoist``) counts toward the
    same budget when the kernel will allocate it — both gates derive from
    one estimate, so a shape cannot pass here and then overflow VMEM by
    adding the hoist scratch."""
    return pallas_vmem_ok(meta.n_max, meta.s_max, meta.rank, meta.d,
                          graph.eidx_i.shape[-1], graph.eidx_i.shape[1],
                          bf16)


def resolved_sel_mode(params: AgentParams) -> str:
    """The kernel selection-matmul mode: ``pallas_sel_mode`` when set,
    else derived from the older ``pallas_bf16_select`` flag."""
    m = params.solver.pallas_sel_mode
    if m:
        if m not in ("f32", "bf16", "bf16x3"):
            raise ValueError(f"unknown pallas_sel_mode {m!r}")
        return m
    return "bf16" if params.solver.pallas_bf16_select else "f32"


def _formulation(meta: GraphMeta, params: AgentParams | None, graph,
                 itemsize: int = 4) -> str:
    """Resolve which tCG/problem formulation a round will run, in priority
    order: explicitly forced Pallas kernel, explicit dense-Q opt-in, Pallas
    auto (TPU backend), ELL edge path.  Shared by ``init_state`` (which
    materializes Qbuf only when "dense" wins — never wasted) and
    ``_rbcd_round`` dispatch."""
    if params is None:
        return "ell"
    rtr = params.solver.algorithm == ROptAlg.RTR
    # The kernel is f32-only: routing an f64 problem through it would
    # silently clamp the iterate (and the gn0 convergence metric) to f32
    # every round, so a converged f64 block never stays at its fixed point
    # and tight grad_norm_tols become unreachable.
    pallas_ok = rtr and itemsize == 4 and graph.eidx_i is not None \
        and _pallas_vmem_ok(meta, graph,
                            bf16=resolved_sel_mode(params) != "f32")
    if params.solver.pallas_tcg is True:
        if not pallas_ok:
            # An explicit force that cannot be honored must not silently
            # downgrade — the caller believes the kernel is being covered.
            if not rtr:
                reason = "algorithm is not RTR"
            elif itemsize != 4:
                reason = ("the kernel is float32-only and the problem is "
                          "float64 — build the graph/state in float32")
            elif graph.eidx_i is None:
                reason = ("the graph was built without edge tiles "
                          "(build_graph(pallas_sel=True))")
            else:
                reason = "the per-agent problem exceeds the kernel's VMEM budget"
            raise ValueError(f"pallas_tcg=True cannot run: {reason}")
        return "pallas"
    if rtr and use_dense_q(meta, params, itemsize):
        return "dense"
    if params.solver.pallas_tcg is None and pallas_ok \
            and jax.default_backend() == "tpu":
        return "pallas"
    return "ell"


def dense_q_all(graph_edges: EdgeSet, meta: GraphMeta) -> jax.Array:
    """Buffer Laplacians for all agents [A, K, K] (``quadratic.dense_q``)."""
    return jax.vmap(lambda e: quadratic.dense_q(e, meta.n_max + meta.s_max))(
        graph_edges)


def _agent_update(X_local, z, edges, params: AgentParams, chol=None, inc=None,
                  qbuf=None, pallas=None):
    """One local solver step for a single agent (vmapped over A).

    Dispatches RTR vs RGD per ``params.solver.algorithm``, the reference's
    ``QuadraticOptimizer::optimize`` branch (``QuadraticOptimizer.cpp:42-47``).
    ``chol`` carries precomputed preconditioner factors (recomputed here when
    omitted — the single-shot path of ``agent.PGOAgent``); ``inc``/``qbuf``
    select the ELL / dense-Q problem formulations (``_agent_local_problem``);
    ``pallas = (eidx_i, eidx_j, rot_t, trn_t, interpret)`` (tile-major edge
    arrays) runs the whole single-step RTR in the VMEM Pallas kernel
    (``ops.pallas_tcg.rtr_call``).
    Returns the updated block and the block gradient norm at the *starting*
    point — the greedy selection metric (``MultiRobotExample.cpp:242-256``)
    — which the RTR solver computes anyway.
    """
    n_max = X_local.shape[0]
    if params.solver.algorithm == ROptAlg.RGD:
        # Fixed-step projected gradient + retraction, preconditioning off
        # (reference ``gradientDescent``, QuadraticOptimizer.cpp:124-149) —
        # no preconditioner to factor on this path.
        buf = jnp.concatenate([X_local, z], axis=0)
        g = manifold.rgrad(X_local, quadratic.egrad(buf, edges, n_out=n_max))
        gn0 = manifold.norm(g)
        return manifold.retract(X_local, -params.solver.rgd_stepsize * g), gn0
    if chol is None:
        blocks = quadratic.diag_blocks(edges, n_max + z.shape[0], n_out=n_max)
        chol = quadratic.precond_factors(blocks, params.solver.precond_shift)
    if pallas is not None:
        from ..ops import pallas_tcg as ptcg

        eidx_i, eidx_j, rot_t, trn_t, interpret = pallas
        nt, tile = eidx_i.shape[0], eidx_i.shape[-1]
        d = trn_t.shape[1]
        k = d + 1
        r = X_local.shape[-2]
        w = edges.mask * edges.weight
        wk = ptcg.edge_tiles((w * edges.kappa).astype(jnp.float32), nt, tile)
        wt = ptcg.edge_tiles((w * edges.tau).astype(jnp.float32), nt, tile)
        Lc = chol.transpose(1, 2, 0).reshape(k * k, n_max)
        # Fully-fused kernel: gradient, curvature term, gradient norm, tCG,
        # retraction, acceptance and radius retries all in VMEM, including
        # the below-tolerance early exit (QuadraticOptimizer.cpp:65-69) —
        # the per-round XLA work is just the exchange and these layout
        # transposes (measured: the old out-of-kernel ELL gradient pass was
        # ~65% of a sphere2500 round).
        X_out_c, stats = ptcg.rtr_full_call(
            eidx_i, eidx_j, rot_t, trn_t, wk, wt,
            ptcg.comp_major(X_local.astype(jnp.float32)),
            ptcg.comp_major(z.astype(jnp.float32)),
            Lc.astype(jnp.float32),
            r=r, d=d, max_iters=params.solver.max_inner_iters,
            kappa=params.solver.tcg_kappa, theta=params.solver.tcg_theta,
            initial_radius=params.solver.initial_radius,
            max_rejections=params.solver.max_rejections,
            grad_tol=params.solver.grad_norm_tol,
            interpret=interpret,
            sel_mode=resolved_sel_mode(params))
        X_new = ptcg.comp_minor(X_out_c, r, k).astype(X_local.dtype)
        gn0 = stats[0, 4].astype(X_local.dtype)
        return X_new, gn0
    problem = _agent_local_problem(z, edges, chol, n_max, inc=inc, qbuf=qbuf)
    out = solver.rtr_single_step(problem, X_local, params.solver, None,
                                 final_grad_norm=False)
    return out.X, out.grad_norm_init


def _edge_residuals(X_local, z, edges):
    """Unweighted per-edge residual norms sqrt(kappa ||rR||^2 + tau ||rt||^2)
    for one agent — ``computeMeasurementError`` (reference
    ``DPGO_utils.cpp:509-515``) evaluated in the lifted space, as
    ``updateLoopClosuresWeights`` does (``PGOAgent.cpp:1181-1245``)."""
    buf = jnp.concatenate([X_local, z], axis=0)
    rR, rt = quadratic._edge_terms(buf, edges)
    sq = edges.kappa * jnp.sum(rR * rR, axis=(-2, -1)) + \
        edges.tau * jnp.sum(rt * rt, axis=-1)
    return jnp.sqrt(jnp.maximum(sq, 0.0))


def _gnc_update_weights(X, Z, edges, mu, params: AgentParams):
    """Recompute robust weights for every loop-closure edge (all agents).

    Reference semantics (``PGOAgent::updateLoopClosuresWeights``,
    ``PGOAgent.cpp:1181-1245``): residual from the current iterate X and the
    cached neighbor pose; weight from the robust cost at the current mu;
    odometry and known-inlier edges keep weight 1.  The reference's ownership
    rule (agent i updates shared edges only toward j > i, the other endpoint
    receives the published weight) exists because cached poses may be stale
    across robots; here both endpoint agents evaluate the *same* gathered
    public poses in the same round, so independent recomputation yields
    bitwise-identical weights on both copies and no ownership/publish
    machinery is needed.
    """
    res = jax.vmap(lambda x, z, e: _edge_residuals(x, z, e))(X, Z, edges)
    w_new = robust.weight(res, params.robust, mu)
    update = edges.mask * edges.is_lc * (1.0 - edges.fixed_weight)
    return jnp.where(update > 0, w_new, edges.weight)


def _converged_weight_ratio(edges, params: AgentParams):
    """Per-agent fraction of non-known-inlier LC edges with weight in {0,1}
    (reference ``computeConvergedLoopClosureRatio``, ``PGOAgent.cpp:1247-1289``;
    meaningful for GNC_TLS only, 1.0 otherwise)."""
    if params.robust.cost_type != RobustCostType.GNC_TLS:
        return None
    lc = edges.mask * edges.is_lc * (1.0 - edges.fixed_weight)
    conv = robust.is_weight_converged(edges.weight).astype(lc.dtype)
    tot = jnp.sum(lc, axis=-1)
    return jnp.where(tot > 0, jnp.sum(lc * conv, axis=-1) / jnp.maximum(tot, 1.0),
                     jnp.ones_like(tot))


def _rbcd_round(state: RBCDState, graph: MultiAgentGraph, meta: GraphMeta,
                params: AgentParams, axis_name: str | None = None,
                update_weights: bool = False, restart: bool = False,
                plan: PPermutePlan | None = None,
                shifts: tuple = (), halo: jax.Array | None = None,
                return_halo: bool = False):
    """One synchronous RBCD round over the agents held by this device.

    Communication happens once per round: the public-pose table is built
    from X (and from the Nesterov sequence Y when accelerated — the aux-pose
    exchange of ``getAuxSharedPoseDict``/``updateAuxNeighborPoses``,
    reference ``PGOAgent.cpp:107-118``, ``460-479``) and re-distributed to
    neighbor buffers.  When ``axis_name`` is set, this function is the
    per-shard body of ``shard_map`` over a device mesh (``dpgo_tpu.parallel``):
    the table is exchanged by ``all_gather`` over ICI (the analog of the
    reference's pose message exchange, ``MultiRobotExample.cpp:186-213``) and
    the greedy schedule resolves its argmax over gathered per-agent gradient
    norms.  With ``axis_name=None`` the same body runs single-device over all
    agents (plain gathers).

    ``update_weights`` and ``restart`` are static flags the driver raises on
    the rounds where the reference's modular counters fire
    (``shouldUpdateLoopClosureWeights``: every ``robust_opt_inner_iters``;
    ``shouldRestart``: every ``restart_interval`` when accelerated) — keeping
    the schedule on the host compiles each round variant branch-free.

    A restart round reproduces ``restartNesterovAcceleration`` (reference
    ``PGOAgent.cpp:1040-1052``): the accelerated step is discarded (X reset
    to the pre-round value), a plain un-accelerated step is taken instead,
    and the aux state collapses (V = Y = X, gamma = alpha = 0) — so it
    compiles as a plain round plus aux reset, with no wasted solve.

    ``plan``/``shifts`` (mesh path only) switch the pose exchange from the
    all_gather v1 to the shift-based ppermute route (``plan_ppermute``):
    same result bitwise, with one collective per ring offset that carries
    any cross-device edge (a win when the partition's device adjacency is
    near-chain; a random partition can need up to ``n_dev - 1`` shifts —
    all_gather volume).  The greedy schedule's argmax still all_gathers its
    [A] gradient-norm vector (negligible payload).

    ``halo`` (plain rounds only — incompatible with ``update_weights``,
    whose warm-start-off path resets X and must re-exchange) supplies the
    neighbor buffer of the CURRENT iterate precomputed by the caller, and
    ``return_halo`` makes the round also return the NEXT round's exchange
    ``exchange(X_next)``, issued right after the Stiefel update so the
    collective is in flight while the trailing status/momentum math runs —
    the software-pipelined halo of ``_rbcd_rounds(overlap=True)``.  Same
    values either way: the halo of round k is always ``exchange(X_k)``.
    """
    if params.acceleration and state.V is None:
        raise ValueError(
            "params.acceleration is set but the state has no V sequence — "
            "build the state with init_state(..., params=params)")
    if (params.robust.cost_type != RobustCostType.L2
            and not params.robust_opt_warm_start and state.X_init is None):
        raise ValueError(
            "robust_opt_warm_start=False requires the state to carry the "
            "initial guess — build it with init_state(..., params=params)")
    accel = params.acceleration and state.V is not None
    if accel and params.schedule == Schedule.ASYNC:
        # The reference forbids this combination (assert at PGOAgent.cpp:863):
        # Nesterov momentum assumes lockstep gamma sequences.
        raise ValueError("acceleration is not supported with the ASYNC schedule")
    X = state.X
    weights = state.weights
    mu = state.mu
    V, gamma, alpha = state.V, state.gamma, state.alpha
    A_loc = X.shape[0]  # agents on this shard (= meta.num_robots if unsharded)
    A_tot = meta.num_robots

    if axis_name is None:
        agent_ids = jnp.arange(A_loc)
        gather = lambda t: t
        if plan is not None:
            raise ValueError("ppermute exchange requires a mesh axis_name")
    else:
        agent_ids = jax.lax.axis_index(axis_name) * A_loc + jnp.arange(A_loc)
        gather = lambda t: jax.lax.all_gather(t, axis_name, axis=0, tiled=True)

    exchange = _exchange_for(graph, A_tot, axis_name, plan, shifts)
    if halo is not None and update_weights:
        raise ValueError(
            "a precomputed halo cannot serve a weight-update round: the "
            "warm-start-off path resets X and must re-exchange")

    # Regular neighbor buffer (from X) — needed always when un-accelerated,
    # and on weight-update / restart rounds when accelerated.
    need_regular = (not accel) or restart or update_weights
    Z = (halo if halo is not None else exchange(X)) if need_regular else None

    # --- GNC weight update (before the pose update, reference iterate()
    # PGOAgent.cpp:654-668) ---
    chol = state.chol
    qbuf = state.Qbuf
    if update_weights:
        edges_r = graph.edges._replace(weight=weights)
        w_new = _gnc_update_weights(X, Z, edges_r, mu, params)
        # Weight freeze, ON DEVICE (beyond-reference, see run_rbcd's note on
        # the robust_opt_num_weight_updates cap): once the GNC inlier/outlier
        # decision has converged (fraction of LC weights in {0,1} >= the
        # reference's min ratio over ALL agents — global min, gathered on
        # the mesh path), further updates would keep annealing mu and flip
        # borderline edges, destabilizing the now-fixed-weight descent, and
        # with warm start disabled would keep resetting the iterate.  The
        # gate mirrors the former host-side check exactly: the ratio is
        # evaluated on the PRE-update weights, and only from the third
        # flagged round on (the first two updates always run; `>= 2 updates
        # before freezing` — the all-ones initialization is trivially
        # "converged").  A frozen flagged round computes the same values as
        # a plain round, so freezing is permanent without any host control
        # flow or readback.
        ratio_pre = _converged_weight_ratio(edges_r, params)
        if ratio_pre is None:
            frozen = jnp.zeros((), bool)
        else:
            ordinal = (state.iteration + 1) // params.robust_opt_inner_iters
            frozen = (ordinal >= 3) & (
                jnp.min(gather(ratio_pre))
                >= params.robust_opt_min_convergence_ratio)
        weights = jnp.where(frozen, weights, w_new)
        mu = jnp.where(frozen, mu, robust.gnc_update_mu(mu, params.robust))
        if state.X_init is not None:
            # Warm start disabled: reset the iterate to the initial guess
            # BEFORE this round's optimization (PGOAgent.cpp:657-662); the
            # reset X also refreshes the regular neighbor buffer.
            X = jnp.where(frozen, X, state.X_init)
            Z = exchange(X)
        if accel:  # initializeAcceleration (PGOAgent.cpp:1054-1063)
            V = jnp.where(frozen, V, X)
            gamma = jnp.where(frozen, gamma, jnp.zeros_like(gamma))
            alpha = jnp.where(frozen, alpha, jnp.zeros_like(alpha))
    edges = graph.edges._replace(weight=weights)
    form = _formulation(meta, params, graph, itemsize=X.dtype.itemsize)
    if form == "dense" and qbuf is None:
        # Mirror the forced-Pallas behavior: an explicit opt-in that cannot
        # run must not silently downgrade to another formulation.
        raise ValueError(
            "dense_quadratic=True but the state carries no Qbuf — build it "
            "with init_state(..., params=...) using the same params, or "
            "refresh_problem() after changing them")
    if update_weights:
        # Reweighted Q -> refactor the block-Jacobi preconditioner (and the
        # materialized dense Q when that formulation is active), the
        # reference's constructQMatrix + CHOLMOD refactorization schedule
        # (PGOAgent.cpp:1110-1112).
        chol = precond_chol(edges, meta.n_max, meta.s_max, params)
        # Refresh the dense buffer when active, and keep (refreshed) a
        # carried one even if this round's params resolve elsewhere — the
        # caller may switch formulations between rounds.
        qbuf = dense_q_all(edges, meta) \
            if (form == "dense" or qbuf is not None) else None
    elif chol is None:
        # State built without solver params (init_state(params=None)):
        # factor from the live edge weights and THIS round's solver config.
        # NOTE: factors baked by init_state follow the params given THERE —
        # stepping with a different precond_shift than the state was built
        # with requires refresh_problem(state, graph, meta, new_params).
        chol = precond_chol(edges, meta.n_max, meta.s_max, params)

    # --- Acceleration bookkeeping (PGOAgent.cpp:1065-1091) ---
    if accel and not restart:
        gamma = (1.0 + jnp.sqrt(1.0 + 4.0 * (A_tot * gamma) ** 2)) / (2.0 * A_tot)
        alpha = 1.0 / (gamma * A_tot)
        a = alpha[:, None, None, None]
        Ynes = manifold.project((1.0 - a) * X + a * V)
        Zaux = exchange(Ynes)
        start, Zuse = Ynes, Zaux
    else:
        start, Zuse = X, Z

    # tCG formulation resolution (``form`` resolved above, before the
    # factor refresh): forced Pallas > explicit dense-Q > Pallas auto (TPU)
    # > ELL edge path.
    interp = jax.default_backend() != "tpu"

    def _update_one(x, z, e, c, s, m, ii=None, ij=None, rc=None, tc=None,
                    q=None):
        """Formulation-dispatched single-agent solve (vmapped below, or
        called once on dynamically-sliced inputs by the greedy path)."""
        if form == "pallas":
            # inc rides along for the start-point gradient (gather-only
            # ELL); the full RTR step runs in the VMEM kernel.
            return _agent_update(x, z, e, params, c, inc=(s, m),
                                 pallas=(ii, ij, rc, tc, interp))
        if form == "dense":  # qbuf presence enforced above
            return _agent_update(x, z, e, params, c, qbuf=q)
        return _agent_update(x, z, e, params, c, inc=(s, m))

    def _solve_all(take=lambda t: t):
        """Per-agent solves over (a selection of) the batch axis."""
        args = [take(t) for t in (start, Zuse, edges, chol, graph.inc_slot,
                                  graph.inc_mask)]
        kw = {}
        if form == "pallas":
            kw = dict(zip("ii ij rc tc".split(),
                          (take(t) for t in (graph.eidx_i, graph.eidx_j,
                                             graph.rot_t, graph.trn_t))))
        elif form == "dense":
            kw = dict(q=take(qbuf))
        return args, kw

    schedule = params.schedule
    if schedule == Schedule.ASYNC:
        # Only the ASYNC Bernoulli clocks consume randomness; the other
        # schedules previously paid a vmapped threefry split every round
        # for keys nothing read.  The carried key is left untouched on
        # those schedules (trajectories are unchanged — the key never
        # feeds their math).
        split = jax.vmap(lambda k: jax.random.split(k, 2))(state.key)
        key, sub = split[:, 0], split[:, 1]  # [A, 2, 2] -> two [A, 2]
    else:
        key, sub = state.key, None
    if schedule == Schedule.GREEDY:
        # One agent fires per round (the reference driver's argmax-gradnorm
        # selection, ``MultiRobotExample.cpp:242-256``).  Solving every
        # block and masking all but one would burn A x the needed work
        # (the round-1/2 behavior); instead a cheap selection pass (ONE
        # edge sweep per agent: Riemannian gradient norm at the start
        # point — the same quantity the solver reports as gn0) picks the
        # agent, and each device solves only its local slot of the argmax
        # (the non-owners' solves are masked out by ``fired``; n_dev
        # solves total instead of A).  This selection gn runs the ELL
        # path in the iterate dtype; the solver's reported gn0 may come
        # from the Pallas/dense formulation (f32 inside the kernel), so
        # on near-exact ties the argmax can differ in the last ulps —
        # the same mathematical quantity either way.
        def gn_of(x, z, e, s, m):
            buf = jnp.concatenate([x, z], axis=0)
            g = manifold.rgrad(x, quadratic.egrad_ell(buf, e, s, m))
            return manifold.norm(g)

        gn0 = jax.vmap(gn_of)(start, Zuse, edges, graph.inc_slot,
                              graph.inc_mask)
        sel = jnp.argmax(gather(gn0))
        li = (sel % A_loc).astype(jnp.int32)  # local slot on every shard
        take1 = lambda t: jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, li, 0, keepdims=False),
            t)
        args1, kw1 = _solve_all(take1)
        upd1, _ = _update_one(*args1, **kw1)
        X_upd = jax.lax.dynamic_update_index_in_dim(start, upd1, li, 0)
    else:
        args, kw = _solve_all()
        X_upd, gn0 = jax.vmap(
            lambda *a: _update_one(*a[:6], **dict(zip(kw.keys(), a[6:]))))(
            *args, *kw.values())

    if schedule == Schedule.JACOBI:
        fired = None  # every agent fires: the select masks below drop out
    elif schedule == Schedule.GREEDY:
        fired = agent_ids == sel
    elif schedule == Schedule.ASYNC:
        fired = jax.vmap(
            lambda k: jax.random.bernoulli(k, params.async_update_prob))(sub)
    elif schedule == Schedule.COLORED:
        if graph.color is None:
            raise ValueError(
                "Schedule.COLORED requires a colored graph — rebuild it "
                "with build_graph (colors are always computed there)")
        # Multi-color Gauss-Seidel: fire one class of mutually non-adjacent
        # agents per round, cycling classes — state.iteration counts the
        # PREVIOUS rounds, so class (iteration mod C) is deterministic and
        # identical on every shard.
        fired = graph.color == (state.iteration % meta.num_colors)
    else:
        raise ValueError(f"unknown schedule {schedule}")
    fired_b = None if fired is None else fired[:, None, None, None]

    if accel and not restart:
        # Non-fired agents take the momentum point (updateX(false, true):
        # X = Y, PGOAgent.cpp:1094-1098); V advances for everyone.
        X_next = X_upd if fired_b is None else jnp.where(fired_b, X_upd, Ynes)
        g = gamma[:, None, None, None]
        V = manifold.project(V + g * (X_next - Ynes))
    else:
        X_next = X_upd if fired_b is None else jnp.where(fired_b, X_upd, X)
        if accel:  # restart round: collapse the aux sequences
            V = X_next
            gamma = jnp.zeros_like(gamma)
            alpha = jnp.zeros_like(alpha)

    # Status update (reference PGOAgent.cpp:703-716): masked relative change.
    # Only fired agents refresh their status — non-selected agents keep their
    # previous readiness, as iterate(false) does in the reference.  In robust
    # mode readiness additionally requires the converged-weight ratio gate
    # (PGOAgent.cpp:713-714).
    diff = (X_next - X) * graph.pose_mask[:, :, None, None]
    rel_new = jnp.sqrt(jnp.sum(diff * diff, axis=(1, 2, 3)) /
                       jnp.maximum(graph.n.astype(X.dtype), 1.0))
    ready_new = rel_new <= params.rel_change_tol
    ratio = _converged_weight_ratio(edges, params)
    if ratio is not None:
        ready_new &= ratio >= params.robust_opt_min_convergence_ratio
    if fired is None:
        rel, ready = rel_new, ready_new
    else:
        rel = jnp.where(fired, rel_new, state.rel_change)
        ready = jnp.where(fired, ready_new, state.ready)

    new_state = RBCDState(X=X_next, weights=weights,
                          iteration=state.iteration + 1, key=key,
                          rel_change=rel, ready=ready,
                          V=V, gamma=gamma, alpha=alpha, mu=mu,
                          X_init=state.X_init, chol=chol, Qbuf=qbuf)
    if not return_halo:
        return new_state
    # Next round's halo, issued here — after the Stiefel update, before
    # the caller's loop re-enters — so the interconnect collective can
    # overlap the status/momentum math above (its result feeds nothing in
    # this round) and whatever pre-solve work the next round does first.
    return new_state, exchange(X_next)


#: Jitted RBCD round. Single-device over all agents with the default
#: ``axis_name=None``; the sharded path re-wraps ``_rbcd_round`` in shard_map.
rbcd_step = jax.jit(_rbcd_round, static_argnames=(
    "meta", "params", "axis_name", "update_weights", "restart", "shifts",
    "return_halo"))


def _rbcd_rounds(state: RBCDState, graph: MultiAgentGraph, num_rounds,
                 meta: GraphMeta, params: AgentParams,
                 axis_name: str | None = None,
                 plan: PPermutePlan | None = None,
                 shifts: tuple = (), overlap: bool = False) -> RBCDState:
    """``num_rounds`` consecutive *plain* rounds (no weight update, no
    restart) as one on-device ``fori_loop``.

    The per-round jitted step leaves the host in the loop: every round pays
    a dispatch (an RPC round-trip on a tunneled TPU), which dominates once
    the device-side round is fast.  Fusing rounds keeps the whole schedule
    segment on-device — one dispatch per segment, identical math (the body
    is ``_rbcd_round`` itself, so single-round and fused traces agree).
    ``num_rounds`` is a traced scalar: one compile serves every segment
    length.

    ``overlap`` (mesh path, un-accelerated schedules) software-pipelines
    the halo: the loop carries each round's neighbor buffer, computed as
    ``exchange(X_k)`` at the END of round k-1 instead of at the top of
    round k — so the interconnect collective for the next round's halo is
    in flight while round k-1's trailing status math (and round k's
    pre-solve bookkeeping) execute, instead of gating the whole round.
    Identical values round for round (the halo of round k is always the
    exchange of X_k); costs one extra exchange per fused call (the
    prologue).  Accelerated schedules exchange the momentum point Ynes
    in-round (it depends on the just-advanced gamma) and their plain
    rounds never read the X-halo, so they take the unpipelined loop."""
    accel = params.acceleration and state.V is not None
    if overlap and axis_name is not None and not accel:
        exchange = _exchange_for(graph, meta.num_robots, axis_name, plan,
                                 shifts)

        def body(_i, carry):
            s, Z = carry
            return _rbcd_round(s, graph, meta, params, axis_name=axis_name,
                               plan=plan, shifts=shifts, halo=Z,
                               return_halo=True)

        state, _ = jax.lax.fori_loop(0, num_rounds, body,
                                     (state, exchange(state.X)))
        return state
    body = lambda _i, s: _rbcd_round(s, graph, meta, params,
                                     axis_name=axis_name, plan=plan,
                                     shifts=shifts)
    return jax.lax.fori_loop(0, num_rounds, body, state)


#: Jitted fused rounds (single-device; ``parallel.make_sharded_multi_step``
#: embeds the same loop inside shard_map for the mesh path).
rbcd_steps = jax.jit(_rbcd_rounds, static_argnames=(
    "meta", "params", "axis_name", "shifts", "overlap"))


def _rbcd_segment(state: RBCDState, graph: MultiAgentGraph, num_rounds,
                  meta: GraphMeta, params: AgentParams,
                  axis_name: str | None = None,
                  plan: PPermutePlan | None = None,
                  shifts: tuple = (),
                  first_update_weights: bool = False,
                  first_restart: bool = False,
                  overlap: bool = False) -> RBCDState:
    """One schedule segment — a (possibly flagged) first round followed by
    ``num_rounds - 1`` plain rounds — as ONE device dispatch.

    The driver's schedule puts weight-update / Nesterov-restart flags on
    modularly-scheduled rounds (``run_rbcd``); with plain-only fusion those
    flagged rounds each cost a separate dispatch (an RPC round-trip on a
    tunneled TPU) between fused stretches.  Folding the flagged round into
    the front of its following stretch keeps every segment at exactly one
    dispatch.  With both flags False this is exactly ``_rbcd_rounds``.
    ``num_rounds`` is traced; the flags are static (<= 4 compiled variants).
    """
    state = _rbcd_round(state, graph, meta, params, axis_name=axis_name,
                        update_weights=first_update_weights,
                        restart=first_restart, plan=plan, shifts=shifts)
    return _rbcd_rounds(state, graph, num_rounds - 1, meta, params,
                        axis_name=axis_name, plan=plan, shifts=shifts,
                        overlap=overlap)


#: Jitted fused segment (single-device; ``parallel.make_sharded_segment``
#: is the mesh equivalent).
rbcd_segment = jax.jit(_rbcd_segment, static_argnames=(
    "meta", "params", "axis_name", "shifts", "first_update_weights",
    "first_restart", "overlap"))


# ---------------------------------------------------------------------------
# Initialization, rounding, and the high-level driver
# ---------------------------------------------------------------------------

def init_state(graph: MultiAgentGraph, meta: GraphMeta, X0: jax.Array,
               seed: int = 0, params: AgentParams | None = None) -> RBCDState:
    A = meta.num_robots
    dtype = X0.dtype
    accel = params is not None and params.acceleration
    mu0 = params.robust.gnc_init_mu if params is not None else 1e-4
    # Preconditioner factors are baked only when the solver params are
    # known; otherwise the round factors from its live params (the shift
    # must match what the solver was configured with).
    chol0 = precond_chol_jit(graph.edges, meta.n_max, meta.s_max, params) \
        if params is not None else None
    qbuf0 = dense_q_all(graph.edges, meta) \
        if _formulation(meta, params, graph,
                        itemsize=jnp.dtype(dtype).itemsize) == "dense" \
        else None
    return RBCDState(
        X=X0,
        weights=graph.edges.weight,
        iteration=jnp.array(0, jnp.int32),
        key=jax.random.split(jax.random.PRNGKey(seed), A),
        rel_change=jnp.full((A,), jnp.inf, dtype),
        ready=jnp.zeros((A,), bool),
        V=X0 if accel else None,  # initializeAcceleration: V = X
        gamma=jnp.zeros((A,), dtype),
        alpha=jnp.zeros((A,), dtype),
        mu=jnp.asarray(mu0, dtype),
        X_init=X0 if (params is not None
                      and params.robust.cost_type != RobustCostType.L2
                      and not params.robust_opt_warm_start) else None,
        chol=chol0,
        Qbuf=qbuf0,
    )


def refresh_problem(state: RBCDState, graph: MultiAgentGraph, meta: GraphMeta,
                    params: AgentParams) -> RBCDState:
    """Recompute the carried problem factors (preconditioner Cholesky, and
    the dense Q when that formulation is active) from ``state.weights``.

    Required after setting weights externally — e.g. resuming a mid-GNC
    solve from a checkpoint via ``state._replace(weights=...)`` — because
    ``_rbcd_round`` otherwise refreshes them only on weight-update rounds
    and would optimize against the stale (unweighted) problem until the
    next GNC update fires."""
    edges = graph.edges._replace(weight=state.weights)
    chol = precond_chol_jit(edges, meta.n_max, meta.s_max, params)
    # Decide the dense buffer from the given params (like init_state does),
    # not from its previous presence — this also (re)creates a missing Qbuf
    # when the caller switched to a dense_quadratic configuration.
    want_dense = _formulation(
        meta, params, graph, itemsize=jnp.dtype(state.X.dtype).itemsize) \
        == "dense"
    qbuf = dense_q_all(edges, meta) if (want_dense or state.Qbuf is not None) \
        else None
    return state._replace(chol=chol, Qbuf=qbuf)


@partial(jax.jit, static_argnames=("meta", "n", "init_fn"))
def _global_init_jit(edges_g: EdgeSet, graph: MultiAgentGraph,
                     meta: GraphMeta, n: int, init_fn) -> jax.Array:
    """Shared body of the centralized init policies: build T0 [n, d, d+1]
    with ``init_fn(edges, n)`` (a module-level function, so the static
    hash is stable), lift, scatter to agents."""
    T0 = init_fn(edges_g, n)
    X0g = lift(T0, lifting_matrix(meta, T0.dtype))
    return scatter_to_agents(X0g, graph)


def lifted_init(edges_g: EdgeSet, graph: MultiAgentGraph, meta: GraphMeta,
                n_total: int, init: str = "chordal") -> jax.Array:
    """Centralized lifted init evaluated directly on a (possibly padded)
    global edge set, scattered to agents.

    The serving plane (``dpgo_tpu.serve``) initializes on the *padded*
    bucket problem, so one compiled init program serves every problem in a
    shape bucket instead of one per raw problem size; masked padding edges
    contribute nothing to the chordal least squares, and padded per-agent
    rows resolve to global pose 0's block through the padded
    ``global_index`` (a valid Stiefel point), exactly as short agents
    already do in unpadded graphs."""
    if init == "chordal":
        fn = chordal.chordal_initialization
    elif init == "odometry":
        fn = chordal.odometry_from_edges
    else:
        raise ValueError(f"unknown centralized init policy {init!r}")
    return _global_init_jit(edges_g, graph, meta, n_total, fn)


def centralized_chordal_init(part: Partition, meta: GraphMeta, graph: MultiAgentGraph,
                             dtype=jnp.float32) -> jax.Array:
    """Centralized chordal init, lifted and scattered to agents — the demo
    initialization of ``MultiRobotExample.cpp:158-165``.

    One jitted program: run eagerly, the chordal CG solves alone dispatch
    thousands of individual device ops — ~105 s on the tunneled TPU for
    ais2klinik vs ~12 s compiled (and ~0 steady-state)."""
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    return lifted_init(edges_g, graph, meta, part.meas_global.num_poses,
                       "chordal")


def centralized_odometry_init(part: Partition, meta: GraphMeta,
                              graph: MultiAgentGraph,
                              dtype=jnp.float32) -> jax.Array:
    """Odometry-chain init, lifted and scattered to agents (reference
    ``odometryInitialization``, ``DPGO_utils.cpp:426-447``).

    The classic outlier-safe initialization for robust (GNC) runs:
    odometry edges are trusted, so corrupted loop closures cannot poison
    the starting basin the way they can poison the chordal init (which
    least-squares over EVERY edge, outliers included).  The tradeoff is
    accumulated drift: on long 2D trajectories the drifted start makes
    ALL loop-closure residuals large and GNC cannot separate inliers
    (measured, 10%-corrupted city10000: odometry init ends at precision
    0.64 / inlier-cost 1.2e7 where chordal + iterated GNC reaches
    precision 0.95 / inlier-cost +4% — see
    ``experiments/gnc_corruption.py``).  Prefer this init on graphs with
    tight odometry (sphere2500-like); prefer chordal +
    ``solve_rbcd_robust_iterated`` when drift dominates."""
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    return lifted_init(edges_g, graph, meta, part.meas_global.num_poses,
                       "odometry")


def lifting_matrix(meta: GraphMeta, dtype=jnp.float32) -> jax.Array:
    """The shared lifting matrix YLift for this problem's (rank, d)."""
    return _lifting_matrix(meta.rank, meta.d, dtype)


def round_global(Xg: jax.Array, ylift: jax.Array) -> jax.Array:
    """Round a global lifted solution to SE(d) and express it in the frame of
    the global anchor (pose 0 = identity), as
    ``getTrajectoryInGlobalFrame`` does (reference ``PGOAgent.cpp:500-519``)."""
    T = round_solution(Xg, ylift)
    d = ylift.shape[1]
    R, t = T[..., :d], T[..., d]
    Ra_inv = R[0].T
    R_out = jnp.einsum("ab,nbc->nac", Ra_inv, R)
    t_out = jnp.einsum("ab,nb->na", Ra_inv, t - t[0])
    return jnp.concatenate([R_out, t_out[..., None]], axis=-1)


@dataclasses.dataclass
class RBCDResult:
    T: jax.Array  # [N, d, d+1] rounded global trajectory
    X: jax.Array  # [A, n_max, r, d+1]
    cost_history: list
    grad_norm_history: list
    iterations: int
    terminated_by: str
    weights: jax.Array | None = None  # [M] final per-measurement GNC weights
    #: Exact terminal solver state (the warm-start handle of the live-session
    #: layer, ``models.incremental``): resuming ``dispatch_prepared`` from it
    #: after streaming new edges skips the centralized init entirely.  Set by
    #: the single-problem driver loops; batched serving results leave it None
    #: (their states ride the session store instead).
    state: "RBCDState | None" = None
    #: True when the serving plane completed this request by re-admitting it
    #: from a crash-recovery session snapshot (``serve.session``), or when
    #: the sharded supervisor rewound it at least once mid-solve.
    recovered: bool = False
    #: Pod-scale resilience summary (``parallel.resilience``): recoveries,
    #: checkpoint counts, fault kinds, injector stats.  None for solves
    #: run without a ``ResilienceConfig``.
    resilience: dict | None = None
    #: Terminal dual certificate (``certify.CertificateResult``) when the
    #: solve ran with ``AgentParams.certify_mode`` != "off": the device
    #: eigensolve rides the fused terminal epilogue (one blocking fetch)
    #: and the host f64 path runs only on a REFUSE.  None otherwise.
    certificate: object | None = None


def global_weights(weights: jax.Array, graph: MultiAgentGraph,
                   num_meas: int) -> jax.Array:
    """Collapse per-agent edge weights [A, E_max] to per-measurement [M].

    Shared measurements appear in both endpoint agents' edge lists with
    identical weights (see ``_gnc_update_weights``), so the masked mean over
    copies is exact; measurements nobody holds (none in practice) default
    to 1."""
    ids = graph.meas_id.reshape(-1)
    m = graph.edges.mask.reshape(-1)
    num = jnp.zeros((num_meas,), weights.dtype).at[ids].add(weights.reshape(-1) * m)
    den = jnp.zeros((num_meas,), weights.dtype).at[ids].add(m)
    return jnp.where(den > 0, num / jnp.maximum(den, 1.0), 1.0)


def schedule_bounds(n_done: int, nwu: int, *, max_iters: int,
                    eval_every: int, params: AgentParams | None,
                    robust_on: bool, accel_on: bool):
    """Host-side schedule arithmetic shared by ``run_rbcd`` and the flight
    recorder's replay (``obs.recorder``): flags for round ``n_done + 1``
    and the segment end — the plain tail runs to (exclusive) the next
    flagged round, capped (inclusive) at the next eval boundary.

    The modular counters of the reference (shouldUpdateLoopClosure-
    Weights / shouldRestart, PGOAgent.cpp:1174-1179, 1033-1038) live on
    the host: round variants compile branch-free.  Beyond-reference:
    weight updates stop after robust_opt_num_weight_updates (<=0 means
    unlimited, the reference behavior) — without the cap, post-
    convergence weight updates keep annealing mu (<- 1.4 mu) and, with
    warm start disabled, keep resetting the iterate to the initial
    guess, so the solve would never settle.  The GNC
    ratio freeze itself (computeConvergedLoopClosureRatio semantics,
    PGOAgent.cpp:1247-1289) is decided ON DEVICE inside the flagged
    round (see ``_rbcd_round``): a frozen flagged round computes exactly
    a plain round, so the host keeps flagging on the modular schedule
    with no weight readback and identical results.  Module-level so a
    replay resumed from a snapshot at round ``n_done`` re-issues the
    exact segment splits the original driver dispatched.
    """
    cap = params.robust_opt_num_weight_updates if params is not None else 0
    updates_remaining = robust_on and (cap <= 0 or nwu < cap)
    uw = updates_remaining and \
        (n_done + 1) % params.robust_opt_inner_iters == 0
    rs = accel_on and (n_done + 1) % params.restart_interval == 0
    n0 = n_done + 1
    end = max_iters
    if updates_remaining:
        end = min(end, (n0 // params.robust_opt_inner_iters + 1)
                  * params.robust_opt_inner_iters - 1)
    if accel_on:
        end = min(end, (n0 // params.restart_interval + 1)
                  * params.restart_interval - 1)
    end = min(max(end, n0),
              ((n0 - 1) // eval_every + 1) * eval_every, max_iters)
    return uw, rs, end


def _central_metrics_body(graph: MultiAgentGraph, edges_g: EdgeSet,
                          n_total: int, num_meas: int, telemetry: bool):
    """The (unjitted) stacked-eval computation shared by
    ``_make_central_metrics`` and the fused verdict program
    (``make_verdict_program``): both trace the *same* Python body, so the
    per-eval rows the verdict program stores in its device-side history
    are bit-identical to what the standalone metrics program fetches —
    the flight-recorder replay contract extends across the verdict seam
    (pinned by ``tests/test_recorder.py``)."""

    def central_metrics(Xa, weights, ready, mu, rel_change):
        Xg = gather_to_global(Xa, graph, n_total)
        eg = edges_g._replace(weight=global_weights(weights, graph, num_meas))
        f = quadratic.cost(Xg, eg)
        g = manifold.rgrad(Xg, quadratic.egrad(Xg, eg))
        vals = [f, manifold.norm(g), jnp.all(ready).astype(f.dtype)]
        if telemetry:
            e = graph.edges
            upd = e.mask * e.is_lc * (1.0 - e.fixed_weight)
            n_upd = jnp.maximum(jnp.sum(upd), 1.0)
            vals += [mu.astype(f.dtype),
                     jnp.sum((weights > 0.5) * upd) / n_upd,
                     jnp.sum(weights * upd) / n_upd]
            return jnp.concatenate(
                [jnp.stack(vals), rel_change.astype(f.dtype)])
        return jnp.stack(vals)

    return central_metrics


def _make_central_metrics(graph: MultiAgentGraph, edges_g: EdgeSet,
                          n_total: int, num_meas: int, telemetry: bool):
    """The jitted per-eval readback program of ``run_rbcd`` — one stacked
    output = ONE device->host transfer per eval (each separate scalar
    fetch costs a full round-trip on a tunneled TPU).  Factored out so the
    flight recorder's replay evaluates the recorded trajectory through the
    byte-identical XLA program (bit-for-bit reproduction requires the same
    compiled reduction order, not merely the same math)."""
    return jax.jit(_central_metrics_body(graph, edges_g, n_total, num_meas,
                                         telemetry))


# ---------------------------------------------------------------------------
# Device-resident verdict loop
# ---------------------------------------------------------------------------
#
# The verdict word is one packed int32 the host reads back every K rounds in
# place of the full per-eval scalar stack:
#
#   bits 0-2   status        0 RUNNING | 1 GRAD_NORM | 2 CONSENSUS
#   bits 3-5   anomaly class 0 none | 1 cost_spike | 2 stall
#                            | 3 grad_explosion | 4 non_finite
#                            (highest-severity class seen so far, latched)
#   bits 6+    GNC stage index (robust.gnc_stage_index, 0 when not robust)
#
# Termination latches ON DEVICE at the first eval whose gradient norm
# clears the tolerance (or whose agents reach consensus); the host only
# learns about it at the next K-round fetch, so the returned iterate may
# carry up to K - eval_every extra polish rounds past the terminal eval —
# histories, telemetry, and ``iterations`` are truncated at the latched
# terminal eval, so the *reported* trajectory is identical to the
# per-eval path's.

VERDICT_RUNNING = 0
VERDICT_GRAD_NORM = 1
VERDICT_CONSENSUS = 2
_VERDICT_STATUS = {VERDICT_RUNNING: "running",
                   VERDICT_GRAD_NORM: "grad_norm",
                   VERDICT_CONSENSUS: "consensus"}

ANOMALY_NONE = 0
ANOMALY_COST_SPIKE = 1
ANOMALY_STALL = 2
ANOMALY_GRAD_EXPLOSION = 3
ANOMALY_NON_FINITE = 4
_VERDICT_ANOMALY = {ANOMALY_NONE: None, ANOMALY_COST_SPIKE: "cost_spike",
                    ANOMALY_STALL: "stall",
                    ANOMALY_GRAD_EXPLOSION: "grad_explosion",
                    ANOMALY_NON_FINITE: "non_finite"}


def pack_verdict(status: int, anomaly: int = 0, stage: int = 0) -> int:
    """Host-side packer (tests / documentation of the word layout)."""
    return int(status) | (int(anomaly) << 3) | (int(stage) << 6)


def unpack_verdict(word: int) -> dict:
    """Decode a fetched verdict word into named fields."""
    word = int(word)
    return {"status": _VERDICT_STATUS.get(word & 7, "?"),
            "anomaly": _VERDICT_ANOMALY.get((word >> 3) & 7),
            "stage": word >> 6}


def _host_fetch(x):
    """THE device->host transfer seam of the driver loops.

    Every sanctioned readback in ``run_rbcd`` (and the serving plane's
    ``run_bucket``) goes through this one function so benchmarks and
    tests can count host syncs by patching it (``bench.py``'s
    ``host_syncs_per_100_rounds`` shim — the same technique as the
    zero-overhead telemetry smoke).  Semantically ``jax.device_get``: it
    accepts arbitrary pytrees, so the fused terminal epilogue (rounded
    trajectory + collapsed weights + history + latched indices +
    certificate payload) is ONE counted blocking read."""
    return jax.device_get(x)


class VerdictState(NamedTuple):
    """Device-resident control/health state carried across evals.

    ``hist`` accumulates the exact per-eval stacked-metrics rows
    (``_central_metrics_body`` output) so the full scalar stack can be
    fetched lazily — once per verdict fetch with telemetry on, once at
    termination with telemetry off — instead of per eval."""

    word: jax.Array        # int32 packed verdict (see module constants)
    eval_idx: jax.Array    # int32 number of eval rows recorded
    term_eval: jax.Array   # int32 eval index of the terminal eval (-1)
    term_it: jax.Array     # int32 iteration of the terminal eval (-1)
    best_cost: jax.Array   # stage-best cost (cost_spike baseline)
    min_gn: jax.Array      # stage-min gradient norm (explosion baseline)
    stage: jax.Array       # int32 GNC stage index
    stall_anchor: jax.Array  # cost at the stall window anchor
    stall_len: jax.Array     # int32 evals since the anchor
    stall_fired: jax.Array   # bool, once per stage
    hist: jax.Array        # [max_evals, W] per-eval metric rows


def init_verdict_state(max_evals: int, num_robots: int, dtype,
                       telemetry: bool) -> VerdictState:
    """Fresh verdict state sized for ``max_evals`` eval boundaries.  Row
    width matches ``_central_metrics_body``: 3 scalars, +3 GNC scalars and
    the per-agent relative change with telemetry on."""
    dt = jnp.dtype(dtype)
    W = (6 + num_robots) if telemetry else 3
    inf = jnp.asarray(jnp.inf, dt)
    z32 = jnp.zeros((), jnp.int32)
    return VerdictState(
        word=z32, eval_idx=z32,
        term_eval=jnp.full((), -1, jnp.int32),
        term_it=jnp.full((), -1, jnp.int32),
        best_cost=inf, min_gn=inf, stage=z32,
        stall_anchor=inf, stall_len=z32,
        stall_fired=jnp.zeros((), bool),
        hist=jnp.zeros((max_evals, W), dt))


def _device_gnc_stage(mu, mu0: float, step: float, kmax: int):
    """Device twin of ``robust.gnc_stage_index`` (same clamp semantics);
    ``mu0``/``step``/``kmax`` are static host floats resolved by the
    program builder."""
    if mu0 <= 0 or step <= 1.0:
        return jnp.zeros((), jnp.int32)
    k = jnp.round(jnp.log(jnp.maximum(mu, mu0) / mu0) / np.log(step))
    return jnp.clip(k.astype(jnp.int32), 0, kmax)


def make_verdict_program(graph: MultiAgentGraph, edges_g: EdgeSet,
                         n_total: int, num_meas: int, telemetry: bool, *,
                         grad_norm_tol: float,
                         robust_params: RobustCostParams | None,
                         max_evals: int, health_cfg=None,
                         metrics_body=None):
    """The fused per-eval program of the device-resident loop: evaluates
    the central metrics (the byte-identical ``_central_metrics_body``
    subcomputation), appends the row to the device-side history, folds the
    convergence test and the health predicates of ``obs.health`` into the
    packed verdict word, and latches the first terminal eval.

    The on-device predicates mirror ``HealthMonitor.observe_solver``'s
    per-stage baselines (non-finite sentinel, cost spike vs stage best,
    gradient explosion vs stage min, stall over a cost window) with one
    documented simplification: the stall window is block-aligned (anchor
    cost refreshed every ``stall_window`` evals) instead of sliding.  The
    word's anomaly class is the in-band signal; with telemetry on the
    host-side monitor re-judges the fetched rows and remains the single
    authority for anomaly *events* and abort policy, so the emitted event
    stream is identical to the per-eval path's.

    ``max_evals`` bounds the history; the driver never records more rows
    than eval boundaries in ``max_iters``.  ``health_cfg`` duck-types
    ``obs.health.HealthConfig`` (defaults used when None).

    ``metrics_body`` overrides the stacked-metrics subcomputation — THE
    reuse seam of the sharded plane: ``parallel.sharded`` traces the same
    row schema inside ``shard_map`` with its reductions as psums
    (``make_sharded_metrics_body``), and everything downstream of the row
    (convergence test, health predicates, latch, history) is this one
    shared program, so the verdict-word semantics cannot drift between
    the single-device and mesh paths.  The override must match
    ``_central_metrics_body``'s signature and row width."""
    if health_cfg is None:
        from ..obs.health import HealthConfig

        health_cfg = HealthConfig()
    body = metrics_body if metrics_body is not None else \
        _central_metrics_body(graph, edges_g, n_total, num_meas, telemetry)
    spike_rtol = float(health_cfg.cost_spike_rtol)
    spike_atol = float(health_cfg.cost_spike_atol)
    expl_factor = float(health_cfg.grad_explosion_factor)
    gn_floor = float(health_cfg.grad_floor)
    stall_window = int(health_cfg.stall_window)
    stall_rtol = float(health_cfg.stall_rtol)
    del max_evals  # sized into the VerdictState by init_verdict_state
    if robust_params is not None:
        gnc_mu0 = float(robust_params.gnc_init_mu)
        gnc_step = float(robust_params.gnc_mu_step)
        gnc_kmax = int(robust_params.gnc_max_iters)

    @jax.jit
    def verdict_step(Xa, weights, ready, mu, rel_change, iteration,
                     vs: VerdictState) -> VerdictState:
        vec = body(Xa, weights, ready, mu, rel_change)
        f, gn, consensus = vec[0], vec[1], vec[2]
        if robust_params is not None:
            stage = _device_gnc_stage(mu, gnc_mu0, gnc_step, gnc_kmax)
        else:
            stage = jnp.zeros((), jnp.int32)

        # Per-stage baselines reset on stage transitions (the monitor's
        # _new_stage); the stall anchor additionally seeds itself on the
        # first finite cost.
        fresh = stage != vs.stage
        inf = jnp.asarray(jnp.inf, vec.dtype)
        best = jnp.where(fresh, inf, vs.best_cost)
        ming = jnp.where(fresh, inf, vs.min_gn)
        seed = fresh | ~jnp.isfinite(vs.stall_anchor)
        anchor = jnp.where(seed, f, vs.stall_anchor)
        slen = jnp.where(seed, 0, vs.stall_len)
        sfired = jnp.where(fresh, False, vs.stall_fired)

        finite = jnp.isfinite(f) & jnp.isfinite(gn) \
            & jnp.all(jnp.isfinite(rel_change))
        # Judge against the PRE-update baselines (monitor order), and only
        # on finite evals (the monitor early-returns on non-finite).
        spike = finite & jnp.isfinite(best) \
            & (f > best * (1.0 + spike_rtol) + spike_atol)
        expl = finite & jnp.isfinite(ming) \
            & (gn > expl_factor * jnp.maximum(ming, gn_floor))
        if stall_window > 1:
            slen = slen + 1
            full = slen >= stall_window
            stalled = finite & full & ~sfired \
                & (anchor - f <= stall_rtol * jnp.abs(anchor))
            sfired = sfired | stalled
            anchor = jnp.where(full, f, anchor)
            slen = jnp.where(full, 0, slen)
        else:
            stalled = jnp.zeros((), bool)

        code = jnp.zeros((), jnp.int32)
        code = jnp.maximum(code, jnp.where(spike, ANOMALY_COST_SPIKE, 0))
        code = jnp.maximum(code, jnp.where(stalled, ANOMALY_STALL, 0))
        code = jnp.maximum(code,
                           jnp.where(expl, ANOMALY_GRAD_EXPLOSION, 0))
        code = jnp.maximum(code,
                           jnp.where(~finite, ANOMALY_NON_FINITE, 0))
        anom = jnp.maximum((vs.word >> 3) & 7, code)

        status_now = jnp.where(
            gn < grad_norm_tol, VERDICT_GRAD_NORM,
            jnp.where(consensus > 0, VERDICT_CONSENSUS,
                      VERDICT_RUNNING)).astype(jnp.int32)
        status = jnp.where(vs.term_eval >= 0, vs.word & 7, status_now)
        first_term = (vs.term_eval < 0) & (status != VERDICT_RUNNING)
        term_eval = jnp.where(first_term, vs.eval_idx, vs.term_eval)
        term_it = jnp.where(first_term, iteration.astype(jnp.int32),
                            vs.term_it)

        best = jnp.where(finite, jnp.minimum(best, f), best)
        ming = jnp.where(finite, jnp.minimum(ming, gn), ming)
        hist = jax.lax.dynamic_update_slice(
            vs.hist, vec[None, :].astype(vs.hist.dtype),
            (vs.eval_idx, jnp.zeros((), vs.eval_idx.dtype)))
        word = (status | (anom << 3) | (stage << 6)).astype(jnp.int32)
        return VerdictState(word=word, eval_idx=vs.eval_idx + 1,
                            term_eval=term_eval, term_it=term_it,
                            best_cost=best, min_gn=ming, stage=stage,
                            stall_anchor=anchor, stall_len=slen,
                            stall_fired=sfired, hist=hist)

    return verdict_step


def _package_version() -> str:
    """The dpgo_tpu version for run fingerprints (lazy import — the
    package __init__ is not a dependency of this module at import time)."""
    try:
        from .. import __version__
        return str(__version__)
    except ImportError:  # pragma: no cover - partial installs
        return "unknown"


@contextlib.contextmanager
def _crash_dump_scope(flight_rec):
    """Dump the attached flight recorder's black box when the driver loop
    dies — a crash is exactly the moment the ring buffer pays for itself.
    ``FlightRecorder.dump`` is first-write-wins, so an anomaly dump that
    already fired (e.g. the abort policy raising SolverHealthError) is
    not overwritten by the crash handler."""
    try:
        yield
    except Exception:
        if flight_rec is not None:
            flight_rec.dump("crash")
        raise


def make_terminal_epilogue(graph: MultiAgentGraph, edges_g: EdgeSet,
                           n_total: int, num_meas: int, meta: GraphMeta, *,
                           certify_mode: str = "off",
                           certify_seed: int = 0):
    """The fused terminal program of a solve: gather + rounding/anchoring
    (``round_global``) + the terminal weight collapse, and — with
    ``certify_mode="device"`` — the gauge-deflated device certificate
    eigensolve (``certify.device_certificate_payload``) on the gathered
    global iterate, all as ONE jitted program.

    ``epilogue(Xa, weights, extras)`` returns a dict with ``T`` (rounded
    trajectory), ``w_glob`` (per-measurement weights), ``extras`` passed
    through verbatim (the verdict loop rides its device-side history and
    latched terminal indices here), plus ``Xg``/``cert`` when a
    certificate mode is on — so the driver's entire epilogue (finalize +
    latched-index fetch + history fetch + certificate) collapses into a
    single blocking ``_host_fetch`` of the returned pytree.  The host
    decision on the fetched payload is ``_epilogue_certificate``."""
    device_cert = certify_mode == "device"
    want_xg = certify_mode in ("device", "host")
    if device_cert:
        from . import certify as certify_mod

    @jax.jit
    def epilogue(Xa, weights, extras: dict) -> dict:
        Xg = gather_to_global(Xa, graph, n_total)
        w_glob = global_weights(weights, graph, num_meas)
        out = {"T": round_global(Xg, lifting_matrix(meta, Xg.dtype)),
               "w_glob": w_glob, **extras}
        if want_xg:
            # The lifted global iterate: the certificate operand, and the
            # host f64 REFUSE fallback's input — riding the same fetch so
            # a REFUSE never costs a second device round-trip.
            out["Xg"] = Xg
        if device_cert:
            eg = edges_g._replace(weight=w_glob)
            out["cert"] = certify_mod.device_certificate_payload(
                Xg, eg, jax.random.PRNGKey(certify_seed))
        return out

    return epilogue


def _epilogue_certificate(fin: dict, edges_g: EdgeSet, params, dtype):
    """HOST decision on a fetched epilogue dict: build the
    ``CertificateResult`` for ``RBCDResult.certificate``.

    ``certify_mode="device"``: decide the already-computed device payload
    (``certify.decide_device_certificate``); the host sparse/f64 path
    runs ONLY when the f32 verdict is REFUSEd, fed from the fetched
    ``Xg``/``w_glob`` (no further device traffic).  ``"host"``: the
    legacy post-hoc ``certify_solution`` round-trip, kept for parity
    runs."""
    from . import certify as certify_mod

    certify_mode = getattr(params, "certify_mode", "off")
    eta = float(getattr(params, "certify_eta", 1e-5))
    eg = edges_g._replace(weight=jnp.asarray(fin["w_glob"]))
    if certify_mode == "host":
        return certify_mod.certify_solution(jnp.asarray(fin["Xg"]), eg,
                                            eta=eta)
    pay = fin["cert"]
    tol = eta * float(pay["wscale"])
    f64_solve = certify_mod.host_f64_solve(fin["Xg"], eg, tol,
                                           warm=pay["direction"])
    return certify_mod.decide_device_certificate(
        pay, eta, float(jnp.finfo(jnp.dtype(dtype)).eps),
        f64_solve=f64_solve)


def run_rbcd(
    state: RBCDState,
    graph: MultiAgentGraph,
    meta: GraphMeta,
    step,
    part: Partition,
    max_iters: int,
    grad_norm_tol: float = 0.1,
    eval_every: int = 1,
    dtype=jnp.float64,
    params: AgentParams | None = None,
    multi_step=None,
    segment=None,
    verdict_every: int | None = None,
    metrics_body_factory=None,
    start_iteration: int = 0,
    start_num_weight_updates: int = 0,
    boundary_cb=None,
) -> RBCDResult:
    """The driver loop shared by the single-device and mesh-sharded solvers —
    the analog of the ``multi-robot-example`` loop
    (``MultiRobotExample.cpp:175-264``): per round ``step`` exchanges public
    poses and updates agents per the schedule; the centralized cost/gradnorm
    trace (the demo's oracle) gates termination at ``grad_norm_tol`` (0.1 in
    the reference driver), with agent consensus (all ``ready``) as the
    deployed alternative (``shouldTerminate``, ``PGOAgent.cpp:1007-1031``).

    ``step(state, update_weights, restart)`` receives the two host-side
    static schedule flags each round.  ``params`` drives the GNC /
    acceleration schedules (omit for plain L2 RBCD).

    ``multi_step(state, k)``, when given, runs ``k`` consecutive plain
    rounds in one device call (``rbcd_steps`` / the shard_map equivalent);
    the driver then dispatches once per schedule segment — the stretch
    between weight-update/restart/eval rounds — instead of once per round,
    which removes the host round-trip that dominates wall-clock on fast
    devices.  Identical math either way (the fused body is ``_rbcd_round``).

    ``segment(state, k, update_weights, restart)``, when given, supersedes
    both: each dispatch covers a flagged first round AND the plain stretch
    to the next flag/eval boundary (``rbcd_segment`` / the shard_map
    equivalent), so flagged rounds stop costing their own round-trips.
    The GNC weight freeze runs on-device either way (see ``_rbcd_round``),
    so no path reads weights back between evals.

    ``verdict_every`` (K, a positive multiple of ``eval_every``) switches
    the driver to the DEVICE-RESIDENT verdict loop: the centralized
    metrics, the convergence test, and the health predicates run in the
    fused verdict program at every eval boundary (``make_verdict_program``
    — requires ``segment``), termination latches on device, and the host
    reads back ONE packed verdict word per K rounds instead of the full
    scalar stack per eval.  With telemetry on, the device-side eval
    history is fetched lazily at each verdict boundary and replayed
    through the same gauges/events/health-monitor/flight-recorder calls,
    so the emitted event stream is identical to the per-eval path's (with
    at most K rounds of latency); with telemetry off, only the word and
    ONE fused terminal epilogue fetch (rounded trajectory, collapsed
    weights, history, latched indices, and — with
    ``params.certify_mode="device"`` — the dual-certificate payload)
    ever cross the link.  Because the host learns
    of termination at the next boundary, the returned iterate may carry
    up to ``K - eval_every`` extra polish rounds; reported histories and
    ``iterations`` are truncated at the latched terminal eval.

    ``metrics_body_factory`` (mesh path) supplies a replacement for the
    stacked-metrics body: called once with the resolved telemetry flag, the
    returned function is jitted for the per-eval readback AND handed to
    ``make_verdict_program`` as its ``metrics_body`` — how the sharded
    solver runs the centralized evals as a shard_map program with psum
    reductions while sharing every downstream line of this driver.

    ``start_iteration`` / ``start_num_weight_updates`` resume the verdict
    loop mid-schedule from a checkpointed state (``parallel.resilience``):
    the schedule arithmetic is a pure function of the ABSOLUTE round
    index, so a resumed solve replays the exact flag sequence of the
    uninterrupted one.  ``boundary_cb(it, nwu, state, word, terminal)``
    fires at every verdict boundary with the pre-speculation state — the
    checkpoint/rewind hook; it may raise to abort the attempt.  All three
    require the verdict loop."""
    if verdict_every is None and (start_iteration or start_num_weight_updates
                                  or boundary_cb is not None):
        raise ValueError(
            "start_iteration / start_num_weight_updates / boundary_cb "
            "are resilience hooks of the verdict loop; pass "
            "verdict_every=K to use them")
    n_total = part.meas_global.num_poses
    num_meas = len(part.meas_global)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)

    # Telemetry (dpgo_tpu.obs): resolved ONCE per solve.  When off, the
    # eval program below is byte-identical to the uninstrumented driver —
    # zero events, zero registry calls, zero added transfers.  When on, the
    # extra per-eval scalars (GNC mu, inlier fraction, per-agent relative
    # change) ride the SAME stacked readback the driver already pays for,
    # so telemetry never adds a device->host round-trip to the hot loop.
    obs_run = obs.get_run()
    telemetry = obs_run is not None

    metrics_body = metrics_body_factory(telemetry) \
        if metrics_body_factory is not None else None
    central_metrics = jax.jit(metrics_body) if metrics_body is not None \
        else _make_central_metrics(graph, edges_g, n_total, num_meas,
                                   telemetry)

    robust_on = params is not None and \
        params.robust.cost_type != RobustCostType.L2
    accel_on = params is not None and params.acceleration

    if segment is None:
        # Legacy callers (step-only, or step + fused plain loop): synthesize
        # the segment so ONE copy of the schedule-boundary arithmetic below
        # serves every path.  Identical math — a segment is a flagged round
        # plus plain rounds.
        def segment(s, k, uw, rs):
            s = step(s, uw, rs)
            if k > 1:
                if multi_step is not None:
                    s = multi_step(s, k - 1)
                else:
                    for _ in range(k - 1):
                        s = step(s, False, False)
            return s

    cost_hist, gn_hist = [], []
    terminated_by = "max_iters"
    it = 0
    num_weight_updates = 0

    def _bounds(n_done, nwu):
        """Schedule arithmetic, shared with the flight-recorder replay —
        see ``schedule_bounds``."""
        return schedule_bounds(n_done, nwu, max_iters=max_iters,
                               eval_every=eval_every, params=params,
                               robust_on=robust_on, accel_on=accel_on)

    health_mon = flight_rec = None
    if telemetry:
        from ..obs import health as health_mod

        # Numerical-health layer (obs.health): judges the same scalars the
        # stacked readback below already carries — no extra transfers.
        # The flight recorder is opt-in (FlightRecorder.attach); when one
        # is attached, register the problem so its black box is
        # self-contained and replayable.
        health_mon = health_mod.monitor_for(obs_run)
        flight_rec = getattr(obs_run, "recorder", None)
        if flight_rec is not None:
            flight_rec.set_problem(part, meta, params, dtype,
                                   eval_every=eval_every,
                                   grad_norm_tol=grad_norm_tol,
                                   max_iters=max_iters)
        obs_run.set_fingerprint(
            version=_package_version(),
            solver="run_rbcd",
            num_robots=meta.num_robots, rank=meta.rank, d=meta.d,
            n_poses=n_total, n_meas=num_meas,
            dtype=str(np.dtype(dtype)),
            schedule=params.schedule.value if params is not None else None,
            robust_cost=params.robust.cost_type.value
            if params is not None else None,
            sel_mode=resolved_sel_mode(params)
            if params is not None else None,
            eval_every=eval_every)

    if telemetry:
        obs_run.event("solve_start", phase="solve",
                      num_robots=meta.num_robots, max_iters=max_iters,
                      eval_every=eval_every, grad_norm_tol=grad_norm_tol,
                      robust=robust_on, acceleration=accel_on)
        g_cost = obs_run.gauge("solver_cost", "centralized SE(d) cost")
        g_gn = obs_run.gauge("solver_grad_norm",
                             "centralized Riemannian gradient norm")
        c_rounds = obs_run.counter("solver_rounds", "RBCD rounds executed")
        c_evals = obs_run.counter("solver_evals",
                                  "centralized metric evaluations")
        h_round = obs_run.histogram(
            "round_latency_seconds",
            "wall-clock per RBCD round at phase boundaries", unit="s")
        g_agent_lat = obs_run.gauge(
            "agent_round_latency_seconds",
            "per-agent round latency (lockstep rounds: the eval-window "
            "wall-clock over rounds, identical across agents)", unit="s")
        g_agent_rel = obs_run.gauge("agent_rel_change",
                                    "per-agent iterate relative change")
        if robust_on:
            g_mu = obs_run.gauge("gnc_mu", "GNC control parameter")
            g_inl = obs_run.gauge("gnc_inlier_fraction",
                                  "fraction of updatable LC edges at w>0.5")

    host_fetches = 0  # sanctioned device->host syncs inside the loop

    def _emit_eval(it_ev, vec, rounds, per_round, state=None, nwu=0):
        """One eval's telemetry — gauges, metric events, flight-recorder
        ring, health verdict — shared verbatim by the per-eval path and
        the verdict path (which feeds it lazily-fetched history rows), so
        both emit the identical event stream.  ``vec`` is a host-side
        telemetry-width metrics row; ``state`` is passed only when an
        exact snapshot is available at this eval (the per-eval path)."""
        f, gn = float(vec[0]), float(vec[1])
        mu_v, inl, mean_w = (float(x) for x in vec[3:6])
        rel = vec[6:]
        g_cost.set(f)
        g_gn.set(gn)
        c_rounds.inc(rounds)
        c_evals.inc()
        h_round.observe(per_round)
        for a in range(rel.shape[0]):
            g_agent_lat.set(per_round, agent=a)
            g_agent_rel.set(float(rel[a]), agent=a)
        ev = {"iteration": it_ev, "round_latency_s": per_round,
              # rel is a host-side row of an already-materialized
              # vector; .max() is numpy. dpgolint: disable=DPG003
              "rel_change_max": float(rel.max()) if rel.size else None}
        obs_run.metric("solver_cost", f, phase="eval", **ev)
        obs_run.metric("solver_grad_norm", gn, phase="eval", **ev)
        if robust_on:
            g_mu.set(mu_v)
            g_inl.set(inl)
            obs_run.metric("gnc_mu", mu_v, phase="eval", iteration=it_ev)
            obs_run.metric("gnc_inlier_fraction", inl, phase="eval",
                           iteration=it_ev, mean_weight=mean_w)
        # Flight recorder first (so an anomaly dump includes this
        # eval), then the health verdict — which may dump and, per
        # the abort policy, raise SolverHealthError.
        if flight_rec is not None:
            flight_rec.record_eval(
                it_ev, {"cost": f, "grad_norm": gn,
                        "mu": mu_v, "inlier_frac": inl,
                        "rel_change": rel},
                state=state, num_weight_updates=nwu)
        if health_mon is not None:
            health_mon.observe_solver(
                it_ev, f, gn,
                mu=mu_v if robust_on else None,
                inlier_frac=inl if robust_on else None,
                rel_change=rel,
                stage=robust.gnc_stage_index(mu_v, params.robust)
                if robust_on else None)

    if verdict_every is not None:
        return _run_verdict_loop(
            state, graph, meta, segment, max_iters=max_iters,
            grad_norm_tol=grad_norm_tol, eval_every=eval_every,
            verdict_every=verdict_every, dtype=dtype, params=params,
            edges_g=edges_g, n_total=n_total, num_meas=num_meas,
            telemetry=telemetry, obs_run=obs_run, health_mon=health_mon,
            flight_rec=flight_rec, emit_eval=_emit_eval,
            bounds=_bounds, robust_on=robust_on,
            metrics_body=metrics_body,
            start_iteration=start_iteration,
            start_nwu=start_num_weight_updates,
            boundary_cb=boundary_cb)

    # Pipelined driver: advance to each eval boundary, ENQUEUE the metrics
    # program, dispatch one speculative segment past the boundary, and only
    # then fetch the metrics — the device works through the speculation
    # while the readback round-trip (the dominant host cost on a tunneled
    # TPU) is in flight.  Flags are host-deterministic functions of the
    # round index, so speculation never changes which rounds are flagged;
    # a termination at the boundary simply discards the speculative state.
    with _crash_dump_scope(flight_rec):
        spec = None  # (state, it, uw) one segment past the last eval boundary
        t_solve0 = t_window = time.perf_counter()
        it_window = 0
        while it < max_iters:
            target = min(((it // eval_every) + 1) * eval_every, max_iters)
            if spec is not None:
                # A spec can only be pending at the top of an outer iteration
                # (set at the previous eval boundary, exactly one segment ahead).
                state, it, uw = spec
                num_weight_updates += int(uw)
                spec = None
            while it < target:
                uw, rs, end = _bounds(it, num_weight_updates)
                num_weight_updates += int(uw)
                state = segment(state, end - it, uw, rs)
                it = end
            fut = central_metrics(state.X, state.weights, state.ready,
                                  state.mu, state.rel_change)
            if it < max_iters:
                uw, rs, end = _bounds(it, num_weight_updates)
                spec = (segment(state, end - it, uw, rs), end, uw)
            if telemetry:
                t_rb_m, t_rb_w = time.monotonic(), time.time()
            # THE sanctioned readback seam: the one stacked device->host
            # fetch per eval.  dpgolint: disable=DPG003 -- sanctioned seam
            vec = _host_fetch(fut)
            host_fetches += 1
            if telemetry:
                # The eval readback span: the device->host fetch the pipelined
                # driver hides behind the speculative segment — its duration on
                # the timeline shows how much of the round-trip stayed hidden.
                trace.emit_span(obs_run, "eval_readback", t_rb_m, t_rb_w,
                                time.monotonic() - t_rb_m, phase="eval",
                                iteration=it)
            f, gn, consensus = vec[:3]
            cost_hist.append(float(f))
            gn_hist.append(float(gn))
            if telemetry:
                # The fetch above already materialized everything this block
                # reads — host-side bookkeeping only from here.
                now = time.perf_counter()
                dt, t_window = now - t_window, now
                rounds = max(it - it_window, 1)
                it_window = it
                _emit_eval(it, vec, rounds, dt / rounds, state=state,
                           nwu=num_weight_updates)
            if float(gn) < grad_norm_tol:
                terminated_by = "grad_norm"
                break
            if consensus > 0:
                terminated_by = "consensus"
                break

    # Final assembly as one jitted program (eager, the gather + rounding
    # chain costs ~15 s in per-op dispatches on a tunneled TPU at 15k
    # poses).  With a certificate mode on, the device eigensolve fuses
    # into the same program and the whole epilogue is read back as ONE
    # blocking fetch; with certification off the outputs stay lazy
    # device arrays exactly as before.
    certify_mode = getattr(params, "certify_mode", "off") \
        if params is not None else "off"
    epilogue = make_terminal_epilogue(graph, edges_g, n_total, num_meas,
                                      meta, certify_mode=certify_mode)
    fin = epilogue(state.X, state.weights, {})
    certificate = None
    if certify_mode != "off":
        # THE terminal blocking read (epilogue + certificate payload) —
        # paid once per solve, excluded from the in-loop sync-rate metric
        # like the lazy finalize it replaces.
        # dpgolint: disable=DPG003 -- sanctioned terminal epilogue fetch
        fin = _host_fetch(fin)
        certificate = _epilogue_certificate(fin, edges_g, params, dtype)
    T, w_glob = fin["T"], fin["w_glob"]
    if telemetry:
        _emit_sync_rate(obs_run, host_fetches, it)
        obs_run.event(
            "solve_end", phase="solve", iterations=it,
            terminated_by=terminated_by,
            duration_s=time.perf_counter() - t_solve0,
            cost=cost_hist[-1] if cost_hist else None,
            grad_norm=gn_hist[-1] if gn_hist else None,
            num_weight_updates=num_weight_updates)
    return RBCDResult(T=T, X=state.X, cost_history=cost_hist,
                      grad_norm_history=gn_hist, iterations=it,
                      terminated_by=terminated_by, weights=w_glob,
                      state=state, certificate=certificate)


def _emit_sync_rate(obs_run, fetches: int, rounds: int) -> None:
    """Record the measured in-loop host-sync rate: the readback-kill
    metric (``host_syncs_per_100_rounds``; lower is better, gated by
    ``obs.regress``).  Counts only the driver-loop fetches through the
    ``_host_fetch`` seam — the terminal finalize transfer is excluded, as
    it is paid once per solve regardless of loop design."""
    rate = 100.0 * fetches / max(rounds, 1)
    obs_run.gauge("host_syncs_per_100_rounds",
                  "driver-loop device->host fetches per 100 RBCD rounds"
                  ).set(rate)
    obs_run.metric("host_syncs_per_100_rounds", rate, phase="solve",
                   fetches=fetches, rounds=rounds)


def _run_verdict_loop(state, graph, meta, segment, *, max_iters,
                      grad_norm_tol, eval_every, verdict_every, dtype,
                      params, edges_g, n_total, num_meas, telemetry,
                      obs_run, health_mon, flight_rec, emit_eval, bounds,
                      robust_on, metrics_body=None, start_iteration=0,
                      start_nwu=0, boundary_cb=None):
    """Body of ``run_rbcd``'s device-resident mode (see its docstring).

    Per verdict boundary (every K rounds): dispatch the schedule segments
    and the fused verdict evals, ENQUEUE the next boundary's work (depth-1
    speculation, so the word fetch's round-trip hides behind device
    execution), then fetch ONE packed int32.  The full per-eval history is
    fetched lazily — per boundary with telemetry on (feeding the identical
    gauge/event/health/recorder calls as the per-eval path), once at
    termination otherwise.

    Resumption (``start_iteration``/``start_nwu``) re-enters at an
    absolute round index: every schedule quantity below is already a pure
    function of it, so the flag sequence is identical to an uninterrupted
    run's.  A resumed attempt gets a fresh verdict state — anomaly
    latches clear, and its history rows cover only the resumed suffix."""
    if verdict_every <= 0 or verdict_every % eval_every != 0:
        raise ValueError(
            f"verdict_every={verdict_every} must be a positive multiple "
            f"of eval_every={eval_every}")
    max_evals = -(-max_iters // eval_every)
    verdict_step = make_verdict_program(
        graph, edges_g, n_total, num_meas, telemetry,
        grad_norm_tol=grad_norm_tol,
        robust_params=params.robust if robust_on else None,
        max_evals=max_evals,
        health_cfg=health_mon.config if health_mon is not None else None,
        metrics_body=metrics_body)
    vs0 = init_verdict_state(max_evals, meta.num_robots, dtype, telemetry)
    certify_mode = getattr(params, "certify_mode", "off") \
        if params is not None else "off"
    epilogue = make_terminal_epilogue(graph, edges_g, n_total, num_meas,
                                      meta, certify_mode=certify_mode)
    if obs_run is not None:
        # Compile accounting (ISSUE 16): the verdict program and the
        # terminal epilogue report their cost/memory analysis and the
        # bytes-per-flop roofline ratio through the same AOT probe as
        # the serve cache — one compile per program either way, and any
        # probe failure falls back to the plain jit callables.
        from ..obs import devprof as _devprof

        _plane = "sharded" if metrics_body is not None else "solve"
        verdict_step = _devprof.profiled_program(
            obs_run, verdict_step, key=f"verdict/k{verdict_every}",
            label="verdict_step", plane=_plane)
        epilogue = _devprof.profiled_program(
            obs_run, epilogue, key="epilogue/terminal",
            label="terminal_epilogue", plane=_plane)

    eval_its: list[int] = []
    fetches = 0

    def advance(st, it, nwu, vs, target):
        """Enqueue segments + fused verdict evals up to ``target`` (no
        host synchronization — everything stays in flight)."""
        while it < target:
            ev_t = min(((it // eval_every) + 1) * eval_every, target)
            while it < ev_t:
                uw, rs, end = bounds(it, nwu)
                nwu += int(uw)
                st = segment(st, end - it, uw, rs)
                it = end
            vs = verdict_step(st.X, st.weights, st.ready, st.mu,
                              st.rel_change, st.iteration, vs)
            eval_its.append(it)
        return st, it, nwu, vs

    t_solve0 = t_window = time.perf_counter()
    it_window = int(start_iteration)
    fed = 0
    hist_rows = None
    terminated_by = "max_iters"
    n_keep = it_final = 0
    with _crash_dump_scope(flight_rec):
        it, nwu, vs = int(start_iteration), int(start_nwu), vs0
        bound = lambda i: min(((i // verdict_every) + 1) * verdict_every,
                              max_iters)
        state, it, nwu, vs = advance(state, it, nwu, vs, bound(it))
        n_pre = len(eval_its)
        while True:
            state_pre, it_pre, nwu_pre, vs_pre = state, it, nwu, vs
            if it < max_iters:
                # Depth-1 speculation: the NEXT boundary's segments and
                # verdict evals execute while the word fetch below blocks
                # the host for a tunnel round-trip; each loop iteration
                # fetches exactly one boundary's word.
                state, it, nwu, vs = advance(state, it, nwu, vs, bound(it))
            # THE verdict readback: one packed int32 per K rounds (from
            # the pre-speculation state, so it never waits on the
            # speculative work).
            # dpgolint: disable=DPG003 -- sanctioned verdict-word fetch
            word = int(_host_fetch(vs_pre.word))
            fetches += 1
            status = word & 7
            terminal = status != VERDICT_RUNNING or it_pre >= max_iters
            if boundary_cb is not None:
                # Resilience hook (parallel.resilience): checkpoint the
                # pre-speculation state, or raise to rewind on a latched
                # anomaly.  The word fetch above already drained this
                # boundary, so a checkpoint gather here adds no new
                # synchronization point.
                boundary_cb(it_pre, nwu_pre, state_pre, word, terminal)
            if telemetry and not terminal:
                # Lazy full-stack fetch: the per-eval scalar rows the
                # telemetry/health/recorder consumers see — recurring
                # (counted) with telemetry on; at termination the rows
                # ride the fused epilogue fetch below instead.
                # dpgolint: disable=DPG003 -- sanctioned lazy history fetch
                hist_rows = _host_fetch(vs_pre.hist)
                fetches += 1
            if terminal:
                # THE terminal blocking read: rounding/anchoring, the
                # weight collapse, the device certificate payload (when
                # certify_mode="device"), the eval history, and the
                # latched terminal indices — one pytree, one fetch.  The
                # history leg replaces the recurring telemetry fetch at
                # this boundary (same count); everything else replaced
                # the old separate tail fetch + lazy finalize.
                # dpgolint: disable=DPG003 -- sanctioned terminal epilogue fetch
                fin = _host_fetch(epilogue(
                    state_pre.X, state_pre.weights,
                    {"hist": vs_pre.hist,
                     "tail": jnp.stack([vs_pre.term_eval,
                                        vs_pre.term_it])}))
                hist_rows = fin["hist"]
                fetches += int(telemetry)
                term_eval, term_it = int(fin["tail"][0]), int(fin["tail"][1])
                if term_eval >= 0:
                    n_keep, it_final = term_eval + 1, term_it
                    terminated_by = _VERDICT_STATUS.get(status, "max_iters")
                else:
                    n_keep, it_final = n_pre, it_pre
                    terminated_by = "max_iters"
            feed_to = min(n_pre, n_keep) if terminal else n_pre
            if telemetry and feed_to > fed:
                now = time.perf_counter()
                dt, t_window = now - t_window, now
                rounds_w = max(it_pre - it_window, 1)
                it_window = it_pre
                per_round = dt / rounds_w
                for r in range(fed, feed_to):
                    rounds_r = eval_its[r] - (eval_its[r - 1] if r
                                              else int(start_iteration))
                    emit_eval(eval_its[r], hist_rows[r], max(rounds_r, 1),
                              per_round)
                fed = feed_to
                if flight_rec is not None and not terminal:
                    # Exact-state snapshot at the verdict boundary (the
                    # K-cadence analog of record_eval's snapshot path).
                    # hist_rows is already host-side (the lazy fetch).
                    rows_finite = np.isfinite(hist_rows[:feed_to]).all()
                    flight_rec.snapshot_state(
                        it_pre, state_pre, nwu_pre,
                        healthy=bool(rows_finite))
            if terminal:
                state = state_pre
                break
            n_pre = len(eval_its)

    cost_hist = [float(hist_rows[r, 0]) for r in range(n_keep)]
    gn_hist = [float(hist_rows[r, 1]) for r in range(n_keep)]

    # The epilogue already crossed the link in the terminal fetch above;
    # what remains is pure host math (the certificate decision ladder —
    # which re-opens device traffic only on a REFUSE, by design).
    T, w_glob = fin["T"], fin["w_glob"]
    certificate = _epilogue_certificate(fin, edges_g, params, dtype) \
        if certify_mode != "off" else None
    if telemetry:
        _emit_sync_rate(obs_run, fetches,
                        max(it_pre - int(start_iteration), 1))
        obs_run.event(
            "solve_end", phase="solve", iterations=it_final,
            terminated_by=terminated_by,
            duration_s=time.perf_counter() - t_solve0,
            cost=cost_hist[-1] if cost_hist else None,
            grad_norm=gn_hist[-1] if gn_hist else None,
            num_weight_updates=nwu_pre,
            verdict_every=verdict_every, verdict=unpack_verdict(word))
    return RBCDResult(T=T, X=state.X, cost_history=cost_hist,
                      grad_norm_history=gn_hist, iterations=it_final,
                      terminated_by=terminated_by, weights=w_glob,
                      state=state, certificate=certificate)


def initial_state_for(init: str, part: Partition, meta: GraphMeta,
                      graph: MultiAgentGraph, params: AgentParams,
                      dtype) -> jax.Array:
    """Initial lifted state by policy: ``"chordal"`` = centralized chordal
    init (the reference demo's, ``MultiRobotExample.cpp:158-165``);
    ``"odometry"`` = trusted-odometry chain init (``DPGO_utils.cpp:
    426-447`` — the outlier-safe choice for robust runs);
    ``"distributed"`` = per-agent local init + robust inter-robot frame
    alignment, no centralized solve (the deployment path,
    ``PGOAgent.cpp:250-432``)."""
    if init == "chordal":
        return centralized_chordal_init(part, meta, graph, dtype)
    if init == "odometry":
        return centralized_odometry_init(part, meta, graph, dtype)
    if init == "distributed":
        from .dist_init import distributed_initialization
        return distributed_initialization(part, meta, graph, params, dtype)
    raise ValueError(f"unknown init policy {init!r}")


@dataclasses.dataclass(frozen=True)
class PreparedProblem:
    """A built, dispatch-ready problem — the schedulable unit of the
    serving plane (``dpgo_tpu.serve``).

    Splits ``solve_rbcd`` into its two halves: *problem build* (partition,
    padded per-agent graph/EdgeSet, metadata, initial lifted state) and
    *solve dispatch* (``dispatch_prepared`` -> ``run_rbcd``).  A prepared
    problem is reusable: it can be dispatched more than once (e.g. with
    different termination settings), padded to a shape bucket and stacked
    with compatible problems for a batched ``vmap`` solve, or held in a
    queue awaiting device capacity — none of which re-runs the host-side
    graph construction.
    """

    part: Partition
    graph: MultiAgentGraph
    meta: GraphMeta
    params: AgentParams
    dtype: object
    X0: jax.Array | None = None

    @property
    def n_total(self) -> int:
        return self.part.meas_global.num_poses

    @property
    def num_meas(self) -> int:
        return len(self.part.meas_global)


def prepare_problem(
    meas: Measurements,
    num_robots: int,
    params: AgentParams | None = None,
    dtype=jnp.float64,
    part: Partition | None = None,
    init: str | None = "chordal",
    pallas_sel: bool | None = None,
) -> PreparedProblem:
    """Problem build: partition, per-agent graph assembly, and (unless
    ``init=None``) the initial lifted state.

    ``init=None`` defers initialization — the serving plane pads the
    problem to its shape bucket first and initializes on the padded
    problem, so the compiled init program is shared across the bucket."""
    params = params or AgentParams(d=meas.d, r=5, num_robots=num_robots)
    part = part or partition_contiguous(meas, num_robots)
    graph, meta = build_graph(part, params.r, dtype, pallas_sel=pallas_sel,
                              sel_mode=resolved_sel_mode(params))
    X0 = initial_state_for(init, part, meta, graph, params, dtype) \
        if init is not None else None
    return PreparedProblem(part=part, graph=graph, meta=meta, params=params,
                           dtype=dtype, X0=X0)


def dispatch_prepared(
    prob: PreparedProblem,
    max_iters: int | None = None,
    grad_norm_tol: float = 0.1,
    eval_every: int = 1,
    state: RBCDState | None = None,
    verdict_every: int | None = None,
) -> RBCDResult:
    """Solve dispatch for a prepared problem: build the step closures and
    run the shared driver loop (``run_rbcd``).  ``state`` overrides the
    fresh ``init_state`` — e.g. to resume from a snapshot.
    ``verdict_every`` opts into the device-resident verdict loop (one
    packed-word readback per K rounds — see ``run_rbcd``)."""
    params = prob.params
    max_iters = params.max_num_iters if max_iters is None else max_iters
    if state is None:
        if prob.X0 is None:
            raise ValueError(
                "prepared problem has no initial state — prepare with "
                "init=... or pass state=")
        state = init_state(prob.graph, prob.meta, prob.X0, params=params)
    graph, meta = prob.graph, prob.meta
    step = lambda s, uw, rs: rbcd_step(s, graph, meta, params,
                                       update_weights=uw, restart=rs)
    multi = lambda s, k: rbcd_steps(s, graph, k, meta, params)
    seg = lambda s, k, uw, rs: rbcd_segment(s, graph, k, meta, params,
                                            first_update_weights=uw,
                                            first_restart=rs)
    return run_rbcd(state, graph, meta, step, prob.part, max_iters,
                    grad_norm_tol, eval_every, prob.dtype, params=params,
                    multi_step=multi, segment=seg,
                    verdict_every=verdict_every)


def solve_rbcd(
    meas: Measurements,
    num_robots: int,
    params: AgentParams | None = None,
    max_iters: int | None = None,
    grad_norm_tol: float = 0.1,
    eval_every: int = 1,
    dtype=jnp.float64,
    part: Partition | None = None,
    init: str = "chordal",
    verdict_every: int | None = None,
) -> RBCDResult:
    """Distributed solve on one device with centralized monitoring —
    ``prepare_problem`` + ``dispatch_prepared`` in one call."""
    prob = prepare_problem(meas, num_robots, params=params, dtype=dtype,
                           part=part, init=init)
    return dispatch_prepared(prob, max_iters=max_iters,
                             grad_norm_tol=grad_norm_tol,
                             eval_every=eval_every,
                             verdict_every=verdict_every)


def solve_rbcd_robust_iterated(
    meas: Measurements,
    num_robots: int,
    params: AgentParams | None = None,
    passes: int = 2,
    reject_thresh: float = 0.5,
    **solve_kw,
) -> tuple[RBCDResult, np.ndarray, np.ndarray]:
    """Iterated GNC: robust solve, HARD-drop rejected loop closures,
    re-anneal on the kept edges — ``passes`` times.

    A single GNC anneal at BCD inner-convergence depth can leave a few
    gross outliers at weight >= ``reject_thresh`` whose constraints bend
    the whole solution (measured on 10%-corrupted city10000: 16 of 1069
    injected outliers survive pass 1 and inflate the inlier-edge cost
    ~25x over the outlier-free optimum).  A second anneal on the filtered
    problem starts from an iterate the surviving outliers can no longer
    hide in — residuals are informative — and rejects them (same
    measurement: recall 0.985 -> 1.000, inlier-edge cost +4% over the
    outlier-free optimum).  Only loop closures are ever dropped
    (``types.loop_closure_mask``): the odometry chain stays intact, so
    the filtered graph cannot disconnect.

    The reference's GNC is single-pass (``updateLoopClosuresWeights``,
    ``PGOAgent.cpp:1181-1245``); the iteration is beyond-reference.

    Between passes, previously-dropped edges whose residual at the new
    solution falls back inside the TLS inlier boundary (``gnc_barc``) are
    REINSTATED: at heavy corruption the re-anneal over-rejects borderline
    clean edges (measured at 40%: precision 0.87-0.97), and once the
    iterate no longer carries the outliers' distortion, a wrongly-dropped
    edge is cheap to recognize — its residual is small again.  (The
    consensus re-test of RANSAC-style pipelines; beyond-reference.)

    Returns ``(result_of_last_pass, weights_full, kept_mask)`` where
    ``weights_full [M]`` maps the last pass's weights back to the
    ORIGINAL measurement indices (dropped edges report weight 0) and
    ``kept_mask [M]`` marks the measurements the last pass solved over.
    ``result.iterations`` is the TOTAL round count across passes.
    """
    from ..types import loop_closure_mask

    if passes < 1:
        raise ValueError(f"passes must be >= 1, got {passes}")
    if "part" in solve_kw:
        # solve_rbcd prefers a supplied Partition over its meas argument,
        # which would silently undo the per-pass edge filtering.
        raise ValueError("solve_rbcd_robust_iterated re-partitions each "
                         "pass; 'part' cannot be supplied")
    lc = loop_closure_mask(meas)
    kept = np.ones(len(meas), bool)
    res = None
    total_rounds = 0
    for p in range(passes):
        sub = meas.select(kept) if not kept.all() else meas
        res = solve_rbcd(sub, num_robots, params, **solve_kw)
        total_rounds += res.iterations
        if res.weights is None and passes > 1:
            # A non-robust cost (the default AgentParams) yields no GNC
            # weights, so the drop/reinstate loop below would silently
            # degenerate to a single plain solve — surface the misuse.
            raise ValueError(
                "solve_rbcd_robust_iterated needs a GNC-weighted cost "
                "(params.robust.cost_type GNC_TLS); the solve returned no "
                "weights")
        w_sub = np.asarray(res.weights) if res.weights is not None \
            else np.ones(int(kept.sum()))
        w_full = np.zeros(len(meas))
        w_full[kept] = w_sub
        if p == passes - 1:
            break
        drop = (w_full < reject_thresh) & kept & lc
        # Re-test every previously-dropped edge against the new iterate.
        reinstate = np.zeros(len(meas), bool)
        dropped = ~kept
        if dropped.any():
            rn = _global_residual_norms(res, meas, num_robots)
            barc = (params.robust if params is not None
                    else RobustCostParams()).gnc_barc
            reinstate = dropped & (rn < barc)
            w_full[reinstate] = 1.0
        new_kept = (kept & ~drop) | reinstate
        if (new_kept == kept).all():
            break
        kept = new_kept
    res = dataclasses.replace(res, iterations=total_rounds)
    return res, w_full, kept


def _global_residual_norms(res: RBCDResult, meas: Measurements,
                           num_robots: int) -> np.ndarray:
    """Per-measurement residual norms sqrt(kappa ||rR||^2 + tau ||rt||^2)
    of the FULL original measurement set at a result's iterate (the
    iterate lives on the filtered problem; poses are unchanged by edge
    filtering, so the pose layout is partition-independent)."""
    from ..utils.partition import gather_poses_to_global

    edges_g = edge_set_from_measurements(meas, dtype=jnp.float32)
    part = partition_contiguous(meas, num_robots)
    Xg = gather_poses_to_global(np.asarray(res.X, np.float32), part)
    rR, rt = quadratic._edge_terms(jnp.asarray(Xg), edges_g)
    sq = edges_g.kappa * jnp.sum(rR * rR, axis=(-2, -1)) \
        + edges_g.tau * jnp.sum(rt * rt, axis=-1)
    return np.sqrt(np.maximum(np.asarray(sq), 0.0))
