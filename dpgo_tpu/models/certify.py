"""Solution certification and the Riemannian staircase — beyond-reference.

The reference implements the RBCD solver of Tian, Khosoussi, Rosen, How
(T-RO 2021) but NOT the certification half of "Distributed Certifiably
Correct Pose-Graph Optimization" (no certificate code exists anywhere in
``/root/reference/src``); SURVEY.md section 7 (M6) scopes it from the paper.
This module provides the centralized version operating on the assembled
lifted solution (the same place the framework already evaluates its
centralized monitoring metrics):

* **Dual certificate.**  A first-order critical point ``X`` of the rank-r
  relaxation yields block-diagonal dual multipliers
  ``Lambda_i = sym(Y_i^T (XQ)_i)`` on the rotation blocks (translations are
  unconstrained, their multiplier is zero).  ``X`` is a global optimum of
  the underlying SDP — and the rounded trajectory certifiably optimal —
  iff ``S = Q - Lambda`` is positive semidefinite (SE-Sync / T-RO 2021
  Prop. "exactness").  ``S`` always annihilates the global-translation
  gauge directions, so the test is ``lambda_min(S) >= -eta``.
* **Minimum eigenvalue.**  ``S`` is only ever applied as an operator: the
  edge-list connection-Laplacian matvec of ``ops.quadratic`` minus a
  per-pose block multiply — no (d+1)n x (d+1)n matrix is assembled.
  ``lambda_min`` comes from LOBPCG on the spectrally shifted operator
  ``sigma I - S`` (sigma from a short power iteration), all jittable.
* **Staircase.**  If ``lambda_min < -eta``, the eigenvector ``v`` is a
  second-order descent direction after lifting to rank r+1
  (``X+ = [[X], [alpha v^T]]``); re-solving and re-certifying ascends the
  rank staircase until certification or ``r_max`` (SE-Sync Algorithm 1
  adapted to the lifted SE(d) manifold).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp

from .. import obs
from ..config import SolverParams
from ..types import EdgeSet, Measurements, edge_set_from_measurements
from ..utils.lie import lifting_matrix
from ..ops import manifold, quadratic, solver
from .local_pgo import make_problem, round_solution


# ---------------------------------------------------------------------------
# Dual certificate operator
# ---------------------------------------------------------------------------

# Latched verdict codes of the DEVICE certificate stage (the f32
# eigensolve fused into the solve's terminal epilogue).  The f32-vs-f64
# disagreement band is an explicit verdict — CERT_REFUSE — not a silent
# recheck: a REFUSE hands the decision to the host sparse/f64 path, and
# no solve is ever certified by f32 alone inside the band.
CERT_NONE = 0      # certify_mode off / certificate not evaluated
CERT_ACCEPT = 1    # f32 verdict decisive and PSD within tolerance
CERT_REFUSE = 2    # disagreement band: host f64 must decide
CERT_FAIL = 3      # decisively negative (sound without f64)

CERT_STATUS = {CERT_NONE: "none", CERT_ACCEPT: "accept",
               CERT_REFUSE: "refuse", CERT_FAIL: "fail"}


def dual_blocks(X: jax.Array, edges: EdgeSet) -> jax.Array:
    """Block-diagonal dual multipliers Lambda [n, d, d] at a critical point.

    ``Lambda_i = sym(Y_i^T G_i)`` with ``G = X Q`` (the Euclidean gradient)
    restricted to the rotation columns.  At exact first-order criticality
    ``G_i = [Y_i Lambda_i | 0]``.
    """
    G = quadratic.egrad(X, edges)
    Y = X[..., :-1]     # [n, r, d]
    GY = G[..., :-1]
    return manifold.sym(jnp.einsum("nra,nrb->nab", Y, GY))


def certificate_matvec(V: jax.Array, edges: EdgeSet, lam: jax.Array) -> jax.Array:
    """Apply ``S = Q - Lambda`` to ``V [n, k, d+1]`` (k probe vectors).

    ``Q V`` reuses the edge-list gradient map (linear in its argument);
    ``Lambda V`` multiplies each pose's rotation columns by ``Lambda_i``
    (translation column untouched by Lambda).
    """
    QV = quadratic.egrad(V, edges)
    LV_rot = jnp.einsum("nka,nab->nkb", V[..., :-1], lam)
    LV = jnp.concatenate([LV_rot, jnp.zeros_like(V[..., -1:])], axis=-1)
    return QV - LV


@dataclasses.dataclass
class CertificateResult:
    certified: bool
    lambda_min: float           # minimum eigenvalue of S
    direction: jax.Array        # [n, d+1] eigenvector of lambda_min
    stationarity_gap: float     # ||X S|| — sanity check, ~0 at criticality
    sigma: float                # spectral shift used
    # Round-5 honesty fields (VERDICT r4 item 3): the PSD tolerance that
    # was actually applied, the measurement-weight scale it derives from,
    # whether the eigensolve's own dtype error could decide at that
    # tolerance, and the host-f64 lambda_min when a verification ran.
    tol: float = float("nan")
    weight_scale: float = float("nan")
    decidable: bool = True
    lambda_min_f64: float | None = None
    # Device-epilogue verdict (CERT_* code) when the certificate rode the
    # fused terminal fetch; CERT_NONE for the legacy post-hoc paths.
    device_verdict: int = CERT_NONE


def weight_scale(edges: EdgeSet) -> float:
    """Per-edge curvature scale of the problem: the median weighted
    concentration over valid edges (rotation and translation channels).

    This is the natural yardstick for the PSD test: S's blocks are sums
    of O(w*kappa)-sized per-edge terms, so an eigenvalue deficit far
    below this scale is physically meaningless gauge/solver noise, while
    one at or above it is a real descent direction.  Contrast the
    round-4 tolerance ``eta * sigma``: sigma is the SPECTRAL RADIUS,
    which grows with graph size and conditioning, so at the 100k-pose
    scale (sigma ~ 1.6e7) it certified a lambda_min of -2.45 against a
    tolerance of ~160 — a vacuous claim (VERDICT r4 item 3).
    """
    import numpy as np

    m = np.asarray(edges.mask, np.float64) > 0
    w = np.asarray(edges.weight, np.float64)[m] * np.asarray(
        edges.mask, np.float64)[m]
    k = np.asarray(edges.kappa, np.float64)[m]
    t = np.asarray(edges.tau, np.float64)[m]
    if k.size == 0:
        return 1.0
    return float(max(np.median(w * k), np.median(w * t), 1.0))


def weight_scale_device(edges: EdgeSet) -> jax.Array:
    """Device twin of ``weight_scale``: same median-of-weighted-
    concentrations yardstick, computed with jnp so it can ride the fused
    terminal epilogue (masked-out edges become NaN and ``nanmedian``
    skips them; an all-masked edge set degrades to the same 1.0 floor)."""
    m = edges.mask > 0
    w = edges.weight * edges.mask
    med_k = jnp.nanmedian(jnp.where(m, w * edges.kappa, jnp.nan))
    med_t = jnp.nanmedian(jnp.where(m, w * edges.tau, jnp.nan))
    scale = jnp.maximum(jnp.maximum(med_k, med_t), 1.0)
    return jnp.where(jnp.isnan(scale), 1.0, scale)


@partial(jax.jit, static_argnames=("num_probe", "power_iters", "lobpcg_iters"))
def _min_eig_jit(X, edges: EdgeSet, key, num_probe: int = 4,
                 power_iters: int = 30, lobpcg_iters: int = 300):
    from jax.experimental.sparse.linalg import lobpcg_standard

    n, _, dh = X.shape
    dtype = X.dtype
    lam = dual_blocks(X, edges)

    def S(V):  # [n, k, d+1] -> [n, k, d+1]
        return certificate_matvec(V, edges, lam)

    # Spectral upper bound: power iteration on S (symmetric, so dominant
    # |eigenvalue|); sigma slightly above max(|lambda|_max, 0).
    def power_body(_, v):
        w = S(v)
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    v0 = jax.random.normal(key, (n, 1, dh), dtype)
    v0 = v0 / jnp.linalg.norm(v0)
    v = jax.lax.fori_loop(0, power_iters, power_body, v0)
    lam_dom = jnp.sum(v * S(v))  # Rayleigh quotient, |.| ~ spectral radius
    sigma = 1.1 * jnp.abs(lam_dom) + 1e-3

    # LOBPCG on sigma I - S (PSD): largest eigenvalue = sigma - lambda_min(S).
    def A_flat(Vf):  # [n(d+1), k]
        k = Vf.shape[1]
        V = Vf.T.reshape(k, n, dh).transpose(1, 0, 2)
        W = sigma * V - S(V)
        return W.transpose(1, 0, 2).reshape(k, n * dh).T

    key2 = jax.random.fold_in(key, 1)
    V0 = jax.random.normal(key2, (n * dh, num_probe), dtype)
    theta, U, iters = lobpcg_standard(A_flat, V0, m=lobpcg_iters)
    lam_min = sigma - theta[0]
    vec = U[:, 0].reshape(n, dh)

    # Stationarity residual ||X S|| = ||XQ - X Lambda|| for diagnostics.
    XS = certificate_matvec(X, edges, lam)
    stat = jnp.sqrt(jnp.sum(XS * XS))
    return lam_min, vec, stat, sigma


def _timed_f64(fn, sink: list):
    """Wrap the host f64 REFUSE-band fallback so its wall seconds land
    in ``sink`` — installed only when telemetry is live (the off path
    keeps the bare closure)."""
    def wrapped(t):
        t_f = time.perf_counter()
        try:
            return fn(t)
        finally:
            sink.append(time.perf_counter() - t_f)
    return wrapped


def _tally_cert(run, certified: bool, decidable: bool, f64_secs: list,
                source: str) -> None:
    """ACCEPT/FAIL/REFUSE decision tallies plus the f64-fallback wall —
    the per-status counters the f32 ACCEPT-band sweep (ROADMAP item 3)
    reads to see how often the expensive host eigensolve fires."""
    status = "accept" if certified else ("fail" if decidable else "refuse")
    run.counter("cert_status_total",
                "certificate decisions by final status").inc(
        status=status, source=source)
    if f64_secs:
        run.counter("cert_f64_fallback_seconds_total",
                    "wall-clock spent in the host f64 REFUSE-band "
                    "eigensolve fallback",
                    unit="s").inc(sum(f64_secs), source=source)


def certify_solution(
    X: jax.Array,
    edges: EdgeSet,
    eta: float = 1e-5,
    seed: int = 0,
    num_probe: int = 4,
    lobpcg_iters: int = 300,
    f64_verify: str = "auto",
) -> CertificateResult:
    """Certify a first-order critical point of the rank-r relaxation.

    ``certified`` means ``lambda_min(S) >= -tol`` with
    ``tol = eta * weight_scale(edges)`` — a threshold at the per-edge
    curvature scale, NOT the spectral radius (the round-4 ``eta * sigma``
    rule was near-vacuous at large sigma; VERDICT r4 item 3).  The gauge
    nullspace of S makes exact zeros expected; ``eta`` absorbs them.

    The eigensolve runs in ``X.dtype``; its error scales with
    ``eps(dtype) * sigma``.  When that error cannot resolve ``tol``
    (an f32 solve on a large/ill-conditioned graph), the f32 verdict is
    NOT trusted: with ``f64_verify="auto"`` the minimum eigenvalue is
    re-computed on the host in float64 (``lambda_min_f64``, warm-started
    from the f32 eigenvector) and THAT value decides; with
    ``f64_verify="never"`` the result reports ``decidable=False`` and
    refuses to certify.
    """
    run = obs.get_run()
    t0 = time.perf_counter() if run is not None else 0.0
    key = jax.random.PRNGKey(seed)
    # lobpcg_standard requires 5*k < dim; clamp the probe count so tiny
    # graphs (triangle/line test fixtures) certify instead of crashing.
    dim = X.shape[0] * X.shape[2]
    num_probe = max(1, min(num_probe, (dim - 1) // 5))
    lam_min, vec, stat, sigma = _min_eig_jit(
        X, edges, key, num_probe=num_probe, lobpcg_iters=lobpcg_iters)
    lam_min_f = float(lam_min)
    sigma_f = float(sigma)
    wscale = weight_scale(edges)
    tol = eta * wscale

    import numpy as np

    def f64_solve(t):
        return lambda_min_f64(np.asarray(X, np.float64), edges,
                              warm=np.asarray(vec, np.float64), tol=t,
                              tol_cert=tol)

    f64_secs: list = []
    chosen_f64 = f64_solve if f64_verify == "auto" else None
    if run is not None and chosen_f64 is not None:
        chosen_f64 = _timed_f64(chosen_f64, f64_secs)
    certified, decidable, lam_used, lam_f64, vec64 = decide_certificate(
        lam_min_f, sigma_f, tol, float(jnp.finfo(X.dtype).eps),
        chosen_f64)
    if vec64 is not None:
        vec = jnp.asarray(vec64, X.dtype)
    if run is not None:
        # The eigenvalue gap is how far the decisive minimum eigenvalue
        # clears the certification threshold -tol: positive = certified
        # margin, negative = descent-direction depth the staircase escapes
        # along.  ``float(lam_min)`` above already materialized the
        # eigensolve, so the timing fence is the existing readback.
        gap = lam_used + tol
        run.gauge("certificate_eigenvalue_gap",
                  "lambda_min + tol of the dual certificate").set(gap)
        run.gauge("certificate_lambda_min",
                  "minimum eigenvalue of the certificate operator").set(
            lam_used)
        run.counter("certificates_evaluated",
                    "certify_solution calls").inc()
        _tally_cert(run, certified, decidable, f64_secs,
                    source="certify_solution")
        run.event("certificate", phase="certify",
                  certified=certified, decidable=decidable,
                  lambda_min=lam_min_f, lambda_min_f64=lam_f64,
                  eigenvalue_gap=gap, tol=tol, sigma=sigma_f,
                  stationarity_gap=float(stat), dim=dim,
                  f64_fallback_s=sum(f64_secs) if f64_secs else None,
                  duration_s=time.perf_counter() - t0)
        # Verdict timeline -> numerical health: a streak of undecidable
        # verdicts (REFUSE loop) is an anomaly the staircase driver would
        # otherwise spin on silently.
        from ..obs.health import monitor_for as _monitor_for

        _monitor_for(run).observe_certificate(
            certified=certified, decidable=decidable, lambda_min=lam_used,
            source="certify_solution")
    return CertificateResult(
        certified=certified,
        lambda_min=lam_min_f,
        direction=vec,
        stationarity_gap=float(stat),
        sigma=sigma_f,
        tol=tol,
        weight_scale=wscale,
        decidable=decidable,
        lambda_min_f64=lam_f64,
    )


def decide_certificate(lam_eig: float, sigma: float, tol: float,
                       dtype_eps: float, f64_solve=None):
    """The post-eigensolve certificate decision, shared by
    ``certify_solution`` and ``parallel.certify.certify_sharded`` so the
    two paths cannot desynchronize (round-5 review).

    Semantics (VERDICT r4 item 3): the eigensolve's error is ~10 ulps of
    the shifted operator (the LOBPCG works on ``sigma I - S``); when that
    cannot resolve ``tol`` the dtype verdict is NOT trusted — the caller's
    ``f64_solve(tol_f64) -> (lam_f64, vec64_or_None, resid)`` host
    verification decides instead, and an UNCONVERGED f64 eigensolve
    (``resid > tol/2``) refuses: Ritz values approach lambda_min from
    above, so accepting one could only ever over-certify.

    Returns ``(certified, decidable, lam_used, lam_f64, vec64)``.
    """
    err_est = 10.0 * dtype_eps * sigma
    decidable = err_est <= 0.5 * tol
    lam_f64 = vec64 = None
    if not decidable and lam_eig + 50.0 * err_est < -tol:
        # Decisively negative FAIL without the (expensive) f64
        # verification.  Asymmetric on purpose — skipping f64 here can
        # only ever UNDER-certify, never over-certify, and it saves a
        # multi-minute host eigensolve per failing staircase rank at
        # 100k.  The 50x safety factor is empirical (round 5): err_est
        # models ROUNDING (~10 ulps of the shifted operator), but an
        # f32 LOBPCG at 300k dims reported lambda ~ -4e-4 at a
        # POLISHED gn-4e-7 optimum — ~20 ulps of sigma of
        # accumulation/non-convergence error.  A wound saddle
        # (lambda ~ -1.5e-2 at sigma 170) still shortcuts; anything
        # within 50 ulps of the tolerance goes to f64.
        return False, True, lam_eig, None, None
    if not decidable and f64_solve is not None:
        certified, decidable, lam_f64, vec64 = f64_recheck(f64_solve, tol)
        return certified, decidable, lam_f64, lam_f64, vec64
    lam_used = lam_eig
    return (bool(decidable and lam_used >= -tol), bool(decidable),
            lam_used, lam_f64, vec64)


def f64_recheck(f64_solve, tol: float):
    """REFUSE-band fallback: the host f64 eigensolve decides.

    Two-sided interval decision on the f64 eigenpair (shared by
    ``decide_certificate`` and the device-epilogue path): the residual
    places a true eigenvalue within ``resid`` of ``lam_f64``, so
      lam_f64 + resid < -tol  => an eigenvalue below -tol exists
                                 (sound FAIL), and
      lam_f64 - resid >= -tol => the targeted bottom eigenvalue
                                 clears -tol (PASS — trusting the
                                 warm-started, gauge-deflated solve
                                 targeted the minimal subspace,
                                 the same trust assumption every
                                 Krylov certificate makes).
    Anything in between is refused.  This replaces the round-5 draft
    rule ``resid <= tol/2`` which refused a CONVERGED-to-0 eigenvalue
    whose residual (2e-4) merely missed an arbitrary threshold while
    the verdict itself was unambiguous.

    Returns ``(certified, decidable, lam_f64, vec64)``.
    """
    lam_f64, vec64, resid = f64_solve(0.25 * tol)
    certified = lam_f64 - resid >= -tol
    decidable = certified or (lam_f64 + resid < -tol)
    return bool(certified), bool(decidable), lam_f64, vec64


# ---------------------------------------------------------------------------
# Device-resident certificate (fused terminal epilogue, ROADMAP item 3)
# ---------------------------------------------------------------------------

def device_certificate_payload(X: jax.Array, edges: EdgeSet, key,
                               num_probe: int = 4, power_iters: int = 30,
                               lobpcg_iters: int = 300) -> dict:
    """Everything the HOST needs to decide the certificate, computed as
    one traceable program so it can ride the solve's fused terminal
    epilogue (a single blocking fetch).

    Unlike ``_min_eig_jit`` this eigensolve is GAUGE-DEFLATED on device:
    at a stationary point the r rows of X span exact zero-eigenvalue
    directions of S, a cluster that stalls LOBPCG's convergence to the
    bottom of the spectrum.  The probes are constrained to the
    complement via the projector ``P = I - Yc Yc^T`` (Yc = orthonormal
    basis of the rows), the LOBPCG runs on ``P (sigma I - S) P``, and
    the full-space minimum is ``min(lambda_complement, 0)`` since the
    deflated directions contribute exact zeros.

    The payload also carries the two soundness probes the host decision
    needs (``decide_device_certificate``):

    * ``defl_resid`` — max column norm of ``S Yc``: the deflation is
      only valid near stationarity; a PASS with an invalid deflation
      basis is unsound and must be refused (same ``0.1 * tol`` bound as
      ``lambda_min_f64_shift_invert``).
    * ``rq`` — the explicit Rayleigh quotient of the returned unit
      direction on S: ``RQ(v) >= lambda_min`` for ANY v, so a decisively
      negative RQ is an unconditional FAIL even if the eigensolve itself
      did not converge.

    All outputs are scalars (plus the ``[n, d+1]`` direction), cheap to
    fetch; no decision happens here — f32 never certifies alone.
    """
    from jax.experimental.sparse.linalg import lobpcg_standard

    n, r, dh = X.shape
    dtype = X.dtype
    dim = n * dh
    # lobpcg_standard requires 5*k < dim; shapes are static at trace
    # time, so the tiny-problem clamp is Python int math.
    num_probe = max(1, min(num_probe, (dim - 1) // 5))
    lam = dual_blocks(X, edges)

    def S(V):  # [n, k, d+1] -> [n, k, d+1]
        return certificate_matvec(V, edges, lam)

    def S_flat(Vf):  # [n(d+1), k]
        k = Vf.shape[1]
        V = Vf.T.reshape(k, n, dh).transpose(1, 0, 2)
        return S(V).transpose(1, 0, 2).reshape(k, dim).T

    # Spectral upper bound: power iteration on S (symmetric, so dominant
    # |eigenvalue|); sigma slightly above max(|lambda|_max, 0).
    def power_body(_, v):
        w = S(v)
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    v0 = jax.random.normal(key, (n, 1, dh), dtype)
    v0 = v0 / jnp.linalg.norm(v0)
    v = jax.lax.fori_loop(0, power_iters, power_body, v0)
    lam_dom = jnp.sum(v * S(v))
    sigma = 1.1 * jnp.abs(lam_dom) + 1e-3

    # Gauge basis: the SIGNIFICANT left-singular directions of X's rows.
    # At (near-)optimality X itself is low-rank (rank ~ d+1 < r), and a
    # plain QR of the rank-deficient row basis manufactures arbitrary
    # complement directions that are NOT near-kernel — deflating along
    # them would blind the eigensolve, and the defl_resid guard below
    # would (correctly) veto every ACCEPT.  Insignificant directions are
    # instead left in the complement where the LOBPCG sees them like any
    # other; the soundness guard only needs the directions we actually
    # remove to be near-kernel.
    Yf = X.transpose(1, 0, 2).reshape(r, dim).T           # [dim, r]
    U_g, sv, _ = jnp.linalg.svd(Yf, full_matrices=False)
    keep = (sv > jnp.max(sv) * jnp.sqrt(jnp.finfo(dtype).eps)
            ).astype(dtype)                               # [r]
    Yc = U_g * keep[None, :]
    SYc = S_flat(U_g)
    defl_resid = jnp.max(jnp.linalg.norm(SYc, axis=0) * keep)

    def project(Vf):
        return Vf - Yc @ (Yc.T @ Vf)

    def A_flat(Vf):  # P (sigma I - S) P
        Pv = project(Vf)
        return project(sigma * Pv - S_flat(Pv))

    key2 = jax.random.fold_in(key, 1)
    V0 = project(jax.random.normal(key2, (dim, num_probe), dtype))
    theta, U, _ = lobpcg_standard(A_flat, V0, m=lobpcg_iters)
    lam_comp = sigma - theta[0]
    # Gauge zeros complete the spectrum: full-space minimum.
    lam_min = jnp.minimum(lam_comp, 0.0)

    vec_f = U[:, 0]
    vec_f = vec_f / jnp.maximum(jnp.linalg.norm(vec_f), 1e-30)
    # Explicit Rayleigh quotient of the unit direction on the TRUE
    # operator — the sound one-sided FAIL bound.
    rq = jnp.sum(vec_f * S_flat(vec_f[:, None])[:, 0])
    vec = vec_f.reshape(n, dh)

    XS = certificate_matvec(X, edges, lam)
    stat = jnp.sqrt(jnp.sum(XS * XS))
    return {
        "lam_min": lam_min,
        "sigma": sigma,
        "stat": stat,
        "wscale": weight_scale_device(edges),
        "defl_resid": defl_resid,
        "rq": rq,
        "direction": vec,
    }


def decide_device_certificate(payload: dict, eta: float, dtype_eps: float,
                              f64_solve=None,
                              source: str = "device_epilogue",
                              ) -> CertificateResult:
    """HOST decision on an already-fetched device certificate payload.

    Mirrors ``decide_certificate``'s ladder exactly, with the deflation
    validity bound gating only the ACCEPT side (a FAIL via the Rayleigh
    quotient is sound regardless of deflation):

    * decidable (``10 ulps of sigma`` resolves tol) and lam >= -tol and
      the deflation basis is near-kernel  -> CERT_ACCEPT;
    * decidable and lam < -tol            -> CERT_FAIL (f32 decides);
    * undecidable but lam or rq is below ``-tol`` by 50x the error
      band                                 -> CERT_FAIL (sound shortcut,
      same asymmetric rule as ``decide_certificate``);
    * anything else                        -> CERT_REFUSE, and the host
      f64 path (``f64_solve``) decides via ``f64_recheck`` when
      provided — never the f32 value.

    The payload values arrive as 0-d arrays from the fused terminal
    fetch; everything here is host float math (no device sync).
    """
    run = obs.get_run()
    t0 = time.perf_counter() if run is not None else 0.0
    f64_secs: list = []
    if run is not None and f64_solve is not None:
        f64_solve = _timed_f64(f64_solve, f64_secs)
    lam = float(payload["lam_min"])
    sigma = float(payload["sigma"])
    rq = float(payload["rq"])
    wscale = float(payload["wscale"])
    defl_resid = float(payload["defl_resid"])
    stat = float(payload["stat"])
    direction = payload["direction"]
    tol = eta * wscale
    err_est = 10.0 * dtype_eps * sigma
    defl_ok = defl_resid <= 0.1 * tol
    decidable = err_est <= 0.5 * tol

    verdict = CERT_REFUSE
    certified = False
    lam_used = lam
    lam_f64 = None
    if decidable and lam < -tol:
        verdict, decidable = CERT_FAIL, True
    elif decidable and defl_ok and lam >= -tol:
        verdict, certified = CERT_ACCEPT, True
    elif min(lam, rq) + 50.0 * err_est < -tol:
        # Decisively negative even through the undecidable band — the
        # RQ bound makes this sound without f64 (under-certify only).
        verdict, decidable, lam_used = CERT_FAIL, True, min(lam, rq)
    elif f64_solve is not None:
        certified, decidable, lam_f64, vec64 = f64_recheck(f64_solve, tol)
        lam_used = lam_f64
        if vec64 is not None:
            direction = jnp.asarray(vec64, payload["direction"].dtype)
    else:
        decidable = False
    if run is not None:
        gap = lam_used + tol
        run.gauge("certificate_eigenvalue_gap",
                  "lambda_min + tol of the dual certificate").set(gap)
        run.gauge("certificate_lambda_min",
                  "minimum eigenvalue of the certificate operator").set(
            lam_used)
        run.counter("certificates_evaluated",
                    "certify_solution calls").inc()
        _tally_cert(run, certified, decidable, f64_secs, source=source)
        run.event("certificate", phase="certify",
                  certified=certified, decidable=decidable,
                  lambda_min=lam, lambda_min_f64=lam_f64,
                  eigenvalue_gap=gap, tol=tol, sigma=sigma,
                  stationarity_gap=stat,
                  device_verdict=CERT_STATUS[verdict], source=source,
                  f64_fallback_s=sum(f64_secs) if f64_secs else None,
                  duration_s=time.perf_counter() - t0)
        from ..obs.health import monitor_for as _monitor_for

        _monitor_for(run).observe_certificate(
            certified=certified, decidable=decidable, lambda_min=lam_used,
            source=source)
    return CertificateResult(
        certified=bool(certified),
        lambda_min=lam,
        direction=direction,
        stationarity_gap=stat,
        sigma=sigma,
        tol=tol,
        weight_scale=wscale,
        decidable=bool(decidable),
        lambda_min_f64=lam_f64,
        device_verdict=verdict,
    )


def host_f64_solve(X, edges: EdgeSet, tol_cert: float, warm=None):
    """Closure adapting ``lambda_min_f64`` to the
    ``f64_solve(t) -> (lam, vec, resid)`` shape the decision ladders
    consume — the REFUSE fallback of both the post-hoc and the
    device-epilogue certificate paths."""
    import numpy as np

    def f64_solve(t):
        return lambda_min_f64(
            np.asarray(X, np.float64), edges,
            warm=None if warm is None else np.asarray(warm, np.float64),
            tol=t, tol_cert=tol_cert)
    return f64_solve


def sparse_certificate(X64, edges: EdgeSet):
    """Assemble the certificate operator ``S = Q - Lambda`` as a scipy
    CSR matrix over the ``[n * (d+1)]`` column space (f64, host).

    Mirrors ``certificate_matvec``'s quadratic form edge-by-edge: with
    ``rR = Y_j - Y_i R`` and ``rt = p_j - p_i - Y_i t`` (the
    ``quadratic._edge_terms`` convention), each edge contributes the
    (d+1)x(d+1) pose blocks

      H_jj = diag(wk I_d, wt)
      H_ii = [[wk I_d + wt t t^T, wt t], [wt t^T, wt]]
      H_ij = [[-wk R, -wt t], [0, -wt]]          (H_ji = H_ij^T)

    and ``Lambda_i = sym(Y_i^T G_i)`` is subtracted on the rotation
    coordinates.  Exists for the at-scale f64 verification: an explicit
    sparse matrix enables shift-invert Lanczos (``eigsh(sigma=-tol)``),
    which converges tightly even inside the dense near-zero clusters
    (gauge + cycle bands) where plain LOBPCG's eigenVECTOR residual
    never resolves (measured round 5 at 300k dims).
    """
    import numpy as np
    from scipy import sparse

    X64 = np.asarray(X64, np.float64)
    n, r, dh = X64.shape
    d = dh - 1
    i = np.asarray(edges.i)
    j = np.asarray(edges.j)
    R = np.asarray(edges.R, np.float64)
    t = np.asarray(edges.t, np.float64)
    w = np.asarray(edges.weight, np.float64) \
        * np.asarray(edges.mask, np.float64)
    wk = w * np.asarray(edges.kappa, np.float64)
    wt = w * np.asarray(edges.tau, np.float64)
    m = i.shape[0]
    valid = w != 0.0

    Hjj = np.zeros((m, dh, dh))
    Hii = np.zeros((m, dh, dh))
    Hij = np.zeros((m, dh, dh))
    eye = np.eye(d)
    Hjj[:, :d, :d] = wk[:, None, None] * eye
    Hjj[:, d, d] = wt
    Hii[:, :d, :d] = wk[:, None, None] * eye \
        + wt[:, None, None] * t[:, :, None] * t[:, None, :]
    Hii[:, :d, d] = wt[:, None] * t
    Hii[:, d, :d] = wt[:, None] * t
    Hii[:, d, d] = wt
    Hij[:, :d, :d] = -wk[:, None, None] * R
    Hij[:, :d, d] = -wt[:, None] * t
    Hij[:, d, d] = -wt

    def coo(blocks, rows_of, cols_of):
        rr = (rows_of[:, None] * dh + np.arange(dh))[:, :, None]
        cc = (cols_of[:, None] * dh + np.arange(dh))[:, None, :]
        rr = np.broadcast_to(rr, (m, dh, dh))
        cc = np.broadcast_to(cc, (m, dh, dh))
        v = np.where(valid[:, None, None], blocks, 0.0)
        return rr.ravel(), cc.ravel(), v.ravel()

    parts = [coo(Hii, i, i), coo(Hjj, j, j), coo(Hij, i, j),
             coo(np.swapaxes(Hij, -1, -2), j, i)]
    rows = np.concatenate([p[0] for p in parts])
    cols = np.concatenate([p[1] for p in parts])
    vals = np.concatenate([p[2] for p in parts])
    Q = sparse.coo_matrix((vals, (rows, cols)),
                          shape=(n * dh, n * dh)).tocsr()

    # Lambda from the assembled Q: G = X Q per probe row.
    Xf = X64.transpose(1, 0, 2).reshape(r, n * dh)
    G = (Q @ Xf.T).T.reshape(r, n, dh).transpose(1, 0, 2)
    lam = np.einsum("nra,nrb->nab", X64[..., :d], G[..., :d])
    lam = 0.5 * (lam + np.swapaxes(lam, -1, -2))
    lr = np.broadcast_to(np.arange(n)[:, None, None] * dh
                         + np.arange(d)[None, :, None], (n, d, d))
    lc = np.broadcast_to(np.arange(n)[:, None, None] * dh
                         + np.arange(d)[None, None, :], (n, d, d))
    L = sparse.coo_matrix((lam.ravel(), (lr.ravel(), lc.ravel())),
                          shape=(n * dh, n * dh)).tocsr()
    return Q - L


def lambda_min_f64_shift_invert(X64, edges: EdgeSet, tol_cert: float,
                                k: int = 12, maxiter: int = 2000,
                                warm=None):
    """Minimum eigenvalue of S near the certification threshold via
    shift-invert Lanczos on the explicit sparse operator.

    ``eigsh(S, sigma=-tol_cert, which="LM")`` factorizes
    ``S + tol_cert I`` (sparse LU) and converges to the eigenvalues
    NEAREST the threshold — exactly the ones that decide certification —
    with the spectral transformation providing the separation that plain
    Krylov lacks inside near-zero clusters.  A negative outlier far
    below the shift ranks above the (bounded-size) gauge cluster in the
    transformed spectrum, so ``k`` directions cover it; ``k`` should
    comfortably exceed the gauge dimension (r gauge rows + slack).

    Returns ``(lam_min, eigenvector [n, d+1] or None, resid)`` with
    ``resid`` the explicit eigenpair residual of the reported pair on S —
    ``decide_certificate``'s two-sided interval rule consumes it.  The
    vector is ``None`` when no eigenpair could be computed (the caller
    must then keep its own direction estimate, e.g. the f32 one).

    Soundness guard against the shift-invert window: ``eigsh(sigma)``
    returns the eigenvalues NEAREST the shift, so a near-zero cluster
    larger than ``k`` could crowd a genuinely negative lambda_min out of
    the window and the window pair alone would falsely PASS.  Every
    available direction is therefore screened by its RAYLEIGH QUOTIENT
    on S — RQ(v) >= lambda_min for ANY v, so RQ(v) < -tol_cert is an
    unconditional proof of failure (no residual required).  Screened
    directions: the SA pass's Ritz vectors (converged or not) and the
    caller's ``warm`` vector (the f32 eigensolve's direction — exactly
    the direction a crowded window would miss).
    """
    import numpy as np
    from scipy.sparse.linalg import ArpackNoConvergence, eigsh

    n, r, dh = np.asarray(X64).shape
    S = sparse_certificate(X64, edges)

    def pair(vals, vecs):
        idx = int(np.argmin(vals))
        lam, v = float(vals[idx]), vecs[:, idx]
        v = v / max(np.linalg.norm(v), 1e-300)
        resid = float(np.linalg.norm(S @ v - lam * v))
        return lam, v, resid

    def rq_veto(v):
        """(rayleigh_quotient, normalized v) — RQ < -tol_cert is a sound
        FAIL certificate for any v."""
        v = np.asarray(v, np.float64).reshape(-1)
        nv = np.linalg.norm(v)
        if not np.isfinite(nv) or nv < 1e-300:
            return None
        v = v / nv
        return float(v @ (S @ v)), v

    # A veto returns resid 0.0: the RQ bound is ONE-SIDED for free
    # (lambda_min <= RQ < -tol needs no eigenpair residual), and
    # decide_certificate's FAIL branch (lam + resid < -tol) then draws
    # exactly the sound conclusion.  The reported value is an upper
    # bound on lambda_min, which only ever understates the deficit.
    if warm is not None:
        r_w = rq_veto(warm)
        if r_w is not None and r_w[0] < -tol_cert:
            return r_w[0], r_w[1].reshape(n, dh), 0.0

    # Pass 1 — plain smallest-algebraic Lanczos: converges fast exactly
    # when lambda_min is a SEPARATED negative outlier (the case the
    # shift-invert pass below can rank beneath the gauge cluster in its
    # transformed spectrum).  Its Ritz values are Rayleigh quotients of
    # the Ritz vectors, so ANY Ritz value < -tol is a sound FAIL even
    # unconverged; an inconclusive pass (all Ritz >= -tol) falls through
    # to shift-invert.
    try:
        vals, vecs = eigsh(S, k=4, which="SA", maxiter=60, tol=1e-7)
        lam_sa, v_sa, r_sa = pair(vals, vecs)
    except ArpackNoConvergence as e:
        lam_sa = v_sa = r_sa = None
        if getattr(e, "eigenvalues", None) is not None \
                and len(e.eigenvalues):
            lam_sa, v_sa, r_sa = pair(e.eigenvalues, e.eigenvectors)
    if lam_sa is not None and lam_sa < -tol_cert:
        # Recompute the RQ explicitly: a salvaged unconverged ARPACK
        # Ritz value can deviate from the true RQ of its vector (lost
        # orthogonality ~ eps * sigma); the SOUND bound is the explicit
        # v @ S v of the actual unit vector, not the reported value.
        r_sa_rq = rq_veto(v_sa)
        if r_sa_rq is not None and r_sa_rq[0] < -tol_cert:
            return r_sa_rq[0], r_sa_rq[1].reshape(n, dh), 0.0

    # Pass 2 — gauge-deflated LOBPCG on the SPARSE operator.  Complement
    # of the shift-invert pass below: on well-connected graphs (random
    # long-range loop closures — e.g. the 100k synthetic) the spectrum
    # has a healthy gap above the gauge kernel, so deflated LOBPCG with
    # ~10 ms sparse matvecs converges in seconds, while the sparse LU of
    # the SAME graph explodes (expander fill-in: measured round 5, >25
    # min and ~7 GB at 400k dims before being killed).  On chain/planar
    # graphs the roles flip (tiny fill, clustered bottom) — which is
    # exactly the case pass 3 handles.
    from scipy.sparse.linalg import lobpcg as _lobpcg

    Yc = np.stack([np.asarray(X64[:, a, :], np.float64).reshape(n * dh)
                   for a in range(r)], axis=1)
    Yc, _ = np.linalg.qr(Yc)
    rng = np.random.default_rng(0)
    V0 = rng.standard_normal((n * dh, 4))
    if warm is not None:
        w = np.asarray(warm, np.float64).reshape(n * dh)
        if np.isfinite(w).all() and np.linalg.norm(w) > 1e-300:
            V0[:, 0] = w
    # Deflation-validity bound for the PASS direction: the constrained
    # search cannot see eigenvalue content INSIDE span(Yc), so a PASS is
    # only sound if Yc really is near-kernel.  With ||S yc|| <= delta, a
    # missing direction u (lambda_u < -tol) satisfies
    # |<u, yc>| <= delta / |lambda_u| <= delta / tol, so delta <=
    # 0.1 * tol leaves >= 99% of u's mass in the complement where the
    # LOBPCG sees it.  An iterate stopped far from stationarity (gauge
    # columns not near-kernel) therefore falls through instead of
    # certifying blind.  The sound-FAIL RQ veto needs no such guard.
    SYc = S @ Yc
    defl_ok = float(np.linalg.norm(SYc, axis=0).max()) <= 0.1 * tol_cert
    try:
        vals_l, vecs_l = _lobpcg(S, V0, Y=Yc, largest=False,
                                 maxiter=300, tol=min(1e-8, 0.1 * tol_cert),
                                 verbosityLevel=0)
        lam_l, v_l, r_l = pair(vals_l, vecs_l)
        rq_l = float(v_l @ (S @ v_l))  # explicit RQ of the unit vector
        lam_l_full = min(lam_l, 0.0)  # gauge zeros complete the spectrum
        if rq_l < -tol_cert:
            # Rayleigh quotient of a genuine unit vector: sound FAIL.
            return rq_l, v_l.reshape(n, dh), 0.0
        if defl_ok and lam_l_full - r_l >= -tol_cert:
            return lam_l_full, v_l.reshape(n, dh), r_l
    except (np.linalg.LinAlgError, ValueError) as e:
        # The EXPECTED numerical failures of deflated LOBPCG (singular
        # Gram/basis breakdown -> LinAlgError; degenerate block shapes ->
        # ValueError) fall through to shift-invert.  Anything else (a
        # programming error, keyboard interrupt, OOM) propagates — the
        # old blanket ``except Exception: pass`` hid those too.
        import warnings
        warnings.warn(
            f"gauge-deflated LOBPCG pass failed with {type(e).__name__}: "
            f"{e}; falling through to shift-invert", RuntimeWarning)

    # Pass 3 — shift-invert at the threshold: the sparse LU of
    # S + tol I separates the near-zero clusters (gauge + graph bands)
    # where plain Krylov eigenvector residuals never resolve; the
    # eigenvalues NEAREST the threshold are exactly the ones that
    # decide certification.  Non-convergence (or a singular LU when the
    # shift lands on an eigenvalue) must REFUSE, not crash a multi-hour
    # staircase: salvage partial eigenpairs when present, else return a
    # pair whose residual can never pass the interval rule.
    # FILL GUARD: sparse LU is only viable on chain/planar-ish graphs.
    # A high fraction of long-range edges (random loop closures) makes
    # the graph an expander whose LU fill is near-dense — measured
    # round 5: >25 min and ~7 GB at 400k dims, twice, on the noisy 100k
    # synthetic (17% random LCs), vs seconds on the stitched-winding
    # chain (1% long-range bridges).  When the guard trips and the
    # Krylov tiers above were inconclusive, the honest outcome is
    # REFUSAL, not an unbounded factorization.
    i_np = np.asarray(edges.i)
    j_np = np.asarray(edges.j)
    msk = (np.asarray(edges.mask) > 0) if hasattr(edges, "mask") \
        else np.ones_like(i_np, bool)
    span = np.abs(i_np[msk] - j_np[msk])
    long_frac = float(np.mean(span > max(64, n // 100))) if span.size \
        else 0.0
    if n * dh > 100_000 and long_frac > 0.05:
        if lam_sa is not None:
            return lam_sa, v_sa.reshape(n, dh), r_sa
        big = float(np.abs(S).sum(axis=1).max())
        return 0.0, None, big
    try:
        vals, vecs = eigsh(S, k=k, sigma=-tol_cert, which="LM",
                           maxiter=maxiter, tol=1e-10)
    except ArpackNoConvergence as e:
        vals, vecs = e.eigenvalues, e.eigenvectors
        if vals is None or not len(vals):
            vals, vecs = None, None
    except RuntimeError:
        vals = vecs = None
    if vals is None:
        if lam_sa is not None:
            return lam_sa, v_sa.reshape(n, dh), r_sa
        # Total failure: refuse (a huge residual can never pass the
        # interval rule).  Vector is None so the caller KEEPS its own
        # (f32) direction — a zero direction would silently no-op the
        # staircase's saddle escape.
        big = float(np.abs(S).sum(axis=1).max())  # >= spectral radius
        return 0.0, None, big
    lam, v, resid = pair(vals, vecs)
    if lam_sa is not None and lam_sa + r_sa < lam - resid:
        # The SA interval proves a true eigenvalue strictly below
        # everything the shift-invert window saw — the window missed
        # the bottom, so its PASS would be unsound; report the more
        # pessimistic SA pair (refusal) instead.
        return lam_sa, v_sa.reshape(n, dh), r_sa
    # The window's Ritz values are RQs too: pair() took the argmin, so a
    # window member below -tol decides FAIL through the interval rule
    # with its (tiny) residual.  At this point every screened direction
    # (warm, SA Ritz, window) has RQ >= -tol; the PASS still rests on
    # the documented trust assumption that SOME screened direction
    # tracks the minimal subspace.
    return lam, v.reshape(n, dh), resid


def lambda_min_f64(X64, edges: EdgeSet, warm=None, num_probe: int = 4,
                   maxiter: int = 4000, tol: float | None = None,
                   deflate: bool = False, tol_cert: float | None = None):
    """HOST float64 minimum eigenvalue of the certificate operator S.

    The device eigensolve cannot resolve a weight-scale tolerance when
    ``eps32 * sigma`` exceeds it (e.g. the 100k-pose synthetic: sigma
    ~1.6e7 makes f32 blind below ~16); this scipy LOBPCG runs the same
    operator in f64 via the numpy edge-gradient (``refine._np_egrad``),
    warm-started from the f32 eigenvector so it polishes rather than
    searches.  Returns ``(lambda_min, eigenvector [n, d+1], resid)`` —
    ``resid`` is the eigenpair residual ``||S v - lambda v||``, and it is
    load-bearing: an unconverged Ritz value approaches lambda_min from
    ABOVE, so callers MUST refuse certification unless ``resid`` resolves
    their tolerance (see the refusal gates in ``certify_solution`` /
    ``parallel.certify.certify_sharded``).
    """
    import numpy as np
    from scipy.sparse.linalg import LinearOperator, lobpcg

    from .refine import _np_egrad, _np_sym, np_edges_batched

    n, r, dh = X64.shape
    d = dh - 1
    if tol_cert is not None and n * dh >= 50_000:
        # Large problems route to shift-invert Lanczos on the explicit
        # sparse operator: the near-zero clusters (gauge + graph bands)
        # that stall LOBPCG's eigenvector residual at this scale are
        # exactly what the spectral transformation separates.
        # ``tol_cert`` is the CERTIFICATION threshold (the certify
        # callers pass their -tol decision point explicitly); ``tol``
        # remains the LOBPCG convergence tolerance of the small path.
        return lambda_min_f64_shift_invert(X64, edges, tol_cert,
                                           warm=warm)
    e64 = np_edges_batched(edges)

    G, _, _, _ = _np_egrad(X64[None], e64, n)
    lam = _np_sym(np.swapaxes(X64[..., :d], -1, -2) @ G[0][..., :d])

    def S_apply(Vf):
        # Vf [n*dh, k] -> S V; probes ride the r axis of the egrad map.
        k = Vf.shape[1]
        V = Vf.T.reshape(k, n, dh).transpose(1, 0, 2)      # [n, k, dh]
        QV, _, _, _ = _np_egrad(V[None], e64, n)
        QV = QV[0]
        LV = np.einsum("nka,nab->nkb", V[..., :d], lam)
        SV = QV.copy()
        SV[..., :d] -= LV
        return SV.transpose(1, 0, 2).reshape(k, n * dh).T

    op = LinearOperator((n * dh, n * dh), matvec=lambda v: S_apply(
        v.reshape(-1, 1)).ravel(), matmat=S_apply, dtype=np.float64)

    rng = np.random.default_rng(0)
    V0 = rng.standard_normal((n * dh, num_probe))
    if warm is not None:
        V0[:, 0] = np.asarray(warm, np.float64).reshape(n * dh)
    # Deflate the GAUGE kernel: at a stationary point X S = 0 exactly, so
    # the r rows of X span known zero-eigenvalue directions — an exact
    # zero CLUSTER that stalls LOBPCG's convergence to the smallest
    # eigenvalue at large n (measured round 5: 300k dims never reached
    # tol 2.5e-5, so every 100k certificate was refused).  Constraining
    # the probes to the complement (scipy's Y) removes the cluster; the
    # gauge directions themselves have lambda = 0 >= -tol by
    # construction, so lambda_min over the full space is
    # min(lambda_complement, 0) and certification is decided by the
    # complement eigenvalue alone.  At a NON-stationary X the deflation
    # vectors are only approximate — harmless: the eigenpair residual
    # below is computed on the TRUE operator, so a poisoned result still
    # refuses (and the stationarity gap is reported separately).
    # OPT-IN only: scipy's constrained LOBPCG is unstable at small dims
    # (measured: resid 50.8 on a 60-dim test that converges
    # unconstrained), and the production large-scale route is the
    # shift-invert path above (which supersedes deflation — the sparse
    # LU separates the zero cluster structurally); deflation remains for
    # matrix-free use where assembling S is not an option.
    if deflate:
        Yc = np.stack([np.asarray(X64[:, a, :], np.float64).reshape(n * dh)
                       for a in range(r)], axis=1)
        Yc, _ = np.linalg.qr(Yc)
        vals, vecs = lobpcg(op, V0, Y=Yc, largest=False, maxiter=maxiter,
                            tol=tol, verbosityLevel=0)
    else:
        vals, vecs = lobpcg(op, V0, largest=False, maxiter=maxiter,
                            tol=tol, verbosityLevel=0)
    i = int(np.argmin(vals))
    lam_min, v = float(vals[i]), vecs[:, i]
    # Eigenpair residual ||S v - lam v||: an UNCONVERGED Ritz value
    # approaches lambda_min from ABOVE, so accepting it would
    # over-certify — exactly the failure this f64 path exists to stop.
    # Callers must refuse certification unless the residual resolves
    # their tolerance.  (The residual of a DEFLATED eigenpair carries a
    # component along the approximate-kernel directions when X is not
    # exactly stationary; that component is bounded by the stationarity
    # gap, which certification already requires to be small.)
    v = v / max(np.linalg.norm(v), 1e-300)
    resid = float(np.linalg.norm(S_apply(v.reshape(-1, 1)).ravel()
                                 - lam_min * v))
    if deflate:
        # Full-space lambda_min = min(complement value, gauge zeros).
        lam_min = min(lam_min, 0.0)
    return lam_min, v.reshape(n, dh), resid


# ---------------------------------------------------------------------------
# Riemannian staircase
# ---------------------------------------------------------------------------

def escape_rank(X: jax.Array, direction: jax.Array, edges: EdgeSet,
                alpha0: float = 1e-2, max_halvings: int = 20) -> jax.Array:
    """Lift ``X`` to rank r+1 along the negative-curvature direction.

    ``X+ = [[X], [alpha v^T]]`` projected to the rank-(r+1) manifold: since
    ``v^T S v < 0``, the cost strictly decreases for small alpha (SE-Sync
    saddle escape).  Backtracks alpha until the projected point improves.
    """
    n, r, dh = X.shape
    f0 = quadratic.cost(X, edges)

    def lifted(alpha):
        row = alpha * direction[:, None, :]  # [n, 1, d+1]
        return manifold.project(jnp.concatenate([X, row], axis=1))

    def cond(s):
        alpha, k, ok = s
        return (~ok) & (k < max_halvings)

    def body(s):
        alpha, k, _ = s
        ok = quadratic.cost(lifted(alpha), edges) < f0
        return jnp.where(ok, alpha, alpha * 0.5), k + 1, ok

    alpha, _, ok = jax.lax.while_loop(
        cond, body, (jnp.asarray(alpha0, X.dtype), jnp.array(0), jnp.array(False)))
    # If no improving step was found (flat direction), keep the zero row:
    # the re-solve at rank r+1 can still escape via its own Hessian steps.
    return lifted(jnp.where(ok, alpha, 0.0))


@dataclasses.dataclass
class StaircaseResult:
    T: jax.Array                # [n, d, d+1] rounded trajectory
    X: jax.Array                # [n, r_final, d+1]
    cost: float
    rank: int                   # rank at which the staircase stopped
    certificate: CertificateResult
    history: list               # [(rank, cost, lambda_min)]


def solve_staircase(
    meas: Measurements,
    r_min: int | None = None,
    r_max: int = 10,
    params: SolverParams | None = None,
    max_iters: int = 300,
    grad_norm_tol: float = 1e-6,
    eta: float = 1e-5,
    init: str = "chordal",
    dtype=jnp.float64,
    verbose: bool = False,
) -> StaircaseResult:
    """Certifiably correct centralized PGO: solve the rank-r relaxation,
    certify, and climb the staircase r -> r+1 on failure (SE-Sync
    Algorithm 1 on the lifted SE(d) manifold; BASELINE config #5 scope).
    """
    from ..ops import chordal as chordal_ops

    d = meas.d
    n = meas.num_poses
    r_min = d + 1 if r_min is None else r_min
    params = params or SolverParams(initial_radius=1e1, max_inner_iters=50)
    edges = edge_set_from_measurements(meas, dtype=dtype)

    if init == "chordal":
        T0 = chordal_ops.chordal_initialization(edges, n)
    elif init == "odometry":
        T0 = chordal_ops.odometry_from_edges(edges, n)
    else:
        raise ValueError(f"Unknown init {init!r}")
    from .local_pgo import lift
    X = lift(T0, lifting_matrix(r_min, d, dtype))

    history = []
    problem = make_problem(edges, n, params.precond_shift)
    for r in range(r_min, r_max + 1):
        out = solver.rtr_solve(problem, X, params, max_iters=max_iters,
                               grad_norm_tol=grad_norm_tol)
        X = out.X
        cert = certify_solution(X, edges, eta=eta, seed=r)
        history.append((r, float(out.f), cert.lambda_min))
        if verbose:
            print(f"[staircase] rank {r}: cost {float(out.f):.6f}, "
                  f"lambda_min {cert.lambda_min:.3e}, "
                  f"certified={cert.certified}")
        if cert.certified or r == r_max:
            ylift = _recover_rounding_basis(X, d)
            T = round_solution(X, ylift)
            return StaircaseResult(T=T, X=X, cost=float(out.f), rank=r,
                                   certificate=cert, history=history)
        X = escape_rank(X, cert.direction, edges)
    raise AssertionError("unreachable")


def _recover_rounding_basis(X: jax.Array, d: int) -> jax.Array:
    """Rank-r -> SE(d) rounding basis via thin SVD of the stacked rotation
    factor (SE-Sync's rounding): project onto the dominant d left singular
    directions rather than a fixed lifting matrix, since the staircase may
    have rotated the solution out of the initial lifted subspace."""
    n, r, dh = X.shape
    Y = X[..., :d].transpose(1, 0, 2).reshape(r, n * d)
    U, _, _ = jnp.linalg.svd(Y, full_matrices=False)
    return U[:, :d]
