"""Re-centered terminal refinement: certified-grade gaps on f32 hardware.

The f32 RBCD iterate floors near a 4e-6 relative suboptimality gap on
sphere2500 (measured, BASELINE.md): close to the optimum the Riemannian
gradient is the small difference of large quantities (``G - Y sym(Y^T G)``
with ``|G| >> |rgrad|``), and f32 rounding of the large terms drowns the
descent direction.  The reference sidesteps this by running everything in
f64 on CPU (Eigen/ROPTLIB); TPU v5e has no f64.

This module reaches f64-grade gaps **on the TPU** by re-centering: the
iterate is held as ``X = R + D`` where

* ``R`` is a reference point kept in float64 on the HOST, refreshed every
  few rounds (fold ``D`` in, re-project to the manifold, recompute
  constants), and
* ``D`` is the small on-device correction, the only thing the TPU updates.

Every large-magnitude cancellation is precomputed on the host in f64 and
shipped as a small f32 constant:

* ``g0   = G(R) - R sym(R_Y^T G_Y(R))`` — the Riemannian gradient at R
  (tiny near the optimum, exactly representable in f32),
* ``rho  = per-edge residuals at R`` (small, f32-exact),
* ``S0   = sym(R_Y^T G_Y(R))`` and ``G_ref = G(R)`` — large, but on the
  device they only ever multiply ``D``-sized quantities,

With that decomposition every f32 rounding error on the device scales with
``|D|``, so each recenter cycle extends the reachable gap by orders of
magnitude; two cycles take sphere2500 from the 4e-6 floor well past 1e-6.
(The ambient cost is exactly quadratic — ``f(R + D)`` expands with no
truncation error, so the decomposition is algebraically exact.)

The round itself mirrors the plain Jacobi RBCD round (neighbor exchange of
``D``, per-agent single-step RTR with block-Jacobi preconditioning, the
reference's shrink-radius-on-rejection semantics,
``QuadraticOptimizer.cpp:92-110``); the retraction updates ``D`` directly
via the polar-correction series ``polar(M) - M = M((I + E)^{-1/2} - I)``,
never materializing ``X`` in f32.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from functools import partial

from ..config import AgentParams
from ..ops import manifold, quadratic, solver
from ..types import EdgeSet
from . import rbcd


class RefineConstants(NamedTuple):
    """Per-recenter device constants (all f32, leading [A] agent axis)."""

    R: jax.Array       # [A, n, r, k] reference point (local poses)
    Rz: jax.Array      # [A, s, r, k] reference neighbor buffer
    G_ref: jax.Array   # [A, n, r, k] Euclidean gradient at R
    g0: jax.Array      # [A, n, r, k] Riemannian gradient at R (f64-computed)
    S0: jax.Array      # [A, n, d, d] sym(R_Y^T G_Y(R))
    chol: jax.Array    # [A, n, k, k] block-Jacobi factors
    # Kernel-mode extras (None when the graph has no edge tiles): reference
    # residuals + point + gradient constants in the tile-major /
    # component-major layouts of ``ops.pallas_tcg.rtr_refine_full_call``.
    rho_rot_t: jax.Array | None = None  # [A, nt, r*d, T]
    rho_trn_t: jax.Array | None = None  # [A, nt, r, T]
    Rc: jax.Array | None = None         # [A, r*k, n]
    wk_t: jax.Array | None = None       # [A, nt, 1, T]
    wt_t: jax.Array | None = None       # [A, nt, 1, T]
    g0_c: jax.Array | None = None       # [A, r*k, n]
    Gref_c: jax.Array | None = None     # [A, r*k, n]
    S0_c: jax.Array | None = None       # [A, d*d, n]
    Lc: jax.Array | None = None         # [A, k*k, n] preconditioner factors


class RefineRef(NamedTuple):
    """Host-side f64 reference state."""

    Xg: np.ndarray         # [N, r, k] global reference iterate (f64)
    f_ref: float           # global cost at Xg (f64)
    consts: RefineConstants


# ---------------------------------------------------------------------------
# Host-side f64 recentering (numpy; the TPU-tunnel process cannot enable x64)
# ---------------------------------------------------------------------------

def _np_edge_terms(Xbuf, ei, ej, R, t):
    """f64 numpy mirror of ``quadratic._edge_terms`` ([A] batched)."""
    a = np.arange(Xbuf.shape[0])[:, None]
    Xi = Xbuf[a, ei]
    Xj = Xbuf[a, ej]
    Yi, pi = Xi[..., :-1], Xi[..., -1]
    Yj, pj = Xj[..., :-1], Xj[..., -1]
    rR = Yj - Yi @ R
    rt = pj - pi - np.einsum("aerd,aed->aer", Yi, t)
    return rR, rt


def _np_egrad(Xbuf, edges_np, n_out):
    """f64 numpy mirror of ``quadratic.egrad`` ([A] batched scatter)."""
    ei, ej = edges_np["i"], edges_np["j"]
    rR, rt = _np_edge_terms(Xbuf, ei, ej, edges_np["R"], edges_np["t"])
    w = edges_np["mask"] * edges_np["weight"]
    wk = (w * edges_np["kappa"])[..., None, None]
    wt = (w * edges_np["tau"])[..., None]
    gj = np.concatenate([wk * rR, (wt * rt)[..., None]], axis=-1)
    giY = -(wk * rR) @ np.swapaxes(edges_np["R"], -1, -2) \
        - (wt * rt)[..., None] * edges_np["t"][:, :, None, :]
    gi = np.concatenate([giY, -(wt * rt)[..., None]], axis=-1)
    A, _, r, k = gi.shape
    N = Xbuf.shape[1]
    out = np.zeros((A, N, r, k))
    a = np.arange(A)[:, None]
    np.add.at(out, (a, ei), gi)
    np.add.at(out, (a, ej), gj)
    return out[:, :n_out], rR, rt, w


def _np_sym(M):
    return 0.5 * (M + np.swapaxes(M, -1, -2))


def _np_chol_blocks(edges_np, n_max, d, shift):
    """Host block-Jacobi factors (numpy mirror of ``rbcd.precond_chol`` —
    the eager device version costs a tunnel round-trip per op)."""
    A, E = edges_np["kappa"].shape
    k = d + 1
    w = edges_np["mask"] * edges_np["weight"]
    wk = w * edges_np["kappa"]
    wt = w * edges_np["tau"]
    t = edges_np["t"]
    Bi = np.zeros((A, E, k, k))
    Bi[..., :d, :d] = wk[..., None, None] * np.eye(d) \
        + wt[..., None, None] * t[..., :, None] * t[..., None, :]
    Bi[..., :d, d] = wt[..., None] * t
    Bi[..., d, :d] = wt[..., None] * t
    Bi[..., d, d] = wt
    diag_j = np.concatenate([np.repeat(wk[..., None], d, -1),
                             wt[..., None]], axis=-1)
    Bj = diag_j[..., None] * np.eye(k)
    n_buf_blocks = np.zeros((A, n_max + 1, k, k))  # +1 catch-all for >=n
    a = np.arange(A)[:, None]
    np.add.at(n_buf_blocks, (a, np.minimum(edges_np["i"], n_max)), Bi)
    np.add.at(n_buf_blocks, (a, np.minimum(edges_np["j"], n_max)), Bj)
    blocks = n_buf_blocks[:, :n_max] + shift * np.eye(k)
    return np.linalg.cholesky(blocks)


def _np_project_manifold(Xg64: np.ndarray, d: int) -> np.ndarray:
    """f64 manifold projection (per-pose Stiefel polar via SVD, numpy).

    LAPACK's divide-and-conquer gesdd can fail to converge on rare
    near-degenerate blocks (observed on parking-garage iterates); the
    polar factor is also U(V^T) of the symmetric eigendecomposition of
    Y^T Y, which is the per-block fallback."""
    Y = Xg64[..., :d]
    try:
        U, _, Vh = np.linalg.svd(Y, full_matrices=False)
        return np.concatenate([U @ Vh, Xg64[..., d:]], axis=-1)
    except np.linalg.LinAlgError:
        pass
    out = Xg64.copy()
    for i in range(Y.shape[0]):
        try:
            U, _, Vh = np.linalg.svd(Y[i], full_matrices=False)
            out[i, :, :d] = U @ Vh
        except np.linalg.LinAlgError:
            # Polar via eigh of the (symmetric PSD) Gram — always converges.
            w, V = np.linalg.eigh(Y[i].T @ Y[i])
            inv_sqrt = V @ np.diag(1.0 / np.sqrt(np.maximum(w, 1e-300))) @ V.T
            out[i, :, :d] = Y[i] @ inv_sqrt
    return out


def _refine_kernel_fits(graph, meta) -> bool:
    """VMEM gate for ``pallas_tcg.rtr_refine_full_call``: the refine
    kernel stages the tCG working set PLUS the reference-point constants
    (rho tiles, Rc/g0/Gref component-major, S0, Lc — ~9 extra [rows, n]
    buffers), so it outgrows the plain-tCG budget before the rbcd gate
    (``rbcd._pallas_vmem_ok``) trips: measured 20.1 MiB requested at
    n=7558, r=3, d=2 (ais2klinik A=2) against the 16 MiB scoped limit.
    Without this gate the Mosaic compile ABORTS; with it the recenter
    simply skips the kernel-layout constants and ``refine_round`` takes
    the XLA formulation."""
    from .rbcd import pallas_vmem_ok

    A, nt, _, T = graph.eidx_i.shape
    d = meta.d
    rk = meta.rank * (d + 1)
    # Extra refine-kernel residents beyond the tCG working set.
    extra = (nt * T * (meta.rank * d + meta.rank) * 4        # rho tiles
             + (3 * rk + d * d + (d + 1) ** 2) * meta.n_max * 4)
    from .rbcd import PALLAS_TCG_VMEM_BUDGET_BYTES
    return pallas_vmem_ok(meta.n_max, meta.s_max, meta.rank, d, T, nt) \
        and extra <= 0.35 * PALLAS_TCG_VMEM_BUDGET_BYTES


def recenter(Xg64: np.ndarray, graph, meta, params: AgentParams,
             edges_global, chol=None, weights=None,
             pre_projected: bool = False,
             f_ref: float | None = None) -> RefineRef:
    """Build the f64 reference and its device constants from a global
    iterate.  ``Xg64 [N, r, k]`` is projected to the manifold in f64 first;
    ``edges_global`` is the global EdgeSet (host arrays ok) for ``f_ref``.
    ``chol`` (device [A, n, k, k]) is reused across recenters when given —
    the factors depend only on the edge weights, which are fixed during
    refinement, so a ``chol`` is only reusable if it was built from the
    SAME weights this call refines under (as ``solve_refine``'s internal
    reuse guarantees); passing a unit-weight ``chol`` together with GNC
    ``weights`` silently preconditions for the wrong objective.

    ``weights [A, E]``, when given, replaces ``graph.edges.weight`` — pass
    the final GNC weights (``RBCDState.weights``) when refining a robust
    solve, since the solver applies weight updates to the state, not the
    build-time graph; ``edges_global`` must then carry the matching
    per-measurement weights (``rbcd.global_weights``) so ``f_ref`` is the
    same objective.

    ``pre_projected``: caller certifies ``Xg64`` is ALREADY the f64
    manifold projection (``solve_refine`` projects once per cycle for its
    cheap verify pass and reuses the result here) — the reference point
    MUST be exactly on-manifold (R^T R = I) or the polar-correction
    series loses its exactness.
    """
    if weights is not None:
        graph = rbcd.with_weights(graph, weights)
    d = meta.d
    if not pre_projected:
        Xg64 = _np_project_manifold(Xg64, d)

    # Per-agent reference buffers (local + neighbor) from the global point.
    gi_np = np.asarray(graph.global_index)
    R_loc = Xg64[gi_np]                                   # [A, n, r, k]
    pub = np.take_along_axis(
        R_loc, np.asarray(graph.pub_idx)[:, :, None, None], axis=1)
    Rz = pub[np.asarray(graph.nbr_robot), np.asarray(graph.nbr_pub)]
    Rz = Rz * np.asarray(graph.nbr_mask)[:, :, None, None]
    Rbuf = np.concatenate([R_loc, Rz], axis=1)

    e = graph.edges
    edges_np = {f: np.asarray(getattr(e, f), np.float64)
                for f in ("R", "t", "kappa", "tau", "weight", "mask")}
    edges_np["i"], edges_np["j"] = np.asarray(e.i), np.asarray(e.j)

    G_ref, rrR, rrt, _ = _np_egrad(Rbuf, edges_np, meta.n_max)
    RY = R_loc[..., :d]
    GY = G_ref[..., :d]
    S0 = _np_sym(np.swapaxes(RY, -1, -2) @ GY)
    g0 = G_ref.copy()
    g0[..., :d] -= RY @ S0

    # Global reference cost in f64 (the bench's gap oracle); reuse the
    # caller's value when it was just computed at the same point
    # (solve_refine's verify pass).
    if f_ref is None:
        f_ref = global_cost(Xg64, edges_global)

    if chol is None:
        chol = jnp.asarray(
            _np_chol_blocks(edges_np, meta.n_max, d,
                            params.solver.precond_shift), jnp.float32)
    else:
        chol = jnp.asarray(chol, jnp.float32)

    # All remaining device constants are built as ONE host f32 buffer and
    # shipped in ONE transfer, then sliced apart by a single jitted unpack
    # (``Lc`` is derived from ``chol`` inside it).  On the tunneled TPU a
    # host->device transfer costs a fixed latency regardless of size, so
    # the previous one-asarray-per-field recenter paid ~14 latencies per
    # cycle where this pays one.
    fields = dict(
        R=R_loc, Rz=Rz, G_ref=G_ref, g0=g0, S0=S0,
    )
    if graph.eidx_i is not None and _refine_kernel_fits(graph, meta):
        # Kernel-layout constants: reference residuals at R over the edge
        # tiles, R component-major, weight tiles (weights are fixed
        # during refinement).
        A, nt, _, T = graph.eidx_i.shape
        E = edges_np["kappa"].shape[1]
        r = rrR.shape[-2]
        pad = nt * T - E

        def tile_cm(arr, rows):  # [A, E, ...] -> [A, nt, rows, T]
            flat = arr.reshape(A, E, rows).transpose(0, 2, 1)
            flat = np.pad(flat, ((0, 0), (0, 0), (0, pad)))
            return flat.reshape(A, rows, nt, T).transpose(0, 2, 1, 3)

        w = edges_np["mask"] * edges_np["weight"]

        def wtile(vals):  # [A, E] -> [A, nt, 1, T]
            p = np.pad(vals, ((0, 0), (0, pad)))
            return p.reshape(A, nt, 1, T)

        def cm(arr):  # [A, n, r, k] -> [A, r*k, n] component-major
            return arr.transpose(0, 2, 3, 1).reshape(A, -1, meta.n_max)

        fields.update(
            rho_rot_t=tile_cm(rrR, r * d),
            rho_trn_t=tile_cm(rrt, r),
            Rc=cm(R_loc),
            wk_t=wtile(w * edges_np["kappa"]),
            wt_t=wtile(w * edges_np["tau"]),
            g0_c=cm(g0),
            Gref_c=cm(G_ref),
            S0_c=S0.transpose(0, 2, 3, 1).reshape(A, d * d, meta.n_max),
        )

    layout = tuple((name, arr.shape) for name, arr in fields.items())
    packed = np.concatenate(
        [np.ascontiguousarray(arr, np.float32).ravel()
         for arr in fields.values()])
    consts = _unpack_consts(jnp.asarray(packed), chol, layout,
                            graph.eidx_i is not None)
    return RefineRef(Xg=Xg64, f_ref=f_ref, consts=consts)


@partial(jax.jit, static_argnames=("layout", "kernel"))
def _unpack_consts(packed, chol, layout, kernel) -> RefineConstants:
    """Slice the packed recenter buffer back into named device constants
    (one dispatch); derives the kernel preconditioner layout from chol."""
    out = {}
    off = 0
    for name, shape in layout:
        size = int(np.prod(shape))
        out[name] = jax.lax.dynamic_slice_in_dim(
            packed, off, size).reshape(shape)
        off += size
    if kernel:
        A, n, k, _ = chol.shape
        out["Lc"] = jnp.transpose(chol, (0, 2, 3, 1)).reshape(A, k * k, n)
    return RefineConstants(chol=chol, **out)


def np_edges_batched(edges) -> dict:
    """The ``[1, ...]``-batched f64 edge dict ``_np_egrad``/
    ``_np_edge_terms`` consume, from any EdgeSet-like (host or device
    arrays) — one definition for the recenter, the certificate's f64
    verification, and the experiment drivers."""
    e = {f: np.asarray(getattr(edges, f), np.float64)[None]
         for f in ("R", "t", "kappa", "tau", "weight", "mask")}
    e["i"] = np.asarray(edges.i)[None]
    e["j"] = np.asarray(edges.j)[None]
    return e


def host_edges_f64(meas) -> EdgeSet:
    """A host-side float64 EdgeSet over global pose indices — the gap
    oracle's edge data.  The tunneled TPU process cannot enable x64, so
    ``edge_set_from_measurements(dtype=float64)`` silently truncates to
    f32 there; the numpy-backed build keeps the oracle's edge data
    (R, t, kappa, tau) at full precision for ``global_cost``."""
    from ..types import edge_set_from_measurements
    return edge_set_from_measurements(meas, dtype=np.float64, as_numpy=True)


def scatter_owned(Xg64: np.ndarray, D, graph) -> np.ndarray:
    """HOST: add each owner's correction rows into a global f64 iterate
    (the owner-scatter both the host-recenter and fused readback paths
    assemble with)."""
    Dg = np.zeros_like(Xg64)
    gi_np = np.asarray(graph.global_index)
    mask = np.asarray(graph.pose_mask) > 0
    Dnp = np.asarray(D, np.float64)
    Dg[gi_np[mask]] = Dnp[mask]
    return Xg64 + Dg


def global_x(ref: RefineRef, D, graph) -> np.ndarray:
    """Assemble the current global f64 iterate R + D (owners' D)."""
    return scatter_owned(ref.Xg, D, graph)


def global_cost(X64: np.ndarray, edges_global) -> float:
    """f64 global cost (host oracle for gap evaluation)."""
    eg = {f: np.asarray(getattr(edges_global, f), np.float64)
          for f in ("R", "t", "kappa", "tau", "weight", "mask")}
    rR, rt = _np_edge_terms(X64[None], np.asarray(edges_global.i)[None],
                            np.asarray(edges_global.j)[None],
                            eg["R"][None], eg["t"][None])
    w = eg["mask"] * eg["weight"]
    return 0.5 * float(np.sum(
        w * (eg["kappa"] * np.sum(rR[0] ** 2, axis=(-2, -1))
             + eg["tau"] * np.sum(rt[0] ** 2, axis=-1))))


# ---------------------------------------------------------------------------
# Device-side re-centered round
# ---------------------------------------------------------------------------

def _delta_cost(Dbuf, rhoR, rhot, edges):
    """f(R + D) - f(R), evaluated without ever forming the large f(R)
    terms: linear cross term against the reference residuals plus the
    quadratic term of the increment (exact — the ambient cost is
    quadratic)."""
    LR, Lt = quadratic._edge_terms(Dbuf, edges)
    w = edges.mask * edges.weight
    cross = edges.kappa * jnp.sum(rhoR * LR, axis=(-2, -1)) \
        + edges.tau * jnp.sum(rhot * Lt, axis=-1)
    quad = edges.kappa * jnp.sum(LR * LR, axis=(-2, -1)) \
        + edges.tau * jnp.sum(Lt * Lt, axis=-1)
    return jnp.sum(w * (cross + 0.5 * quad))


def _retract_d(D, eta, R):
    """D_new with X_new = polar_retract(R + D + eta): the polar correction
    computed from small quantities only.

    Per pose, with M_Y = R_Y + U_Y (U = D + eta):
      E   = R^T U + U^T R + U^T U               (= M^T M - I, small;
                                                  R^T R = I exactly — R is
                                                  the f64-projected host
                                                  reference)
      C   = (I + E)^{-1/2} - I  ~=  -E/2 + 3/8 E^2 - 5/16 E^3 + 35/128 E^4
      D_Y' = D_Y + eta_Y + M_Y C ;  D_t' = D_t + eta_t.
    """
    d = R.shape[-1] - 1
    U = D + eta
    UY = U[..., :d]
    RY = R[..., :d]
    MY = RY + UY
    E = jnp.swapaxes(RY, -1, -2) @ UY \
        + jnp.swapaxes(UY, -1, -2) @ RY \
        + jnp.swapaxes(UY, -1, -2) @ UY
    E = 0.5 * (E + jnp.swapaxes(E, -1, -2))
    eye = jnp.eye(d, dtype=D.dtype)
    E2 = E @ E
    C = -0.5 * E + 0.375 * E2 - 0.3125 * (E2 @ E) + 0.2734375 * (E2 @ E2)
    Dn = U.at[..., :d].add(MY @ C)
    return Dn


def _agent_refine(D, Dz, consts_a, edges, inc, params: AgentParams,
                  eidx=None, interpret=False):
    """Single-step re-centered RTR for one agent (vmapped).

    Mirrors ``rbcd._agent_update``'s RTR semantics (tCG, retraction,
    acceptance rho > 0.1 with non-increase, radius /= 4 on rejection,
    ``QuadraticOptimizer.cpp:92-110``) on the correction variable D.
    With ``eidx = (eidx_i, eidx_j, rot_t, trn_t)`` the ENTIRE solve —
    recentered gradient included — runs in the fused VMEM kernel
    (``pallas_tcg.rtr_refine_full_call``); the XLA path below computes
    the gradient out here and is the off-TPU/test formulation.
    """
    consts_a = RefineConstants(*consts_a)
    R, Rz, G_ref, g0, S0, chol = consts_a[:6]
    inc_slot, inc_mask = inc
    n = R.shape[0]
    n_buf = n + Rz.shape[0]
    d = S0.shape[-1]
    r = R.shape[-2]
    k = d + 1
    sp = params.solver

    if eidx is not None:
        # Fully-fused kernel path: the recentered gradient, curvature
        # corrections, adaptive radius, and the attempt loop all run in
        # VMEM (``pallas_tcg.rtr_refine_full_call``) — no XLA pre-pass.
        from ..ops import pallas_tcg as ptcg

        from .rbcd import resolved_sel_mode

        # The 2-pass "bf16" mode (~2^-16 selection error) never applies
        # here — this kernel exists to dissolve the f32 floor, and the
        # legacy pallas_bf16_select flag is documented as ignored by
        # refinement.  The 3-pass "bf16x3" mode IS allowed: it covers the
        # full f32 mantissa (f32-grade; measured identical refine result
        # on sphere2500), at half the HIGHEST-emulation pass count.
        sel_mode = resolved_sel_mode(params)
        if sel_mode == "bf16":
            sel_mode = "f32"

        D_out_c, stats = ptcg.rtr_refine_full_call(
            eidx[0], eidx[1], eidx[2], eidx[3],
            consts_a.wk_t, consts_a.wt_t,
            consts_a.rho_rot_t, consts_a.rho_trn_t,
            consts_a.Rc,
            ptcg.comp_major(D), ptcg.comp_major(Dz),
            consts_a.g0_c, consts_a.Gref_c, consts_a.S0_c, consts_a.Lc,
            r=r, d=d, max_iters=sp.max_inner_iters, kappa=sp.tcg_kappa,
            theta=sp.tcg_theta, initial_radius=sp.initial_radius,
            max_rejections=sp.max_rejections,
            grad_tol=sp.grad_norm_tol, interpret=interpret,
            sel_mode=sel_mode)
        return ptcg.comp_minor(D_out_c, r, k), stats[0, 4]

    Dbuf = jnp.concatenate([D, Dz], axis=0)
    Y = R + D

    # Re-centered Riemannian gradient:
    #   rgrad(Y) = g0 + dG - R S1 - D (S0 + S1),   (translation rows: + dG_t)
    #   S1 = sym(D_Y^T G_refY + Y_Y^T dG_Y).
    dG = quadratic.egrad_ell(Dbuf, edges, inc_slot, inc_mask)
    DY, YY = D[..., :d], Y[..., :d]
    S1 = manifold.sym(jnp.swapaxes(DY, -1, -2) @ G_ref[..., :d]
                      + jnp.swapaxes(YY, -1, -2) @ dG[..., :d])
    g = (g0 + dG).at[..., :d].add(
        -(R[..., :d] @ S1) - DY @ (S0 + S1))
    gn0 = manifold.norm(g)

    S = S0 + S1  # curvature term at the expansion point Y

    # Refinement steps live at the |D| scale: start the trust region near
    # the preconditioned-gradient (Cauchy) scale instead of the solver's
    # global initial_radius — with a huge radius the tCG step is
    # unconstrained and the cubic model error (O(kappa |eta|^3), vs the
    # O(|g||eta|) model decrease) can reject every attempt before the
    # divide-by-4 schedule reaches the step scale.
    pg = manifold.tangent_project(Y, quadratic.precond_apply(chol, g))
    radius0 = jnp.minimum(jnp.asarray(sp.initial_radius, g.dtype),
                          10.0 * manifold.norm(pg))

    rhoR, rhot = quadratic._edge_terms(jnp.concatenate([R, Rz]), edges)

    def hvp(V):
        HV = quadratic.hessvec_ell(V, edges, inc_slot, inc_mask, n_buf)
        HV = HV.at[..., :d].add(-(V[..., :d] @ S))
        return manifold.tangent_project(Y, HV)

    def pre(V):
        return manifold.tangent_project(Y, quadratic.precond_apply(chol, V))

    df0 = _delta_cost(Dbuf, rhoR, rhot, edges)
    eps = jnp.asarray(1e-30, D.dtype)

    def attempt_body(s):
        k_att, radius, D_best, accepted = s
        res = solver.truncated_cg(Y, g, hvp, pre, radius,
                                  sp.max_inner_iters, sp.tcg_kappa,
                                  sp.tcg_theta)
        D_prop = _retract_d(D, res.eta, R)
        df_prop = _delta_cost(jnp.concatenate([D_prop, Dz], axis=0),
                              rhoR, rhot, edges)
        mdec = -(manifold.inner(g, res.eta)
                 + 0.5 * manifold.inner(res.eta, res.heta))
        rho = (df0 - df_prop) / jnp.maximum(mdec, eps)
        ok = (rho > 0.1) & (df_prop <= df0)
        return (k_att + 1, jnp.where(ok, radius, radius / 4.0),
                jnp.where(ok, D_prop, D_best), accepted | ok)

    def attempt_cond(s):
        k_att, _, _, accepted = s
        return (k_att < sp.max_rejections) & ~accepted

    init = (jnp.asarray(0, jnp.int32), radius0.astype(D.dtype), D,
            jnp.asarray(False))
    _, _, D_out, _ = jax.lax.while_loop(attempt_cond, attempt_body, init)
    below = gn0 < sp.grad_norm_tol
    return jnp.where(below, D, D_out), gn0


def refine_round(D, consts: RefineConstants, graph, meta,
                 params: AgentParams, active=None):
    """One re-centered round: exchange D, solve each agent's correction
    with neighbors fixed.  Returns (D_new, gradnorms).

    ``active [A] bool`` restricts the update to a subset of agents
    (colored Gauss-Seidel — see ``refine_rounds_colored``); default is
    the Jacobi all-agents round.  Runs the VMEM kernel when the recenter
    built kernel-layout constants (graph has edge tiles); interpreter
    mode off-TPU keeps tests honest.
    """
    Dz = rbcd.neighbor_buffer(rbcd.public_table(D, graph), graph)
    if consts.Rc is not None:
        interp = jax.default_backend() != "tpu"
        D_new, gn = jax.vmap(
            lambda dd, dz, ca, e, s, m, ii, ij, rc, tc: _agent_refine(
                dd, dz, ca, e, (s, m), params, eidx=(ii, ij, rc, tc),
                interpret=interp))(
            D, Dz, consts, graph.edges, graph.inc_slot, graph.inc_mask,
            graph.eidx_i, graph.eidx_j, graph.rot_t, graph.trn_t)
    else:
        D_new, gn = jax.vmap(
            lambda dd, dz, ca, e, s, m: _agent_refine(dd, dz, ca, e,
                                                      (s, m), params))(
            D, Dz, consts, graph.edges, graph.inc_slot, graph.inc_mask)
    if active is not None:
        D_new = jnp.where(active[:, None, None, None], D_new, D)
    return D_new, gn


def refine_rounds_colored(D, consts: RefineConstants, graph, meta,
                          params: AgentParams, num_rounds):
    """Colored Gauss-Seidel re-centered rounds: each round updates ONE
    color class of the agent coloring (``graph.color``), so adjacent
    blocks never move simultaneously.

    Exists for the strongly-coupled graphs where simultaneous (Jacobi)
    block updates of the correction oscillate or diverge — the same
    failure mode Schedule.COLORED fixes for the main RBCD loop (measured
    on ais2klinik: plain Jacobi refine rounds sent the centralized
    gradnorm 5.8 -> 26 per cycle; colored rounds descend).  Mirrors the
    RBCD theory's licensed parallelism: blocks sharing no edge have
    independent subproblems (T-RO 2021).
    """
    nc = max(meta.num_colors, 1)

    def body(i, DD):
        active = graph.color == (i % nc)
        return refine_round(DD, consts, graph, meta, params,
                            active=active)[0]

    return jax.lax.fori_loop(0, num_rounds, body, D)


def refine_rounds(D, consts: RefineConstants, graph, meta,
                  params: AgentParams, num_rounds):
    """``num_rounds`` fused re-centered rounds (one device dispatch).
    ``num_rounds`` is traced, so one compile serves every cycle length."""

    def body(_, DD):
        return refine_round(DD, consts, graph, meta, params)[0]

    return jax.lax.fori_loop(0, num_rounds, body, D)


def _retract_d0(U, R):
    """Map a raw correction U to a feasible one (R + D on-manifold): the
    zero-step polar correction (``_retract_d`` with eta = 0)."""
    return _retract_d(U, jnp.zeros_like(U), R)


def refine_rounds_accel(D, consts: RefineConstants, graph, meta,
                        params: AgentParams, num_rounds):
    """Nesterov-accelerated re-centered rounds with adaptive restart.

    The momentum sequences mirror the RBCD acceleration (reference
    ``PGOAgent.cpp:1054-1091``: gamma/alpha recursions, solve from the
    momentum point Y, V update), applied to the correction variable D at
    the fixed host reference R.  Two deviations, both required at
    refinement scales:

    * feasibility is maintained by the polar-correction series on the
      small quantities (``_retract_d``), never by projecting R + D in f32;
    * restart is ADAPTIVE (O'Donoghue & Candes 2015-style x-scheme:
      collapse the momentum when <Y - D_new, D_new - D_prev> > 0, i.e. the
      new step fights the momentum direction) instead of the reference's
      fixed ``restartInterval`` — measured on sphere2500, fixed-cadence
      momentum oscillates once the gap is below ~1e-3 while the adaptive
      scheme keeps the re-centered descent monotone per cycle.
    """
    def body(_, carry):
        return accel_round_carry(carry, consts, graph, meta, params)

    init = (D, D, jnp.zeros((), D.dtype), jnp.asarray(False))
    D_out, *_ = jax.lax.fori_loop(0, num_rounds, body, init)
    return D_out


def accel_round_carry(carry, consts: RefineConstants, graph, meta,
                      params: AgentParams):
    """One accelerated re-centered round on the momentum carry
    ``(D, V, gamma, restart)`` — the shared body of
    ``refine_rounds_accel`` and the fused on-device loop
    (``refine_fused.refine_until``), so the two pipelines cannot drift."""
    A = meta.num_robots
    D, V, gamma, restart = carry
    # Collapse the aux sequence when last round's test fired
    # (initializeAcceleration semantics: V = X, gamma = alpha = 0).
    V = jnp.where(restart, D, V)
    gamma = jnp.where(restart, jnp.zeros_like(gamma), gamma)

    gamma = (1.0 + jnp.sqrt(1.0 + 4.0 * (A * gamma) ** 2)) / (2.0 * A)
    alpha = 1.0 / (gamma * A)
    Ynes = jax.vmap(_retract_d0)((1.0 - alpha) * D + alpha * V, consts.R)
    D_new, _gn = refine_round(Ynes, consts, graph, meta, params)
    V = jax.vmap(_retract_d0)(V + gamma * (D_new - Ynes), consts.R)
    # Adaptive restart test on the actual step vs the momentum lead.
    # >= 0, not > 0: a zero step (solver rejected every attempt or
    # early-exited at the gradient floor) gives exactly 0 and MUST
    # restart — otherwise Ynes keeps extrapolating toward a stale V
    # with no descent correction and the iterate runs away (measured
    # at the f32 floor).
    restart = jnp.sum((Ynes - D_new) * (D_new - D)) >= 0.0
    return D_new, V, gamma, restart


def accel_sweep_carry(carry, consts: RefineConstants, graph, meta,
                      params: AgentParams):
    """One Nesterov-accelerated FULL COLORED SWEEP on the momentum carry
    ``(D, V, gamma, restart)``.

    The base operator is ``num_colors`` sequential color sub-rounds
    (Gauss-Seidel) instead of one simultaneous Jacobi round — for the
    strongly-coupled graphs where momentum over simultaneous updates
    diverges (ais2klinik: Jacobi+momentum oscillates, plain colored
    descends but crawls at ~0.3 gradnorm/cycle — measured round 5; this
    operator keeps sequential stability AND the momentum horizon).  One
    sweep updates every block exactly once, so the momentum algebra is
    the single-block recursion (A_eff = 1), not the 1/A-scaled one of
    ``accel_round_carry``.
    """
    D, V, gamma, restart = carry
    V = jnp.where(restart, D, V)
    gamma = jnp.where(restart, jnp.zeros_like(gamma), gamma)
    gamma = (1.0 + jnp.sqrt(1.0 + 4.0 * gamma ** 2)) / 2.0
    alpha = 1.0 / gamma
    Ynes = jax.vmap(_retract_d0)((1.0 - alpha) * D + alpha * V, consts.R)
    nc = max(meta.num_colors, 1)

    def body(i, DD):
        active = graph.color == (i % nc)
        return refine_round(DD, consts, graph, meta, params,
                            active=active)[0]

    D_new = jax.lax.fori_loop(0, nc, body, Ynes)
    V = jax.vmap(_retract_d0)(V + gamma * (D_new - Ynes), consts.R)
    # Same adaptive-restart test as accel_round_carry (>= 0: a zero
    # step must restart, see the note there).
    restart = jnp.sum((Ynes - D_new) * (D_new - D)) >= 0.0
    return D_new, V, gamma, restart


@partial(jax.jit, static_argnames=("meta", "params"))
def _accel_sweep_chunk_jit(carry, consts, graph, meta, params, num_sweeps):
    """``num_sweeps`` accelerated colored sweeps on an explicit momentum
    carry (traced count — one compile serves every chunk size)."""
    return jax.lax.fori_loop(
        0, num_sweeps,
        lambda _, c: accel_sweep_carry(c, consts, graph, meta, params),
        carry)


def refine_rounds_accel_colored_chunked(D, consts: RefineConstants, graph,
                                        meta, params: AgentParams,
                                        num_rounds: int, chunk: int = 100):
    """Accelerated colored sweeps in <=``chunk``-ROUND device dispatches
    with the momentum carry preserved across boundaries (the colored
    analog of ``refine_rounds_accel_chunked``; same tunneled-TPU ~35 s
    program ceiling).  ``num_rounds`` counts color sub-rounds, so the
    device time budget matches the other drivers; the sweep count is
    ``num_rounds // num_colors``."""
    nc = max(meta.num_colors, 1)
    sweeps = max(1, num_rounds // nc)
    per_chunk = max(1, chunk // nc)
    carry = (D, D, jnp.zeros((), D.dtype), jnp.asarray(False))
    done = 0
    while done < sweeps:
        k = min(per_chunk, sweeps - done)
        carry = _accel_sweep_chunk_jit(carry, consts, graph, meta, params,
                                       k)
        done += k
    return carry[0]


_refine_rounds_jit = jax.jit(refine_rounds,
                             static_argnames=("meta", "params"))
_refine_rounds_colored_jit = jax.jit(refine_rounds_colored,
                                     static_argnames=("meta", "params"))
_refine_rounds_accel_jit = jax.jit(refine_rounds_accel,
                                   static_argnames=("meta", "params"))


@partial(jax.jit, static_argnames=("meta", "params"))
def _accel_carry_chunk_jit(carry, consts, graph, meta, params, num_rounds):
    """``num_rounds`` accelerated rounds on an explicit momentum carry
    (traced round count — one compile serves every chunk size)."""
    return jax.lax.fori_loop(
        0, num_rounds,
        lambda _, c: accel_round_carry(c, consts, graph, meta, params),
        carry)


def refine_rounds_accel_chunked(D, consts: RefineConstants, graph, meta,
                                params: AgentParams, num_rounds: int,
                                chunk: int = 100):
    """``refine_rounds_accel`` split into <=``chunk``-round device
    dispatches that PRESERVE the momentum carry across dispatch
    boundaries (no readback between chunks — the chain stays async).

    Exists for the tunneled-TPU execution-time ceiling: single device
    programs running ~35 s+ kill the remote worker outright (measured on
    ais2klinik A=2: 300 fused rounds at 28 s survive, 400 at ~38 s
    crash), while the same rounds as a chain of shorter programs run
    fine.  Long Nesterov horizons therefore MUST be chunked, not
    shortened — cycle length is the momentum horizon and the contraction
    lever on ill-conditioned graphs."""
    carry = (D, D, jnp.zeros((), D.dtype), jnp.asarray(False))
    done = 0
    while done < num_rounds:
        k = min(chunk, num_rounds - done)
        carry = _accel_carry_chunk_jit(carry, consts, graph, meta, params,
                                       k)
        done += k
    return carry[0]


def central_gradnorm64(Xg64p: np.ndarray, e64, n_out: int,
                       d: int) -> float:
    """f64 centralized Riemannian gradient norm of a global iterate —
    THE stationarity yardstick shared by ``polish`` and the gate
    experiments (one implementation so the polish stopping rule and the
    gate measurement cannot desynchronize)."""
    G = _np_egrad(Xg64p[None], e64, n_out)[0][0]
    Y = Xg64p[..., :d]
    S1 = _np_sym(np.swapaxes(Y, -1, -2) @ G[..., :d])
    rg = G.copy()
    rg[..., :d] -= Y @ S1
    return float(np.sqrt((rg * rg).sum()))


def polish(Xg64: np.ndarray, graph, meta, params: AgentParams, meas,
           cycles: int = 3, rounds_per_cycle: int = 200, chunk: int = 100,
           gn_tol: float = 0.0, colored: bool = True):
    """Drive the centralized f64 GRADNORM down with re-centered refine
    cycles — the stationarity polish.

    Exists for certification (round 5): lambda_min of the dual operator
    S = Q - Lambda(X) at a non-stationary X carries an -O(||rgrad||)
    error term, so an iterate at the f32 descent floor (gn ~1e-3 at 100k
    scale) reads as "not certified" even AT the global optimum — the
    certificate is answering stationarity, not optimality.  Polishing to
    the re-centered floor (f64-grade gn) makes lambda_min reflect the
    actual curvature; ``solve_staircase_sharded`` calls this before every
    certificate.

    Returns ``(Xg64_polished, gn_history)`` with one gn entry per cycle
    boundary (f64, centralized).  ``colored`` selects momentum over full
    colored sweeps (``accel_sweep_carry`` — the stable operator on
    strongly-coupled graphs) when the graph carries a coloring; plain
    Jacobi momentum otherwise.  The best-gn iterate is returned (an
    accelerated tail can overshoot).
    """
    edges_np = host_edges_f64(meas)
    e64 = np_edges_batched(edges_np)
    n_out = Xg64.shape[0]
    d = meta.d

    def gn64(Xp):
        return central_gradnorm64(Xp, e64, n_out, d)

    use_colored = colored and graph.color is not None \
        and meta.num_colors > 1
    chol = None
    best = None
    hist = []
    Xg64 = _np_project_manifold(np.asarray(Xg64, np.float64), d)
    for _ in range(cycles):
        if not np.isfinite(Xg64).all():
            # Divergence safeguard (momentum over strongly-coupled
            # blocks can blow up — the solve_refine lesson): revert to
            # the best verified iterate (or the entry iterate when the
            # very first cycle diverged) and stop.
            if best is not None:
                Xg64 = best[1]
            break
        gn = gn64(Xg64)
        hist.append(gn)
        if best is None or gn < best[0]:
            best = (gn, Xg64)
        if gn_tol and gn < gn_tol:
            break
        ref = recenter(Xg64, graph, meta, params, edges_np, chol=chol,
                       pre_projected=True)
        chol = ref.consts.chol
        D0 = jnp.zeros(ref.consts.R.shape, jnp.float32)
        if use_colored:
            D = refine_rounds_accel_colored_chunked(
                D0, ref.consts, graph, meta, params, rounds_per_cycle,
                chunk=chunk)
        else:
            D = refine_rounds_accel_chunked(
                D0, ref.consts, graph, meta, params, rounds_per_cycle,
                chunk=chunk)
        Xg64 = _np_project_manifold(
            np.asarray(global_x(ref, np.asarray(D), graph), np.float64), d)
    if np.isfinite(Xg64).all():
        gn = gn64(Xg64)
        hist.append(gn)
        if best is None or gn < best[0]:
            best = (gn, Xg64)
    if best is None:   # non-finite entry iterate (or cycles = 0 on one)
        raise ValueError("polish: entry iterate is non-finite")
    return best[1], hist


def solve_refine(Xg64: np.ndarray, graph, meta, params: AgentParams,
                 edges_global, f_opt: float, rel_gap: float = 1e-6,
                 rounds_per_cycle: int = 50, max_cycles: int = 12,
                 weights=None, accel: bool = True):
    """Drive re-centered refinement until the f64 global gap reaches
    ``rel_gap`` (or ``max_cycles`` recenters).  Returns
    (X64, gap, cycles, history).

    ``weights [A, E]``: final GNC weights of the solve being refined (see
    ``recenter``); ``edges_global`` must carry the matching global weights.
    ``accel`` selects the adaptively-restarted Nesterov rounds
    (``refine_rounds_accel``, the default — fewer recenter cycles) over
    plain Jacobi rounds.

    ``history`` is a list of ``(rel_gap, elapsed_s)`` per VERIFY pass —
    one at every cycle boundary, so ``len(history) == cycles_run + 1``
    and the last entry is the final verification (not a recenter).  Each
    entry is a verified f64 gap with its wall-clock offset from the call
    start, so drivers can credit gap-ladder crossings that happen inside
    refinement (bench_convergence.py does).
    """
    import time

    if weights is not None:
        graph = rbcd.with_weights(graph, weights)
    accel_on = accel
    history = []
    t0 = time.perf_counter()
    target = f_opt * (1.0 + rel_gap)
    chol = None
    best = None  # (gap, X64) — accelerated tails can overshoot slightly
    last_revert = -10  # cycle index of the most recent safeguard revert
    for cyc in range(max_cycles + 1):
        # Cheap verify pass: f64 projection + global cost only.  The full
        # recenter (reference gradients, residual tiles, device transfers)
        # is built ONLY when another cycle actually runs — on the success
        # and exhaustion paths this saves most of a recenter's host work.
        if not np.all(np.isfinite(Xg64)):
            # A diverged accelerated cycle can go non-finite outright;
            # NaN compares False against every threshold, so it would
            # slip the worsened-gap safeguard below (and the manifold
            # projection would raise) — treat it as a worsened cycle.
            if best is None:
                raise ValueError("initial iterate is non-finite")
            accel_on = False
            Xg64 = best[1]
            last_revert = cyc
            history.append((float("inf"), time.perf_counter() - t0))
            continue
        Xg64 = _np_project_manifold(Xg64, meta.d)
        f = global_cost(Xg64, edges_global)
        gap_now = f / f_opt - 1.0
        history.append((gap_now, time.perf_counter() - t0))
        if best is not None and accel_on and \
                (not np.isfinite(gap_now)
                 or gap_now > best[0] + 1e-12 * max(1.0, abs(best[0]))):
            # Cycle-level safeguard: every cycle boundary VERIFIES the gap
            # in f64, so a worsened accelerated cycle is caught here —
            # revert to the best point and continue un-accelerated.
            # Momentum over simultaneous (Jacobi) block updates can
            # diverge on strongly coupled graphs even though each block's
            # solver only accepts non-increasing LOCAL steps (each block's
            # acceptance cannot see the coupling); plain refine rounds are
            # damped enough in practice (BASELINE.md) and serve as the
            # fallback.
            accel_on = False
            Xg64 = best[1]
            last_revert = cyc
            continue
        if best is None or gap_now < best[0]:
            best = (gap_now, Xg64)
        if f <= target or cyc == max_cycles:
            # best may be marginally below gap_now (safeguard tolerance
            # band) — honor the "returns the best verified point" contract
            # on both exits.
            return best[1], best[0], cyc, history
        # Condition-limited early exit: when the last two cycles together
        # contracted less than ~0.1 decades and several decades remain,
        # exhausting max_cycles cannot reach the target — return now so a
        # caller's fallback (e.g. the centralized A=1 continuation,
        # bench_convergence.py) gets the time instead.
        # Skipped for 3 cycles after a safeguard revert: the revert paths
        # leave a flat/worsened entry in the window (g_init, g_bad,
        # g_init), which would read as "no contraction" before a single
        # plain cycle has actually run.
        if cyc >= 2 and len(history) >= 3 and rel_gap > 0 \
                and cyc >= last_revert + 3:
            g2, g1, g0 = (history[-3][0], history[-2][0], history[-1][0])
            if np.isfinite(g2) and np.isfinite(g1) and np.isfinite(g0) \
                    and g0 > 30 * rel_gap:
                import math
                gained = math.log10(max(g2, 1e-300) / g0)
                need = math.log10(g0 / (rel_gap * 0.3))
                if gained < 0.1 and need > gained * (max_cycles - cyc):
                    return best[1], best[0], cyc, history
        ref = recenter(Xg64, graph, meta, params, edges_global, chol=chol,
                       pre_projected=True, f_ref=f)
        chol = ref.consts.chol  # weight-only: constant across recenters
        rounds_fn = _refine_rounds_accel_jit if accel_on \
            else _refine_rounds_jit
        D = jnp.zeros(ref.consts.R.shape, jnp.float32)
        D = rounds_fn(D, ref.consts, graph, meta, params,
                      rounds_per_cycle)
        Xg64 = global_x(ref, np.asarray(D), graph)
    # Only reachable when the safeguard fired on the last verify pass
    # (its `continue` consumed the final iteration): the safeguard only
    # fires with a recorded best, so return it.
    return best[1], best[0], max_cycles, history


# ---------------------------------------------------------------------------
# Gauss-Newton-CG centralized tail (the BCD-stall breaker)
# ---------------------------------------------------------------------------
#
# BCD (and the momentum polish above) are first-order in the coupling
# between blocks: on ill-conditioned graphs (ais2klinik's long chain, the
# noisy-100k synthetic) the centralized gradient norm floors orders of
# magnitude above the absolute gate while per-block solves keep
# converging (docs/NEXT.md).  The lifted PGO cost is QUADRATIC in X, so
# its Riemannian Hessian at X is the certificate operator S = Q - Lambda
# that ``certify.sparse_certificate`` already assembles on the host in
# f64 — one sparse matrix gives both the exact gradient (X S, since
# Lambda IS the tangent-projection multiplier) and the exact
# Gauss-Newton/Newton model.  A preconditioned CG solve of
# P (V S) = -grad on the tangent space, followed by a projective
# retraction with a backtracking step, is a full second-order step at
# O(E) memory — the polish stage that breaks the block-coordinate floor.


@dataclasses.dataclass(frozen=True)
class GNTailConfig:
    """Knobs of the Gauss-Newton-CG tail (``gn_tail``)."""

    max_outer: int = 20          # outer GN steps
    grad_norm_tol: float = 0.1   # stop below this centralized grad norm
    cg_max_iters: int = 400      # CG iterations per outer step
    cg_rtol: float = 0.05        # relative residual target per CG solve
    damping: float = 0.0         # Levenberg-style shift added to S
    precond_shift: float = 0.1   # block-Jacobi factorization shift
    step_shrink: float = 0.25    # backtracking factor
    max_backtracks: int = 8


@dataclasses.dataclass
class GNTailResult:
    X: np.ndarray                # [n, r, d+1] f64 polished iterate
    cost_history: list
    grad_norm_history: list      # per outer step, INCLUDING the final point
    outer_iterations: int
    cg_iterations: int
    converged: bool
    terminated_by: str           # grad_norm | max_outer | no_decrease


def _gn_diag_blocks(S, n: int, dh: int, shift: float) -> np.ndarray:
    """Per-pose (d+1)x(d+1) diagonal blocks of the sparse certificate
    operator, plus a Tikhonov shift — the block-Jacobi preconditioner of
    the tail's CG (the same Q + shift I recipe as the RBCD block solves).
    Vectorized COO filter + scatter-add: no per-pose Python loop."""
    C = S.tocoo()
    m = (C.row // dh) == (C.col // dh)
    blocks = np.zeros((n, dh, dh))
    np.add.at(blocks, (C.row[m] // dh, C.row[m] % dh, C.col[m] % dh),
              C.data[m])
    blocks += shift * np.eye(dh)
    return blocks


def gn_precond_blocks(edges, lam, n_max: int, s_max: int, d: int,
                      shift: float) -> jax.Array:
    """Per-pose (d+1)x(d+1) diagonal blocks of ``S = Q - Lambda`` for a
    BATCH of agents — ``_gn_diag_blocks`` (the host tail's block-Jacobi
    preconditioner) vectorized per shard, on device, for the sharded
    device-resident tail (``parallel.sharded.gn_tail_sharded``).

    ``edges`` is the per-agent EdgeSet ([A, E] fields, buffer-indexed);
    each agent's diag-block scatter drops neighbor-slot rows (index >=
    ``n_max``), so a shared edge contributes exactly one block per
    endpoint across the fleet — the same no-double-counting argument as
    the sharded S matvec.  ``lam [A, n, d, d]`` carries the per-pose dual
    blocks ``sym(Y^T (XQ)_Y)``; the Tikhonov ``shift`` mirrors the host
    recipe."""

    def one(e):
        return quadratic.diag_blocks(e, n_max + s_max, n_out=n_max)

    blocks = jax.vmap(one)(edges)
    blocks = blocks.at[..., :d, :d].add(-lam)
    return blocks + shift * jnp.eye(d + 1, dtype=blocks.dtype)


def _gn_tangent(X: np.ndarray, V: np.ndarray, d: int) -> np.ndarray:
    """Tangent projection at X (numpy twin of ``manifold.tangent_project``):
    rotation columns lose their Y sym(Y^T W) component, translations pass."""
    Y = X[..., :d]
    W = V[..., :d]
    YtW = np.einsum("nrd,nre->nde", Y, W)
    sym = 0.5 * (YtW + np.swapaxes(YtW, -1, -2))
    out = V.copy()
    out[..., :d] = W - np.einsum("nrd,nde->nre", Y, sym)
    return out


def gn_tail(X64: np.ndarray, edges_global,
            cfg: GNTailConfig | None = None, log=None) -> GNTailResult:
    """Preconditioned Gauss-Newton-CG polish of a lifted global iterate
    (host f64).  Opt-in: run it after the BCD/momentum stages stall
    (``stall_handoff``) when an absolute gradient-norm gate matters.

    Per outer step: assemble ``S = Q - Lambda(X)`` via
    ``certify.sparse_certificate`` (the Riemannian gradient is exactly
    ``X S`` and the Riemannian Hessian-vector ``P(V S)``), solve the
    Newton system with block-Jacobi-preconditioned CG on the tangent
    space (negative-curvature guard for indefinite saddles), and take a
    backtracking projective retraction accepted only on true f64 cost
    decrease.  Every quantity matches the driver's centralized oracle:
    the reported gradient norm is the same ``manifold.norm(rgrad)`` the
    ``run_rbcd`` gate reads."""
    from .certify import sparse_certificate

    cfg = cfg or GNTailConfig()
    X = np.asarray(X64, np.float64).copy()
    n, r, dh = X.shape
    d = dh - 1
    cost = global_cost(X, edges_global)
    cost_hist = [cost]
    gn_hist: list = []
    cg_total = 0
    terminated_by = "max_outer"
    outer_done = 0

    for outer in range(int(cfg.max_outer)):
        S = sparse_certificate(X, edges_global)
        Xf = X.transpose(1, 0, 2).reshape(r, n * dh)
        grad = (Xf @ S).reshape(r, n, dh).transpose(1, 0, 2)
        # X S is already tangent (Lambda is the projection multiplier);
        # re-project for numerical hygiene before measuring the gate.
        grad = _gn_tangent(X, grad, d)
        gn = float(np.sqrt(np.sum(grad * grad)))
        gn_hist.append(gn)
        if log is not None:
            log(f"  gn_tail outer {outer}: cost {cost:.9g} gn {gn:.4g}")
        if gn < cfg.grad_norm_tol:
            terminated_by = "grad_norm"
            break
        outer_done = outer + 1

        blocks = _gn_diag_blocks(S, n, dh, cfg.precond_shift)

        def A(V):
            Vf = V.transpose(1, 0, 2).reshape(r, n * dh)
            W = (Vf @ S).reshape(r, n, dh).transpose(1, 0, 2)
            if cfg.damping:
                W = W + cfg.damping * V
            return _gn_tangent(X, W, d)

        def Minv(V):
            W = np.linalg.solve(blocks, V.transpose(0, 2, 1))
            return _gn_tangent(X, W.transpose(0, 2, 1), d)

        # Preconditioned CG on the tangent space, Steihaug-style negative
        # curvature exit (fall back to the accumulated step, or steepest
        # descent on the very first iteration).
        b = -grad
        v = np.zeros_like(b)
        res = b.copy()
        z = Minv(res)
        p = z.copy()
        rz = float(np.sum(res * z))
        b_norm = float(np.sqrt(np.sum(b * b)))
        for k in range(int(cfg.cg_max_iters)):
            Ap = A(p)
            pAp = float(np.sum(p * Ap))
            cg_total += 1
            if pAp <= 0:
                if k == 0:
                    v = b.copy()  # gradient direction
                break
            alpha = rz / pAp
            v = v + alpha * p
            res = res - alpha * Ap
            if float(np.sqrt(np.sum(res * res))) <= cfg.cg_rtol * b_norm:
                break
            z = Minv(res)
            rz_new = float(np.sum(res * z))
            p = z + (rz_new / rz) * p
            rz = rz_new

        # Backtracking projective retraction on true f64 cost.
        step = 1.0
        accepted = False
        for _ in range(int(cfg.max_backtracks)):
            Xc = X + step * v
            Xc = _np_project_manifold(Xc, d)
            c_new = global_cost(Xc, edges_global)
            if np.isfinite(c_new) and c_new < cost:
                X, cost = Xc, c_new
                accepted = True
                break
            step *= cfg.step_shrink
        cost_hist.append(cost)
        if not accepted:
            terminated_by = "no_decrease"
            break
    else:
        # max_outer exhausted: measure the final point's gate value.
        S = sparse_certificate(X, edges_global)
        Xf = X.transpose(1, 0, 2).reshape(r, n * dh)
        grad = _gn_tangent(
            X, (Xf @ S).reshape(r, n, dh).transpose(1, 0, 2), d)
        gn_hist.append(float(np.sqrt(np.sum(grad * grad))))

    return GNTailResult(
        X=X, cost_history=cost_hist, grad_norm_history=gn_hist,
        outer_iterations=outer_done, cg_iterations=cg_total,
        converged=terminated_by == "grad_norm",
        terminated_by=terminated_by)


def stall_handoff(gn_history, window: int = 8, rtol: float = 1e-2,
                  grad_norm_tol: float = 0.1) -> bool:
    """The GN-tail trigger: True when the BCD gradient-norm trajectory
    has plateaued ABOVE the absolute gate — no relative improvement over
    the trailing ``window`` evals.  Mirrors the health layer's stall
    detector semantics on the gradient norm instead of the cost, so the
    driver can hand the iterate to ``gn_tail`` exactly when more BCD
    rounds stopped paying."""
    hist = [float(g) for g in gn_history]
    if len(hist) < window:
        return False
    if hist[-1] < grad_norm_tol:
        return False  # already through the gate — nothing to break
    first, last = hist[-window], hist[-1]
    if not (np.isfinite(first) and np.isfinite(last)):
        return False
    return first - last <= rtol * abs(first)
