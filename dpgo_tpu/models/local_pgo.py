"""Single-agent (centralized) pose-graph optimization — the minimum
end-to-end slice.

Equivalent of reference ``PGOAgent::localPoseGraphOptimization``
(``PGOAgent.cpp:964-1005``) and the ``single-robot-example`` driver
(``examples/SingleRobotExample.cpp``): chordal (or odometry) initialization
followed by a Riemannian trust-region solve of the full problem on one
device.  Everything from initialization through the RTR loop is jitted; this
exercises every hot kernel of the framework (edge-list Laplacian ops,
batched manifold projections, tCG) and is the first performance checkpoint
(SURVEY.md section 7, M1).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..config import SolverParams
from ..types import EdgeSet, Measurements, edge_set_from_measurements
from ..utils.lie import lifting_matrix, project_to_rotation
from ..ops import chordal, quadratic, solver


def lift(T: jax.Array, ylift: jax.Array) -> jax.Array:
    """Lift SE(d) poses T [n, d, d+1] to rank r: X_i = YLift T_i
    (reference ``PGOAgent.cpp:183,415``)."""
    return jnp.einsum("rd,nde->nre", ylift, T)


def round_solution(X: jax.Array, ylift: jax.Array) -> jax.Array:
    """Round lifted X [n, r, d+1] back to SE(d): T = YLift^T X, then project
    rotation blocks to SO(d) (reference ``PGOAgent::roundSolution``,
    ``PGOAgent.cpp:487-494``)."""
    T = jnp.einsum("rd,nre->nde", ylift, X)
    d = ylift.shape[1]
    R = project_to_rotation(T[..., :d])
    return jnp.concatenate([R, T[..., d:]], axis=-1)


def make_problem(edges: EdgeSet, n: int, precond_shift: float = 0.1) -> solver.Problem:
    """Assemble solver closures for a single-buffer problem (all edges
    private; the buffer is exactly the n local poses)."""
    blocks = quadratic.diag_blocks(edges, n)
    chol = quadratic.precond_factors(blocks, precond_shift)
    return solver.Problem(
        cost=lambda X: quadratic.cost(X, edges),
        egrad=lambda X: quadratic.egrad(X, edges),
        ehess=lambda X, V: quadratic.hessvec(V, edges, n),
        precond=lambda X, V: quadratic.precond_apply(chol, V),
    )


@dataclasses.dataclass
class LocalSolveResult:
    T: jax.Array  # [n, d, d+1] rounded SE(d) trajectory
    X: jax.Array  # [n, r, d+1] lifted solution
    cost: float
    grad_norm: float
    iters: int


@partial(jax.jit, static_argnames=("n", "rank", "params", "max_iters",
                                   "grad_norm_tol", "init"))
def _solve_local_jit(edges: EdgeSet, n: int, rank: int, params: SolverParams,
                     max_iters: int, grad_norm_tol: float, init: str):
    dtype = edges.R.dtype
    d = edges.d
    if init == "chordal":
        T0 = chordal.chordal_initialization(edges, n)
    elif init == "odometry":
        T0 = chordal.odometry_from_edges(edges, n)
    else:
        raise ValueError(f"unknown init {init!r}")

    ylift = lifting_matrix(rank, d, dtype)
    X0 = lift(T0, ylift)
    problem = make_problem(edges, n, params.precond_shift)
    out = solver.rtr_solve(problem, X0, params, max_iters=max_iters,
                           grad_norm_tol=grad_norm_tol)
    T = round_solution(out.X, ylift)
    return T, out


def solve_local(
    meas: Measurements,
    rank: int | None = None,
    params: SolverParams | None = None,
    max_iters: int = 100,
    grad_norm_tol: float = 1e-1,
    init: str = "chordal",
    dtype=jnp.float64,
) -> LocalSolveResult:
    """Centralized PGO solve of a full measurement set.

    Defaults mirror the reference's local solve configuration
    (``PGOAgent.cpp:979-987``: RTR, gradnorm tol 1e-1; rank r = d means no
    relaxation).  ``rank > d`` gives the lifted (Burer-Monteiro) solve.
    """
    params = params or SolverParams(initial_radius=1e1, max_inner_iters=50)
    n = meas.num_poses
    rank = meas.d if rank is None else rank
    edges = edge_set_from_measurements(meas, dtype=dtype)
    T, out = _solve_local_jit(edges, n, rank, params, max_iters,
                              grad_norm_tol, init)
    return LocalSolveResult(T=T, X=out.X, cost=float(out.f),
                            grad_norm=float(out.grad_norm), iters=int(out.iters))
