"""Distributed (multi-robot) initialization — the no-centralized-init path.

TPU-native equivalent of the reference's inter-agent frame alignment
(``PGOAgent::initializeInGlobalFrame`` and helpers, reference
``src/PGOAgent.cpp:250-432``): each agent initializes its trajectory in its
OWN frame from its private measurements (``localInitialization``,
``PGOAgent.cpp:947-962``), robot 0 anchors the global frame
(``PGOAgent.cpp:182-186``), and every other robot estimates the rigid
transform aligning its local frame to the global frame from the inter-robot
loop closures it shares with an already-initialized neighbor — robustly,
via GNC rotation averaging over per-edge candidate transforms.

The reference runs this as a message-driven protocol (first pose message
from an initialized neighbor triggers alignment, abort-and-retry on empty
inlier sets, ``PGOAgent.cpp:396-400``).  Here the same dependency structure
is a host-side BFS over the robot adjacency graph: alignment order is
by hop distance from robot 0, each robot aligns against its
best-connected initialized neighbor and falls back to its other initialized
neighbors when the inlier set is too small — the batched averaging math
runs in jitted JAX.  This is a one-time host phase; the steady-state RBCD
loop is unaffected.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AgentParams, RobustCostType
from ..types import edge_set_from_measurements
from ..utils.lie import angular_to_chordal_so3
from ..utils.partition import Partition
from ..ops import averaging, chordal
from .local_pgo import lift
from .rbcd import GraphMeta, MultiAgentGraph, lifting_matrix


def _se(R: np.ndarray, t: np.ndarray, d: int) -> np.ndarray:
    """(d+1)x(d+1) homogeneous matrix from (R [d,d], t [d])."""
    T = np.eye(d + 1)
    T[:d, :d] = R
    T[:d, d] = t
    return T


def _se_inv(T: np.ndarray, d: int) -> np.ndarray:
    R, t = T[:d, :d], T[:d, d]
    return _se(R.T, -R.T @ t, d)


def local_initialization(part: Partition, params: AgentParams,
                         dtype=jnp.float64) -> np.ndarray:
    """Per-agent trajectory estimate in each agent's OWN frame.

    [A, n_max, d, d+1]; chordal initialization from the agent's private
    measurements for the L2 cost, odometry propagation for robust costs —
    the reference's ``localInitialization`` policy (``PGOAgent.cpp:947-962``,
    odometry under GNC because the chordal solve has no outlier rejection).
    """
    meas = part.meas
    A = part.num_robots
    d = meas.d
    use_chordal = params.robust.cost_type == RobustCostType.L2
    out = np.zeros((A, part.n_max, d, d + 1))
    out[..., :d] = np.eye(d)
    for a in range(A):
        sel = (np.asarray(meas.r1) == a) & (np.asarray(meas.r2) == a)
        sub = dataclasses.replace(
            meas,
            num_poses=int(part.n[a]),
            r1=meas.r1[sel], p1=meas.p1[sel],
            r2=meas.r2[sel], p2=meas.p2[sel],
            R=meas.R[sel], t=meas.t[sel],
            kappa=meas.kappa[sel], tau=meas.tau[sel],
            weight=meas.weight[sel], is_known_inlier=meas.is_known_inlier[sel],
        )
        edges = edge_set_from_measurements(sub, dtype=dtype)
        n_a = int(part.n[a])
        if use_chordal:
            T = chordal.chordal_initialization(edges, n_a)
        else:
            T = chordal.odometry_from_edges(edges, n_a)
        out[a, :n_a] = np.asarray(T)
    return out


def _alignment_candidates(part: Partition, T_local: np.ndarray,
                          T_global: np.ndarray, b: int, a: int):
    """Candidate frame-alignment transforms for robot ``b`` (uninitialized,
    frame ``world1``) from robot ``a`` (initialized, frame ``world2``).

    One candidate per shared edge between the two robots — the loop of
    ``computeRobustNeighborTransformTwoStage`` over the pose dict
    (``PGOAgent.cpp:290-305``), each candidate being
    ``computeNeighborTransform`` (``PGOAgent.cpp:250-288``):

        T_world2_world1 = T_world2_frame2 . T_frame1_frame2^-1 . T_world1_frame1^-1

    where frame1 is b's endpoint pose (in b's local trajectory) and frame2
    is a's endpoint pose (already in the global frame).  The reference
    rounds the neighbor's lifted pose via YLift^T; here agent a's global
    SE(d) estimate is available directly.
    """
    meas = part.meas
    d = meas.d
    r1 = np.asarray(meas.r1)
    r2 = np.asarray(meas.r2)
    Rs, ts = [], []
    for k in np.nonzero(((r1 == a) & (r2 == b)) | ((r1 == b) & (r2 == a)))[0]:
        dT = _se(np.asarray(meas.R[k]), np.asarray(meas.t[k]), d)
        if int(r1[k]) == a:  # incoming edge a -> b
            T_f1_f2 = _se_inv(dT, d)
            p_b, p_a = int(meas.p2[k]), int(meas.p1[k])
        else:                # outgoing edge b -> a
            T_f1_f2 = dT
            p_b, p_a = int(meas.p1[k]), int(meas.p2[k])
        T_w2_f2 = _se(T_global[a, p_a, :, :d], T_global[a, p_a, :, d], d)
        T_w1_f1 = _se(T_local[b, p_b, :, :d], T_local[b, p_b, :, d], d)
        T = T_w2_f2 @ _se_inv(T_f1_f2, d) @ _se_inv(T_w1_f1, d)
        Rs.append(T[:d, :d])
        ts.append(T[:d, d])
    return np.stack(Rs), np.stack(ts)


def robust_frame_alignment(Rs: np.ndarray, ts: np.ndarray, *,
                           two_stage: bool = True,
                           rotation_threshold_rad: float = 0.5):
    """Robust average of candidate transforms -> (R, t, num_inliers).

    Two-stage (default): GNC rotation averaging at a ~30 degree chordal
    threshold, then translation averaging over the rotation inliers
    (``computeRobustNeighborTransformTwoStage``, ``PGOAgent.cpp:290-331``).
    Single-stage: joint robust SE(d) averaging with the reference's
    kappa=1.82 / tau=0.01 / chi2(0.9, 3) threshold
    (``computeRobustNeighborTransform``, ``PGOAgent.cpp:333-367``).
    """
    Rs_j = jnp.asarray(Rs)
    ts_j = jnp.asarray(ts)
    if two_stage:
        thr = angular_to_chordal_so3(rotation_threshold_rad)
        rot = averaging.robust_single_rotation_averaging(
            Rs_j, error_threshold=thr)
        inl = rot.inlier_mask.astype(Rs_j.dtype)
        t = averaging.single_translation_averaging(ts_j, mask=inl)
        return (np.asarray(rot.R), np.asarray(t),
                int(np.asarray(rot.inlier_mask).sum()))
    from ..utils.lie import error_threshold_at_quantile
    k = Rs_j.shape[0]
    res = averaging.robust_single_pose_averaging(
        Rs_j, ts_j,
        kappa=jnp.full(k, 1.82, Rs_j.dtype),
        tau=jnp.full(k, 0.01, Rs_j.dtype),
        error_threshold=error_threshold_at_quantile(0.9, 3))
    return (np.asarray(res.R), np.asarray(res.t),
            int(np.asarray(res.inlier_mask).sum()))


def distributed_initialization(
    part: Partition,
    meta: GraphMeta,
    graph: MultiAgentGraph,
    params: AgentParams,
    dtype=jnp.float64,
    two_stage: bool = True,
) -> jax.Array:
    """Initial lifted state X0 [A, n_max, r, d+1] without any centralized
    solve — the deployment initialization path.

    Robot 0's local frame IS the global frame (``PGOAgent.cpp:182-186``);
    remaining robots align by BFS from robot 0.  A robot prefers the
    initialized neighbor sharing the most edges and falls back to others
    when GNC finds fewer than ``params.robust_init_min_inliers`` inliers
    (the message-driven retry of ``PGOAgent.cpp:396-400``); if every
    neighbor fails, the largest candidate set is used unweighted (with a
    warning) so the solve can proceed — RBCD itself corrects moderate
    misalignment.
    """
    A = part.num_robots
    d = part.meas.d
    min_inliers = max(1, params.robust_init_min_inliers)

    T_local = local_initialization(part, params, dtype)
    T_global = np.array(T_local)

    # Robot adjacency weighted by shared-edge counts.
    r1 = np.asarray(part.meas.r1)
    r2 = np.asarray(part.meas.r2)
    n_shared = np.zeros((A, A), np.int64)
    for k in np.nonzero(r1 != r2)[0]:
        n_shared[r1[k], r2[k]] += 1
        n_shared[r2[k], r1[k]] += 1

    initialized = {0}
    while len(initialized) < A:
        # Next robot: most shared edges into the initialized set (BFS-ish,
        # best-connected first — the robots the reference would reach first).
        frontier = [
            (int(n_shared[b, list(initialized)].sum()), b)
            for b in range(A) if b not in initialized
        ]
        weight, b = max(frontier)
        if weight == 0:
            raise ValueError(
                f"robot {b} shares no edges with the initialized component; "
                "the robot-level pose graph is disconnected")
        neighbors = sorted((a for a in initialized if n_shared[b, a] > 0),
                           key=lambda a: -n_shared[b, a])
        best = None  # (num_inliers, R, t)
        for a in neighbors:
            Rs, ts = _alignment_candidates(part, T_local, T_global, b, a)
            R, t, ninl = robust_frame_alignment(Rs, ts, two_stage=two_stage)
            if best is None or ninl > best[0]:
                best = (ninl, R, t)
            if ninl >= min_inliers:
                break
        ninl, R, t = best
        if 0 < ninl < min_inliers:
            # Fewer inliers than requested but a usable robust estimate —
            # the reference accepts any non-empty inlier set
            # (PGOAgent.cpp:396-400 only aborts on zero).
            warnings.warn(
                f"[dist_init] robot {b}: robust alignment found only "
                f"{ninl} inlier(s) (< {min_inliers}); using them")
        elif ninl == 0:
            # Every neighbor's GNC rejected everything.  Unweighted
            # averaging over the best-connected neighbor's candidates keeps
            # the solve going (RBCD corrects moderate misalignment), but the
            # estimate may be poisoned by outliers — warn loudly.
            a = neighbors[0]
            Rs, ts = _alignment_candidates(part, T_local, T_global, b, a)
            R, t = averaging.single_pose_averaging(jnp.asarray(Rs), jnp.asarray(ts))
            R, t = np.asarray(R), np.asarray(t)
            warnings.warn(
                f"[dist_init] robot {b}: robust alignment found NO inliers "
                f"against any initialized neighbor; falling back to "
                f"unweighted averaging over {len(Rs)} candidates")
        # T_global_pose = T_align . T_local_pose for the whole trajectory
        # (initializeInGlobalFrame, PGOAgent.cpp:402-419).
        n_b = int(part.n[b])
        Rl = T_local[b, :n_b, :, :d]
        tl = T_local[b, :n_b, :, d]
        T_global[b, :n_b, :, :d] = np.einsum("ab,nbc->nac", R, Rl)
        T_global[b, :n_b, :, d] = tl @ R.T + t
        initialized.add(b)

    # Lift: X = YLift . T per pose (PGOAgent.cpp:415), batched.
    ylift = lifting_matrix(meta, dtype)
    flat = jnp.asarray(T_global.reshape(-1, d, d + 1), dtype)
    X0 = lift(flat, ylift).reshape(A, part.n_max, meta.rank, d + 1)
    return X0 * jnp.asarray(graph.pose_mask, dtype)[:, :, None, None]
