"""dpgo_tpu — a TPU-native distributed pose-graph optimization framework.

Built from scratch with the capabilities of the reference C++ library
lajoiepy/dpgo (distributed certifiably-correct PGO, T-RO 2021; asynchronous
parallel distributed PGO, RA-L 2020), re-designed for TPU: agents are shards
of a JAX device mesh, the Riemannian block-coordinate descent inner loop is
an XLA-compiled ``lax.while_loop``, sparse connection-Laplacian products are
edge-list segment-sums, and neighbor pose exchange is an ICI/DCN collective.
"""

from .config import (
    AgentParams,
    RobustCostParams,
    RobustCostType,
    ROptAlg,
    Schedule,
    SolverParams,
)
from .types import EdgeSet, Measurements, edge_set_from_measurements
from .utils.g2o import read_g2o

__version__ = "0.1.0"

__all__ = [
    "AgentParams",
    "RobustCostParams",
    "RobustCostType",
    "ROptAlg",
    "Schedule",
    "SolverParams",
    "EdgeSet",
    "Measurements",
    "edge_set_from_measurements",
    "read_g2o",
]
