"""dpgo_tpu — a TPU-native distributed pose-graph optimization framework.

Built from scratch with the capabilities of the reference C++ library
lajoiepy/dpgo (distributed certifiably-correct PGO, T-RO 2021; asynchronous
parallel distributed PGO, RA-L 2020), re-designed for TPU: agents are shards
of a JAX device mesh, the Riemannian block-coordinate descent inner loop is
an XLA-compiled ``lax.while_loop``, sparse connection-Laplacian products are
edge-list segment-sums, and neighbor pose exchange is an ICI/DCN collective.
"""

import os as _os

import jax as _jax

# On TPU, float32 matmuls/einsums default to bfloat16 MXU passes (~1e-2
# relative error).  PGO is a high-accuracy optimization: chordal init,
# Stiefel projections/retractions, and the tCG model values all sit on
# matmuls, and bf16 error is enough to push iterates visibly off the
# manifold (the reference runs in full float64 throughout — Eigen/ROPTLIB).
# Full-f32 accumulation is required for the 1e-6 suboptimality targets
# (SURVEY.md section 7, hard part #3); its MXU cost is negligible for the
# small (r x d) pose blocks this framework multiplies.  A precision the
# user already chose — via JAX_DEFAULT_MATMUL_PRECISION or an explicit
# jax.config.update before this import — is left untouched;
# DPGO_TPU_MATMUL_PRECISION in {default, float32, highest} overrides both.
_forced = _os.environ.get("DPGO_TPU_MATMUL_PRECISION") or None  # "" = unset
_user_set = ("JAX_DEFAULT_MATMUL_PRECISION" in _os.environ
             or _jax.config.jax_default_matmul_precision is not None)
if _forced is not None or not _user_set:
    _jax.config.update("jax_default_matmul_precision", _forced or "highest")

from .config import (
    AgentParams,
    RobustCostParams,
    RobustCostType,
    ROptAlg,
    Schedule,
    SolverParams,
)
from .types import EdgeSet, Measurements, edge_set_from_measurements
from .utils.g2o import read_g2o

__version__ = "0.1.0"

__all__ = [
    "AgentParams",
    "RobustCostParams",
    "RobustCostType",
    "ROptAlg",
    "Schedule",
    "SolverParams",
    "EdgeSet",
    "Measurements",
    "edge_set_from_measurements",
    "read_g2o",
]
