"""dpgo_tpu — a TPU-native distributed pose-graph optimization framework.

Built from scratch with the capabilities of the reference C++ library
lajoiepy/dpgo (distributed certifiably-correct PGO, T-RO 2021; asynchronous
parallel distributed PGO, RA-L 2020), re-designed for TPU: agents are shards
of a JAX device mesh, the Riemannian block-coordinate descent inner loop is
an XLA-compiled ``lax.while_loop``, sparse connection-Laplacian products are
edge-list segment-sums, and neighbor pose exchange is an ICI/DCN collective.
"""

import os as _os

import jax as _jax

# On TPU, float32 matmuls/einsums default to bfloat16 MXU passes (~1e-2
# relative error).  PGO is a high-accuracy optimization: chordal init,
# Stiefel projections/retractions, and the tCG model values all sit on
# matmuls, and bf16 error is enough to push iterates visibly off the
# manifold (the reference runs in full float64 throughout — Eigen/ROPTLIB).
# Full-f32 accumulation is required for the 1e-6 suboptimality targets
# (SURVEY.md section 7, hard part #3); its MXU cost is negligible for the
# small (r x d) pose blocks this framework multiplies.  A precision the
# user already chose — via JAX_DEFAULT_MATMUL_PRECISION or an explicit
# jax.config.update before this import — is left untouched;
# DPGO_TPU_MATMUL_PRECISION in {default, float32, highest} overrides both.
_forced = _os.environ.get("DPGO_TPU_MATMUL_PRECISION") or None  # "" = unset
_user_set = ("JAX_DEFAULT_MATMUL_PRECISION" in _os.environ
             or _jax.config.jax_default_matmul_precision is not None)
if _forced is not None or not _user_set:
    _jax.config.update("jax_default_matmul_precision", _forced or "highest")

# Persistent compilation cache: the solver's programs (fused RBCD segments,
# chordal-init CG, metrics, kernels) cost seconds-to-tens-of-seconds to
# compile and are identical across process runs of the same problem shape;
# without a disk cache every script/benchmark invocation pays full XLA
# compilation again.  Opt out with DPGO_TPU_COMPILATION_CACHE=0; a cache
# dir the user already configured (flag or env) wins.  The default is
# enabled only for SOURCE CHECKOUTS (a pyproject.toml two levels up marks
# one) and lives in the project tree — a pip-installed package must not
# grow a cache inside site-packages, and gets no silent default.
_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _os.environ.get("DPGO_TPU_COMPILATION_CACHE", "1") != "0" \
        and _jax.config.jax_compilation_cache_dir is None \
        and "JAX_COMPILATION_CACHE_DIR" not in _os.environ \
        and _os.path.exists(_os.path.join(_root, "pyproject.toml")):
    _cache = _os.path.join(_root, ".jax_cache")
    try:
        _os.makedirs(_cache, exist_ok=True)
        _probe = _os.path.join(_cache, ".writable")
        with open(_probe, "w"):
            pass
        _os.unlink(_probe)
    except OSError:
        pass
    else:
        _jax.config.update("jax_compilation_cache_dir", _cache)
        # 0.2 s threshold: catch the many mid-size programs whose
        # recompilation adds up on repeat runs.
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

from .config import (
    AgentParams,
    RobustCostParams,
    RobustCostType,
    ROptAlg,
    Schedule,
    SolverParams,
)
from .types import EdgeSet, Measurements, edge_set_from_measurements
from .utils.g2o import read_g2o

__version__ = "0.1.0"

__all__ = [
    "AgentParams",
    "RobustCostParams",
    "RobustCostType",
    "ROptAlg",
    "Schedule",
    "SolverParams",
    "EdgeSet",
    "Measurements",
    "edge_set_from_measurements",
    "read_g2o",
]
