"""Deterministic, seeded fault injection for the deployment transports.

A ``FaultInjector`` sits on the *send* side of a transport: every outgoing
frame's bytes pass through ``apply(src, dst, data)``, which returns the
deliveries the network actually performs — possibly none (drop, partition),
possibly late (delay), possibly swapped with the next frame on the link
(reorder), possibly bit-flipped (corrupt).  The decision stream is a
per-link ``np.random.default_rng`` derived from ``(seed, src, dst)``, so a
chaos run is reproducible per link regardless of how threads interleave
*across* links — the property the seeded chaos tests rely on.

The injector is shared mutable state guarded by one lock; ``enabled``
toggles it live (the deployment examples run the lifting-matrix broadcast
and the final anchor sync clean, injecting faults only during solve
rounds).
"""

from __future__ import annotations

import dataclasses
import threading
import zlib

import numpy as np


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Per-message fault probabilities and shapes (all independent)."""

    drop: float = 0.0            # P(frame silently dropped)
    delay: float = 0.0           # P(frame delayed)
    delay_s: tuple[float, float] = (0.0, 0.0)  # uniform delay range, seconds
    reorder: float = 0.0         # P(frame held and swapped with the next)
    corrupt: float = 0.0         # P(payload bytes flipped)
    # Node groups that cannot talk across (network partition); nodes absent
    # from every group communicate freely.
    partitions: tuple[tuple, ...] = ()

    def any_active(self) -> bool:
        return bool(self.drop or self.delay or self.reorder or self.corrupt
                    or self.partitions)


class FaultInjector:
    """Seeded fault decisions, one RNG stream per directed link."""

    def __init__(self, spec: FaultSpec, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self.enabled = True
        self._lock = threading.Lock()
        self._rngs: dict[tuple, np.random.Generator] = {}
        self._held: dict[tuple, bytes] = {}  # reorder: one held frame/link
        self.stats = {"delivered": 0, "dropped": 0, "delayed": 0,
                      "reordered": 0, "corrupted": 0, "partitioned": 0}

    def _rng(self, link: tuple) -> np.random.Generator:
        rng = self._rngs.get(link)
        if rng is None:
            # Stable per-link derivation: independent of creation order.
            h = zlib.crc32(repr(link).encode())
            rng = np.random.default_rng((self.seed << 32) ^ h)
            self._rngs[link] = rng
        return rng

    def partitioned(self, src, dst) -> bool:
        for group in self.spec.partitions:
            if (src in group) != (dst in group):
                return True
        return False

    def apply(self, src, dst, data: bytes) -> list[tuple[float, bytes]]:
        """Deliveries for one sent frame, as ``(delay_seconds, bytes)``.

        Empty list = the network ate the frame.  More than one entry =
        a previously held (reordered) frame rides out with this one.
        """
        if not self.enabled:
            return [(0.0, data)]
        with self._lock:
            if self.partitioned(src, dst):
                self.stats["partitioned"] += 1
                return []
            rng = self._rng((src, dst))
            sp = self.spec
            # One uniform draw per fault class keeps the stream length
            # deterministic per message (reproducibility under any spec).
            u_drop, u_delay, u_reorder, u_corrupt = rng.uniform(size=4)
            if u_drop < sp.drop:
                self.stats["dropped"] += 1
                return []
            if u_corrupt < sp.corrupt and len(data):
                data = bytearray(data)
                for k in rng.integers(0, len(data), size=3):
                    data[int(k)] ^= 0xFF
                data = bytes(data)
                self.stats["corrupted"] += 1
            delay = 0.0
            if u_delay < sp.delay:
                delay = float(rng.uniform(*sp.delay_s))
                self.stats["delayed"] += 1
            link = (src, dst)
            held = self._held.pop(link, None)
            if held is None and u_reorder < sp.reorder:
                self._held[link] = data
                self.stats["reordered"] += 1
                return []
            out = [(delay, data)]
            if held is not None:
                out.append((delay, held))  # swapped: newer first, older after
            self.stats["delivered"] += len(out)
            return out

    def flush(self, src, dst) -> list[tuple[float, bytes]]:
        """Release any frame held for reordering on a link (called when the
        sender closes so a held frame is not silently lost forever)."""
        with self._lock:
            held = self._held.pop((src, dst), None)
        return [(0.0, held)] if held is not None else []
