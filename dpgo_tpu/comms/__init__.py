"""Fault-tolerant deployment transport (``dpgo_tpu.comms``).

The per-robot runtime (``dpgo_tpu.agent``) deliberately owns no transport:
the reference delegates it to the external ``dpgo_ros`` wrapper, and our
deployment examples used to carry their own ad-hoc socket code that assumed
a perfect network — blocking reads with no deadline, no retries, no
staleness bookkeeping, and a hang if any robot process died.  The RA-L 2020
asynchronous DPGO convergence result holds precisely *because* messages may
be delayed, stale, or lost; this package makes the deployment path live up
to that claim:

* ``protocol`` — the wire format: length-prefixed frames (arrays only, no
  pickle) in the packed columnar v2 codec (CRC32-protected, zero-copy
  ``frombuffer`` decode, columnar pose sets with an opt-in bf16 payload)
  with the v1 ``npz`` archive as a versioned fallback (receivers sniff
  the magic, so mixed-version fleets interoperate), a validated
  frame-size cap (a corrupt or malicious length header raises
  ``ProtocolError`` instead of attempting an OOM-sized allocation) and an
  incremental ``FrameAssembler`` so a read deadline can interrupt and
  later resume a partially received frame.
* ``transport`` — the ``Transport`` abstraction plus the two shipped
  implementations: ``LoopbackTransport`` (in-process pair, delay-aware
  inboxes) and ``TcpTransport`` (localhost/TCP, lifted out of
  ``examples/tcp_deployment_example.py``).  Both thread every outgoing
  frame through an optional ``FaultInjector``.
* ``faults`` — deterministic, seeded fault injection: drop / delay /
  reorder / corrupt / partition, with per-link RNG streams so results do
  not depend on thread scheduling across links.
* ``reliable`` — the fault-tolerance layer: ``ReliableChannel`` wraps any
  transport with per-message send/recv deadlines, bounded retry with
  exponential backoff + jitter, monotonic sequence numbers (stale and
  reordered frames are dropped, counted), corrupt-frame rejection,
  heartbeat-based peer liveness, and ``dpgo_tpu.obs`` instrumentation
  (``comms_retries`` / ``comms_timeouts`` / ``comms_stale_dropped`` /
  ``comms_corrupt_dropped`` counters, terminal ``run_summary`` event)
  behind the same zero-overhead telemetry-off fence as the solver paths.
* ``bus`` — the hub role the launcher plays (what dpgo_ros' pub/sub does in
  the reference's deployments): ``RoundBus`` gathers one fresh frame per
  live robot per round and rebroadcasts the union; a silent or dead robot
  is detected (closed transport, or consecutive misses with a stale
  heartbeat), excluded, and announced to the survivors, so the solve
  degrades gracefully instead of hanging.  ``BusClient`` is the robot-side
  counterpart, with an overlapped mode (``start_overlap``) that
  double-buffers the publish/collect round against the caller's compute
  under a bounded-staleness knob; ``pack_agent_frame`` /
  ``apply_peer_frame`` serialize the ``PGOAgent`` message vocabulary onto
  the wire.

Failure semantics on peer death: in async mode the dead robot's cached
poses stay frozen in every survivor (the RA-L delay-tolerance argument —
optimization continues against the last received iterate); in sync mode
the dead robot is excluded from the ``should_terminate`` quorum
(``PGOAgent.mark_neighbor_lost``) so the remaining team can still reach
consensus and finish.
"""

from __future__ import annotations

from .faults import FaultInjector, FaultSpec
from .protocol import (
    BF16_REL_ERR,
    CLOCK_KEY,
    DEFAULT_MAX_FRAME_BYTES,
    PACKED_MAGIC,
    TRACE_IDS_KEY,
    TRACE_T_KEY,
    FrameAssembler,
    ProtocolError,
    bf16_decode,
    bf16_encode,
    decode_payload,
    encode_payload,
    pack_pose_arrays,
    pack_pose_dict,
    pack_pose_set,
    pack_trace_entries,
    pose_payload_nbytes,
    recv_frame,
    send_frame,
    unpack_pose_arrays,
    unpack_pose_dict,
    unpack_pose_set,
    unpack_trace_entries,
)
from .reliable import ChannelTotals, ReliableChannel, RetryPolicy
from .transport import (
    LoopbackTransport,
    TcpTransport,
    Transport,
    TransportClosed,
    TransportError,
    TransportTimeout,
    connect_tcp,
    listen_tcp,
)
from .bus import (BusClient, RoundBus, apply_peer_frame,
                  loopback_fleet, pack_agent_frame)

__all__ = [
    "BF16_REL_ERR",
    "BusClient",
    "CLOCK_KEY",
    "ChannelTotals",
    "DEFAULT_MAX_FRAME_BYTES",
    "FaultInjector",
    "FaultSpec",
    "FrameAssembler",
    "LoopbackTransport",
    "PACKED_MAGIC",
    "ProtocolError",
    "ReliableChannel",
    "RetryPolicy",
    "RoundBus",
    "TRACE_IDS_KEY",
    "TRACE_T_KEY",
    "TcpTransport",
    "Transport",
    "TransportClosed",
    "TransportError",
    "TransportTimeout",
    "apply_peer_frame",
    "bf16_decode",
    "bf16_encode",
    "connect_tcp",
    "decode_payload",
    "encode_payload",
    "listen_tcp",
    "loopback_fleet",
    "pack_agent_frame",
    "pack_pose_arrays",
    "pack_pose_dict",
    "pack_pose_set",
    "pack_trace_entries",
    "pose_payload_nbytes",
    "recv_frame",
    "send_frame",
    "unpack_pose_arrays",
    "unpack_pose_dict",
    "unpack_pose_set",
    "unpack_trace_entries",
]
