"""The round bus: hub-and-spoke relay with graceful agent dropout.

The launcher of ``examples/tcp_deployment_example.py`` plays the pub/sub
role the reference delegates to ``dpgo_ros``: it accepts one connection per
robot and, each round, collects one frame from every robot and rebroadcasts
the union (keys namespaced ``r{id}|...``).  ``RoundBus`` is that loop as a
library, made fault-tolerant:

* A robot whose frame misses the round deadline is *not* waited on forever:
  its last known frame is rebroadcast (its poses freeze — the RA-L delay
  tolerance), and a miss is counted.
* A robot is declared **lost** when its transport closes, or after
  ``miss_limit`` consecutive misses with a stale heartbeat (silence, not
  slowness).  Lost robots are excluded from the gather, announced to the
  survivors in the ``_lost`` broadcast key, and the solve continues.
* ``poll`` draining after each fresh frame re-synchronizes a link that
  delay faults pushed a round behind.

``BusClient`` is the robot side: stamp-and-publish, collect with a
deadline (a missed broadcast skips one update, it does not deadlock), and
surface the bus's lost-peer announcements so the agent can adjust its
termination quorum (``PGOAgent.mark_neighbor_lost``).

``pack_agent_frame`` / ``apply_peer_frame`` serialize the ``PGOAgent``
message vocabulary (status gossip, public poses, GNC weights, global
anchor) onto the wire — shared by the TCP example, the in-process async
example, and the chaos tests so every path speaks the same protocol.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import obs
from ..obs import trace
from .protocol import (pack_pose_arrays, pack_pose_dict,
                       pack_trace_entries, unpack_pose_arrays,
                       unpack_pose_set, unpack_trace_entries)
from .reliable import ChannelTotals, ReliableChannel, RetryPolicy
from .transport import TcpTransport, TransportClosed, TransportTimeout


# ---------------------------------------------------------------------------
# Hub side
# ---------------------------------------------------------------------------

def accept_robots(srv, num_robots: int, injector=None,
                  policy: RetryPolicy | None = None,
                  hello_timeout_s: float = 30.0,
                  max_frame_bytes: int | None = None,
                  wire_format: str = "packed"
                  ) -> dict[int, ReliableChannel]:
    """Accept one TCP connection per robot; each must introduce itself with
    a ``{"hello": robot_id}`` frame within the deadline."""
    import socket as _socket

    channels: dict[int, ReliableChannel] = {}
    srv.settimeout(hello_timeout_s)
    while len(channels) < num_robots:
        try:
            conn, _ = srv.accept()
        except _socket.timeout:
            raise ConnectionError(
                f"only {len(channels)}/{num_robots} robots connected "
                f"within {hello_timeout_s}s") from None
        kw = {} if max_frame_bytes is None else \
            {"max_frame_bytes": max_frame_bytes}
        t = TcpTransport(conn, src="bus", dst="?", injector=injector,
                         wire_format=wire_format, **kw)
        ch = ReliableChannel(t, policy=policy, origin=-1)
        hello = ch.recv(timeout=hello_timeout_s)
        rid = int(hello["hello"])
        t.dst = f"robot{rid}"
        ch.name = f"bus->robot{rid}"
        channels[rid] = ch
    return channels


class RoundBus:
    """Gather one fresh frame per live robot, rebroadcast the union."""

    def __init__(self, channels: dict[int, ReliableChannel],
                 round_timeout_s: float = 5.0, miss_limit: int = 3,
                 liveness_timeout_s: float = 2.0):
        self.channels = channels
        self.round_timeout_s = round_timeout_s
        self.miss_limit = miss_limit
        self.liveness_timeout_s = liveness_timeout_s
        self.lost: set[int] = set()
        #: Robots admitted AFTER the bus started (the join handshake);
        #: rebroadcast cumulatively in the ``_joined`` key — like
        #: ``_lost`` — so a drop-lossy link still learns about every
        #: joiner eventually.
        self.joined: set[int] = set()
        self._last_frames: dict[int, dict] = {}
        self._last_seqs: dict[int, int] = {}
        self._misses: dict[int, int] = {rid: 0 for rid in channels}
        self._anom_seen: dict[int, int] = {}  # rid -> last gossiped count
        self.rounds_served = 0
        # Joins land between rounds from any thread (a launcher's accept
        # loop); the relay drains them at the top of its next round.
        self._admit_lock = threading.Lock()
        self._admit_pending: list[tuple[int, ReliableChannel]] = []

    def _mark_lost(self, rid: int, reason: str) -> None:
        if rid in self.lost:
            return
        self.lost.add(rid)
        run = obs.get_run()
        if run is not None:
            run.event("peer_lost", phase="comms", peer=rid, reason=reason,
                      round=self.rounds_served)

    def _gather_one(self, rid: int) -> None:
        ch = self.channels[rid]
        try:
            frame = ch.recv(timeout=self.round_timeout_s)
        except TransportTimeout:
            self._misses[rid] += 1
            age = ch.last_seen_age()
            hb_stale = age is None or age > self.liveness_timeout_s
            if self._misses[rid] >= self.miss_limit and hb_stale:
                self._mark_lost(rid, "silent")
            return
        except TransportClosed:
            self._mark_lost(rid, "closed")
            return
        # Drain to the freshest queued frame: delay faults can leave a link
        # a round behind; the channel's sequence check guarantees each
        # poll() result is strictly newer.  A peer that closed right after
        # its last frame is marked lost here instead of crashing the round.
        try:
            while True:
                newer = ch.poll()
                if newer is None:
                    break
                frame = newer
        except TransportClosed:
            self._mark_lost(rid, "closed")
        self._misses[rid] = 0
        self._last_frames[rid] = frame
        self._last_seqs[rid] = ch.last_recv_seq
        # Fleet-wide numerical health: a robot whose frame gossips a grown
        # anomaly counter gets surfaced on the HUB's event stream (the
        # hub's report renders the fleet view; the robot's own run dir has
        # the detailed anomaly events).
        if "anom" in frame:
            run = obs.get_run()
            count, worst = (int(x) for x in np.asarray(frame["anom"])[:2])
            if run is not None and count > self._anom_seen.get(rid, 0):
                run.event("peer_anomaly", phase="health", peer=rid,
                          count=count,
                          severity=("critical" if worst >= 2 else "warning"),
                          round=self.rounds_served)
            self._anom_seen[rid] = max(self._anom_seen.get(rid, 0), count)

    def admit(self, rid: int, channel: ReliableChannel) -> None:
        """The join handshake, hub side: attach a robot's channel to the
        live relay.  Effective at the start of the next round; the robot
        is announced to the fleet in the cumulative ``_joined`` broadcast
        key so survivors can grow their problems
        (``PGOAgent.admit_neighbor``).  Re-admitting a previously-lost
        robot revives it (fresh channel, miss counters reset)."""
        with self._admit_lock:
            self._admit_pending.append((int(rid), channel))

    def admit_hello(self, channel: ReliableChannel,
                    timeout: float | None = None) -> int:
        """Receive the joiner's ``{"hello": robot_id}`` introduction frame
        (the same vocabulary ``accept_robots`` uses at launch) and admit
        it.  Returns the robot id — the TCP launcher's accept-loop
        helper."""
        hello = channel.recv(timeout=timeout)
        rid = int(hello["hello"])
        channel.name = f"bus->robot{rid}"
        self.admit(rid, channel)
        return rid

    def _drain_admissions(self) -> None:
        with self._admit_lock:
            pending, self._admit_pending = self._admit_pending, []
        for rid, ch in pending:
            stale = self.channels.pop(rid, None)
            if stale is not None and stale is not ch:
                try:
                    stale.close(emit_summary=False)
                except Exception:
                    pass
            self.channels[rid] = ch
            self.lost.discard(rid)
            self._misses[rid] = 0
            self._last_frames.pop(rid, None)
            self._last_seqs.pop(rid, None)
            self.joined.add(rid)
            run = obs.get_run()
            if run is not None:
                run.event("peer_joined", phase="comms", peer=rid,
                          round=self.rounds_served)

    def round(self) -> dict:
        """One relay round; returns the merged broadcast frame."""
        self._drain_admissions()
        # The hub's span (robot = -1): gather + rebroadcast wall-clock,
        # the wire half of every round's critical path.
        sp = trace.span("bus_round", phase="comms", robot=-1,
                        round=self.rounds_served)
        with sp:
            for rid in sorted(self.channels):
                if rid not in self.lost:
                    self._gather_one(rid)
            merged: dict = {}
            for rid, frame in sorted(self._last_frames.items()):
                if rid in self.lost:
                    continue
                merged.update({f"r{rid}|{k}": v for k, v in frame.items()})
                merged[f"r{rid}|_pseq"] = np.asarray(
                    self._last_seqs.get(rid, -1), np.int64)
            merged["_lost"] = np.asarray(sorted(self.lost), np.int64)
            if self.joined:
                merged["_joined"] = np.asarray(sorted(self.joined),
                                               np.int64)
            for rid, ch in sorted(self.channels.items()):
                if rid in self.lost:
                    continue
                try:
                    ch.send(merged, timeout=self.round_timeout_s)
                except (TransportClosed, TransportTimeout):
                    self._mark_lost(rid, "broadcast_failed")
            self.rounds_served += 1
            sp.add(lost=len(self.lost))
        return merged

    def serve(self, total_rounds: int) -> None:
        """Relay ``total_rounds`` rounds, stopping early if every robot is
        gone (nothing left to serve — never hang on a dead fleet)."""
        for _ in range(total_rounds):
            if len(self.lost) == len(self.channels):
                break
            self.round()

    def totals(self) -> ChannelTotals:
        agg = ChannelTotals()
        for ch in self.channels.values():
            agg.add(ch.totals)
        return agg

    def close(self) -> None:
        """Emit one aggregated ``run_summary`` for the hub, close links."""
        run = obs.get_run()
        if run is not None:
            run.event("run_summary", phase="comms", channel="bus",
                      peers_lost=sorted(self.lost),
                      rounds_served=self.rounds_served,
                      **self.totals().as_dict())
        for ch in self.channels.values():
            ch.close(emit_summary=False)


# ---------------------------------------------------------------------------
# Robot side
# ---------------------------------------------------------------------------

class BusClient:
    """A robot's view of the bus: publish, collect, track lost peers.

    **Overlap mode** (``start_overlap``): a background exchange thread
    double-buffers the publish/collect round so the caller's compute (the
    RTR step) runs concurrently with the wire round.  ``exchange`` then
    submits round k's frame and returns the freshest broadcast already
    collected — typically round k-1's — blocking only when the number of
    in-flight exchanges would exceed the ``staleness`` bound.  RBCD's
    convergence is unchanged under bounded staleness (the RA-L 2020 async
    DPGO model), so ``staleness=1`` overlaps compute and comms for free;
    ``staleness=0`` (the default, no thread) is today's lockstep.  The
    overlap composes with the sequence-number/dropout machinery unchanged:
    publishes still ride the ``ReliableChannel`` (stamped ``_seq``), and
    the worker's ``collect`` keeps ``lost`` current.
    """

    def __init__(self, channel: ReliableChannel, robot_id: int):
        self.channel = channel
        self.robot_id = int(robot_id)
        if channel.origin is None:
            channel.origin = self.robot_id  # clock-domain identity
        self.lost: set[int] = set()
        #: Robots the hub admitted mid-run (the ``_joined`` broadcast key);
        #: the driver reacts by growing its agent's problem
        #: (``PGOAgent.admit_neighbor``) for joiners it has not seen.
        self.joined: set[int] = set()
        self.staleness = 0
        # Overlap state is shared between the caller's compute thread and
        # the exchange worker; everything below rides one condition.
        self._ov_cond = threading.Condition()
        self._ov_thread: threading.Thread | None = None
        self._ov_queue: list[dict] = []                # guarded-by: _ov_cond
        self._ov_merged: dict | None = None            # guarded-by: _ov_cond
        self._ov_submitted = 0                         # guarded-by: _ov_cond
        self._ov_done = 0                              # guarded-by: _ov_cond
        self._ov_stop = False                          # guarded-by: _ov_cond
        self._ov_error: Exception | None = None        # guarded-by: _ov_cond

    def hello(self, timeout: float | None = None) -> None:
        self.channel.send({"hello": np.asarray(self.robot_id, np.int64)},
                          timeout=timeout)

    def publish(self, frame: dict, timeout: float | None = None) -> int:
        sp = trace.start_span("publish", phase="comms",
                              robot=self.robot_id)
        if sp is None:
            return self.channel.send(frame, timeout=timeout)
        # The publish span's context rides the frame (both wire codecs,
        # ignored by untraced peers): receivers link their scatter spans
        # to it, which is what joins a round's publish -> exchange ->
        # scatter chain into one causal trace across robots.
        frame = dict(frame)
        frame.update(pack_trace_entries(sp.trace_id, sp.span_id,
                                        self.robot_id))
        try:
            n = self.channel.send(frame, timeout=timeout)
        except Exception:
            sp.end(ok=False)
            raise
        sp.end(bytes=n)
        return n

    def collect(self, timeout: float | None = None) -> dict | None:
        """The next broadcast, or None when the deadline passed (skip this
        round's updates and carry on — the bus caches our last frame).
        Raises ``TransportClosed`` when the bus itself is gone."""
        with trace.span("collect", phase="comms",
                        robot=self.robot_id) as sp:
            try:
                merged = self.channel.recv(timeout=timeout)
            except TransportTimeout:
                sp.add(got=False)
                return None
            sp.add(got=True)
        if "_lost" in merged:
            self.lost = {int(x) for x in np.asarray(merged["_lost"]).ravel()}
        if "_joined" in merged:
            self.joined = {int(x)
                           for x in np.asarray(merged["_joined"]).ravel()}
        return merged

    def exchange(self, frame: dict,
                 timeout: float | None = None) -> dict | None:
        """One round's publish + broadcast.  Lockstep when no overlap
        worker is running; with ``start_overlap`` the call returns the
        freshest collected broadcast within the staleness bound (possibly
        None before the first broadcast lands)."""
        if self._ov_thread is None:
            self.publish(frame, timeout=timeout)
            return self.collect(timeout=timeout)
        # The ONLY time the caller's compute thread blocks on the wire in
        # overlap mode is this staleness gate — its span duration is the
        # un-hidden remainder of the exchange, the number the overlap
        # efficiency report divides by the worker's wire_round time.
        with trace.span("exchange_wait", phase="comms",
                        robot=self.robot_id) as sp:
            with self._ov_cond:
                if self._ov_error is not None:
                    raise self._ov_error
                self._ov_queue.append(frame)
                self._ov_submitted += 1
                sp.add(in_flight=self._ov_submitted - self._ov_done)
                self._ov_cond.notify_all()
                while (self._ov_submitted - self._ov_done > self.staleness
                       and self._ov_error is None):
                    self._ov_cond.wait(timeout=1.0)
                if self._ov_error is not None:
                    raise self._ov_error
                return self._ov_merged

    # -- overlap worker -----------------------------------------------------

    def start_overlap(self, staleness: int = 1,
                      timeout: float | None = None) -> None:
        """Enable double-buffered exchange with the given staleness bound
        (max broadcast rounds the caller may run ahead of the wire;
        ``staleness=0`` keeps lockstep and starts no thread)."""
        if staleness <= 0 or self._ov_thread is not None:
            self.staleness = max(0, int(staleness))
            return
        self.staleness = int(staleness)
        run = obs.get_run()
        if run is not None:
            # Staleness is a convergence-relevant knob: stamp it into the
            # fingerprint so --compare refuses lockstep-vs-overlap deltas.
            run.set_fingerprint(staleness=self.staleness)
        with self._ov_cond:
            # A previous worker may have died on an error mid-run; reset
            # the shared flags under the lock it shares with exchange().
            self._ov_stop = False

        def run():
            while True:
                with self._ov_cond:
                    while not self._ov_queue and not self._ov_stop:
                        self._ov_cond.wait()
                    if self._ov_stop and not self._ov_queue:
                        return
                    frame = self._ov_queue.pop(0)
                merged = None
                err = None
                try:
                    # wire_round parents the publish/collect spans it
                    # drives (same thread) — the worker's whole round is
                    # one span, the hidden half of the overlap.
                    with trace.span("wire_round", phase="comms",
                                    robot=self.robot_id):
                        self.publish(frame, timeout=timeout)
                        merged = self.collect(timeout=timeout)
                except TransportClosed as e:
                    err = e
                except Exception as e:  # surfaced to the next exchange()
                    err = e
                with self._ov_cond:
                    self._ov_done += 1
                    if merged is not None:
                        self._ov_merged = merged
                    if err is not None:
                        self._ov_error = err
                    self._ov_cond.notify_all()
                    if err is not None:
                        return

        self._ov_thread = threading.Thread(
            target=run, name=f"bus-overlap-{self.robot_id}", daemon=True)
        self._ov_thread.start()

    def drain_overlap(self, timeout: float = 30.0) -> dict | None:
        """Block until every submitted exchange completed (the lockstep
        barrier at the end of an overlapped run); returns the last
        broadcast.  Raises the worker's pending error, if any."""
        if self._ov_thread is None:
            with self._ov_cond:
                return self._ov_merged
        end = time.monotonic() + timeout
        with trace.span("drain", phase="comms", robot=self.robot_id):
            with self._ov_cond:
                while self._ov_submitted > self._ov_done:
                    if self._ov_error is not None:
                        raise self._ov_error
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._ov_cond.wait(timeout=remaining)
                return self._ov_merged

    def stop_overlap(self) -> None:
        if self._ov_thread is None:
            return
        with self._ov_cond:
            self._ov_stop = True
            self._ov_cond.notify_all()
        self._ov_thread.join(timeout=10.0)
        self._ov_thread = None

    def peer_frames(self, merged: dict) -> dict[int, dict]:
        """Split a broadcast into per-peer sub-frames (self excluded)."""
        out: dict[int, dict] = {}
        for key, v in merged.items():
            if not key.startswith("r") or "|" not in key:
                continue
            rid_s, sub = key.split("|", 1)
            rid = int(rid_s[1:])
            if rid == self.robot_id:
                continue
            out.setdefault(rid, {})[sub] = v
        return out

    def close(self) -> None:
        self.stop_overlap()
        self.channel.close()


def loopback_fleet(num_robots: int, injector=None,
                   policy: RetryPolicy | None = None,
                   round_timeout_s: float = 2.0, miss_limit: int = 3,
                   liveness_timeout_s: float = 2.0,
                   wire_format: str = "packed"
                   ) -> tuple[RoundBus, dict[int, BusClient]]:
    """An in-process fleet: one ``LoopbackTransport`` pair per robot, the
    hub ends assembled into a ``RoundBus``, the robot ends into
    ``BusClient``s.  The chaos tests and the async example run on this —
    same framing, fault, retry, and dropout code paths as TCP, no
    sockets."""
    from .transport import LoopbackTransport

    channels: dict[int, ReliableChannel] = {}
    clients: dict[int, BusClient] = {}
    for rid in range(num_robots):
        t_bus, t_robot = LoopbackTransport.pair(
            "bus", f"robot{rid}", injector=injector,
            wire_format=wire_format)
        channels[rid] = ReliableChannel(t_bus, f"bus->robot{rid}", policy,
                                        origin=-1)
        clients[rid] = BusClient(
            ReliableChannel(t_robot, f"robot{rid}->bus", policy), rid)
    bus = RoundBus(channels, round_timeout_s=round_timeout_s,
                   miss_limit=miss_limit,
                   liveness_timeout_s=liveness_timeout_s)
    return bus, clients


# ---------------------------------------------------------------------------
# Agent frame vocabulary
# ---------------------------------------------------------------------------

def pack_agent_frame(agent, robust: bool = False,
                     include_anchor: bool = False,
                     wire_dtype: str = "f64",
                     packed: bool = True) -> dict:
    """One round's outgoing frame for a ``PGOAgent``: status gossip, public
    poses, owned GNC weights, and (robot 0) the global anchor.

    ``packed=True`` (default) ships the public poses as one columnar
    ``pose:r/pose:p/pose:x`` set (``wire_dtype`` selects f64/f32/bf16 on
    the wire); ``packed=False`` keeps the per-pose v1 keys for old peers.
    ``apply_peer_frame`` ingests either."""
    st = agent.get_status()
    frame = {"status": np.asarray(
        [st.robot_id, st.state.value, st.instance_number,
         st.iteration_number, int(st.ready_to_terminate)], np.int64),
        "relchange": np.asarray(st.relative_change, np.float64)}
    # Numerical-health gossip: anomaly counters detected locally
    # (obs.health via PGOAgent._obs_anomaly) ride the round frame so the
    # hub's report sees fleet-wide health.  Counters are only ever nonzero
    # when telemetry was on (detection is fenced), so the telemetry-off
    # wire is unchanged.
    anom = getattr(agent, "health_counters", lambda: (0, 0))()
    if anom[0]:
        frame["anom"] = np.asarray(anom, np.int64)
    if packed:
        pub = agent.get_public_pose_arrays()
        if pub is not None:
            frame.update(pack_pose_arrays("pose", *pub,
                                          wire_dtype=wire_dtype))
    else:
        frame.update(pack_pose_dict("pose", agent.get_shared_pose_dict()))
    if robust:
        frame.update({
            f"wt_{r1}_{p1}_{r2}_{p2}": np.asarray(w, np.float64)
            for ((r1, p1), (r2, p2)), w in
            agent.get_shared_weight_dict().items()})
    if include_anchor:
        anchor = agent.get_global_anchor()
        if anchor is not None:
            frame["anchor"] = np.asarray(anchor)
    return frame


def apply_peer_frame(agent, peer_id: int, pf: dict, robust: bool = False,
                     accept_anchor: bool = False) -> None:
    """Ingest one peer's sub-frame into a ``PGOAgent``: status, poses
    (sequence-checked via the bus's ``_pseq`` tag), weights, anchor.

    A trace context riding the sub-frame (the sender's publish span,
    rebroadcast under its ``r{id}|`` namespace) is popped uncondition-
    ally and, when telemetry is on, lands on this ingest's ``scatter``
    span as the ``link_*`` fields the timeline renders as a cross-robot
    flow arrow."""
    ctx = unpack_trace_entries(pf)  # popped even with telemetry off
    anom = pf.pop("anom", None)  # health gossip: popped even with obs off
    if anom is not None:
        run = obs.get_run()
        if run is not None:
            run.gauge("peer_anomalies_seen",
                      "anomaly count gossiped by each peer").set(
                float(np.asarray(anom)[0]), robot=agent.robot_id,
                peer=peer_id)
    sp = trace.start_span("scatter", phase="comms", robot=agent.robot_id,
                          link=ctx)
    try:
        _apply_peer_frame(agent, peer_id, pf, robust, accept_anchor)
    finally:
        if sp is not None:
            sp.end(peer=peer_id)


def _apply_peer_frame(agent, peer_id: int, pf: dict, robust: bool,
                      accept_anchor: bool) -> None:
    from ..agent import AgentState, PGOAgentStatus

    if "status" in pf:
        ps = np.asarray(pf["status"], np.int64)
        agent.set_neighbor_status(PGOAgentStatus(
            robot_id=int(ps[0]), state=AgentState(int(ps[1])),
            instance_number=int(ps[2]), iteration_number=int(ps[3]),
            ready_to_terminate=bool(ps[4]),
            relative_change=float(pf.get("relchange", np.inf))))
    seq = int(pf["_pseq"]) if "_pseq" in pf else None
    packed = unpack_pose_arrays(pf, "pose")
    if packed is not None:
        # Fast path: the columnar set feeds the agent's vectorized
        # neighbor-buffer scatter with no per-pose dict materialization.
        agent.update_neighbor_poses_packed(peer_id, *packed, sequence=seq)
    else:
        agent.update_neighbor_poses(peer_id, unpack_pose_set(pf, "pose"),
                                    sequence=seq)
    if robust:
        wd = {}
        for k, v in pf.items():
            if k.startswith("wt_"):
                _, r1, p1, r2, p2 = k.split("_")
                wd[((int(r1), int(p1)), (int(r2), int(p2)))] = float(v)
        if wd:
            agent.update_shared_weights(wd)
    if accept_anchor and "anchor" in pf:
        agent.set_global_anchor(pf["anchor"])
