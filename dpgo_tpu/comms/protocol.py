"""Wire format: length-prefixed ``npz`` frames (arrays only — no pickle).

A frame on the wire is an 8-byte little-endian unsigned length followed by
an ``np.savez`` archive.  The length header is *untrusted input*: it is
validated against a configurable cap (default 64 MiB) before any buffer is
sized from it, so a corrupt or malicious header raises a clean
``ProtocolError`` instead of attempting an OOM-sized allocation.  Payload
decoding likewise wraps ``np.load`` failures (bit-flipped archives) in
``ProtocolError`` so the fault-tolerance layer can count and drop corrupt
frames rather than crash the robot.

``FrameAssembler`` is the incremental decoder used by the deadline-aware
TCP transport: bytes are fed in as they arrive, complete payloads come out,
and a recv deadline can interrupt mid-frame and resume later without
desynchronizing the stream.
"""

from __future__ import annotations

import io
import socket
import struct

import numpy as np

HEADER = struct.Struct("<Q")
DEFAULT_MAX_FRAME_BYTES = 64 * 2 ** 20  # 64 MiB


class ProtocolError(Exception):
    """The byte stream violates the frame protocol (oversized length
    header, truncated/corrupt npz payload).  Distinct from transport errors:
    the connection may still be usable — the *frame* is bad."""


def encode_payload(arrays: dict) -> bytes:
    """Serialize an array dict to npz bytes (the frame body, no header)."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_payload(data: bytes) -> dict:
    """Decode npz bytes; a mangled archive raises ``ProtocolError``."""
    try:
        with np.load(io.BytesIO(data)) as npz:
            return {k: npz[k] for k in npz.files}
    except Exception as e:  # zipfile/np.load raise a zoo of types
        raise ProtocolError(f"corrupt frame payload ({len(data)} bytes): "
                            f"{e}") from e


def encode_frame(arrays: dict) -> bytes:
    data = encode_payload(arrays)
    return HEADER.pack(len(data)) + data


class FrameAssembler:
    """Incremental length-prefixed frame decoder with a size cap.

    Feed raw bytes as they arrive; completed payloads (undecoded npz bytes)
    come out.  State survives across calls, so a transport can stop reading
    at a deadline mid-frame and resume on the next ``recv``.
    """

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()
        self._length: int | None = None

    def feed(self, data: bytes) -> list[bytes]:
        self._buf.extend(data)
        out = []
        while True:
            if self._length is None:
                if len(self._buf) < HEADER.size:
                    break
                (length,) = HEADER.unpack(bytes(self._buf[:HEADER.size]))
                if length > self.max_frame_bytes:
                    raise ProtocolError(
                        f"frame length header {length} exceeds the "
                        f"{self.max_frame_bytes}-byte cap (corrupt or "
                        "malicious peer?)")
                del self._buf[:HEADER.size]
                self._length = int(length)
            if len(self._buf) < self._length:
                break
            out.append(bytes(self._buf[:self._length]))
            del self._buf[:self._length]
            self._length = None
        return out

    @property
    def pending_bytes(self) -> int:
        """Bytes buffered toward an incomplete frame."""
        return len(self._buf)


# ---------------------------------------------------------------------------
# Blocking socket helpers (the original example wire functions, now capped)
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, arrays: dict) -> int:
    """Send one frame; returns bytes put on the wire."""
    frame = encode_frame(arrays)
    sock.sendall(frame)
    return len(frame)


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> dict:
    """Blocking receive of one frame, header validated against the cap."""

    def recv_exact(k):
        chunks = []
        while k:
            c = sock.recv(k)
            if not c:
                raise ConnectionError("peer closed")
            chunks.append(c)
            k -= len(c)
        return b"".join(chunks)

    (length,) = HEADER.unpack(recv_exact(HEADER.size))
    if length > max_frame_bytes:
        raise ProtocolError(
            f"frame length header {length} exceeds the "
            f"{max_frame_bytes}-byte cap (corrupt or malicious peer?)")
    return decode_payload(recv_exact(int(length)))


# ---------------------------------------------------------------------------
# Pose-dictionary packing (the agent message vocabulary on the wire)
# ---------------------------------------------------------------------------

def pack_pose_dict(prefix: str, pose_dict: dict) -> dict:
    """Flatten {(robot, pose): block} to npz-safe ``{prefix}_{r}_{p}`` keys."""
    return {f"{prefix}_{r}_{p}": np.asarray(block)
            for (r, p), block in pose_dict.items()}


def unpack_pose_dict(frame: dict, prefix: str) -> dict:
    out = {}
    for key, arr in frame.items():
        if key.startswith(prefix + "_"):
            _, r, p = key.rsplit("_", 2)
            out[(int(r), int(p))] = arr
    return out
