"""Wire format: length-prefixed frames (arrays only — no pickle).

A frame on the wire is an 8-byte little-endian unsigned length followed by
a payload in one of two self-describing formats:

* **packed (v2, the default)** — a raw little-endian columnar encoding:
  magic ``DPW2``, a CRC32 of the body, then per entry a UTF-8 key, the
  numpy dtype string, the shape, and the array bytes verbatim
  (``tobytes``).  Decoding is zero-copy: each array is a ``frombuffer``
  view into the received byte buffer, so a pose frame costs one
  allocation for the socket read and nothing per array.
* **npz (v1, the versioned fallback)** — an ``np.savez`` archive (one zip
  member per array).  Old peers send this; ``decode_payload`` sniffs the
  leading magic, so a fleet can mix v1 and v2 senders during a rolling
  upgrade (``Transport(wire_format="npz")`` keeps a new robot speaking v1
  to an old bus).

The length header is *untrusted input*: it is validated against a
configurable cap (default 64 MiB) before any buffer is sized from it, so a
corrupt or malicious header raises a clean ``ProtocolError`` instead of
attempting an OOM-sized allocation.  Payload decoding likewise wraps
failures (bit-flipped archives, CRC mismatches, truncated packed bodies)
in ``ProtocolError`` so the fault-tolerance layer can count and drop
corrupt frames rather than crash the robot.

``FrameAssembler`` is the incremental decoder used by the deadline-aware
TCP transport: bytes are fed in as they arrive, complete payloads come out,
and a recv deadline can interrupt mid-frame and resume later without
desynchronizing the stream.

Pose-set packing (the deployment hot path): ``pack_pose_set`` lays a
``{(robot, pose): block}`` dict out as ONE contiguous ``[k, r, d+1]``
payload plus int32 robot/pose index vectors — three arrays total instead
of one zip member per pose — with an opt-in bf16 wire dtype (values are
rounded to bfloat16 on send and accumulated in f32/f64 on receipt; see
``bf16_encode``).  ``pack_pose_dict`` remains the per-pose v1 vocabulary;
``unpack_pose_set`` reads either.
"""

from __future__ import annotations

import io
import socket
import struct
import time
import zlib

import numpy as np

HEADER = struct.Struct("<Q")
DEFAULT_MAX_FRAME_BYTES = 64 * 2 ** 20  # 64 MiB

#: Packed-payload (v2) leading magic.  An npz body starts with zip's
#: ``PK\x03\x04``, so the first bytes unambiguously select the decoder.
PACKED_MAGIC = b"DPW2"
_PACKED_HEAD = struct.Struct("<4sII")     # magic, crc32(body), n_entries
_ENTRY_HEAD = struct.Struct("<HBB")       # key_len, dtype_len, ndim


class ProtocolError(Exception):
    """The byte stream violates the frame protocol (oversized length
    header, truncated/corrupt payload, CRC mismatch).  Distinct from
    transport errors: the connection may still be usable — the *frame* is
    bad."""


def encode_payload_npz(arrays: dict) -> bytes:
    """Serialize an array dict to npz bytes (the v1 frame body)."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def encode_payload_packed(arrays: dict) -> bytes:
    """Serialize an array dict to the packed v2 frame body: raw
    little-endian header + ``tobytes`` per array, CRC32-protected."""
    parts = []
    for key, arr in arrays.items():
        a = np.asarray(arr)
        kb = key.encode("utf-8")
        dt = np.dtype(a.dtype).str.encode("ascii")
        if len(kb) > 0xFFFF or len(dt) > 0xFF or a.ndim > 0xFF:
            raise ProtocolError(f"unencodable entry {key!r}: "
                                f"key/dtype/ndim out of range")
        parts.append(_ENTRY_HEAD.pack(len(kb), len(dt), a.ndim))
        parts.append(kb)
        parts.append(dt)
        parts.append(struct.pack(f"<{a.ndim}I", *a.shape))
        parts.append(struct.pack("<Q", a.nbytes))
        parts.append(np.ascontiguousarray(a).tobytes())
    body = b"".join(parts)
    return _PACKED_HEAD.pack(PACKED_MAGIC, zlib.crc32(body),
                             len(arrays)) + body


def decode_payload_packed(data: bytes) -> dict:
    """Decode a packed v2 body into ``frombuffer`` views (zero-copy: the
    returned arrays alias ``data`` and are read-only)."""
    try:
        magic, crc, n_entries = _PACKED_HEAD.unpack_from(data, 0)
        if magic != PACKED_MAGIC:
            raise ProtocolError("bad packed-frame magic")
        body = memoryview(data)[_PACKED_HEAD.size:]
        if zlib.crc32(body) != crc:
            raise ProtocolError("packed-frame CRC mismatch")
        out = {}
        pos = 0
        for _ in range(n_entries):
            key_len, dt_len, ndim = _ENTRY_HEAD.unpack_from(body, pos)
            pos += _ENTRY_HEAD.size
            key = bytes(body[pos:pos + key_len]).decode("utf-8")
            pos += key_len
            dt = np.dtype(bytes(body[pos:pos + dt_len]).decode("ascii"))
            pos += dt_len
            shape = struct.unpack_from(f"<{ndim}I", body, pos)
            pos += 4 * ndim
            (nbytes,) = struct.unpack_from("<Q", body, pos)
            pos += 8
            count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
            if nbytes != count * dt.itemsize or pos + nbytes > len(body):
                raise ProtocolError(
                    f"packed entry {key!r} inconsistent with body")
            # 0-d entries reshape to () like their npz counterparts.
            arr = np.frombuffer(body, dt, count,
                                offset=pos).reshape(shape)
            pos += nbytes
            out[key] = arr
        if pos != len(body):
            raise ProtocolError(f"{len(body) - pos} trailing bytes after "
                                "the last packed entry")
        return out
    except ProtocolError:
        raise
    except Exception as e:  # struct/unicode/dtype errors on mangled bytes
        raise ProtocolError(f"corrupt packed frame ({len(data)} bytes): "
                            f"{e}") from e


def encode_payload(arrays: dict, wire_format: str = "packed") -> bytes:
    """Serialize an array dict to a frame body (no length header).

    ``wire_format="packed"`` (default) emits the v2 columnar layout;
    ``"npz"`` keeps the v1 archive for old peers.  ``decode_payload``
    accepts either regardless of what this endpoint sends.
    """
    if wire_format == "npz":
        return encode_payload_npz(arrays)
    if wire_format != "packed":
        raise ValueError(f"unknown wire_format {wire_format!r}")
    return encode_payload_packed(arrays)


def decode_payload(data: bytes) -> dict:
    """Decode a frame body, sniffing the format off the leading magic; a
    mangled body of either format raises ``ProtocolError``."""
    if data[:4] == PACKED_MAGIC:
        return decode_payload_packed(data)
    try:
        with np.load(io.BytesIO(data)) as npz:
            return {k: npz[k] for k in npz.files}
    except Exception as e:  # zipfile/np.load raise a zoo of types
        raise ProtocolError(f"corrupt frame payload ({len(data)} bytes): "
                            f"{e}") from e


def encode_frame(arrays: dict, wire_format: str = "packed") -> bytes:
    data = encode_payload(arrays, wire_format)
    return HEADER.pack(len(data)) + data


class FrameAssembler:
    """Incremental length-prefixed frame decoder with a size cap.

    Feed raw bytes as they arrive; completed payloads (undecoded npz bytes)
    come out.  State survives across calls, so a transport can stop reading
    at a deadline mid-frame and resume on the next ``recv``.
    """

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()
        self._length: int | None = None

    def feed(self, data: bytes) -> list[bytes]:
        self._buf.extend(data)
        out = []
        while True:
            if self._length is None:
                if len(self._buf) < HEADER.size:
                    break
                (length,) = HEADER.unpack(bytes(self._buf[:HEADER.size]))
                if length > self.max_frame_bytes:
                    raise ProtocolError(
                        f"frame length header {length} exceeds the "
                        f"{self.max_frame_bytes}-byte cap (corrupt or "
                        "malicious peer?)")
                del self._buf[:HEADER.size]
                self._length = int(length)
            if len(self._buf) < self._length:
                break
            out.append(bytes(self._buf[:self._length]))
            del self._buf[:self._length]
            self._length = None
        return out

    @property
    def pending_bytes(self) -> int:
        """Bytes buffered toward an incomplete frame."""
        return len(self._buf)


# ---------------------------------------------------------------------------
# Blocking socket helpers (the original example wire functions, now capped)
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, arrays: dict) -> int:
    """Send one frame; returns bytes put on the wire."""
    frame = encode_frame(arrays)
    sock.sendall(frame)
    return len(frame)


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> dict:
    """Blocking receive of one frame, header validated against the cap."""

    def recv_exact(k):
        chunks = []
        while k:
            c = sock.recv(k)
            if not c:
                raise ConnectionError("peer closed")
            chunks.append(c)
            k -= len(c)
        return b"".join(chunks)

    (length,) = HEADER.unpack(recv_exact(HEADER.size))
    if length > max_frame_bytes:
        raise ProtocolError(
            f"frame length header {length} exceeds the "
            f"{max_frame_bytes}-byte cap (corrupt or malicious peer?)")
    return decode_payload(recv_exact(int(length)))


# ---------------------------------------------------------------------------
# bf16 wire dtype (opt-in): round-to-nearest-even truncation to the high
# 16 bits of f32, shipped as uint16 — dependency-free (no ml_dtypes on the
# wire) and codec-agnostic (rides packed v2 and npz alike).
# ---------------------------------------------------------------------------

#: Documented bf16 wire parity bound: round-to-nearest bfloat16 keeps 7
#: explicit mantissa bits, so per-element relative error is at most
#: 2^-8 (half an ULP).  Tests assert round-trip error against this.
BF16_REL_ERR = 2.0 ** -8


def bf16_encode(arr: np.ndarray) -> np.ndarray:
    """f32/f64 -> uint16 holding the round-to-nearest-even bfloat16 bits."""
    f = np.ascontiguousarray(arr, np.float32)
    u = f.view(np.uint32)
    u = u + 0x7FFF + ((u >> 16) & 1)  # RNE: break ties toward even
    return (u >> 16).astype(np.uint16)


def bf16_decode(u16: np.ndarray) -> np.ndarray:
    """uint16 bfloat16 bits -> f32 (exact: bf16 embeds in f32)."""
    u = np.asarray(u16, np.uint32) << np.uint32(16)
    return u.view(np.float32)


# ---------------------------------------------------------------------------
# Pose-dictionary packing (the agent message vocabulary on the wire)
# ---------------------------------------------------------------------------

def pack_pose_dict(prefix: str, pose_dict: dict) -> dict:
    """Flatten {(robot, pose): block} to npz-safe ``{prefix}_{r}_{p}`` keys
    (the v1 per-pose vocabulary — one frame entry per pose block)."""
    return {f"{prefix}_{r}_{p}": np.asarray(block)
            for (r, p), block in pose_dict.items()}


def unpack_pose_dict(frame: dict, prefix: str) -> dict:
    out = {}
    for key, arr in frame.items():
        if key.startswith(prefix + "_"):
            _, r, p = key.rsplit("_", 2)
            out[(int(r), int(p))] = arr
    return out


# -- packed pose sets (v2 vocabulary: 3 frame entries for ANY pose count) ---

def pack_pose_arrays(prefix: str, robots: np.ndarray, poses: np.ndarray,
                     vals: np.ndarray, wire_dtype: str = "f64") -> dict:
    """Columnar pose payload: ``{prefix}:r`` / ``{prefix}:p`` int32 index
    vectors plus one contiguous ``[k, r, d+1]`` value payload
    (``{prefix}:x``, or ``{prefix}:xb`` uint16 when ``wire_dtype="bf16"``).
    """
    out = {f"{prefix}:r": np.asarray(robots, np.int32),
           f"{prefix}:p": np.asarray(poses, np.int32)}
    if wire_dtype == "bf16":
        out[f"{prefix}:xb"] = bf16_encode(vals)
    elif wire_dtype == "f32":
        out[f"{prefix}:x"] = np.asarray(vals, np.float32)
    elif wire_dtype == "f64":
        out[f"{prefix}:x"] = np.asarray(vals, np.float64)
    else:
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    return out


def pack_pose_set(prefix: str, pose_dict: dict,
                  wire_dtype: str = "f64") -> dict:
    """``pack_pose_arrays`` from a ``{(robot, pose): block}`` dict."""
    if not pose_dict:
        return {}
    keys = list(pose_dict)
    robots = np.fromiter((k[0] for k in keys), np.int32, len(keys))
    poses = np.fromiter((k[1] for k in keys), np.int32, len(keys))
    vals = np.stack([np.asarray(pose_dict[k]) for k in keys])
    return pack_pose_arrays(prefix, robots, poses, vals, wire_dtype)


def unpack_pose_arrays(frame: dict, prefix: str):
    """The packed-pose fast path: ``(robots, poses, vals_f64)`` with no
    per-pose Python, or None when the frame carries no packed set under
    ``prefix``.  bf16 payloads are widened through f32 on receipt (f32
    accumulate) before the f64 cast."""
    ri = frame.get(f"{prefix}:r")
    if ri is None:
        return None
    pi = frame[f"{prefix}:p"]
    xb = frame.get(f"{prefix}:xb")
    if xb is not None:
        vals = np.asarray(bf16_decode(np.asarray(xb)), np.float64)
    else:
        vals = np.asarray(frame[f"{prefix}:x"], np.float64)
    return (np.asarray(ri, np.int64).ravel(),
            np.asarray(pi, np.int64).ravel(), vals)


def unpack_pose_set(frame: dict, prefix: str) -> dict:
    """Pose dict from a frame in EITHER vocabulary: the packed ``:r/:p/:x``
    triplet when present, else the per-pose v1 keys."""
    packed = unpack_pose_arrays(frame, prefix)
    if packed is None:
        return unpack_pose_dict(frame, prefix)
    robots, poses, vals = packed
    return {(int(r), int(p)): vals[i]
            for i, (r, p) in enumerate(zip(robots, poses))}


# -- measurement batches (the serve-fleet RPC vocabulary) -------------------

def pack_measurements(prefix: str, meas) -> dict:
    """Columnar ``types.Measurements`` payload: the full struct-of-arrays
    batch as 12 frame entries under ``prefix`` — edge indices int32,
    value/precision columns float64, the inlier flags uint8.  Unlike the
    g2o-bytes upload this round-trips EVERYTHING (multi-robot indexing,
    GNC weights, known-inlier flags) bit-exactly, which is what lets an
    out-of-process fleet replica solve the same problem its parent
    constructed in memory."""
    return {
        f"{prefix}:d": np.int32(meas.d),
        f"{prefix}:n": np.int32(meas.num_poses),
        f"{prefix}:r1": np.asarray(meas.r1, np.int32),
        f"{prefix}:p1": np.asarray(meas.p1, np.int32),
        f"{prefix}:r2": np.asarray(meas.r2, np.int32),
        f"{prefix}:p2": np.asarray(meas.p2, np.int32),
        f"{prefix}:R": np.asarray(meas.R, np.float64),
        f"{prefix}:t": np.asarray(meas.t, np.float64),
        f"{prefix}:k": np.asarray(meas.kappa, np.float64),
        f"{prefix}:tau": np.asarray(meas.tau, np.float64),
        f"{prefix}:w": np.asarray(meas.weight, np.float64),
        f"{prefix}:in": np.asarray(meas.is_known_inlier, np.uint8),
    }


def unpack_measurements(frame: dict, prefix: str):
    """The ``Measurements`` under ``prefix``, or None when the frame does
    not carry one (``{prefix}:d`` absent)."""
    from ..types import Measurements  # local: protocol stays types-light

    if f"{prefix}:d" not in frame:
        return None
    return Measurements(
        d=int(np.asarray(frame[f"{prefix}:d"])),
        num_poses=int(np.asarray(frame[f"{prefix}:n"])),
        r1=np.asarray(frame[f"{prefix}:r1"], np.int64),
        p1=np.asarray(frame[f"{prefix}:p1"], np.int64),
        r2=np.asarray(frame[f"{prefix}:r2"], np.int64),
        p2=np.asarray(frame[f"{prefix}:p2"], np.int64),
        R=np.asarray(frame[f"{prefix}:R"], np.float64),
        t=np.asarray(frame[f"{prefix}:t"], np.float64),
        kappa=np.asarray(frame[f"{prefix}:k"], np.float64),
        tau=np.asarray(frame[f"{prefix}:tau"], np.float64),
        weight=np.asarray(frame[f"{prefix}:w"], np.float64),
        is_known_inlier=np.asarray(frame[f"{prefix}:in"], bool),
    )


# ---------------------------------------------------------------------------
# Trace context + clock stamps (the distributed-tracing wire vocabulary)
# ---------------------------------------------------------------------------

#: Optional trace-context entries a sender MAY attach to any frame: ids as
#: one int64 triplet, send timestamps as one float64 pair.  They ride both
#: codecs unchanged (just two more dict entries) and old peers ignore the
#: keys — ``unpack_pose_*`` matches on the pose prefix, ``apply_peer_frame``
#: pops them before parsing — so mixed traced/untraced fleets interoperate.
TRACE_IDS_KEY = "_trace"    # int64 [trace_id, span_id, sender_robot]
TRACE_T_KEY = "_trace_t"    # float64 [t_send_mono, t_send_wall]

#: Channel-level clock stamp (``ReliableChannel`` attaches one per outgoing
#: frame — heartbeats included — when telemetry is on): float64
#: [origin, t_send_mono, t_send_wall].  ``origin`` is the sender's robot id,
#: -1 for the bus hub, -2 when unknown.  The receiver pops it and records a
#: ``clock_sample`` event; ``obs.timeline`` estimates pairwise clock
#: offsets from the send/receive timestamp pairs.
CLOCK_KEY = "_ts"

#: Named negative ``origin`` / trace ``robot`` sentinels.  Robot ids are
#: non-negative; everything else on a timeline identifies itself with one
#: of these.  ``obs.timeline`` maps the serving-plane pair (<= -3) onto
#: the host track, the hub onto the bus track.
ORIGIN_BUS_HUB = -1
ORIGIN_UNKNOWN = -2
ORIGIN_SERVE_CLIENT = -3   # serve front-end client (solve_g2o)
ORIGIN_SERVE_SERVER = -4   # serve server/worker side
ORIGIN_FLEET_PARENT = -5   # fleet launcher/manager parent process

#: Fleet-plane actor id bands (ISSUE 20): every process on a merged
#: generation timeline identifies itself with one id.  Robots stay
#: non-negative and the serving sentinels keep -1..-5; multihost ranks
#: occupy -100-rank and out-of-process replicas -200-index, so
#: ``obs.timeline`` can give each process its own track and the clock
#: aligner can tell the launcher, every rank, and every replica apart.
_MH_RANK_BASE = 100
_PROC_REPLICA_BASE = 200


def mh_rank_actor(rank: int) -> int:
    """Timeline actor id of multihost rank ``rank`` (rank 0 -> -100)."""
    return -(_MH_RANK_BASE + int(rank))


def proc_replica_actor(replica_id) -> int:
    """Timeline actor id of an out-of-process replica.  Accepts an index
    or a replica-id string (``"r3"`` -> -203); non-numeric ids hash into
    the band deterministically."""
    if isinstance(replica_id, (int, np.integer)):
        idx = int(replica_id)
    else:
        digits = "".join(ch for ch in str(replica_id) if ch.isdigit())
        idx = int(digits) if digits else \
            sum(str(replica_id).encode("utf-8")) % 97
    return -(_PROC_REPLICA_BASE + abs(idx))


def pack_trace_entries(trace_id: int, span_id: int, robot: int) -> dict:
    """The optional trace-context frame entries for one outgoing message,
    stamped with the send time."""
    return {
        TRACE_IDS_KEY: np.asarray([trace_id, span_id, robot], np.int64),
        TRACE_T_KEY: np.asarray([time.monotonic(), time.time()],
                                np.float64),
    }


def unpack_trace_entries(frame: dict, pop: bool = True):
    """``(trace_id, span_id, robot, t_send_mono, t_send_wall)`` from a
    frame carrying trace context, else None.  ``pop=True`` (default)
    removes the entries so downstream parsers never see them.  A mangled
    context is dropped (None), never fatal — tracing must not break the
    data path."""
    get = frame.pop if pop else frame.get
    ids = get(TRACE_IDS_KEY, None)
    ts = get(TRACE_T_KEY, None)
    if ids is None or ts is None:
        return None
    try:
        ids = np.asarray(ids, np.int64).ravel()
        ts = np.asarray(ts, np.float64).ravel()
        return (int(ids[0]), int(ids[1]), int(ids[2]),
                float(ts[0]), float(ts[1]))
    except (ValueError, IndexError, TypeError):
        return None


def attach_clock(frame: dict, origin: int) -> dict:
    """Stamp ``frame`` with the channel-level clock entry — the SAME
    float64 triplet ``ReliableChannel`` attaches ([origin, t_send_mono,
    t_send_wall] under ``CLOCK_KEY``) — and return it.  Callers guard on
    ``obs.get_run()``: with telemetry off no stamp is attached and the
    wire stays byte-identical."""
    frame[CLOCK_KEY] = np.asarray(
        [float(origin), time.monotonic(), time.time()], np.float64)
    return frame


def pop_clock(frame: dict):
    """``(origin, t_send_mono, t_send_wall)`` popped off a stamped frame,
    else None.  Always pops (mixed telemetry-on/off peers interoperate);
    a mangled stamp is dropped, never fatal."""
    ts = frame.pop(CLOCK_KEY, None)
    if ts is None:
        return None
    try:
        ts = np.asarray(ts, np.float64).ravel()
        return (int(ts[0]), float(ts[1]), float(ts[2]))
    except (ValueError, IndexError, TypeError):
        return None


def pose_payload_nbytes(frame: dict, prefix: str) -> int:
    """Wire bytes of the pose set under ``prefix`` — read off the packed
    entries directly (no per-block iteration) when present."""
    n = 0
    for suffix in (":r", ":p", ":x", ":xb"):
        arr = frame.get(prefix + suffix)
        if arr is not None:
            n += np.asarray(arr).nbytes
    if n:
        return n
    return sum(np.asarray(v).nbytes for k, v in frame.items()
               if k.startswith(prefix + "_"))
