"""``ReliableChannel``: the fault-tolerance layer over any ``Transport``.

What it adds on top of a raw transport:

* **Deadlines** — every send/recv carries a timeout (per-call override or
  the ``RetryPolicy`` default); a silent peer costs a bounded wait, never a
  hang.
* **Bounded retry with exponential backoff + jitter** — sends that time
  out are retried up to ``max_attempts`` with ``base * 2^k`` sleeps,
  jittered so a fleet of robots retrying in lockstep doesn't synchronize.
* **Sequence numbers** — every outgoing frame is stamped with a monotonic
  ``_seq``; the receiver drops frames at or below the highest sequence
  already seen (stale, reordered, or duplicated by the network), so a
  delayed pose frame can never roll an agent's neighbor cache backwards.
* **Corrupt-frame rejection** — ``ProtocolError`` frames are counted and
  skipped; the recv deadline bounds how long a poisoned stream is drained.
* **Heartbeats** — an optional background thread sends tiny ``_kind="hb"``
  frames; any valid incoming frame refreshes ``last_seen_age()``, giving
  the caller (the bus, the launcher) a liveness signal that distinguishes
  a slow peer from a dead one.

Every failure is visible: plain-int ``ChannelTotals`` always count (they
feed the terminal ``run_summary`` event), and when a ``dpgo_tpu.obs`` run
is ambient the channel also records ``comms_retries`` /
``comms_timeouts`` / ``comms_stale_dropped`` / ``comms_corrupt_dropped``
counters — behind the same ``get_run() is None`` early exit as every other
instrumented hot path, so telemetry off adds zero obs work.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib

import numpy as np

from .. import obs
from ..obs import trace
from .protocol import CLOCK_KEY, ProtocolError
from .transport import Transport, TransportClosed, TransportTimeout

_RESERVED = ("_seq", "_kind", CLOCK_KEY)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Send retry and default-deadline knobs."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5                  # multiplicative jitter fraction
    send_timeout_s: float | None = 5.0   # per-attempt send deadline
    recv_timeout_s: float | None = 5.0   # default recv deadline

    def backoff_s(self, attempt: int, rng: np.random.Generator) -> float:
        base = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        return base * (1.0 + self.jitter * float(rng.uniform()))


@dataclasses.dataclass
class ChannelTotals:
    """Always-on plain-int accounting (fed to the ``run_summary`` event)."""

    messages_sent: int = 0
    messages_received: int = 0
    bytes_sent: int = 0
    bytes_received: int = 0
    retries: int = 0
    timeouts: int = 0
    stale_dropped: int = 0
    corrupt_dropped: int = 0
    heartbeats_sent: int = 0
    heartbeats_received: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def add(self, other: "ChannelTotals") -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name,
                    getattr(self, f.name) + getattr(other, f.name))


class ReliableChannel:
    """One fault-tolerant endpoint over a ``Transport``."""

    def __init__(self, transport: Transport, name: str = "",
                 policy: RetryPolicy | None = None,
                 origin: int | None = None):
        self.transport = transport
        self.name = name or f"{transport.src}->{transport.dst}"
        self.policy = policy or RetryPolicy()
        # Clock-domain identity stamped on outgoing frames when telemetry
        # is on: the sending robot's id, -1 for the bus hub, None =
        # unknown (stamped as -2; timeline skips such samples).
        self.origin = origin
        self.totals = ChannelTotals()
        self._send_lock = threading.Lock()
        self._seq = 0
        self._last_seq = -1          # highest sequence accepted from peer
        self.last_recv_seq = -1      # sequence of the last returned frame
        self._last_seen: float | None = None
        self._rng = np.random.default_rng(zlib.crc32(self.name.encode()))
        self._hb_stop: threading.Event | None = None
        self._hb_thread: threading.Thread | None = None
        self._closed = False

    # -- obs (zero work when no run is ambient) -----------------------------

    def _obs_inc(self, counter: str, help_: str, n: int = 1) -> None:
        run = obs.get_run()
        if run is None:
            return
        run.counter(counter, help_).inc(n, channel=self.name)

    # -- send ---------------------------------------------------------------

    def send(self, arrays: dict, timeout: float | None = None,
             kind: str = "data", retry: bool = True) -> int:
        """Send one frame with the retry policy; returns wire bytes of the
        successful attempt.  Raises ``TransportTimeout`` when every attempt
        timed out, ``TransportClosed`` when the link is gone (not retried —
        a closed peer does not come back on backoff)."""
        if timeout is None:
            timeout = self.policy.send_timeout_s
        with self._send_lock:
            seq = self._seq
            self._seq += 1
        frame = dict(arrays)
        frame["_seq"] = np.asarray(seq, np.int64)
        frame["_kind"] = np.asarray(kind)
        run = obs.get_run()
        t0_mono = t0_wall = 0.0
        if run is not None:
            t0_mono, t0_wall = time.monotonic(), time.time()
        attempts = self.policy.max_attempts if retry else 1
        for attempt in range(attempts):
            if run is not None:
                # Clock stamp, refreshed per attempt so the receiver's
                # clock_sample pairs the bytes that actually arrived.
                origin = -2 if self.origin is None else int(self.origin)
                frame[CLOCK_KEY] = np.asarray(
                    [float(origin), time.monotonic(), time.time()],
                    np.float64)
            try:
                n = self.transport.send(frame, timeout=timeout)
            except TransportTimeout:
                self.totals.timeouts += 1
                self._obs_inc("comms_timeouts",
                              "send/recv deadline expirations")
                if attempt + 1 >= attempts:
                    if run is not None and kind != "hb":
                        trace.emit_span(
                            run, "send_failed", t0_mono, t0_wall,
                            time.monotonic() - t0_mono, phase="comms",
                            robot=self.origin, channel=self.name,
                            attempts=attempt + 1)
                    raise
                self.totals.retries += 1
                self._obs_inc("comms_retries", "frame send retries")
                time.sleep(self.policy.backoff_s(attempt, self._rng))
                continue
            if kind == "hb":
                self.totals.heartbeats_sent += 1
            else:
                self.totals.messages_sent += 1
                self.totals.bytes_sent += n
            if run is not None and attempt > 0 and kind != "hb":
                # Only retried sends earn a span: the wire round itself is
                # already covered by the bus client's publish span, and a
                # clean send would double the event volume for nothing.
                trace.emit_span(run, "send_retry", t0_mono, t0_wall,
                                time.monotonic() - t0_mono, phase="comms",
                                robot=self.origin, channel=self.name,
                                attempts=attempt + 1, bytes=n)
            return n
        raise AssertionError("unreachable")

    # -- recv ---------------------------------------------------------------

    def recv(self, timeout: float | None = None) -> dict:
        """Receive the next *fresh data* frame (heartbeats refresh liveness
        and are consumed; stale/corrupt frames are counted and skipped).
        Raises ``TransportTimeout`` at the deadline."""
        return self._recv(timeout, count_timeout=True)

    def poll(self) -> dict | None:
        """Non-blocking recv: the freshest immediately-available data frame,
        or None.  Used by the bus to drain a link back to the present after
        delay faults put it behind."""
        try:
            return self._recv(0.0, count_timeout=False)
        except TransportTimeout:
            return None

    def _recv(self, timeout: float | None, count_timeout: bool) -> dict:
        if timeout is None:
            timeout = self.policy.recv_timeout_s
        end = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if end is None else end - time.monotonic()
            try:
                frame = self.transport.recv(
                    timeout=remaining if remaining is None
                    else max(0.0, remaining))
            except ProtocolError:
                self.totals.corrupt_dropped += 1
                self._obs_inc("comms_corrupt_dropped",
                              "frames dropped as undecodable")
                continue
            except TransportTimeout:
                if count_timeout:
                    self.totals.timeouts += 1
                    self._obs_inc("comms_timeouts",
                                  "send/recv deadline expirations")
                raise
            self._last_seen = time.monotonic()
            kind = str(frame.pop("_kind")) if "_kind" in frame else "data"
            seq = int(frame.pop("_seq")) if "_seq" in frame else None
            # The sender's clock stamp is popped unconditionally (a traced
            # peer may be talking to an untraced one) but only becomes a
            # clock_sample event when telemetry is on locally.
            ts = frame.pop(CLOCK_KEY, None)
            if ts is not None:
                run = obs.get_run()
                if run is not None:
                    try:
                        src = int(np.asarray(ts).ravel()[0])
                        if src != -2:
                            run.event(
                                "clock_sample", phase="comms", src=src,
                                dst=(-2 if self.origin is None
                                     else int(self.origin)),
                                channel=self.name, kind=kind,
                                t_send_mono=float(np.asarray(ts)[1]),
                                t_send_wall=float(np.asarray(ts)[2]))
                    except (ValueError, IndexError, TypeError):
                        pass  # mangled stamp: tracing never breaks data
            if kind == "hb":
                self.totals.heartbeats_received += 1
                continue
            if seq is not None:
                if seq <= self._last_seq:
                    self.totals.stale_dropped += 1
                    self._obs_inc("comms_stale_dropped",
                                  "frames dropped as stale/reordered")
                    continue
                self._last_seq = seq
                self.last_recv_seq = seq
            self.totals.messages_received += 1
            self.totals.bytes_received += sum(
                np.asarray(v).nbytes for v in frame.values())
            return frame

    # -- liveness -----------------------------------------------------------

    def start_heartbeat(self, interval_s: float = 0.25) -> None:
        """Background liveness beacon; safe alongside concurrent sends
        (the transport serializes frame writes)."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        stop = threading.Event()
        self._hb_stop = stop

        def run():
            while not stop.wait(interval_s):
                try:
                    self.send({}, timeout=interval_s, kind="hb", retry=False)
                except TransportTimeout:
                    continue
                except (TransportClosed, ProtocolError, OSError):
                    return

        self._hb_thread = threading.Thread(
            target=run, name=f"comms-hb-{self.name}", daemon=True)
        self._hb_thread.start()

    def last_seen_age(self) -> float | None:
        """Seconds since the last valid frame (heartbeats count), or None
        when nothing has ever arrived."""
        if self._last_seen is None:
            return None
        return time.monotonic() - self._last_seen

    # -- lifecycle ----------------------------------------------------------

    def close(self, emit_summary: bool = True) -> None:
        """Stop heartbeating, emit the terminal ``run_summary`` obs event
        (when a run is ambient), close the transport.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        if emit_summary:
            run = obs.get_run()
            if run is not None:
                run.event("run_summary", phase="comms", channel=self.name,
                          **self.totals.as_dict())
        self.transport.close()

    @property
    def closed(self) -> bool:
        return self._closed
