"""The ``Transport`` abstraction and its two shipped implementations.

A transport moves one npz array-dict frame at a time between two endpoints,
with an optional recv/send deadline and an optional ``FaultInjector`` on
the send side.  It is deliberately dumb: no retries, no sequence numbers,
no liveness — that is ``reliable.ReliableChannel``'s job, layered on top of
any transport.

* ``LoopbackTransport`` — an in-process pair over delay-aware inboxes
  (condition variables, no sockets).  This is what the chaos tests and the
  in-process async example run on: deterministic, fast, and it exercises
  the exact same framing/fault/retry code paths as TCP because frames are
  encoded to bytes even in-process (so corruption faults and the frame cap
  behave identically).
* ``TcpTransport`` — length-prefixed npz over a connected socket (the wire
  code previously living inside ``examples/tcp_deployment_example.py``).
  Receives are ``select``-based so a deadline never touches the socket
  timeout state shared with a concurrently sending heartbeat thread, and a
  deadline that strikes mid-frame leaves the partial bytes buffered in the
  ``FrameAssembler`` — the next recv resumes the same frame.

Error vocabulary: ``TransportTimeout`` (deadline expired — retryable),
``TransportClosed`` (endpoint or peer gone — not retryable),
``ProtocolError`` (this frame is bad; the link may still be fine).
"""

from __future__ import annotations

import heapq
import itertools
import select
import socket
import threading
import time

from .. import obs
from .faults import FaultInjector
from .protocol import (DEFAULT_MAX_FRAME_BYTES, HEADER, FrameAssembler,
                       ProtocolError, decode_payload, encode_payload)


class TransportError(ConnectionError):
    """Base class for transport failures."""


class TransportClosed(TransportError):
    """This endpoint or its peer is gone; no more frames will flow."""


class TransportTimeout(TimeoutError):
    """The per-message deadline expired before a frame arrived/was sent."""


class Transport:
    """One endpoint of a bidirectional frame link.

    ``wire_format`` selects the OUTGOING payload encoding: ``"packed"``
    (default, the v2 zero-copy columnar codec) or ``"npz"`` (the v1
    archive, kept so a new robot can keep speaking v1 to an old bus).
    Receives always auto-detect the format off the payload magic, so
    mixed-version fleets interoperate.

    ``max_frame_bytes`` bounds frames in BOTH directions (default 64 MiB):
    an outgoing frame over the cap, or an incoming length header claiming
    more, raises ``ProtocolError`` before any buffer is sized from it.
    The serving front-end threads its ``--max-frame-mb`` flag through
    here, so one knob governs problem-upload and result-download sizing.
    """

    def __init__(self, src="", dst="",
                 injector: FaultInjector | None = None,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 wire_format: str = "packed"):
        self.src = src
        self.dst = dst
        self.injector = injector
        if int(max_frame_bytes) <= 0:
            raise ValueError(
                f"max_frame_bytes must be positive, got {max_frame_bytes}")
        self.max_frame_bytes = int(max_frame_bytes)
        self.wire_format = wire_format
        run = obs.get_run()
        if run is not None:
            # Wire identity into the run fingerprint: a v1-npz and a
            # packed-wire run of the same deployment are not comparable
            # runs for the convergence regression gate.
            run.set_fingerprint(wire_format=wire_format)

    def send(self, arrays: dict, timeout: float | None = None) -> int:
        """Send one frame; returns wire bytes of the *intended* frame (what
        the network then does to it is the injector's business)."""
        raise NotImplementedError

    def recv(self, timeout: float | None = None) -> dict:
        """Receive one frame; raises ``TransportTimeout`` at the deadline,
        ``TransportClosed`` when the link is gone, ``ProtocolError`` for a
        corrupt frame (link still usable)."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    # -- shared helpers -----------------------------------------------------

    def _encode_checked(self, arrays: dict) -> bytes:
        data = encode_payload(arrays, self.wire_format)
        if len(data) > self.max_frame_bytes:
            raise ProtocolError(
                f"outgoing frame ({len(data)} bytes) exceeds the "
                f"{self.max_frame_bytes}-byte cap")
        return data

    def _deliveries(self, data: bytes) -> list[tuple[float, bytes]]:
        if self.injector is None:
            return [(0.0, data)]
        return self.injector.apply(self.src, self.dst, data)


# ---------------------------------------------------------------------------
# In-process loopback
# ---------------------------------------------------------------------------

class _Inbox:
    """Delay-aware mailbox: entries become visible at their deliver time."""

    def __init__(self):
        self._cond = threading.Condition()
        self._heap: list[tuple[float, int, bytes]] = []
        self._tie = itertools.count()
        self.closed = False

    def put(self, deliver_time: float, data: bytes) -> None:
        with self._cond:
            if self.closed:
                return  # receiver is gone; the network drops the frame
            heapq.heappush(self._heap, (deliver_time, next(self._tie), data))
            self._cond.notify_all()

    def get(self, timeout: float | None) -> bytes:
        end = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                now = time.monotonic()
                if self._heap and self._heap[0][0] <= now:
                    return heapq.heappop(self._heap)[2]
                if self.closed:
                    raise TransportClosed("loopback peer closed")
                waits = []
                if self._heap:
                    waits.append(self._heap[0][0] - now)
                if end is not None:
                    if now >= end:
                        raise TransportTimeout("loopback recv deadline")
                    waits.append(end - now)
                self._cond.wait(min(waits) if waits else None)

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()


class LoopbackTransport(Transport):
    """One endpoint of an in-process pair (see ``LoopbackTransport.pair``)."""

    def __init__(self, src, dst, inbox: _Inbox, peer_inbox: _Inbox,
                 injector: FaultInjector | None = None,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 wire_format: str = "packed"):
        super().__init__(src, dst, injector, max_frame_bytes, wire_format)
        self._inbox = inbox
        self._peer_inbox = peer_inbox
        self._closed = False

    @classmethod
    def pair(cls, a="a", b="b", injector: FaultInjector | None = None,
             max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
             wire_format: str = "packed"
             ) -> tuple["LoopbackTransport", "LoopbackTransport"]:
        """Two connected endpoints; ``a``/``b`` name the ends for the
        injector's per-link RNG streams and partition groups."""
        ia, ib = _Inbox(), _Inbox()
        return (cls(a, b, ia, ib, injector, max_frame_bytes, wire_format),
                cls(b, a, ib, ia, injector, max_frame_bytes, wire_format))

    def send(self, arrays: dict, timeout: float | None = None) -> int:
        if self._closed:
            raise TransportClosed("transport closed")
        data = self._encode_checked(arrays)
        now = time.monotonic()
        for delay, d in self._deliveries(data):
            self._peer_inbox.put(now + delay, d)
        return HEADER.size + len(data)

    def recv(self, timeout: float | None = None) -> dict:
        if self._closed:
            raise TransportClosed("transport closed")
        return decode_payload(self._inbox.get(timeout))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.injector is not None:
            # A frame held for reordering still reaches the peer.
            now = time.monotonic()
            for delay, d in self.injector.flush(self.src, self.dst):
                self._peer_inbox.put(now + delay, d)
        self._inbox.close()
        self._peer_inbox.close()


# ---------------------------------------------------------------------------
# TCP
# ---------------------------------------------------------------------------

class TcpTransport(Transport):
    """Length-prefixed npz frames over a connected socket."""

    def __init__(self, sock: socket.socket, src="", dst="",
                 injector: FaultInjector | None = None,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 wire_format: str = "packed"):
        super().__init__(src, dst, injector, max_frame_bytes, wire_format)
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # AF_UNIX socketpair (tests) has no Nagle to disable
        self._send_lock = threading.Lock()
        self._assembler = FrameAssembler(max_frame_bytes)
        self._ready: list[bytes] = []
        self._timers: list[threading.Timer] = []
        self._closed = False

    def _raw_send(self, data: bytes, swallow: bool = False) -> None:
        try:
            with self._send_lock:
                self._sock.sendall(HEADER.pack(len(data)) + data)
        except OSError as e:
            if swallow:
                return  # delayed frame into a dead link: the network ate it
            raise TransportClosed(f"send failed: {e}") from e

    def send(self, arrays: dict, timeout: float | None = None) -> int:
        if self._closed:
            raise TransportClosed("transport closed")
        data = self._encode_checked(arrays)
        if timeout is not None:
            _, wlist, _ = select.select([], [self._sock], [], timeout)
            if not wlist:
                raise TransportTimeout("send buffer full past deadline")
        for delay, d in self._deliveries(data):
            if delay > 0:
                t = threading.Timer(delay, self._raw_send, args=(d, True))
                t.daemon = True
                t.start()
                self._timers = [x for x in self._timers if x.is_alive()]
                self._timers.append(t)
            else:
                self._raw_send(d)
        return HEADER.size + len(data)

    def recv(self, timeout: float | None = None) -> dict:
        end = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._ready:
                return decode_payload(self._ready.pop(0))
            if self._closed:
                raise TransportClosed("transport closed")
            remaining = None
            if end is not None:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    raise TransportTimeout("recv deadline")
            try:
                rlist, _, _ = select.select([self._sock], [], [], remaining)
            except (OSError, ValueError) as e:
                raise TransportClosed(f"socket gone: {e}") from e
            if not rlist:
                raise TransportTimeout("recv deadline")
            try:
                chunk = self._sock.recv(1 << 16)
            except OSError as e:
                raise TransportClosed(f"recv failed: {e}") from e
            if not chunk:
                raise TransportClosed("peer closed")
            # May raise ProtocolError (oversized header) — the caller's
            # fault layer decides whether the link is salvageable.
            self._ready.extend(self._assembler.feed(chunk))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for t in self._timers:
            t.cancel()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def listen_tcp(host: str = "127.0.0.1", port: int = 0) -> socket.socket:
    """Bind and listen; bind FIRST (port 0 = OS-assigned), then hand the
    resolved port to whoever needs it — no pick-then-rebind TOCTOU race."""
    return socket.create_server((host, port))


class ConnectError(ConnectionError):
    """Structured connect failure: the retry budget ran out.

    Carries the dial target and the budget actually spent so callers
    (fleet respawn loops, CI harnesses) can log/decide without parsing
    the message.  ``__cause__`` is the last socket-level error."""

    def __init__(self, host: str, port: int, attempts: int,
                 elapsed_s: float):
        super().__init__(
            f"could not reach {host}:{port} after {attempts} connect "
            f"attempts over {elapsed_s:.2f}s")
        self.host = host
        self.port = int(port)
        self.attempts = int(attempts)
        self.elapsed_s = float(elapsed_s)


#: Dial-retry budget: more attempts than ``RetryPolicy``'s send default
#: (a listener that is still binding is the EXPECTED cold-start case,
#: not a fault), same base/cap/jitter constants.  Total worst-case wait
#: ~= 5-8s depending on jitter draws.
CONNECT_ATTEMPTS = 9


def connect_tcp(host: str, port: int, attempts: int | None = None,
                policy=None, rng=None) -> socket.socket:
    """Dial with bounded connect retries (the listener may not be up yet).

    Backoff is ``reliable.RetryPolicy``'s exponential-plus-jitter
    schedule — the same constants the send-retry path uses — instead of
    a fixed poll interval, so a thundering herd of replicas dialing one
    freshly spawned peer decorrelates.  Raises ``ConnectError`` (a
    ``ConnectionError``) once the budget is spent."""
    from .reliable import RetryPolicy  # lazy: reliable layers on transport

    policy = RetryPolicy() if policy is None else policy
    attempts = CONNECT_ATTEMPTS if attempts is None else int(attempts)
    if rng is None:
        import numpy as np

        rng = np.random.default_rng()
    t0 = time.monotonic()
    last: Exception | None = None
    for attempt in range(attempts):
        try:
            sock = socket.create_connection((host, port))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except (ConnectionRefusedError, ConnectionResetError,
                TimeoutError) as e:
            last = e
            if attempt + 1 < attempts:
                time.sleep(policy.backoff_s(attempt, rng))
    raise ConnectError(host, port, attempts,
                       time.monotonic() - t0) from last
