"""Synthetic pose-graph generation for tests.

Plays the role of the reference's hand-coded micro-graphs
(``tests/testLineGraph.cpp``, ``tests/testTriangleGraph.cpp``) in
property-based form: generate a random ground-truth trajectory, emit exact
or noise-perturbed relative measurements, and assert recovery.
"""

import numpy as np

from dpgo_tpu.types import Measurements
from dpgo_tpu.utils import lie


def _project_rotations_np(M: np.ndarray) -> np.ndarray:
    """Batched numpy SO(d) projection (SVD with det fix).

    Pure host work on purpose: the JAX equivalent (``lie.project_to_rotation``)
    would dispatch one tiny kernel per call to the *default* backend — on the
    tunneled-TPU image that is an RPC round-trip each, which turns a
    100k-pose synthesis into hours."""
    U, _, Vh = np.linalg.svd(M)
    det = np.linalg.det(U @ Vh)
    U[det < 0, :, -1] *= -1.0
    return U @ Vh


def random_rotation(rng, d=3):
    return _project_rotations_np(rng.standard_normal((d, d))[None])[0]


def random_trajectory(rng, n, d=3, step=1.0):
    """Ground-truth poses: random rotations, random-walk translations."""
    Rs = _project_rotations_np(rng.standard_normal((n, d, d)))
    ts = np.cumsum(step * rng.standard_normal((n, d)), axis=0)
    # Anchor pose 0 at the identity for easy gauge comparison.
    R0inv = Rs[0].T
    ts = (ts - ts[0]) @ R0inv.T
    Rs = np.einsum("ab,nbc->nac", R0inv, Rs)
    return Rs, ts


def relative_measurement(Rs, ts, i, j, rng=None, rot_noise=0.0, trans_noise=0.0, d=3):
    """Relative measurement i -> j: R = R_i^T R_j, t = R_i^T (t_j - t_i)."""
    R = Rs[i].T @ Rs[j]
    t = Rs[i].T @ (ts[j] - ts[i])
    if rng is not None and rot_noise > 0:
        axis = rng.standard_normal(3 if d == 3 else 1)
        if d == 3:
            axis /= np.linalg.norm(axis)
            ang = rng.normal(0, rot_noise)
            q = np.concatenate([np.sin(ang / 2) * axis, [np.cos(ang / 2)]])
            R = lie.quat_to_rotation(q) @ R
        else:
            R = np.asarray(lie.rotation2d(rng.normal(0, rot_noise))) @ R
    if rng is not None and trans_noise > 0:
        t = t + rng.normal(0, trans_noise, d)
    return R, t


def make_measurements(rng, n, d=3, num_lc=5, rot_noise=0.0, trans_noise=0.0,
                      kappa=100.0, tau=10.0, outlier_lc=0):
    """Odometry chain + random loop closures (+ optional gross outliers)."""
    Rs, ts = random_trajectory(rng, n, d)
    edges = [(i, i + 1) for i in range(n - 1)]
    seen = set(edges)
    while len(edges) < (n - 1) + num_lc:
        i, j = sorted(rng.choice(n, 2, replace=False))
        if j > i + 1 and (i, j) not in seen:
            edges.append((int(i), int(j)))
            seen.add((int(i), int(j)))
    Rm, tm = [], []
    for (i, j) in edges:
        R, t = relative_measurement(Rs, ts, i, j, rng, rot_noise, trans_noise, d)
        Rm.append(R)
        tm.append(t)
    # Gross outliers: random rotation + large random translation.  Keep
    # them off the odometry chain (j > i + 1) — a consecutive-index edge
    # would be classified as trusted odometry and never GNC-reweighted.
    while outlier_lc > 0:
        i, j = sorted(rng.choice(n, 2, replace=False))
        if j <= i + 1:
            continue
        edges.append((int(i), int(j)))
        Rm.append(random_rotation(rng, d))
        tm.append(5.0 * rng.standard_normal(d))
        outlier_lc -= 1
    m = len(edges)
    e = np.asarray(edges)
    meas = Measurements(
        d=d, num_poses=n,
        r1=np.zeros(m, np.int32), p1=e[:, 0].astype(np.int64),
        r2=np.zeros(m, np.int32), p2=e[:, 1].astype(np.int64),
        R=np.stack(Rm), t=np.stack(tm),
        kappa=np.full(m, kappa), tau=np.full(m, tau),
        weight=np.ones(m), is_known_inlier=np.zeros(m, bool),
    )
    return meas, (Rs, ts)


def _quats_to_rotations_np(q: np.ndarray) -> np.ndarray:
    """Batched unit quaternion (x, y, z, w) -> rotation matrix [n, 3, 3]
    (vectorized twin of ``lie.quat_to_rotation``)."""
    x, y, z, w = q[:, 0], q[:, 1], q[:, 2], q[:, 3]
    R = np.empty((q.shape[0], 3, 3))
    R[:, 0, 0] = 1 - 2 * (y * y + z * z)
    R[:, 0, 1] = 2 * (x * y - z * w)
    R[:, 0, 2] = 2 * (x * z + y * w)
    R[:, 1, 0] = 2 * (x * y + z * w)
    R[:, 1, 1] = 1 - 2 * (x * x + z * z)
    R[:, 1, 2] = 2 * (y * z - x * w)
    R[:, 2, 0] = 2 * (x * z - y * w)
    R[:, 2, 1] = 2 * (y * z + x * w)
    R[:, 2, 2] = 1 - 2 * (x * x + y * y)
    return R


def _random_rotations_np(rng, n: int, d: int) -> np.ndarray:
    """n uniform random rotations, fully vectorized (quaternions for
    SO(3), angles for SO(2)) — no per-pose SVD, so million-pose
    trajectories synthesize in seconds."""
    if d == 3:
        q = rng.standard_normal((n, 4))
        q /= np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        return _quats_to_rotations_np(q)
    th = rng.uniform(0.0, 2.0 * np.pi, n)
    c, s = np.cos(th), np.sin(th)
    return np.stack([np.stack([c, -s], -1), np.stack([s, c], -1)], axis=1)


def _rotation_noise_np(rng, n: int, d: int, sigma: float) -> np.ndarray:
    """n small random rotations (axis-angle, angle ~ N(0, sigma)),
    vectorized — the noise model of ``relative_measurement``."""
    ang = rng.normal(0.0, sigma, n)
    if d == 2:
        c, s = np.cos(ang), np.sin(ang)
        return np.stack([np.stack([c, -s], -1), np.stack([s, c], -1)],
                        axis=1)
    axis = rng.standard_normal((n, 3))
    axis /= np.maximum(np.linalg.norm(axis, axis=1, keepdims=True), 1e-12)
    q = np.concatenate([np.sin(ang / 2)[:, None] * axis,
                        np.cos(ang / 2)[:, None]], axis=1)
    return _quats_to_rotations_np(q)


def make_measurements_vectorized(rng, n, d=3, num_lc=5, rot_noise=0.0,
                                 trans_noise=0.0, kappa=100.0, tau=10.0):
    """``make_measurements`` without the per-edge Python loop: odometry
    chain + random loop closures assembled entirely from batched numpy.

    Exists for the pod-scale bench arms (``bench_sharded.py``): the
    looped generator synthesizes ~1e4 edges/s, which turns a 1M-pose /
    1M-edge problem into a multi-minute build before the solver even
    starts; this one does the same construction in a handful of batched
    ops.  Same measurement model (exact relative transforms plus optional
    axis-angle rotation noise and Gaussian translation noise), not
    edge-for-edge identical to the looped generator's RNG stream."""
    Rs = _random_rotations_np(rng, n, d)
    ts = np.cumsum(rng.standard_normal((n, d)), axis=0)
    R0inv = Rs[0].T
    ts = (ts - ts[0]) @ R0inv.T
    Rs = np.einsum("ab,nbc->nac", R0inv, Rs)

    i_odo = np.arange(n - 1)
    j_odo = i_odo + 1
    if num_lc > 0:
        # Oversample, keep i + 1 < j, dedupe — vectorized rejection.
        cand = rng.integers(0, n, (4 * num_lc + 64, 2))
        lo, hi = cand.min(1), cand.max(1)
        keep = hi > lo + 1
        pairs = np.unique(np.stack([lo[keep], hi[keep]], -1), axis=0)
        take = rng.permutation(pairs.shape[0])[:num_lc]
        i_lc, j_lc = pairs[take, 0], pairs[take, 1]
    else:
        i_lc = j_lc = np.zeros(0, np.int64)
    ei = np.concatenate([i_odo, i_lc])
    ej = np.concatenate([j_odo, j_lc])
    m = ei.shape[0]

    # R = R_i^T R_j, t = R_i^T (t_j - t_i), batched.
    Ri = Rs[ei]
    Rm = np.einsum("eba,ebc->eac", Ri, Rs[ej])
    tm = np.einsum("eba,eb->ea", Ri, ts[ej] - ts[ei])
    if rot_noise > 0:
        Rm = np.einsum("eab,ebc->eac", _rotation_noise_np(rng, m, d,
                                                          rot_noise), Rm)
    if trans_noise > 0:
        tm = tm + rng.normal(0.0, trans_noise, (m, d))

    return Measurements(
        d=d, num_poses=n,
        r1=np.zeros(m, np.int32), p1=ei.astype(np.int64),
        r2=np.zeros(m, np.int32), p2=ej.astype(np.int64),
        R=Rm, t=tm,
        kappa=np.full(m, kappa), tau=np.full(m, tau),
        weight=np.ones(m), is_known_inlier=np.zeros(m, bool),
    ), (Rs, ts)


def corrupt_loop_closures(meas: Measurements, fraction: float, rng=None,
                          seed: int = 0):
    """Replace a random ``fraction`` of the loop closures with gross
    outliers (the GNC-paper corruption protocol).

    The reference's GNC machinery (``src/DPGO_robust.cpp:23-103``,
    ``src/PGOAgent.cpp:1181-1245``) exists to survive corrupted loop
    closures, but its repo ships no corrupted datasets or injection
    protocol — this is the standard one used by the robust-PGO
    literature: keep odometry trusted, pick round(fraction * num_lc)
    loop closures uniformly at random, and overwrite each with a
    uniformly random rotation and a random translation at the scale of
    the trajectory's own extent (so the outliers are gross but not
    astronomically out of distribution; precisions are kept, as the
    corrupted edge still CLAIMS the dataset noise model).

    ``meas`` must be globally indexed (as from ``read_g2o``).  Returns
    ``(corrupted, outlier_idx)`` where ``outlier_idx`` are the global
    measurement indices that were overwritten — the ground truth for
    precision/recall scoring of GNC edge rejection.
    """
    from dpgo_tpu.types import loop_closure_mask

    rng = rng or np.random.default_rng(seed)
    d = meas.d
    lc_idx = np.flatnonzero(loop_closure_mask(meas))
    k = int(round(fraction * lc_idx.size))
    outlier_idx = np.sort(rng.choice(lc_idx, size=k, replace=False))

    out = meas.select(np.arange(len(meas)))  # fancy indexing copies every field
    out.weight = np.ones(len(meas))
    if k:
        out.R[outlier_idx] = _project_rotations_np(
            rng.standard_normal((k, d, d)))
        # Translation scale from the data itself: outlier norms uniform in
        # [0, 2 * the 95th-percentile measured translation norm].
        scale = 2.0 * float(np.percentile(np.linalg.norm(meas.t, axis=1), 95))
        dirs = rng.standard_normal((k, d))
        dirs /= np.maximum(np.linalg.norm(dirs, axis=1, keepdims=True), 1e-12)
        out.t[outlier_idx] = dirs * rng.uniform(0.0, scale, (k, 1))
    return out, outlier_idx


def integrate_odometry_np(meas: Measurements):
    """Dead-reckoned world poses from the odometry chain (global indexing):
    ``X_{p+1} = X_p * meas_{p->p+1}``.  The pose estimates a front-end
    would hold — and therefore the frame in which perceptually-aliased
    loop closures are self-consistent."""
    d = meas.d
    n = meas.num_poses
    Rs = np.zeros((n, d, d))
    ts = np.zeros((n, d))
    Rs[0] = np.eye(d)
    odo = {}
    same = meas.r1 == meas.r2
    for k in np.flatnonzero(same & (meas.p2 == meas.p1 + 1)):
        odo[int(meas.p1[k])] = k
    for p in range(n - 1):
        k = odo.get(p)
        if k is None:  # gap in the chain: restart at identity (rare)
            Rs[p + 1] = np.eye(d)
            ts[p + 1] = ts[p]
            continue
        Rs[p + 1] = Rs[p] @ meas.R[k]
        ts[p + 1] = ts[p] + Rs[p] @ meas.t[k]
    return Rs, ts


def corrupt_loop_closures_correlated(
    meas: Measurements, fraction: float, clusters: int | None = None,
    rng=None, seed: int = 0, rot_noise: float = 0.005,
    trans_noise: float = 0.01, min_separation_frac: float = 0.1,
):
    """Perceptual-aliasing corruption: clusters of MUTUALLY CONSISTENT
    false loop closures (VERDICT r4 item 4 — the hard case).

    ``corrupt_loop_closures`` injects independent uniform-random gross
    edges — the regime GNC-TLS provably crushes (measured recall 1.000 at
    every level).  The failure mode that actually breaks single-anneal
    GNC in the robust-SLAM literature is CORRELATED: a front-end that
    aliases two similar-looking places emits a whole cluster of loop
    closures, all consistent with ONE wrong relative transform between
    two trajectory segments.  Inside the cluster the edges corroborate
    each other, so per-edge residual tests can lock onto the wrong mode.

    Protocol: round(fraction * num_lc) false edges split into
    ``clusters`` groups (default: ~15 edges each).  Each group picks two
    well-separated same-length segments [a, a+m) and [b, b+m) of the
    dead-reckoned trajectory (``integrate_odometry_np``), draws one
    gross transform ``T`` (uniform random rotation, translation at the
    trajectory scale), and overwrites m existing loop closures with
    edges (a+i) -> (b+i) whose measurements are exactly consistent with
    "segment B sits at T relative to segment A" plus small i.i.d. noise
    — i.e. ``R_meas = R_a^T (R_T R_b)``, ``t_meas = R_a^T (R_T t_b +
    t_T - t_a)`` in the dead-reckoned frame.  Precisions are kept
    (the false edges claim the dataset's own noise model).

    Returns ``(corrupted, outlier_idx)`` like ``corrupt_loop_closures``.
    Reference machinery under test: ``src/DPGO_robust.cpp:23-103``,
    ``src/PGOAgent.cpp:1181-1245``.
    """
    from dpgo_tpu.types import loop_closure_mask

    rng = rng or np.random.default_rng(seed)
    d = meas.d
    n = meas.num_poses
    lc_idx = np.flatnonzero(loop_closure_mask(meas))
    k_total = int(round(fraction * lc_idx.size))
    if clusters is None:
        clusters = max(1, k_total // 15)
    clusters = min(clusters, max(1, k_total))
    outlier_idx = np.sort(rng.choice(lc_idx, size=k_total, replace=False))

    Rs, ts = integrate_odometry_np(meas)
    extent = 2.0 * float(np.percentile(np.linalg.norm(meas.t, axis=1), 95))
    min_sep = int(min_separation_frac * n)

    out = meas.select(np.arange(len(meas)))
    out.weight = np.ones(len(meas))
    sizes = np.full(clusters, k_total // clusters)
    sizes[: k_total - sizes.sum()] += 1
    pos = 0
    for c in range(clusters):
        m = int(sizes[c])
        if m == 0:
            continue
        for _ in range(200):  # rejection-sample well-separated segments
            a = int(rng.integers(0, n - m))
            b = int(rng.integers(0, n - m))
            if abs(a - b) >= max(min_sep, m):
                break
        else:
            # Unsatisfiable geometry (cluster size ~ graph size): falling
            # through would silently create overlapping or self-loop
            # segments, breaking the two-distinct-places invariant the
            # aliasing protocol models.
            raise ValueError(
                f"cannot place two disjoint segments of {m} poses "
                f">= {max(min_sep, m)} apart in a {n}-pose graph; "
                "reduce fraction or increase clusters")
        R_T = random_rotation(rng, d)
        t_T = rng.standard_normal(d)
        t_T *= rng.uniform(0.3, 1.0) * extent / max(np.linalg.norm(t_T),
                                                    1e-12)
        rows = outlier_idx[pos:pos + m]
        pos += m
        for i, row in enumerate(rows):
            ia, ib = a + i, b + i
            Rb = R_T @ Rs[ib]
            tb = R_T @ ts[ib] + t_T
            Rm = Rs[ia].T @ Rb
            tm = Rs[ia].T @ (tb - ts[ia])
            # Small in-cluster noise so edges corroborate, not duplicate.
            Rm = _project_rotations_np(
                (Rm + rot_noise * rng.standard_normal((d, d)))[None])[0]
            tm = tm + trans_noise * rng.standard_normal(d)
            out.p1[row], out.p2[row] = ia, ib  # r1/r2 stay 0 (global ids)
            out.R[row] = Rm
            out.t[row] = tm
            out.is_known_inlier[row] = False  # aliasing is never "known"
    return out, outlier_idx


def make_stitched_winding(n_cycles: int, cycle_len: int,
                          kappa: float = 10.0, tau: float = 1.0,
                          bridge_kappa: float = 10.0, windings: int = 2):
    """A large SE(2) dataset with a CERTIFIABLY SUBOPTIMAL rank-2
    critical point, plus that critical point as an iterate.

    Construction (VERDICT r4 item 2 — the at-scale escape demo): take
    ``n_cycles`` identity-measurement cycle graphs of length
    ``cycle_len`` (the classic angular-synchronization trap: the global
    optimum is all-identity at cost 0, but the "winding" configuration
    ``R_k = rot(2 pi w k / L)`` is a GENUINE LOCAL MINIMUM of the rank-2
    problem while the per-step angle stays below pi/2 — the micro
    version is ``tests/test_certify.py``'s ``_winding_cycle``), and
    stitch consecutive cycles with one identity bridge edge each.

    ``bridge_kappa`` defaults to the CYCLE kappa, not a weak value, for
    a spectral reason measured at 100k (round 5): with near-zero
    bridges the graph is nearly disconnected, so the certificate
    operator carries ~n_cycles inter-cycle modes crowded against zero —
    a cluster that stalls every Lanczos/LOBPCG eigensolve at scale
    (the f64 verification then rightly refuses to certify).  Bridge
    strength does not disturb the construction: the wound
    configuration's pose-0 rotations are identity, so bridge residuals
    vanish EXACTLY at any kappa and the wound point stays exactly
    critical; stability of each cycle's winding basin is an intra-cycle
    property.

    ``windings`` (the winding number w) defaults to 2 for a topological
    reason measured at 100k scale (round 5): a w=1 loop of planar
    rotations is the NON-contractible class of pi_1(St(3,2)) =
    pi_1(SO(3)) = Z_2, so at rank 3 it cannot unwind to cost 0 — descent
    stalls at the half-cost great-circle representative of the
    nontrivial class (measured: cost 3946.5 -> exactly 1973.4 on
    1000x100, then a ~1e-4-curvature plateau that survives rank 4).
    w=2 is contractible at rank 3 (and any even w), so ONE escape leads
    downhill to the global optimum and a PASSING certificate — the
    demo the staircase needs.

    Returns ``(meas, X_winding [N, 2, 3])`` with every cycle wound: a
    first-order critical point of the stitched problem up to the
    bridge coupling (the bridges connect pose 0 of each cycle, whose
    winding rotation is the identity, so the bridge residuals vanish at
    the wound configuration and it remains EXACTLY critical).  Running
    the staircase from it must therefore go descent -> certificate FAIL
    at r=2 -> saddle escape -> re-certify at r>=3 (SE-Sync Algorithm 1;
    no reference counterpart exists — certification is absent from the
    reference codebase).
    """
    n = n_cycles * cycle_len
    e_i, e_j, kap = [], [], []
    rng_b = np.random.default_rng(7)
    for c in range(n_cycles):
        base = c * cycle_len
        for k in range(cycle_len):
            e_i.append(base + k)
            e_j.append(base + (k + 1) % cycle_len)
            kap.append(kappa)
        # Bridges: chain (connectivity) + one RANDOM earlier cycle
        # (expander-style stitching).  A pure chain of n_cycles
        # super-nodes has inter-cycle diffusion modes at ~(pi k /
        # n_cycles)^2 * bridge scale — at 1000 cycles that is a dense
        # near-zero cluster which stalls every eigensolve the honest
        # certificate relies on (measured round 5: the 100k f64
        # verification hit maxiter and refused even gauge-deflated).
        # The random extra edge makes the cycle-quotient graph an
        # expander: constant spectral gap, so the near-zero spectrum is
        # just the gauge + genuine curvature and LOBPCG converges.  All
        # bridges are identity measurements between pose-0 frames, so
        # they vanish exactly at the wound configuration.
        if c + 1 < n_cycles:
            e_i.append(base)            # bridge: cycle c pose 0 ->
            e_j.append(base + cycle_len)  # cycle c+1 pose 0
            kap.append(bridge_kappa)
        if c >= 2:
            e_i.append(base)            # expander bridge: -> random
            e_j.append(int(rng_b.integers(0, c - 1)) * cycle_len)
            kap.append(bridge_kappa)
    m = len(e_i)
    meas = Measurements(
        d=2, num_poses=n,
        r1=np.zeros(m, np.int32), p1=np.asarray(e_i, np.int64),
        r2=np.zeros(m, np.int32), p2=np.asarray(e_j, np.int64),
        R=np.tile(np.eye(2), (m, 1, 1)), t=np.zeros((m, 2)),
        kappa=np.asarray(kap, float), tau=np.full(m, tau),
        weight=np.ones(m), is_known_inlier=np.zeros(m, bool),
    )
    th = 2 * np.pi * windings * (np.arange(n) % cycle_len) / cycle_len
    Rw = np.stack([np.stack([np.cos(th), -np.sin(th)], -1),
                   np.stack([np.sin(th), np.cos(th)], -1)], -2)
    Xw = np.concatenate([Rw, np.zeros((n, 2, 1))], axis=-1)  # [n, 2, 3]
    return meas, Xw


def rejection_scores(weights: np.ndarray, meas: Measurements,
                     outlier_idx: np.ndarray, thresh: float = 0.5):
    """Precision/recall of GNC edge rejection against injected ground truth.

    ``weights`` are final per-measurement GNC weights ([M], as in
    ``RBCDResult.weights``); an edge is *rejected* when its weight falls
    below ``thresh``.  ALL edges count, not just the global loop-closure
    mask: interior odometry keeps weight 1 by construction, but
    globally-consecutive edges that span a robot boundary are shared
    edges the solver CAN reweight (``types.loop_closure_mask`` note) —
    a false rejection there must count against precision.
    Returns ``(precision, recall, n_rejected)``.
    """
    rejected = np.asarray(weights) < thresh
    truth = np.zeros(len(meas), bool)
    truth[outlier_idx] = True
    tp = int(np.sum(rejected & truth))
    n_rej = int(np.sum(rejected))
    precision = tp / n_rej if n_rej else 1.0
    recall = tp / truth.sum() if truth.any() else 1.0
    return precision, recall, n_rej


def trajectory_error(T, Rs, ts):
    """Max pose error of T [n, d, d+1] vs ground truth, after aligning
    pose 0 (gauge)."""
    d = Rs.shape[-1]
    R_est = np.asarray(T[..., :d])
    t_est = np.asarray(T[..., d])
    # Align: G = pose0_true * pose0_est^{-1}
    Rg = Rs[0] @ R_est[0].T
    tg = ts[0] - Rg @ t_est[0]
    R_al = np.einsum("ab,nbc->nac", Rg, R_est)
    t_al = t_est @ Rg.T + tg
    return max(
        float(np.abs(R_al - Rs).max()),
        float(np.abs(t_al - ts).max()),
    )
