"""ctypes binding to the native (C++) dataset loader.

The reference's IO layer is C++ (``read_g2o_file``,
``src/DPGO_utils.cpp:78-212``); this framework keeps IO native too —
``native/g2o_parser.cpp`` tokenizes the file in place and returns
struct-of-arrays buffers that become the numpy arrays of ``Measurements``
with one copy.  The library auto-builds on first use (``make -C native``)
and callers fall back to the pure-Python parser when no C++ toolchain is
available (``dpgo_tpu.utils.g2o.read_g2o`` handles the dispatch).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings

import numpy as np

from ..types import Measurements
from .g2o import key_to_robot_keyframe

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdpgo_native.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


class _DpgoG2O(ctypes.Structure):
    _fields_ = [
        ("d", ctypes.c_int32),
        ("m", ctypes.c_int64),
        ("num_vertices", ctypes.c_int64),
        ("key1", ctypes.POINTER(ctypes.c_uint64)),
        ("key2", ctypes.POINTER(ctypes.c_uint64)),
        ("R", ctypes.POINTER(ctypes.c_double)),
        ("t", ctypes.POINTER(ctypes.c_double)),
        ("kappa", ctypes.POINTER(ctypes.c_double)),
        ("tau", ctypes.POINTER(ctypes.c_double)),
        ("error", ctypes.c_char * 256),
    ]


def _build_library() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        # Installed package without the native/ source tree (pip install
        # ships only dpgo_tpu/*): the Python parser is the supported path —
        # fall back silently rather than warning on every import.
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError) as e:
        warnings.warn(f"[native_io] build failed ({e}); "
                      "falling back to the Python parser")
        return False


def load_library():
    """The loaded native library, building it on first use; None when
    unavailable (no toolchain / build failure) — callers must fall back."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        # Always run make when the source tree is present: the Makefile's
        # dependency check makes it a no-op when current, and it rebuilds a
        # stale .so (e.g. one predating a newly added native component)
        # that would otherwise be served with missing symbols.
        if not _build_library() and not os.path.exists(_LIB_PATH):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            warnings.warn(f"[native_io] load failed ({e})")
            _load_failed = True
            return None
        lib.dpgo_g2o_read.argtypes = [ctypes.c_char_p,
                                      ctypes.POINTER(_DpgoG2O)]
        lib.dpgo_g2o_read.restype = ctypes.c_int
        lib.dpgo_g2o_free.argtypes = [ctypes.POINTER(_DpgoG2O)]
        lib.dpgo_g2o_free.restype = None
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_library() is not None


def read_g2o_native(path: str) -> Measurements:
    """Parse a .g2o file through the native loader.

    Raises ``RuntimeError`` when the library is unavailable or the file is
    malformed (same failure surface as the Python parser's ValueError).
    """
    lib = load_library()
    if lib is None:
        raise RuntimeError("native g2o loader unavailable")

    out = _DpgoG2O()
    rc = lib.dpgo_g2o_read(os.fspath(path).encode(), ctypes.byref(out))
    if rc != 0:
        err = out.error.decode(errors="replace")
        if rc == 1:  # IO error — out buffers are empty, nothing to free
            raise RuntimeError(f"native g2o read failed: {err}")
        lib.dpgo_g2o_free(ctypes.byref(out))
        raise ValueError(f"native g2o parse failed: {err}")

    try:
        m, d = int(out.m), int(out.d)
        as_np = np.ctypeslib.as_array
        key1 = as_np(out.key1, (m,)).copy()
        key2 = as_np(out.key2, (m,)).copy()
        R = as_np(out.R, (m, d, d)).copy()
        t = as_np(out.t, (m, d)).copy()
        kappa = as_np(out.kappa, (m,)).copy()
        tau = as_np(out.tau, (m,)).copy()
        num_vertices = int(out.num_vertices)
    finally:
        lib.dpgo_g2o_free(ctypes.byref(out))

    r1, p1 = key_to_robot_keyframe(key1)
    r2, p2 = key_to_robot_keyframe(key2)
    num_poses = max(num_vertices, int(max(p1.max(), p2.max())) + 1)
    return Measurements(
        d=d, num_poses=num_poses,
        r1=r1, p1=p1, r2=r2, p2=p2,
        R=R, t=t, kappa=kappa, tau=tau,
        weight=np.ones(m),
        is_known_inlier=np.zeros(m, dtype=bool),
    )
