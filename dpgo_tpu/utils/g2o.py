"""g2o dataset reader.

TPU-native replacement for reference ``read_g2o_file``
(``src/DPGO_utils.cpp:78-212``) and the multi-robot key decoding
``key_to_robot_keyframe`` (``src/DPGO_utils.cpp:21-33``).  Parses with
vectorized numpy over all EDGE lines at once instead of a per-line
``stringstream`` loop, producing the struct-of-arrays ``Measurements``
container directly (no per-edge objects).
"""

from __future__ import annotations

import io

import numpy as np

from ..types import Measurements
from .lie import quat_to_rotation, rotation2d

_KEY_BITS = 64
_CHR_BITS = 8
_LBL_BITS = 8
_INDEX_BITS = _KEY_BITS - _CHR_BITS - _LBL_BITS
_INDEX_MASK = (1 << _INDEX_BITS) - 1


def key_to_robot_keyframe(key):
    """Decode gtsam-style symbol keys: high byte = robot char, low 48 bits = index.

    Vectorized port of reference ``key_to_robot_keyframe``
    (``DPGO_utils.cpp:21-33``).  Plain small integers decode to robot 0 with
    index = key.
    """
    key = np.asarray(key, dtype=np.uint64)
    robot = (key >> np.uint64(_INDEX_BITS + _LBL_BITS)) & np.uint64(0xFF)
    index = key & np.uint64(_INDEX_MASK)
    return robot.astype(np.int32), index.astype(np.int64)


def _is_bytes_like(source) -> bool:
    return isinstance(source, (bytes, bytearray, memoryview))


def _open_g2o_text(source):
    """A text stream over any accepted g2o source: a filesystem path,
    raw ``bytes``/``bytearray``/``memoryview`` (an uploaded payload — the
    serving plane parses request bodies without temp files), or a
    file-like object opened in text or binary mode."""
    if _is_bytes_like(source):
        return io.StringIO(bytes(source).decode("utf-8"))
    if hasattr(source, "read"):
        data = source.read()
        if isinstance(data, bytes):
            data = data.decode("utf-8")
        return io.StringIO(data)
    return open(source)


def read_g2o(source, backend: str = "auto") -> Measurements:
    """Parse a .g2o dataset into a ``Measurements`` batch.

    ``source`` is a filesystem path, the file's ``bytes`` (also
    ``bytearray``/``memoryview``), or a file-like object — in-memory
    sources let a server parse uploaded g2o payloads without temp files.

    ``backend``: ``"auto"`` uses the native (C++) loader when available —
    the framework's IO layer is native like the reference's
    (``native/g2o_parser.cpp``) — and falls back to the pure-Python parser;
    ``"native"`` / ``"python"`` force one side (native raises when the
    library can't be built).  The native loader reads from the filesystem
    only: in-memory sources always parse in Python (``backend="native"``
    with one raises).
    """
    if backend not in ("auto", "native", "python"):
        raise ValueError(f"unknown backend {backend!r}")
    in_memory = _is_bytes_like(source) or hasattr(source, "read")
    if backend != "python" and not in_memory:
        from . import native_io
        if backend == "native":
            return native_io.read_g2o_native(source)
        if native_io.native_available():
            return native_io.read_g2o_native(source)
    if backend == "native" and in_memory:
        raise ValueError(
            "backend='native' requires a filesystem path; bytes/file-like "
            "sources parse with the Python backend")
    return read_g2o_python(source)


def write_g2o(meas: Measurements, path: str) -> None:
    """Write ``Measurements`` as a standard g2o edge list (the inverse of
    ``read_g2o`` for single-robot/global indexing).

    Precisions round-trip exactly through the reader's
    information-divergence formulas: the translation info block is
    ``tau * I`` and the rotation block ``2 * kappa * I`` (SE(3)) /
    ``I33 = kappa`` (SE(2)).  Edge weights and known-inlier flags have no
    g2o representation and are dropped.  Lets tests and demos synthesize
    datasets for the file-driven deployment examples without an external
    dataset directory.
    """
    from .lie import rotation_to_quat

    r1 = np.asarray(meas.r1)
    r2 = np.asarray(meas.r2)
    if (r1 != 0).any() or (r2 != 0).any():
        raise ValueError("write_g2o expects global (single-robot) indexing; "
                         "partition after reading back instead")
    with open(path, "w") as fh:
        for k in range(len(meas)):
            i, j = int(meas.p1[k]), int(meas.p2[k])
            t = np.asarray(meas.t[k], np.float64)
            tau = float(meas.tau[k])
            kappa = float(meas.kappa[k])
            if meas.d == 3:
                q = np.asarray(rotation_to_quat(np.asarray(meas.R[k])))
                c = 2.0 * kappa
                info = [tau, 0, 0, 0, 0, 0, tau, 0, 0, 0, 0, tau, 0, 0, 0,
                        c, 0, 0, c, 0, c]
                vals = [*t, *q]
                tag = "EDGE_SE3:QUAT"
            else:
                theta = float(np.arctan2(meas.R[k][1, 0], meas.R[k][0, 0]))
                info = [tau, 0, 0, tau, 0, kappa]
                vals = [*t, theta]
                tag = "EDGE_SE2"
            fh.write(f"{tag} {i} {j} "
                     + " ".join(repr(float(v)) for v in [*vals, *info])
                     + "\n")


def read_g2o_python(source) -> Measurements:
    """Pure-Python (vectorized numpy) g2o parser — the portable fallback.
    Accepts the same path / bytes / file-like sources as ``read_g2o``.

    Supports ``EDGE_SE2`` and ``EDGE_SE3:QUAT``; ``VERTEX_*`` lines only
    contribute to the pose count, as in the reference (which ignores vertex
    initial values, ``DPGO_utils.cpp:196-199``).  Precisions follow the
    reference's information-divergence-minimizing choices
    (``DPGO_utils.cpp:139-143``, ``184-194``):

    * SE(2): ``tau = 2 / tr(Sigma_t^-1)`` from the 2x2 translation info block,
      ``kappa = I33`` directly.
    * SE(3): ``tau = 3 / tr(Sigma_t^-1)``, ``kappa = 3 / (2 tr(Sigma_R^-1))``.
    """
    se2_rows: list[list[float]] = []
    se3_rows: list[list[float]] = []
    se2_keys: list[tuple[int, int]] = []
    se3_keys: list[tuple[int, int]] = []
    num_vertices = 0
    max_index = -1

    with _open_g2o_text(source) as f:
        for line in f:
            toks = line.split()  # whitespace-agnostic, like the reference's stringstream
            if not toks:
                continue
            tag = toks[0]
            if tag == "EDGE_SE2" or tag == "EDGE_SE3:QUAT":
                # Keys must be parsed as ints: gtsam symbol keys exceed 2^53
                # and would lose their low (index) bits through float64.
                key = (int(toks[1]), int(toks[2]))
                vals = [float(x) for x in toks[3:]]
                if tag == "EDGE_SE2":
                    se2_keys.append(key)
                    se2_rows.append(vals)
                else:
                    se3_keys.append(key)
                    se3_rows.append(vals)
            elif tag.startswith("VERTEX"):
                num_vertices += 1
            elif tag == "FIX":
                # Standard g2o gauge anchor (present in ais2klinik.g2o).  The
                # reference would assert on it (DPGO_utils.cpp:201) but the
                # framework fixes gauge via the global anchor, so the line is
                # deliberately accepted and ignored.
                continue
            else:
                raise ValueError(f"Unrecognized g2o token: {tag!r}")

    if se2_rows and se3_rows:
        raise ValueError("Mixed SE2/SE3 edges in one file")
    if not se2_rows and not se3_rows:
        where = source if isinstance(source, str) else "g2o source"
        raise ValueError(f"No edges found in {where}")

    if se3_rows:
        d = 3
        rows = np.asarray(se3_rows, dtype=np.float64)
        keys = np.asarray(se3_keys, dtype=np.uint64)
        keys1, keys2 = keys[:, 0], keys[:, 1]
        t = rows[:, 0:3]
        R = quat_to_rotation(rows[:, 3:7])  # (qx, qy, qz, qw)
        info = rows[:, 7:28]
        # Upper-triangular 6x6 info: order I11..I16, I22..I26, I33..I36, I44..I46, I55, I56, I66
        I11, I12, I13 = info[:, 0], info[:, 1], info[:, 2]
        I22, I23, I33 = info[:, 6], info[:, 7], info[:, 11]
        I44, I45, I46 = info[:, 15], info[:, 16], info[:, 17]
        I55, I56, I66 = info[:, 18], info[:, 19], info[:, 20]
        TranCov = np.stack(
            [I11, I12, I13, I12, I22, I23, I13, I23, I33], axis=-1
        ).reshape(-1, 3, 3)
        RotCov = np.stack(
            [I44, I45, I46, I45, I55, I56, I46, I56, I66], axis=-1
        ).reshape(-1, 3, 3)
        tau = 3.0 / np.trace(np.linalg.inv(TranCov), axis1=-2, axis2=-1)
        kappa = 3.0 / (2.0 * np.trace(np.linalg.inv(RotCov), axis1=-2, axis2=-1))
    else:
        d = 2
        rows = np.asarray(se2_rows, dtype=np.float64)
        keys = np.asarray(se2_keys, dtype=np.uint64)
        keys1, keys2 = keys[:, 0], keys[:, 1]
        t = rows[:, 0:2]
        R = rotation2d(rows[:, 2])
        I11, I12, _I13, I22, _I23, I33 = (rows[:, 3 + k] for k in range(6))
        TranCov = np.stack([I11, I12, I12, I22], axis=-1).reshape(-1, 2, 2)
        tau = 2.0 / np.trace(np.linalg.inv(TranCov), axis1=-2, axis2=-1)
        kappa = I33

    r1, p1 = key_to_robot_keyframe(keys1)
    r2, p2 = key_to_robot_keyframe(keys2)
    max_index = int(max(p1.max(), p2.max()))

    # Deliberate divergence: the reference returns #VERTEX-lines + 1
    # (``DPGO_utils.cpp:197,209``), one more than the real pose count for
    # files that list every vertex (e.g. 126 for the 125-pose smallGrid3D),
    # leaving a measurement-less trailing pose.  We use the actual count.
    num_poses = max(num_vertices, max_index + 1)

    m = len(rows)
    return Measurements(
        d=d,
        num_poses=num_poses,
        r1=r1,
        p1=p1,
        r2=r2,
        p2=p2,
        R=R,
        t=t,
        kappa=np.asarray(kappa, np.float64),
        tau=np.asarray(tau, np.float64),
        weight=np.ones(m),
        is_known_inlier=np.zeros(m, dtype=bool),
    )
