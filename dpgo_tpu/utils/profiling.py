"""Profiling / tracing hooks — the framework's observability layer.

The reference's "tracing" is wall-clock + objective bookkeeping in
``ROPTResult`` (``DPGO_types.h:40-59``, filled at
``QuadraticOptimizer.cpp:36-54``) plus verbose printouts.  The TPU-native
equivalents here (SURVEY.md section 5):

* ``trace(logdir)`` — context manager around ``jax.profiler`` capturing a
  device timeline (XLA op breakdown, HBM traffic) viewable in
  TensorBoard/Perfetto.  Works on CPU and TPU backends.
* ``annotate(name)`` — named region that shows up inside the timeline
  (wraps ``jax.profiler.TraceAnnotation``); use around driver phases
  (exchange / solve / eval) when hunting dispatch gaps.
* ``RoundTimer`` — lightweight host-side per-phase wall-clock accumulator
  for driver loops, with the readback caveat of the tunneled-TPU platform
  (see bench.py) baked in: ``stop`` optionally blocks on a device value
  by materializing it.

The per-iteration *metrics* (cost, gradient norm, relative change,
per-agent readiness) are first-class solver outputs — ``RBCDResult.
cost_history`` / ``grad_norm_history`` and the gossiped status arrays —
not a tracing concern; this module is about *where the time goes*.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a JAX device/host profile into ``logdir``.

    Usage::

        with profiling.trace("/tmp/dpgo-trace"):
            state = rbcd.rbcd_steps(state, graph, 100, meta, params)
            np.asarray(state.X)   # materialize inside the trace window
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named timeline region: ``with profiling.annotate("exchange"): ...``"""
    import jax

    return jax.profiler.TraceAnnotation(name)


class RoundTimer:
    """Host-side per-phase wall-clock accumulator for driver loops.

    ``stop(phase, sync=x)`` materializes ``x`` (device->host readback)
    before taking the timestamp — on the tunneled-TPU platform
    ``block_until_ready`` returns early (see bench.py), so a transfer is
    the only trustworthy fence.
    """

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._t0: dict[str, float] = {}

    def start(self, phase: str) -> None:
        self._t0[phase] = time.perf_counter()

    def stop(self, phase: str, sync=None) -> float:
        if phase not in self._t0:
            # Checked BEFORE the sync materialization: a mistyped phase
            # must fail fast with the clear error, not first pay a
            # device->host transfer for a window that was never opened.
            open_ = ", ".join(sorted(self._t0)) or "none"
            raise ValueError(
                f"stop({phase!r}) without a matching start() "
                f"(open phases: {open_})")
        if sync is not None:
            np.asarray(sync)
        dt = time.perf_counter() - self._t0.pop(phase)
        self.totals[phase] = self.totals.get(phase, 0.0) + dt
        self.counts[phase] = self.counts.get(phase, 0) + 1
        return dt

    @contextlib.contextmanager
    def phase(self, name: str, sync_fn=None):
        """``with timer.phase("solve", lambda: state.X): ...`` — the sync
        callable (if given) produces the device value to materialize at
        exit."""
        self.start(name)
        try:
            yield
        finally:
            self.stop(name, sync=sync_fn() if sync_fn is not None else None)

    def summary(self) -> str:
        rows = [f"{k}: {v:.4f}s / {self.counts[k]} "
                f"({1e3 * v / max(self.counts[k], 1):.2f} ms avg)"
                for k, v in sorted(self.totals.items(),
                                   key=lambda kv: -kv[1])]
        return "\n".join(rows)

    def as_dict(self) -> dict[str, dict[str, float]]:
        """Machine-readable accumulated timings:
        ``{phase: {"total_s", "count", "avg_ms"}}`` — the payload the
        telemetry event stream carries as ``phase_timings`` (the print-only
        ``summary()`` renders the same numbers)."""
        return {k: {"total_s": v, "count": self.counts[k],
                    "avg_ms": 1e3 * v / max(self.counts[k], 1)}
                for k, v in self.totals.items()}

    def reset(self) -> None:
        """Drop all accumulated totals/counts and any in-flight ``start``
        marks, so one timer instance can be reused across runs/windows."""
        self.totals.clear()
        self.counts.clear()
        self._t0.clear()
