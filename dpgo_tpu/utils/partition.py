"""Partitioning a global pose graph into per-robot blocks.

Host-side equivalent of the dataset partitioning in the reference drivers:
contiguous-index splitting (``examples/MultiRobotExample.cpp:73-121``) and
key-encoded robot ids (``examples/MultiRobotCSLAMComparison.cpp:75-101``,
where each robot's pose count is inferred from its odometry chain).
Produces a ``Partition`` with robot-local measurement indexing plus the
local->global pose map used for centralized evaluation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..types import Measurements


@dataclasses.dataclass
class Partition:
    """A pose graph split into per-robot blocks (host side)."""

    num_robots: int
    meas: Measurements  # r1/p1/r2/p2 rewritten robot-local
    n: np.ndarray  # [A] poses per robot
    global_index: np.ndarray  # [A, n_max] local -> global pose id (-1 pad)
    meas_global: Measurements  # same measurements with global pose indexing

    @property
    def n_max(self) -> int:
        return int(self.n.max())

    def classify(self):
        """Per-measurement category: 0 = odometry, 1 = private LC, 2 = shared.

        Odometry = same robot, consecutive local indices
        (``MultiRobotExample.cpp:104-113``).
        """
        m = self.meas
        same = m.r1 == m.r2
        odo = same & (m.p1 + 1 == m.p2)
        return np.where(odo, 0, np.where(same, 1, 2))


def partition_contiguous(meas: Measurements, num_robots: int) -> Partition:
    """Split poses into contiguous equal blocks; robot k owns
    [k*npr, (k+1)*npr) with the last robot absorbing the remainder
    (``MultiRobotExample.cpp:73-90``).

    ``meas`` must use global pose indexing (r1 == r2 == 0).
    """
    if np.any(meas.r1 != 0) or np.any(meas.r2 != 0):
        raise ValueError(
            "partition_contiguous requires globally-indexed measurements "
            "(r1 == r2 == 0); use partition_by_keys for robot-encoded keys")
    n_total = meas.num_poses
    npr = n_total // num_robots
    if npr <= 0:
        raise ValueError("More robots than poses")

    robot_of = np.minimum(np.arange(n_total) // npr, num_robots - 1).astype(np.int32)
    local_of = np.arange(n_total) - robot_of * npr

    n = np.bincount(robot_of, minlength=num_robots)
    n_max = int(n.max())
    global_index = np.full((num_robots, n_max), -1, np.int64)
    for a in range(num_robots):
        ids = np.nonzero(robot_of == a)[0]
        global_index[a, : len(ids)] = ids

    g1 = meas.p1.astype(np.int64)
    g2 = meas.p2.astype(np.int64)
    local = dataclasses.replace(
        meas,
        r1=robot_of[g1],
        p1=local_of[g1],
        r2=robot_of[g2],
        p2=local_of[g2],
    )
    return Partition(num_robots=num_robots, meas=local, n=n,
                     global_index=global_index, meas_global=meas)


def agent_measurements(part: Partition, robot_id: int):
    """One robot's (odometry, private_loop_closures, shared_loop_closures),
    robot-locally indexed — the three arguments of ``PGOAgent::setPoseGraph``
    (reference ``PGOAgent.cpp:126``), as split by the example drivers
    (``MultiRobotExample.cpp:92-121``)."""
    cls = part.classify()
    m = part.meas
    mine = (m.r1 == robot_id) | (m.r2 == robot_id)
    odometry = m.select(mine & (cls == 0))
    private_lc = m.select(mine & (cls == 1))
    shared_lc = m.select(mine & (cls == 2))
    return odometry, private_lc, shared_lc


def partition_by_keys(meas: Measurements) -> Partition:
    """Partition using the robot ids already encoded in the measurement keys
    (multi-robot g2o files; ``MultiRobotCSLAMComparison.cpp:75-101``).

    Robot ids are renumbered densely in sorted order; per-robot pose counts
    are max local index + 1.  Global pose ids are assigned contiguously by
    robot for centralized evaluation.
    """
    robots = np.unique(np.concatenate([meas.r1, meas.r2]))
    remap = {int(r): k for k, r in enumerate(robots)}
    A = len(robots)
    r1 = np.asarray([remap[int(r)] for r in meas.r1], np.int32)
    r2 = np.asarray([remap[int(r)] for r in meas.r2], np.int32)

    # Densify each robot's pose ids (keyed files need not start at 0 or be
    # contiguous; phantom poses would make the init Laplacian singular).
    n = np.zeros(A, np.int64)
    p1 = np.zeros_like(meas.p1)
    p2 = np.zeros_like(meas.p2)
    for a in range(A):
        sel1 = r1 == a
        sel2 = r2 == a
        used = np.unique(np.concatenate([meas.p1[sel1], meas.p2[sel2]]))
        dense = {int(q): k for k, q in enumerate(used)}
        n[a] = len(used)
        p1[sel1] = [dense[int(q)] for q in meas.p1[sel1]]
        p2[sel2] = [dense[int(q)] for q in meas.p2[sel2]]

    offsets = np.concatenate([[0], np.cumsum(n)[:-1]])
    n_max = int(n.max())
    global_index = np.full((A, n_max), -1, np.int64)
    for a in range(A):
        global_index[a, : n[a]] = offsets[a] + np.arange(n[a])

    local = dataclasses.replace(meas, r1=r1, p1=p1, r2=r2, p2=p2)
    meas_global = dataclasses.replace(
        meas,
        num_poses=int(n.sum()),
        r1=np.zeros_like(r1),
        p1=offsets[r1] + p1,
        r2=np.zeros_like(r2),
        p2=offsets[r2] + p2,
    )
    return Partition(num_robots=A, meas=local, n=n,
                     global_index=global_index, meas_global=meas_global)


def gather_poses_to_global(X, part: Partition):
    """Per-agent pose array ``[A, n_max, ...]`` -> global ``[N, ...]``
    using only the Partition's index table (numpy; no multi-agent graph
    needed).  The pose layout depends only on ``num_poses``, so a
    filtered problem's iterate gathers with the full measurement set's
    partition."""
    import numpy as np

    X = np.asarray(X)
    out = np.zeros((int(part.meas_global.num_poses),) + X.shape[2:], X.dtype)
    valid = part.global_index >= 0
    out[part.global_index[valid]] = X[valid]
    return out
