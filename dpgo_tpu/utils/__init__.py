from . import g2o, lie  # noqa: F401
