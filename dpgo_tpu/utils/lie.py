"""Rotation / Stiefel / SE(d) primitives, batched for TPU.

TPU-native equivalents of the dense-linear-algebra helpers in reference
``src/DPGO_utils.cpp:478-531`` (``projectToRotationGroup``,
``projectToStiefelManifold``, ``fixedStiefelVariable``,
``angular2ChordalSO3``) plus quaternion conversions used by the g2o reader
and CSV logger.  Everything accepts arbitrary leading batch dimensions and is
differentiable / jittable; per-pose loops in the reference (OpenMP in
``LiftedSEManifold.cpp:40-44``) become batched SVDs here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def quat_to_rotation(q: np.ndarray) -> np.ndarray:
    """Quaternion(s) [..., 4] in (x, y, z, w) order -> rotation matrices [..., 3, 3].

    Host-side (numpy) helper for the g2o reader; matches Eigen's
    ``Quaterniond(w, x, y, z).toRotationMatrix()`` used at reference
    ``DPGO_utils.cpp:182``.
    """
    q = np.asarray(q, dtype=np.float64)
    q = q / np.linalg.norm(q, axis=-1, keepdims=True)
    x, y, z, w = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    xx, yy, zz = x * x, y * y, z * z
    xy, xz, yz = x * y, x * z, y * z
    wx, wy, wz = w * x, w * y, w * z
    R = np.stack(
        [
            1 - 2 * (yy + zz), 2 * (xy - wz), 2 * (xz + wy),
            2 * (xy + wz), 1 - 2 * (xx + zz), 2 * (yz - wx),
            2 * (xz - wy), 2 * (yz + wx), 1 - 2 * (xx + yy),
        ],
        axis=-1,
    )
    return R.reshape(q.shape[:-1] + (3, 3))


def rotation_to_quat(R: np.ndarray) -> np.ndarray:
    """Rotation matrices [..., 3, 3] -> quaternions [..., 4] in (x, y, z, w).

    Host-side helper for the CSV trajectory logger (reference
    ``PGOLogger.cpp:18-45`` stores qx,qy,qz,qw).  Uses the numerically-stable
    Shepperd branch selection, vectorized over the batch.
    """
    R = np.asarray(R, dtype=np.float64)
    batch = R.shape[:-2]
    Rf = R.reshape((-1, 3, 3))
    m00, m01, m02 = Rf[:, 0, 0], Rf[:, 0, 1], Rf[:, 0, 2]
    m10, m11, m12 = Rf[:, 1, 0], Rf[:, 1, 1], Rf[:, 1, 2]
    m20, m21, m22 = Rf[:, 2, 0], Rf[:, 2, 1], Rf[:, 2, 2]
    tr = m00 + m11 + m22
    q = np.empty((Rf.shape[0], 4), dtype=np.float64)

    c0 = tr > 0
    s = np.sqrt(np.maximum(tr + 1.0, 0.0)) * 2  # s = 4w
    q[c0, 3] = 0.25 * s[c0]
    q[c0, 0] = (m21 - m12)[c0] / s[c0]
    q[c0, 1] = (m02 - m20)[c0] / s[c0]
    q[c0, 2] = (m10 - m01)[c0] / s[c0]

    c1 = (~c0) & (m00 >= m11) & (m00 >= m22)
    s = np.sqrt(np.maximum(1.0 + m00 - m11 - m22, 0.0)) * 2  # s = 4x
    q[c1, 3] = (m21 - m12)[c1] / s[c1]
    q[c1, 0] = 0.25 * s[c1]
    q[c1, 1] = (m01 + m10)[c1] / s[c1]
    q[c1, 2] = (m02 + m20)[c1] / s[c1]

    c2 = (~c0) & (~c1) & (m11 >= m22)
    s = np.sqrt(np.maximum(1.0 + m11 - m00 - m22, 0.0)) * 2  # s = 4y
    q[c2, 3] = (m02 - m20)[c2] / s[c2]
    q[c2, 0] = (m01 + m10)[c2] / s[c2]
    q[c2, 1] = 0.25 * s[c2]
    q[c2, 2] = (m12 + m21)[c2] / s[c2]

    c3 = (~c0) & (~c1) & (~c2)
    s = np.sqrt(np.maximum(1.0 + m22 - m00 - m11, 0.0)) * 2  # s = 4z
    q[c3, 3] = (m10 - m01)[c3] / s[c3]
    q[c3, 0] = (m02 + m20)[c3] / s[c3]
    q[c3, 1] = (m12 + m21)[c3] / s[c3]
    q[c3, 2] = 0.25 * s[c3]

    return q.reshape(batch + (4,))


def rotation2d(theta) -> np.ndarray:
    """Angle(s) [...] -> SO(2) matrices [..., 2, 2] (reference ``DPGO_utils.cpp:138``)."""
    theta = np.asarray(theta, dtype=np.float64)
    c, s = np.cos(theta), np.sin(theta)
    R = np.stack([c, -s, s, c], axis=-1)
    return R.reshape(theta.shape + (2, 2))


def _project_to_rotation_batch(M: jax.Array) -> jax.Array:
    U, _, Vh = jnp.linalg.svd(M, full_matrices=False)
    det = jnp.linalg.det(U @ Vh)
    # Flip the last column of U where det(U Vh) < 0.
    d = M.shape[-1]
    flip = jnp.where(det < 0, -1.0, 1.0).astype(M.dtype)
    signs = jnp.concatenate(
        [jnp.ones(M.shape[:-2] + (d - 1,), M.dtype), flip[..., None]], axis=-1
    )
    return (U * signs[..., None, :]) @ Vh


#: Batched-SVD chunk bound: XLA:TPU stack-allocates the whole SVD batch in
#: VMEM (observed: [100000, 3, 3] wants 24 MB scoped vmem against a 16 MB
#: limit), so huge init-time projections run as a lax.map over chunks.
_SVD_CHUNK = 16384


def project_to_rotation(M: jax.Array) -> jax.Array:
    """Project [..., d, d] matrices onto SO(d) (det +1).

    Batched SVD with determinant fix, the equivalent of reference
    ``projectToRotationGroup`` (``DPGO_utils.cpp:478-492``).  Batches past
    ``_SVD_CHUNK`` are chunked (cold init path at 100k-pose scale).
    """
    d = M.shape[-1]
    flat = M.reshape((-1, d, d))
    N = flat.shape[0]
    if N <= _SVD_CHUNK:
        return _project_to_rotation_batch(M)
    pad = (-N) % _SVD_CHUNK
    flat = jnp.concatenate(
        [flat, jnp.zeros((pad, d, d), M.dtype)]) if pad else flat
    out = jax.lax.map(_project_to_rotation_batch,
                      flat.reshape((-1, _SVD_CHUNK, d, d)))
    return out.reshape((-1, d, d))[:N].reshape(M.shape)


def project_to_stiefel(M: jax.Array) -> jax.Array:
    """Project [..., r, d] matrices (r >= d) onto the Stiefel manifold St(r, d).

    The polar factor ``M (M^T M)^{-1/2}``, the equivalent of reference
    ``projectToStiefelManifold`` (``DPGO_utils.cpp:494-500``, thin-SVD
    ``U V^T`` there).  Computed by the closed-form Newton-Schulz kernel:
    XLA's batched SVD on TPU is a generic one-sided-Jacobi loop that costs
    milliseconds on the [A*n, r, d] batches of the RBCD hot path, while the
    fixed-size iteration is a handful of d x d matmuls.  Robust to
    condition(M) ~1e5-1e6 (see ``smallmat.polar_orthonormalize``); for
    potentially rank-deficient inputs use ``project_to_stiefel_svd``.
    """
    from ..ops.smallmat import polar_orthonormalize

    return polar_orthonormalize(M)


def project_to_stiefel_svd(M: jax.Array) -> jax.Array:
    """SVD form of ``project_to_stiefel`` (robust at any conditioning;
    slow on TPU — cold paths only)."""
    U, _, Vh = jnp.linalg.svd(M, full_matrices=False)
    return U @ Vh


def random_stiefel(key: jax.Array, r: int, d: int, batch=(), dtype=jnp.float32) -> jax.Array:
    """Uniform random point(s) on St(r, d) via QR of a Gaussian."""
    G = jax.random.normal(key, batch + (r, d), dtype=dtype)
    Q, R = jnp.linalg.qr(G)
    # Fix signs so the factorization is unique (diag(R) > 0).
    s = jnp.sign(jnp.diagonal(R, axis1=-2, axis2=-1))
    s = jnp.where(s == 0, 1.0, s).astype(dtype)
    return Q * s[..., None, :]


def fixed_stiefel(r: int, d: int, dtype=jnp.float32) -> jax.Array:
    """Deterministic element of St(r, d), identical across all agents/hosts.

    The shared "lifting matrix" YLift: reference ``fixedStiefelVariable``
    (``DPGO_utils.cpp:502-507``) seeds ``srand(1)``; here a fixed PRNG key
    plays that role.  Only cross-agent determinism matters, not the specific
    value.
    """
    return random_stiefel(jax.random.PRNGKey(1), r, d, dtype=jnp.float64).astype(dtype)


def lifting_matrix(rank: int, d: int, dtype=jnp.float32) -> jax.Array:
    """The shared lifting matrix YLift in St(rank, d).

    Identity for rank == d (no relaxation); the deterministic fixed Stiefel
    element otherwise (robot 0 generates and broadcasts it in the reference,
    ``PGOAgent.cpp:46``; determinism makes every agent agree without a
    broadcast).  Single source of truth for the rank-lifting policy.
    """
    if rank < d:
        raise ValueError(f"relaxation rank {rank} must be >= d = {d}")
    if rank == d:
        return jnp.eye(d, dtype=dtype)
    return fixed_stiefel(rank, d, dtype)


def check_rotation_matrix(R, tol: float = 1e-8) -> bool:
    """Validate SO(d) membership: det +1 and orthonormal within ``tol``
    (reference ``checkRotationMatrix``, ``DPGO_utils.cpp:526-531`` — an
    assert there; a boolean here so callers choose raise vs mask).
    Batched: returns an [...] bool array for [..., d, d] input."""
    R = np.asarray(R)
    d = R.shape[-1]
    det_ok = np.abs(np.linalg.det(R) - 1.0) < tol
    eye = np.eye(d)
    orth = np.linalg.norm(
        np.swapaxes(R, -1, -2) @ R - eye, axis=(-2, -1)) < tol
    out = det_ok & orth
    return bool(out) if out.ndim == 0 else out


def angular_to_chordal_so3(rad: float) -> float:
    """Angular distance (radians) -> chordal (Frobenius) distance on SO(3).

    Reference ``angular2ChordalSO3`` (``DPGO_utils.cpp:522-524``).

    Returns a Python float: a ``np.float64`` scalar is strongly typed under
    jax_enable_x64 and would promote float32 GNC arithmetic to float64.
    """
    return float(2.0 * np.sqrt(2.0) * np.sin(rad / 2.0))


def chi2inv(quantile: float, dof: int) -> float:
    """Chi-squared quantile (reference ``DPGO_utils.cpp:517-520``, Boost.math).

    Config-time host scalar; uses scipy.
    """
    from scipy.stats import chi2

    return float(chi2.ppf(quantile, dof))


def error_threshold_at_quantile(quantile: float, dof: int = 6) -> float:
    """sqrt(chi2inv(q, dof)) — GNC barc from a probabilistic quantile
    (reference ``RobustCost::computeErrorThresholdAtQuantile``)."""
    return float(np.sqrt(chi2inv(quantile, dof)))


def se_matrix(R: np.ndarray, t: np.ndarray) -> np.ndarray:
    """Homogeneous SE(d) matrices [..., d+1, d+1] from R [..., d, d], t [..., d]."""
    R = np.asarray(R)
    t = np.asarray(t)
    d = R.shape[-1]
    T = np.zeros(R.shape[:-2] + (d + 1, d + 1), dtype=R.dtype)
    T[..., :d, :d] = R
    T[..., :d, d] = t
    T[..., d, d] = 1.0
    return T
