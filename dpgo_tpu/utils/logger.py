"""CSV trajectory / measurement logging and warm-restart checkpointing.

TPU-native equivalent of the reference's ``PGOLogger`` (``src/PGOLogger.cpp``):

* ``log_trajectory`` / ``load_trajectory`` — per-pose quaternion + translation
  CSV (header ``pose_index,qx,qy,qz,qw,tx,ty,tz``, ``PGOLogger.cpp:64``).
  Note a reference quirk: its *writer* emits translation before quaternion
  (``PGOLogger.cpp:70-77``) while its header and *loader* expect quaternion
  first (``PGOLogger.cpp:110-129``), so reference-written files do not
  round-trip through the reference loader.  We write in the header/loader
  order, so files written here load in both frameworks' loaders.
* ``log_measurements`` / ``load_measurements`` — measurement CSV including
  GNC weights and the known-inlier flag (``PGOLogger.cpp:29``, ``148-225``),
  enabling warm restart of a robust solve.
* ``save_matrix`` / ``load_matrix`` — raw matrix dump, standing in for the
  reference's ``writeMatrixToFile`` ``X.txt`` dumps (``DPGO_utils.cpp:35-63``,
  ``PGOAgent.cpp:602``).
* ``save_checkpoint`` / ``load_checkpoint`` — one-call solver checkpoint
  (lifted ``X``, edge weights, GNC ``mu``, iteration counter) for resuming
  an interrupted robust RBCD run; beyond-reference convenience built on the
  same CSV primitives.
* ``save_checkpoint_orbax`` / ``load_checkpoint_orbax`` — the same bundle
  through Orbax (atomic directory commits; sharding-aware restore against
  an abstract target), via the optional ``orbax`` extra.

Unlike the reference, which silently skips 2D problems (``PGOLogger.cpp:27``,
``57``), SE(2) trajectories/measurements are logged by embedding the yaw
rotation as a quaternion about z; pass ``d=2`` to the loaders to recover the
planar form.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from ..types import Measurements
from .lie import quat_to_rotation, rotation_to_quat

TRAJECTORY_HEADER = "pose_index,qx,qy,qz,qw,tx,ty,tz"
MEASUREMENT_HEADER = ("robot_src,pose_src,robot_dst,pose_dst,"
                     "qx,qy,qz,qw,tx,ty,tz,kappa,tau,is_known_inlier,weight")


def _embed_rotations(R: np.ndarray) -> np.ndarray:
    """[n, d, d] rotations -> [n, 3, 3], embedding SE(2) yaw about z."""
    R = np.asarray(R, np.float64)
    if R.shape[-1] == 3:
        return R
    n = R.shape[0]
    out = np.tile(np.eye(3), (n, 1, 1))
    out[:, :2, :2] = R
    return out


def _embed_translations(t: np.ndarray) -> np.ndarray:
    t = np.asarray(t, np.float64)
    if t.shape[-1] == 3:
        return t
    return np.concatenate([t, np.zeros((t.shape[0], 1))], axis=-1)


def log_trajectory(T: np.ndarray, path: str) -> None:
    """Write a trajectory ``T: [n, d, d+1]`` of SE(d) poses to CSV.

    Header-order columns (quaternion then translation), matching the
    reference loader (``PGOLogger.cpp:110-129``).
    """
    T = np.asarray(T, np.float64)
    n, d = T.shape[0], T.shape[1]
    q = rotation_to_quat(_embed_rotations(T[:, :, :d]))  # [n, 4] (x, y, z, w)
    t = _embed_translations(T[:, :, d])
    with open(path, "w") as f:
        f.write(TRAJECTORY_HEADER + "\n")
        for i in range(n):
            row = [i, *q[i], *t[i]]
            f.write(",".join(_fmt(v) for v in row) + "\n")


def load_trajectory(path: str, d: int = 3) -> np.ndarray:
    """Load a trajectory CSV back into ``[n, d, d+1]`` (indexed by pose_index)."""
    raw = np.loadtxt(path, delimiter=",", skiprows=1, ndmin=2)
    if raw.size == 0:
        return np.zeros((0, d, d + 1))
    order = np.argsort(raw[:, 0].astype(int))
    raw = raw[order]
    q = raw[:, 1:5]
    q /= np.linalg.norm(q, axis=-1, keepdims=True)
    R = quat_to_rotation(q)
    t = raw[:, 5:8]
    n = raw.shape[0]
    T = np.zeros((n, d, d + 1))
    T[:, :, :d] = R[:, :d, :d]
    T[:, :, d] = t[:, :d]
    return T


def log_measurements(meas: Measurements, path: str) -> None:
    """Write a ``Measurements`` batch (incl. GNC weights) to CSV.

    Same schema as the reference (``PGOLogger.cpp:29``): the final weights of
    a robust solve ride along so a restart can skip re-running GNC from
    scratch.
    """
    q = rotation_to_quat(_embed_rotations(meas.R))
    t = _embed_translations(meas.t)
    with open(path, "w") as f:
        f.write(MEASUREMENT_HEADER + "\n")
        for k in range(len(meas)):
            row = [int(meas.r1[k]), int(meas.p1[k]),
                   int(meas.r2[k]), int(meas.p2[k]),
                   *q[k], *t[k],
                   meas.kappa[k], meas.tau[k],
                   int(meas.is_known_inlier[k]), meas.weight[k]]
            f.write(",".join(_fmt(v) for v in row) + "\n")


def load_measurements(path: str, load_weight: bool = True,
                      d: int = 3) -> Measurements:
    """Load a measurement CSV back into ``Measurements``.

    ``load_weight=False`` resets GNC weights to 1 (fresh robust solve from
    logged data), mirroring the reference's flag (``PGOLogger.cpp:148``).
    """
    raw = np.loadtxt(path, delimiter=",", skiprows=1, ndmin=2)
    if raw.size == 0:
        z = np.zeros(0)
        return Measurements(
            d=d, num_poses=0,
            r1=z.astype(np.int32), p1=z.astype(np.int64),
            r2=z.astype(np.int32), p2=z.astype(np.int64),
            R=np.zeros((0, d, d)), t=np.zeros((0, d)),
            kappa=z, tau=z, weight=z, is_known_inlier=z.astype(bool))
    m = raw.shape[0]
    q = raw[:, 4:8]
    q /= np.linalg.norm(q, axis=-1, keepdims=True)
    R = quat_to_rotation(q)[:, :d, :d]
    t = raw[:, 8:11][:, :d]
    p1 = raw[:, 1].astype(np.int64)
    p2 = raw[:, 3].astype(np.int64)
    return Measurements(
        d=d,
        num_poses=int(max(p1.max(), p2.max())) + 1 if m else 0,
        r1=raw[:, 0].astype(np.int32),
        p1=p1,
        r2=raw[:, 2].astype(np.int32),
        p2=p2,
        R=np.ascontiguousarray(R),
        t=np.ascontiguousarray(t),
        kappa=raw[:, 11],
        tau=raw[:, 12],
        weight=raw[:, 14] if load_weight else np.ones(m),
        is_known_inlier=raw[:, 13].astype(bool),
    )


def save_matrix(M: np.ndarray, path: str) -> None:
    """Plain-text matrix dump (reference ``writeMatrixToFile``,
    ``DPGO_utils.cpp:35-49``: one row per line, space-separated)."""
    np.savetxt(path, np.asarray(M).reshape(M.shape[0], -1))


def load_matrix(path: str, shape=None) -> np.ndarray:
    M = np.loadtxt(path, ndmin=2)
    return M.reshape(shape) if shape is not None else M


def _fmt(v) -> str:
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    return repr(float(v))


# ---------------------------------------------------------------------------
# Solver checkpoint (warm restart)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Checkpoint:
    """Everything needed to resume a (robust) solve.

    The reference's resume path is ``loadTrajectory`` +
    ``loadMeasurements(load_weight=true)`` feeding ``setPoseGraph``
    (``PGOLogger.cpp:83-225``); this bundles the same data plus the lifted
    iterate and GNC state so resumption is exact, not just warm.
    """

    X: np.ndarray          # lifted iterate, solver-native shape
    weights: np.ndarray    # per-edge GNC weights (solver-native layout)
    mu: float              # current GNC mu
    iteration: int         # outer iteration count


def save_checkpoint(ckpt: Checkpoint, directory: str) -> None:
    os.makedirs(directory, exist_ok=True)
    np.savez(os.path.join(directory, "state.npz"),
             X=np.asarray(ckpt.X), weights=np.asarray(ckpt.weights))
    with open(os.path.join(directory, "meta.json"), "w") as f:
        json.dump({"mu": float(ckpt.mu), "iteration": int(ckpt.iteration)}, f)


def load_checkpoint(directory: str) -> Checkpoint:
    data = np.load(os.path.join(directory, "state.npz"))
    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    return Checkpoint(X=data["X"], weights=data["weights"],
                      mu=meta["mu"], iteration=meta["iteration"])


# ---------------------------------------------------------------------------
# Orbax backend (TPU-ecosystem-native store)
# ---------------------------------------------------------------------------

def save_checkpoint_orbax(ckpt: Checkpoint, directory: str) -> None:
    """Write the checkpoint through Orbax (the JAX-ecosystem store: atomic
    directory commits, sharding-aware restore, async-capable for multi-host
    runs).  Same ``Checkpoint`` contents as the npz backend, but the two
    formats are distinct — load with ``load_checkpoint_orbax`` (installing
    the ``orbax`` extra: ``pip install dpgo-tpu[orbax]``)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"), {
            "X": np.asarray(ckpt.X),
            "weights": np.asarray(ckpt.weights),
            "mu": np.asarray(float(ckpt.mu)),
            "iteration": np.asarray(int(ckpt.iteration)),
        }, force=True)


def load_checkpoint_orbax(directory: str,
                          like: Checkpoint | None = None) -> Checkpoint:
    """Restore an Orbax-format checkpoint written by
    ``save_checkpoint_orbax``.

    Pass ``like`` (anything with the target shapes/dtypes, e.g. the freshly
    built solver state wrapped in a ``Checkpoint``) to restore against an
    abstract target — required for sharding-aware multi-host restore and to
    avoid Orbax's untyped-restore path; without it the restore is
    host-local and untyped (fine for the single-process resume flow)."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    target = None
    if like is not None:
        target = {
            "X": jax.ShapeDtypeStruct(np.shape(like.X),
                                      np.asarray(like.X).dtype),
            "weights": jax.ShapeDtypeStruct(np.shape(like.weights),
                                            np.asarray(like.weights).dtype),
            "mu": jax.ShapeDtypeStruct((), np.float64),
            "iteration": jax.ShapeDtypeStruct((), np.int64),
        }
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.join(path, "state"), target)
    return Checkpoint(X=np.asarray(tree["X"]),
                      weights=np.asarray(tree["weights"]),
                      mu=float(tree["mu"]), iteration=int(tree["iteration"]))
