"""Multi-agent graph topology planning (host runtime).

Computes the padded index structure of the batched RBCD layout from edge
endpoints: per-agent edge rows with remote endpoints redirected to neighbor
slots, public-pose tables, neighbor-slot tables, and the ELL incidence —
the double bookkeeping of the reference's ``PGOAgent::addSharedLoopClosure``
(``src/PGOAgent.cpp:228-248``) as index arrays.

Two backends with bit-identical output (same scan/insertion orders):

* **native** — ``native/graph_builder.cpp`` via ctypes (the reference's
  ingestion/classification runtime is C++; so is ours).  O(M) with hash
  maps, ~10-20x the Python planner at 100k-pose scale.
* **python** — dict-based fallback when no toolchain is available.

``plan_topology`` dispatches (``backend="auto" | "native" | "python"``).
"""

from __future__ import annotations

import ctypes
from typing import NamedTuple

import numpy as np

from . import native_io


class TopologyPlan(NamedTuple):
    e_max: int
    s_max: int
    p_max: int
    k_max: int
    ei: np.ndarray        # [A, e_max] int32, index into [n_max + s_max]
    ej: np.ndarray        # [A, e_max] int32
    meas_id: np.ndarray   # [A, e_max] int64 global measurement id
    emask: np.ndarray     # [A, e_max] bool
    pub_idx: np.ndarray   # [A, p_max] int64 local indices of public poses
    pub_mask: np.ndarray  # [A, p_max] bool
    nbr_robot: np.ndarray  # [A, s_max] int32
    nbr_pub: np.ndarray    # [A, s_max] int32 position in that robot's table
    nbr_mask: np.ndarray   # [A, s_max] bool
    inc_slot: np.ndarray   # [A, n_max, k_max] int32 into [gi | gj]
    inc_mask: np.ndarray   # [A, n_max, k_max] bool


class _DpgoGraphPlan(ctypes.Structure):
    _fields_ = [
        ("A", ctypes.c_int32),
        ("n_max", ctypes.c_int32),
        ("e_max", ctypes.c_int32),
        ("s_max", ctypes.c_int32),
        ("p_max", ctypes.c_int32),
        ("k_max", ctypes.c_int32),
        ("ei", ctypes.POINTER(ctypes.c_int32)),
        ("ej", ctypes.POINTER(ctypes.c_int32)),
        ("meas_id", ctypes.POINTER(ctypes.c_int64)),
        ("emask", ctypes.POINTER(ctypes.c_uint8)),
        ("pub_idx", ctypes.POINTER(ctypes.c_int64)),
        ("pub_mask", ctypes.POINTER(ctypes.c_uint8)),
        ("nbr_robot", ctypes.POINTER(ctypes.c_int32)),
        ("nbr_pub", ctypes.POINTER(ctypes.c_int32)),
        ("nbr_mask", ctypes.POINTER(ctypes.c_uint8)),
        ("inc_slot", ctypes.POINTER(ctypes.c_int32)),
        ("inc_mask", ctypes.POINTER(ctypes.c_uint8)),
        ("error", ctypes.c_char * 256),
    ]


_registered = False


def _graph_lib():
    """The shared native library with the graph symbols registered, or
    None when unavailable."""
    global _registered
    lib = native_io.load_library()
    if lib is None:
        return None
    if not _registered:
        if not hasattr(lib, "dpgo_graph_plan"):
            # A stale prebuilt library without the graph symbols (load_library
            # rebuilds when the source tree is present, so this only happens
            # for a shipped .so) — fall back to the Python planner.
            return None
        lib.dpgo_graph_plan.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(_DpgoGraphPlan),
        ]
        lib.dpgo_graph_plan.restype = ctypes.c_int
        lib.dpgo_graph_free.argtypes = [ctypes.POINTER(_DpgoGraphPlan)]
        lib.dpgo_graph_free.restype = None
        _registered = True
    return lib


def plan_native(r1, p1, r2, p2, num_robots: int, n_max: int) -> TopologyPlan:
    lib = _graph_lib()
    if lib is None:
        raise RuntimeError("native graph planner unavailable")
    r1 = np.ascontiguousarray(r1, np.int32)
    p1 = np.ascontiguousarray(p1, np.int64)
    r2 = np.ascontiguousarray(r2, np.int32)
    p2 = np.ascontiguousarray(p2, np.int64)
    M = len(r1)
    out = _DpgoGraphPlan()
    rc = lib.dpgo_graph_plan(M, r1, p1, r2, p2, num_robots, n_max,
                             ctypes.byref(out))
    if rc != 0:
        err = out.error.decode(errors="replace")
        raise ValueError(f"native graph plan failed: {err}")
    try:
        A = num_robots
        e, s, p, k = out.e_max, out.s_max, out.p_max, out.k_max
        as_np = np.ctypeslib.as_array
        plan = TopologyPlan(
            e_max=int(e), s_max=int(s), p_max=int(p), k_max=int(k),
            ei=as_np(out.ei, (A, e)).copy(),
            ej=as_np(out.ej, (A, e)).copy(),
            meas_id=as_np(out.meas_id, (A, e)).copy(),
            emask=as_np(out.emask, (A, e)).astype(bool),
            pub_idx=as_np(out.pub_idx, (A, p)).copy(),
            pub_mask=as_np(out.pub_mask, (A, p)).astype(bool),
            nbr_robot=as_np(out.nbr_robot, (A, s)).copy(),
            nbr_pub=as_np(out.nbr_pub, (A, s)).copy(),
            nbr_mask=as_np(out.nbr_mask, (A, s)).astype(bool),
            inc_slot=as_np(out.inc_slot, (A, n_max, k)).copy(),
            inc_mask=as_np(out.inc_mask, (A, n_max, k)).astype(bool),
        )
    finally:
        lib.dpgo_graph_free(ctypes.byref(out))
    return plan


def plan_python(r1, p1, r2, p2, num_robots: int, n_max: int) -> TopologyPlan:
    """Pure-Python planner — the specification the native backend mirrors
    (including input validation, so both backends fail identically on bad
    indices instead of one silently corrupting the plan)."""
    A = num_robots
    M = len(r1)
    r = np.concatenate([np.asarray(r1), np.asarray(r2)])
    p = np.concatenate([np.asarray(p1), np.asarray(p2)])
    if M and ((r < 0).any() or (r >= A).any()):
        raise ValueError(f"edge references robot out of range [0, {A})")
    if M and ((p < 0).any() or (p >= n_max).any()):
        raise ValueError(f"edge pose index out of range [0, {n_max})")

    pub: list[dict[int, int]] = [dict() for _ in range(A)]
    for k in range(M):
        a, b = int(r1[k]), int(r2[k])
        if a != b:
            pub[a].setdefault(int(p1[k]), len(pub[a]))
            pub[b].setdefault(int(p2[k]), len(pub[b]))

    nbr: list[dict[tuple[int, int], int]] = [dict() for _ in range(A)]
    edge_rows: list[list[tuple]] = [[] for _ in range(A)]
    for k in range(M):
        a, b = int(r1[k]), int(r2[k])
        p, q = int(p1[k]), int(p2[k])
        if a == b:
            edge_rows[a].append((p, q, k))
        else:
            sa = nbr[a].setdefault((b, q), len(nbr[a]))
            edge_rows[a].append((p, n_max + sa, k))
            sb = nbr[b].setdefault((a, p), len(nbr[b]))
            edge_rows[b].append((n_max + sb, q, k))

    e_max = max(1, max(len(r) for r in edge_rows))
    s_max = max(1, max(len(x) for x in nbr))
    p_max = max(1, max(len(x) for x in pub))

    ei = np.zeros((A, e_max), np.int32)
    ej = np.zeros((A, e_max), np.int32)
    meas_id = np.zeros((A, e_max), np.int64)
    emask = np.zeros((A, e_max), bool)
    for a in range(A):
        for idx, (i, j, k) in enumerate(edge_rows[a]):
            ei[a, idx] = i
            ej[a, idx] = j
            meas_id[a, idx] = k
            emask[a, idx] = True

    pub_idx = np.zeros((A, p_max), np.int64)
    pub_mask = np.zeros((A, p_max), bool)
    for a in range(A):
        for q, pos in pub[a].items():
            pub_idx[a, pos] = q
            pub_mask[a, pos] = True

    nbr_robot = np.zeros((A, s_max), np.int32)
    nbr_pub = np.zeros((A, s_max), np.int32)
    nbr_mask = np.zeros((A, s_max), bool)
    for a in range(A):
        for (b, q), slot in nbr[a].items():
            nbr_robot[a, slot] = b
            nbr_pub[a, slot] = pub[b][q]
            nbr_mask[a, slot] = True

    inc: list[list[list[int]]] = [[[] for _ in range(n_max)] for _ in range(A)]
    for a in range(A):
        for idx, (i, j, _k) in enumerate(edge_rows[a]):
            if i < n_max:
                inc[a][i].append(idx)
            if j < n_max:
                inc[a][j].append(e_max + idx)
    k_max = max(1, max((len(s) for rows in inc for s in rows), default=1))
    inc_slot = np.zeros((A, n_max, k_max), np.int32)
    inc_mask = np.zeros((A, n_max, k_max), bool)
    for a in range(A):
        for v in range(n_max):
            for c, slot in enumerate(inc[a][v]):
                inc_slot[a, v, c] = slot
                inc_mask[a, v, c] = True

    return TopologyPlan(e_max=e_max, s_max=s_max, p_max=p_max, k_max=k_max,
                        ei=ei, ej=ej, meas_id=meas_id, emask=emask,
                        pub_idx=pub_idx, pub_mask=pub_mask,
                        nbr_robot=nbr_robot, nbr_pub=nbr_pub,
                        nbr_mask=nbr_mask, inc_slot=inc_slot,
                        inc_mask=inc_mask)


def plan_topology(r1, p1, r2, p2, num_robots: int, n_max: int,
                  backend: str = "auto") -> TopologyPlan:
    """Dispatch: ``"native"`` (raise when unavailable), ``"python"``, or
    ``"auto"`` (native when the library loads, else Python)."""
    if backend == "native":
        return plan_native(r1, p1, r2, p2, num_robots, n_max)
    if backend == "python":
        return plan_python(r1, p1, r2, p2, num_robots, n_max)
    if backend != "auto":
        raise ValueError(f"unknown planner backend {backend!r}")
    if _graph_lib() is not None:
        return plan_native(r1, p1, r2, p2, num_robots, n_max)
    return plan_python(r1, p1, r2, p2, num_robots, n_max)


def color_agents(nbr_robot: np.ndarray, nbr_mask: np.ndarray,
                 num_robots: int) -> tuple[np.ndarray, int]:
    """Greedy (largest-degree-first) coloring of the agent-adjacency graph.

    Agents are adjacent when they share an inter-robot measurement (the
    planner's neighbor-slot tables already encode exactly this).  Returns
    ``(color [A] int32, num_colors)``: same-colored agents have no shared
    edge, so updating a whole color class simultaneously is the
    parallelism the RBCD convergence theory actually licenses (blocks of
    non-adjacent agents have independent local subproblems) — the
    ``Schedule.COLORED`` multi-color Gauss-Seidel sweep.
    """
    adj = [set() for _ in range(num_robots)]
    nr = np.asarray(nbr_robot)
    nm = np.asarray(nbr_mask) > 0
    for a in range(num_robots):
        for b in np.unique(nr[a][nm[a]]):
            b = int(b)
            if b != a:
                adj[a].add(b)
                adj[b].add(a)
    order = sorted(range(num_robots), key=lambda a: -len(adj[a]))
    color = np.full(num_robots, -1, np.int32)
    for a in order:
        used = {color[b] for b in adj[a] if color[b] >= 0}
        c = 0
        while c in used:
            c += 1
        color[a] = c
    return color, int(color.max()) + 1 if num_robots else 1
