"""Configuration dataclasses for the TPU-native DPGO framework.

Mirrors the reference's plain-struct configuration surface
(``PGOAgentParameters``, reference ``include/DPGO/PGOAgent.h:59-160``, and
``RobustCostParameters``, reference ``include/DPGO/DPGO_robust.h:34-68``)
with the same defaults, re-expressed as frozen dataclasses so they can be
closed over by jitted step functions as static configuration.
"""

from __future__ import annotations

import dataclasses
import enum


class ROptAlg(enum.Enum):
    """Local solver choice (reference ``DPGO_types.h:28-32``)."""

    RTR = "RTR"  # Riemannian trust region with truncated CG
    RGD = "RGD"  # Riemannian gradient descent (fixed step)


class RobustCostType(enum.Enum):
    """Supported robust cost functions (reference ``DPGO_robust.h:20-27``)."""

    L2 = "L2"
    L1 = "L1"
    TLS = "TLS"
    Huber = "Huber"
    GM = "GM"
    GNC_TLS = "GNC_TLS"


class Schedule(enum.Enum):
    """Block-update schedule for distributed RBCD.

    GREEDY reproduces the reference driver's one-agent-per-round selection by
    largest block gradient norm (``examples/MultiRobotExample.cpp:242-256``).
    JACOBI updates all agents simultaneously each round — the TPU-native
    default (serializing agents on a mesh wastes the hardware; the papers'
    RBCD admits parallel updates, and the reference's async mode realizes the
    same delay-tolerant semantics).  ASYNC updates an independent random
    subset per round, the on-device analog of the reference's Poisson-clock
    threads (``PGOAgent.cpp:876-898``).  COLORED fires one color class of a
    greedy coloring of the agent-adjacency graph per round — simultaneous
    updates only of NON-adjacent blocks, which is exactly the parallelism
    the RBCD theory licenses (Tian et al., T-RO 2021: blocks sharing no
    edge have independent subproblems): a deterministic multi-color
    Gauss-Seidel sweep that cannot oscillate the way JACOBI does on
    strongly-coupled graphs (measured on ais2klinik, BASELINE.md), at the
    cost of advancing only ~A/num_colors agents per round.
    """

    GREEDY = "greedy"
    JACOBI = "jacobi"
    ASYNC = "async"
    COLORED = "colored"


@dataclasses.dataclass(frozen=True)
class RobustCostParams:
    """Defaults mirror reference ``DPGO_robust.h:48-55``."""

    cost_type: RobustCostType = RobustCostType.L2
    gnc_max_iters: int = 100
    gnc_barc: float = 10.0
    gnc_mu_step: float = 1.4
    gnc_init_mu: float = 1e-4
    huber_threshold: float = 3.0
    tls_threshold: float = 10.0


@dataclasses.dataclass(frozen=True)
class SolverParams:
    """Local trust-region / gradient solver knobs.

    Defaults follow the per-iteration budget the reference agent uses inside
    RBCD (``PGOAgent.cpp:1131-1137``): 1 outer RTR iteration, <=10 truncated
    CG inner iterations, gradnorm tolerance 1e-2, initial radius 100, and the
    shrink-on-reject loop of ``QuadraticOptimizer.cpp:92-110`` (radius /= 4,
    at most 10 rejections).

    The reference additionally bounds each solve by 5 s of wall clock
    (``QuadraticOptimizer.cpp:90``).  A data-dependent time bound cannot
    exist inside a compiled XLA program; the equivalent safety here is that
    every loop has a static trip count (outer/inner iteration caps,
    rejection cap), so a solve's cost is bounded at compile time rather
    than interrupted at runtime.
    """

    algorithm: ROptAlg = ROptAlg.RTR
    grad_norm_tol: float = 1e-2
    max_outer_iters: int = 1
    max_inner_iters: int = 10
    initial_radius: float = 100.0
    max_rejections: int = 10
    # tCG convergence: ||r|| <= ||r0|| * min(kappa, ||r0||^theta)
    tcg_kappa: float = 0.1
    tcg_theta: float = 1.0
    # Riemannian gradient descent stepsize (reference gradientDescent:
    # fixed step, preconditioning present but commented out,
    # QuadraticOptimizer.cpp:124-149)
    rgd_stepsize: float = 1e-3
    # Tikhonov shift used when factoring the block-Jacobi preconditioner,
    # matching the reference's Q + 0.1 I CHOLMOD factorization
    # (QuadraticProblem.cpp:31-42)
    precond_shift: float = 0.1
    # Run the truncated-CG subproblem as the single VMEM-resident Pallas
    # kernel (``ops.pallas_tcg``).  None = auto: on when the backend is TPU
    # and the graph carries the kernel's selection matrices; True forces it
    # (interpreter mode off-TPU — slow, for testing); False disables.
    pallas_tcg: bool | None = None
    # Run the kernel's one-hot gather/scatter matmuls as two bf16 passes
    # (hi/lo split of the gathered vectors; the 0/1 selection matrices are
    # bf16-exact) instead of f32 — ~2x on the MXU-bound large-problem
    # shapes, at ~2^-16 relative hessvec/cost error.  Opt-in: appropriate
    # when running the reference's loose per-step budget (tol 1e-2); the
    # refine kernel ignores this flag (it runs f32 — or bf16x3 when that
    # f32-grade mode is selected via pallas_sel_mode).
    pallas_bf16_select: bool = False
    # Selection-matmul mode, superseding ``pallas_bf16_select`` when set:
    # "f32" (Precision.HIGHEST — ~6 emulated bf16 MXU passes), "bf16"
    # (2-pass hi/lo split, ~2^-16 error — what pallas_bf16_select turns
    # on), or "bf16x3" (3-pass hi/mid/lo split covering the full 24-bit
    # f32 mantissa: f32-grade accuracy at half the HIGHEST pass count,
    # since the bf16-exact one-hots need no split of their own).
    # "" = derive from pallas_bf16_select.
    pallas_sel_mode: str = ""
    # Materialize each agent's buffer connection Laplacian and run
    # cost/gradient/Hessian as dense matmuls (``quadratic.dense_q``).
    # Opt-in: the dense products are HBM-bandwidth-bound reading the
    # (mostly zero) [K, K] matrix and measure ~4x slower than the ELL edge
    # path on sphere2500/8 on TPU v5e; the formulation is kept for parity
    # testing and for parts with denser connectivity.
    dense_quadratic: bool = False


@dataclasses.dataclass(frozen=True)
class AgentParams:
    """Distributed RBCD parameters (reference ``PGOAgent.h:59-160``)."""

    d: int = 3
    r: int = 5
    num_robots: int = 1
    solver: SolverParams = SolverParams()
    # Nesterov acceleration (RA-L 2020)
    acceleration: bool = False
    restart_interval: int = 30
    # Robust optimization (GNC)
    robust: RobustCostParams = RobustCostParams()
    robust_init_min_inliers: int = 2
    # Beyond-reference: cap on the number of GNC weight updates (<= 0 means
    # unlimited, the reference behavior; mu annealing is separately capped at
    # robust.gnc_max_iters steps as in the reference).  Converged weights
    # make further updates no-ops, but with warm start disabled each update
    # also resets the iterate, so an uncapped schedule never settles — set a
    # finite cap for that configuration.
    robust_opt_num_weight_updates: int = 0
    robust_opt_inner_iters: int = 30
    robust_opt_warm_start: bool = True
    robust_opt_min_convergence_ratio: float = 0.8
    # Termination
    max_num_iters: int = 500
    rel_change_tol: float = 5e-3
    # Deployment-plane verdict cadence (beyond-reference): PGOAgent's
    # iterate() materializes its one status scalar (the relative change)
    # only every this-many iterates, leaving it device-latched in
    # between — the per-robot analog of the solver core's K-round
    # verdict-word readback.  The gossiped termination status then lags
    # the iterate by at most this many rounds.  1 (default) fetches every
    # iterate (the exact pre-verdict behavior); telemetry-on runs always
    # fetch per iterate regardless (the events carry the scalar).
    status_fetch_every: int = 1
    # Terminal certification (ROADMAP item 3): "off" returns no
    # certificate; "device" folds a gauge-deflated LOBPCG on the dual
    # operator S = Q - Lambda into the solve's terminal epilogue so the
    # certificate rides the single terminal fetch (the host sparse/f64
    # path runs only when the f32 verdict lands in the disagreement band
    # and is REFUSEd); "host" runs the legacy post-hoc
    # ``certify.certify_solution`` host round-trip on the rounded result.
    certify_mode: str = "off"
    # Relative suboptimality tolerance for the terminal certificate
    # (same eta as ``certify.certify_solution``; the acceptance threshold
    # is eta * weight_scale(edges)).
    certify_eta: float = 1e-5
    # Schedule for the TPU step function
    schedule: Schedule = Schedule.JACOBI
    # Probability that an agent fires in a given ASYNC round (Poisson-clock
    # analog; each agent updates independently with this probability)
    async_update_prob: float = 0.5
    verbose: bool = False
    # Data logging (reference logData/logDirectory, PGOAgent.h:131-136):
    # when enabled the per-robot runtime dumps trajectory/measurement CSVs
    # and the raw lifted X on reset() and an early-stop trajectory snapshot
    # at iteration 50 (PGOAgent.cpp:583-603, 646-651).  Each agent writes
    # under log_directory/robot{id}/ — unlike the reference's one-process-
    # per-robot layout, one AgentParams is commonly shared by all agents
    # here, and a flat directory would collide on the fixed file names.
    log_data: bool = False
    log_directory: str = ""
