"""Crash-recovery session store: durable solver-state snapshots.

The flight recorder (``obs.recorder``) snapshots exact ``RBCDState``\\ s
for *replay* — a black box read after the fact.  This module promotes the
same snapshot payload to a *session store*: a directory of
schema-versioned ``.npz`` state files a live server writes on solve
boundaries and reads back to re-admit work that died mid-batch.  It is a
durability feature, not telemetry — it works with the obs stack entirely
off (events/counters about it are separately fenced by the callers).

Layout (one subdirectory per session id)::

    <root>/<session id>/snap-00000040.npz     # newest wins
    <root>/<session id>/snap-00000020.npz
    <root>/<session id>/snap-00000020.npz.quarantined  # failed validation

Every snapshot carries ``__schema__`` (``SESSION_SCHEMA_VERSION``) and the
full ``RBCDState`` array set (``models.incremental.state_to_arrays``); the
factors (``chol``/``Qbuf``) are never persisted — ``refresh_problem``
recomputes them bit-for-bit from the stored weights.  Writes are atomic
(temp file + rename), so a crash mid-write leaves at worst one torn temp
file, never a torn snapshot.

``load_newest`` is the recovery contract the server worker relies on:
newest-first, any snapshot that fails to parse (truncated zip, bit-flipped
member, wrong schema version, missing state field) is QUARANTINED — renamed
aside so it is never retried — and the previous snapshot is tried instead.
A corrupt store therefore degrades to an older resume point or a clean
``None`` (cold re-solve); it never raises into the worker loop.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading

import numpy as np

from .. import obs
from ..models.incremental import state_from_arrays, state_to_arrays
from ..models.rbcd import RBCDState

#: Bump on any incompatible change to the snapshot array set.  A loader
#: finding an unknown version quarantines the file — resuming a solver
#: from arrays with silently different semantics is worse than a cold
#: re-solve.  v2 (the pod-scale resilience round) adds the OPTIONAL
#: mesh tags ``__mesh_shape__`` / ``__global_index__``: the mesh the
#: snapshot was taken on and the agent->global-pose layout it assumes,
#: so a mesh-elastic restore can verify the layout before resuming.
SESSION_SCHEMA_VERSION = 2

#: Schema versions this reader accepts.  v1 snapshots are a strict
#: subset of v2 (no mesh tags), so old single-device snapshots keep
#: loading; v1-era readers see ``2 != 1`` and quarantine mesh-tagged
#: snapshots (fail-open: recovery degrades to an older snapshot or a
#: cold re-solve, never a mis-resumed one).
_COMPAT_SCHEMAS = (1, 2)

_SNAP_RE = re.compile(r"^snap-(\d{8})\.npz$")
#: RBCDState fields every valid snapshot must carry (the optional
#: ``V``/``X_init`` are schema-legal absences).
_REQUIRED = ("X", "weights", "key", "rel_change", "ready", "gamma",
             "alpha", "mu")


@dataclasses.dataclass
class SessionSnapshot:
    """One recovered snapshot: the rebuilt state plus its bookkeeping."""

    session_id: str
    path: str
    iteration: int
    num_weight_updates: int
    state: RBCDState
    meta: dict
    #: Mesh tags (schema v2, ``parallel.resilience``); None on v1
    #: snapshots and single-device saves.
    mesh_shape: tuple | None = None
    global_index: "np.ndarray | None" = None


def _sanitize(session_id: str) -> str:
    """Session ids become directory names; keep them path-safe."""
    out = re.sub(r"[^A-Za-z0-9._-]", "_", str(session_id))
    if not out or out in (".", ".."):
        raise ValueError(f"invalid session id {session_id!r}")
    return out


class SessionStore:
    """Directory-backed store of per-session solver snapshots.

    Thread-safe: the server worker saves while client threads may list or
    discard; one lock serializes directory mutations per store."""

    def __init__(self, root: str, keep: int = 2,
                 async_write: bool = False):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.root = str(root)
        self.keep = int(keep)
        self._lock = threading.Lock()
        #: Off-thread write mode (``save_async``): one daemon writer and
        #: a ONE-SLOT pending buffer — last writer wins, so a slow disk
        #: never queues a backlog of stale snapshots; the freshest state
        #: is always the one that lands.  ``flush()`` drains it.
        self.async_write = bool(async_write)
        self._wcond = threading.Condition()
        self._wpending: dict | None = None
        self._winflight = False
        self._wthread: threading.Thread | None = None
        self.last_write_error: Exception | None = None
        os.makedirs(self.root, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _dir(self, session_id: str) -> str:
        return os.path.join(self.root, _sanitize(session_id))

    def _snaps(self, sdir: str) -> list[tuple[int, str]]:
        """(sequence, filename) of intact-looking snapshots, oldest first."""
        try:
            names = os.listdir(sdir)
        except OSError:
            return []
        out = []
        for name in names:
            m = _SNAP_RE.match(name)
            if m:
                out.append((int(m.group(1)), name))
        return sorted(out)

    # -- writing -------------------------------------------------------------

    def save(self, session_id: str, state: RBCDState, iteration: int,
             num_weight_updates: int = 0, meta: dict | None = None,
             mesh_shape: tuple | None = None,
             global_index=None) -> str:
        """Persist one snapshot atomically; prune to the ``keep`` newest.
        ``iteration`` doubles as the snapshot sequence number, so saves on
        the solver's K-boundaries land in replayable order.
        ``mesh_shape`` / ``global_index`` are the v2 mesh tags
        (``parallel.resilience``): the mesh the state was gathered from
        and the agent->global-pose layout the arrays assume."""
        arrays = self._snapshot_arrays(state, iteration, num_weight_updates,
                                       meta, mesh_shape, global_index)
        return self._write(session_id, arrays, int(iteration))

    def _snapshot_arrays(self, state, iteration, num_weight_updates, meta,
                         mesh_shape, global_index) -> dict:
        """Materialize the snapshot payload on the CALLER'S thread — any
        device arrays in the state transfer here, so the async writer
        only ever touches host memory and the filesystem."""
        arrays = {k: np.asarray(v)
                  for k, v in state_to_arrays(state).items()}
        arrays["__schema__"] = np.asarray(SESSION_SCHEMA_VERSION, np.int64)
        arrays["__iteration__"] = np.asarray(int(iteration), np.int64)
        arrays["__nwu__"] = np.asarray(int(num_weight_updates), np.int64)
        if mesh_shape is not None:
            arrays["__mesh_shape__"] = np.asarray(mesh_shape, np.int64)
        if global_index is not None:
            arrays["__global_index__"] = np.asarray(global_index)
        if meta:
            arrays["__meta__"] = np.frombuffer(
                json.dumps(meta, sort_keys=True).encode("utf-8"), np.uint8)
        return arrays

    def _write(self, session_id: str, arrays: dict, iteration: int) -> str:
        sdir = self._dir(session_id)
        with self._lock:
            os.makedirs(sdir, exist_ok=True)
            path = os.path.join(sdir, f"snap-{int(iteration):08d}.npz")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                np.savez_compressed(fh, **arrays)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            for _, name in self._snaps(sdir)[:-self.keep]:
                try:
                    os.remove(os.path.join(sdir, name))
                except OSError:
                    pass
        run = obs.get_run()
        if run is not None:
            run.counter("session_saves_total",
                        "session snapshots persisted").inc()
            run.event("session_saved", phase="session",
                      session=str(session_id), iteration=int(iteration),
                      path=path)
        return path

    # -- off-thread writes ---------------------------------------------------

    def save_async(self, session_id: str, state: RBCDState, iteration: int,
                   num_weight_updates: int = 0, meta: dict | None = None,
                   mesh_shape: tuple | None = None,
                   global_index=None) -> str:
        """``save`` with the npz compression + fsync moved to the store's
        writer thread (``async_write=True``; otherwise falls back to the
        synchronous ``save``).  The state materializes on the caller's
        thread, so the enqueued payload is immutable host memory; the
        pending slot is last-writer-wins — a newer boundary snapshot
        replaces an unwritten older one rather than queueing behind it.
        Returns the path the snapshot WILL land at; call ``flush()``
        before reading it back."""
        if not self.async_write:
            return self.save(session_id, state, iteration,
                             num_weight_updates, meta, mesh_shape,
                             global_index)
        arrays = self._snapshot_arrays(state, iteration, num_weight_updates,
                                       meta, mesh_shape, global_index)
        path = os.path.join(self._dir(session_id),
                            f"snap-{int(iteration):08d}.npz")
        with self._wcond:
            self._wpending = {"session_id": session_id, "arrays": arrays,
                              "iteration": int(iteration)}
            if self._wthread is None or not self._wthread.is_alive():
                self._wthread = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="dpgo-session-writer")
                self._wthread.start()
            self._wcond.notify_all()
        return path

    def _writer_loop(self) -> None:
        while True:
            with self._wcond:
                while self._wpending is None:
                    self._wcond.wait()
                job, self._wpending = self._wpending, None
                self._winflight = True
            try:
                self._write(job["session_id"], job["arrays"],
                            job["iteration"])
                err = None
            except Exception as e:  # fail-open: recovery degrades to an
                err = e             # older snapshot, never a crash here
            with self._wcond:
                self._winflight = False
                if err is not None:
                    self.last_write_error = err
                self._wcond.notify_all()

    def flush(self, timeout: float | None = None) -> bool:
        """Block until the async writer has drained (no pending slot, no
        write in flight).  Call before ``load_newest`` on a store that
        saves asynchronously, so recovery sees the freshest snapshot.
        Returns False on timeout; a writer error is surfaced on
        ``last_write_error`` (the store itself stays fail-open)."""
        with self._wcond:
            return self._wcond.wait_for(
                lambda: self._wpending is None and not self._winflight,
                timeout=timeout)

    # -- reading / recovery --------------------------------------------------

    def _load_one(self, path: str) -> tuple[dict, dict]:
        """Parse + validate one snapshot file; raises on any defect."""
        arrays = dict(np.load(path, allow_pickle=False))
        schema = int(np.asarray(arrays.pop("__schema__")))
        if schema not in _COMPAT_SCHEMAS:
            raise ValueError(f"schema version {schema} not in "
                             f"{_COMPAT_SCHEMAS}")
        for f in _REQUIRED:
            if f not in arrays:
                raise ValueError(f"missing state field {f!r}")
            # Decompress every member now: a bit-flip deep in the zip
            # stream must fail HERE, in the quarantine path, not later
            # inside the solver.
            np.asarray(arrays[f])
        book = {
            "iteration": int(np.asarray(arrays.pop("__iteration__", 0))),
            "num_weight_updates": int(np.asarray(arrays.pop("__nwu__", 0))),
        }
        mesh_shape = arrays.pop("__mesh_shape__", None)
        book["mesh_shape"] = tuple(int(v) for v in np.asarray(mesh_shape)) \
            if mesh_shape is not None else None
        gidx = arrays.pop("__global_index__", None)
        book["global_index"] = np.asarray(gidx) if gidx is not None else None
        raw_meta = arrays.pop("__meta__", None)
        book["meta"] = json.loads(bytes(np.asarray(raw_meta, np.uint8))
                                  .decode("utf-8")) \
            if raw_meta is not None else {}
        return arrays, book

    def _quarantine(self, path: str, error: Exception) -> None:
        try:
            os.replace(path, path + ".quarantined")
        except OSError:
            pass
        run = obs.get_run()
        if run is not None:
            run.counter("session_quarantined_total",
                        "corrupt session snapshots set aside").inc()
            run.event("session_quarantined", phase="session", path=path,
                      error=f"{type(error).__name__}: {error}")

    def load_newest(self, session_id: str) -> SessionSnapshot | None:
        """The newest VALID snapshot, quarantining corrupt ones on the way
        down; None when no valid snapshot remains.  Never raises on bad
        data — the recovery path must not kill the worker a second time.
        Drains the async writer first, so a read-after-save always sees
        the snapshot the save promised."""
        self.flush()
        sdir = self._dir(session_id)
        with self._lock:
            candidates = [os.path.join(sdir, name)
                          for _, name in reversed(self._snaps(sdir))]
        for path in candidates:
            try:
                arrays, book = self._load_one(path)
            except Exception as e:  # any defect: quarantine, fall back
                self._quarantine(path, e)
                continue
            return SessionSnapshot(
                session_id=str(session_id), path=path,
                iteration=book["iteration"],
                num_weight_updates=book["num_weight_updates"],
                state=state_from_arrays(arrays), meta=book["meta"],
                mesh_shape=book["mesh_shape"],
                global_index=book["global_index"])
        return None

    # -- maintenance ---------------------------------------------------------

    def sessions(self) -> list[str]:
        try:
            return sorted(d for d in os.listdir(self.root)
                          if os.path.isdir(os.path.join(self.root, d)))
        except OSError:
            return []

    def discard(self, session_id: str) -> None:
        """Drop a finished session's snapshots (kept quarantined files are
        dropped too — the session is over)."""
        sdir = self._dir(session_id)
        with self._lock:
            try:
                names = os.listdir(sdir)
            except OSError:
                return
            for name in names:
                try:
                    os.remove(os.path.join(sdir, name))
                except OSError:
                    pass
            try:
                os.rmdir(sdir)
            except OSError:
                pass
