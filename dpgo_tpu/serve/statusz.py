"""Live observability endpoints: ``/metrics``, ``/healthz``, ``/statusz``.

The report CLI is post-hoc — it reads artifacts after the run closes.  An
operated service needs its numbers *while it runs*: Prometheus scrapes
``/metrics`` on an interval, load balancers poll ``/healthz``, and humans
(or ``python -m dpgo_tpu.obs.report --live HOST:PORT``) read ``/statusz``.
``MetricsSidecar`` is a stdlib ``ThreadingHTTPServer`` on a daemon thread
bound to one ``SolveServer`` + one ``TelemetryRun``:

* ``GET /metrics`` — the Prometheus text exposition of the run's live
  registry (``obs.exporters.to_prometheus_text``): request/shed/cache
  counters, latency histograms, SLO burn gauges, compile/device timings.
* ``GET /healthz`` — liveness JSON: ``{"ok": true, "uptime_s": ...}``
  while the server accepts work, HTTP 503 once it is closed.
* ``GET /statusz`` — ``SolveServer.status()`` as JSON: queue depth,
  per-tenant in-flight vs. quota, last-batch occupancy, cache
  hit/compile tallies, SLO burn rates, uptime.

Zero-overhead fence: ``SolveServer`` constructs a sidecar only when a
telemetry run is live (there is no registry to scrape otherwise), so
telemetry-off servers spawn no HTTP threads — the serving boom test
patches ``MetricsSidecar.__init__`` to prove it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs.events import _jsonable
from ..obs.exporters import to_prometheus_text

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsSidecar:
    """HTTP observability sidecar for one ``SolveServer``.

    Binds on construction (``port=0`` = OS-assigned; read the resolved
    ``.port``), serves on daemon threads, and never touches devices —
    every endpoint renders host-side state the serving plane already
    keeps."""

    def __init__(self, server, run, host: str = "127.0.0.1",
                 port: int = 0):
        self.server = server
        self.run = run
        sidecar = self

        class _Handler(BaseHTTPRequestHandler):
            # One scrape per line of access log would drown the real
            # events; errors still surface through the response codes.
            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = to_prometheus_text(
                            sidecar.run.registry).encode("utf-8")
                        ctype = PROMETHEUS_CONTENT_TYPE
                        code = 200
                    elif path == "/healthz":
                        # status() reads the lifecycle flags under the
                        # server lock — no bare cross-thread attribute
                        # peeking from the scrape threads.  A draining
                        # server still answers 200 (in-flight work is
                        # finishing) but says so, so load balancers can
                        # stop routing BEFORE the hard 503.
                        st = sidecar.server.status()
                        closed = st["closed"]
                        payload = {"ok": not closed,
                                   "draining": st.get("draining", False),
                                   "uptime_s": st["uptime_s"],
                                   "run": sidecar.run.run_id}
                        # Replica identity (serve.fleet): lets a prober
                        # tell WHICH replica answered — id, pid, device —
                        # the distinction the router/manager health loop
                        # and rolling-restart tooling key on.
                        if st.get("replica") is not None:
                            payload["replica"] = st["replica"]
                        body = json.dumps(payload).encode("utf-8")
                        ctype = "application/json"
                        code = 200 if not closed else 503
                    elif path == "/statusz":
                        body = json.dumps(
                            _jsonable(sidecar.server.status())).encode(
                                "utf-8")
                        ctype = "application/json"
                        code = 200
                    else:
                        body = json.dumps(
                            {"error": f"unknown path {path!r}",
                             "paths": ["/metrics", "/healthz",
                                       "/statusz"]}).encode("utf-8")
                        ctype = "application/json"
                        code = 404
                except Exception as e:  # never take the scrape loop down
                    body = json.dumps({"error": repr(e)}).encode("utf-8")
                    ctype = "application/json"
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        try:
            self._httpd.daemon_threads = True
            self.host, self.port = self._httpd.server_address[:2]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="dpgo-serve-metrics")
            self._thread.start()
        except BaseException:
            # Never strand the bound listening socket on a failed start
            # (leakcheck-enforced contract).
            self._httpd.server_close()
            raise

    def close(self) -> None:
        try:
            self._httpd.shutdown()
        finally:
            # The socket must die even when shutdown() fails — a wedged
            # serve thread should not keep the port bound.
            self._httpd.server_close()
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsSidecar":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
