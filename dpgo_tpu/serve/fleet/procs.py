"""Out-of-process fleet replicas: each replica is its own OS process.

The in-process fleet (``manager``/``router``) proves the routing,
migration, and autoscale logic, but every replica shares the parent's
address space — a wedged or dying replica can take the whole fleet with
it, and ``kill()`` is a polite in-process shutdown rather than an actual
process death.  ``ProcServer`` closes that gap: it satisfies the exact
server surface ``Replica``/``FleetRouter`` already consume (``submit``/
``status``/``drain``/``close``/``kill`` plus ticket futures), but the
solve happens in a CHILD PROCESS running an ordinary ``SolveServer``
behind an ordinary ``ServeFrontend`` — the packed v2 TCP frames are the
real RPC surface, not a test double.

Wiring:

* **spawn** — the parent launches ``python -m dpgo_tpu.serve.fleet.procs
  --child`` with the replica's config, and the child reports its
  OS-assigned front-end port through a tmp+rename port file.  The parent
  dials with ``connect_tcp``'s jittered-backoff budget.
* **submit** — one local ``ProcTicket`` per request plus a pump thread
  that performs the blocking ``solve_m`` RPC (full ``Measurements``
  round-trip — ``comms.protocol.pack_measurements``) and finishes the
  ticket.  Admission mirrors the child's bounds locally (closed/draining
  and an in-flight cap) so the router's fall-through-the-rendezvous-order
  behavior is preserved synchronously.
* **heartbeat** — a monitor thread polls the child's ``status`` op; the
  parent's ``status()["accepting"]`` (the ``ReplicaManager`` liveness
  probe) goes False the moment the child process dies, the heartbeat
  budget is exhausted, or the child stops accepting.  A ``kill -9``'d
  child therefore reads as dead within one heartbeat and the manager
  respawns a fresh process.
* **drain / migration** — ``drain()`` marks the parent draining, tells
  the child to evacuate (its in-flight batch stops at the next boundary
  snapshot, so session-tagged work leaves a fresh ``SessionStore``
  snapshot in the SHARED store), and hands the unanswered local tickets
  back for the router to re-admit — live migration across real process
  boundaries.
* **kill** — an actual ``SIGKILL`` of the child.  In-flight RPCs see the
  connection die and finish their tickets with the structured
  replica-death error the router reroutes on.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np

from ... import obs
from ...comms.protocol import (DEFAULT_MAX_FRAME_BYTES, ORIGIN_FLEET_PARENT,
                               ProtocolError, attach_clock, pop_clock,
                               proc_replica_actor)
from ...comms.transport import (TcpTransport, TransportClosed,
                                TransportTimeout, connect_tcp)
from ..server import OverCapacityError

#: Child boot budget: a cold child pays a full ``import jax`` before it
#: can bind; shared-core CI boxes stretch that well past laptop numbers.
DEFAULT_SPAWN_TIMEOUT_S = 180.0
#: Parent->child liveness poll cadence and the consecutive-miss budget
#: that flips ``accepting`` False (kill -9 detection latency is
#: ``heartbeat_s * heartbeat_misses`` at worst, typically one poll).
DEFAULT_HEARTBEAT_S = 0.2
DEFAULT_HEARTBEAT_MISSES = 3


def _unpack_str(a) -> str:
    return bytes(np.asarray(a, np.uint8)).decode("utf-8")


def _death_error(replica_id: str, detail: str) -> RuntimeError:
    # The message must read as a replica death to the router's
    # ``_is_replica_death`` classifier ("closed"/"died mid-batch").
    return RuntimeError(
        f"replica {replica_id} process closed mid-request: {detail}")


class ProcTicket:
    """Local future for one request pumped to a child replica.

    Satisfies the inner-ticket contract ``FleetRouter`` consumes:
    ``done()``, ``result(timeout=)``, ``_finish(...)`` (first caller
    wins — the router's migration marker and the pump thread may race),
    and ``queue_wait_s`` (the CHILD's admission wait, off the reply)."""

    def __init__(self, request):
        self.request = request
        self.t_submit = time.monotonic()
        self.queue_wait_s: float | None = None
        self._event = threading.Event()
        self._result = None
        self._exception: BaseException | None = None
        self._lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("solve not finished within timeout")
        if self._exception is not None:
            raise self._exception
        return self._result

    def _finish(self, result=None, exception=None) -> None:
        with self._lock:
            if self._event.is_set():
                return  # first finisher wins (migration marker vs pump)
            self._result = result
            self._exception = exception
            self._event.set()


def _result_from_reply(reply: dict):
    """An ``RBCDResult`` view of a ``solve_m`` success reply."""
    from ...models.rbcd import RBCDResult

    return RBCDResult(
        T=np.asarray(reply["T"]),
        X=None,
        cost_history=list(np.asarray(reply["cost_history"], np.float64)),
        grad_norm_history=list(np.asarray(reply["grad_norm_history"],
                                          np.float64)),
        iterations=int(np.asarray(reply["iterations"])),
        terminated_by=_unpack_str(reply["terminated_by"]),
        recovered=bool(int(np.asarray(reply.get("recovered", 0)))),
    )


class ProcServer:
    """One out-of-process solve replica behind the in-process surface.

    Drop-in for ``SolveServer`` wherever a ``ReplicaManager``'s
    ``make_server`` factory is the consumer: the constructor spawns the
    child and blocks until its front-end port lands, so a returned
    ``ProcServer`` is live."""

    def __init__(self, replica_id: str | None = None, *,
                 max_batch: int = 8, max_queue: int = 64,
                 batch_window_s: float = 0.005,
                 aot_cache_dir: str | None = None,
                 session_store: str | None = None,
                 session_every: int = 1,
                 resume_sessions: bool = False,
                 host: str = "127.0.0.1",
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 heartbeat_s: float = DEFAULT_HEARTBEAT_S,
                 heartbeat_misses: int = DEFAULT_HEARTBEAT_MISSES,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 workdir: str | None = None,
                 telemetry_dir: str | None = None):
        self.replica_id = replica_id
        self.max_queue = int(max_queue)
        self.host = host
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_misses = int(heartbeat_misses)
        self.max_frame_bytes = int(max_frame_bytes)
        self.telemetry_dir = telemetry_dir
        self.child_metrics_port: int | None = None
        self._lost_emitted = False

        self._lock = threading.Lock()
        self._tickets: dict[int, ProcTicket] = {}  # guarded-by: _lock
        self._closed = False                       # guarded-by: _lock
        self._draining = False                     # guarded-by: _lock
        self._child_status: dict = {}              # guarded-by: _lock
        self._beat_misses = 0                      # guarded-by: _lock
        self._n_requests = 0                       # guarded-by: _lock
        self._pumps: list[threading.Thread] = []   # guarded-by: _lock
        self._stop = threading.Event()

        self._workdir = workdir or tempfile.mkdtemp(prefix="dpgo-proc-")
        port_file = os.path.join(self._workdir,
                                 f"port-{replica_id or 'r'}.json")
        cmd = [sys.executable, "-m", "dpgo_tpu.serve.fleet.procs",
               "--child", "--port-file", port_file,
               "--replica-id", str(replica_id or ""),
               "--max-batch", str(int(max_batch)),
               "--max-queue", str(int(max_queue)),
               "--batch-window", str(float(batch_window_s)),
               "--session-every", str(int(session_every))]
        if aot_cache_dir is not None:
            cmd += ["--aot-cache", str(aot_cache_dir)]
        if session_store is not None:
            cmd += ["--session-store", str(session_store)]
        if resume_sessions:
            cmd += ["--resume-sessions"]
        if telemetry_dir is not None:
            # The child runs inside its own TelemetryRun there (its
            # sidecar port comes back through the port file); the parent
            # harvests the directory post-mortem on a replica death.
            cmd += ["--telemetry-dir", str(telemetry_dir)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo_root = str(Path(__file__).resolve().parents[3])
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        self._log_path = os.path.join(self._workdir,
                                      f"child-{replica_id or 'r'}.log")
        log = open(self._log_path, "w")
        try:
            self.proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                         cwd=repo_root, env=env)
        finally:
            log.close()
        self.port = self._await_port(port_file, float(spawn_timeout_s))
        self._monitor = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"dpgo-proc-heartbeat-{replica_id or self.proc.pid}")
        self._monitor.start()

    # -- child lifecycle ----------------------------------------------------

    def _await_port(self, port_file: str, timeout_s: float) -> int:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica child exited rc={self.proc.returncode} "
                    f"before binding (log: {self._log_path})")
            try:
                with open(port_file) as fh:
                    record = json.load(fh)
                if record.get("metrics_port"):
                    self.child_metrics_port = int(record["metrics_port"])
                return int(record["port"])
            except (OSError, ValueError, KeyError):
                time.sleep(0.05)
        self.proc.kill()
        self.proc.wait()
        raise TimeoutError(
            f"replica child did not report a port within {timeout_s}s "
            f"(log: {self._log_path})")

    def _rpc(self, frame: dict, timeout: float | None):
        """One connect-send-recv round trip (its own connection: the
        front-end serves one request at a time per connection, and pumps
        run concurrently)."""
        tr = TcpTransport(connect_tcp(self.host, self.port, attempts=3),
                          src="fleet-proc",
                          max_frame_bytes=self.max_frame_bytes)
        try:
            tr.send(frame)
            return tr.recv(timeout=timeout)
        finally:
            tr.close()

    # -- admission + pump ---------------------------------------------------

    def submit(self, request) -> ProcTicket:
        with self._lock:
            if self._closed or self._draining:
                raise OverCapacityError(
                    f"replica {self.replica_id} is closed", reason="closed")
            if self.proc.poll() is not None:
                raise OverCapacityError(
                    f"replica {self.replica_id} process is dead",
                    reason="closed")
            if len(self._tickets) >= self.max_queue:
                raise OverCapacityError(
                    f"replica {self.replica_id} pump queue full "
                    f"({self.max_queue})", reason="queue")
            ticket = ProcTicket(request)
            self._tickets[id(ticket)] = ticket
            self._n_requests += 1
            pump = threading.Thread(target=self._pump, args=(ticket,),
                                    daemon=True, name="dpgo-proc-pump")
            self._pumps.append(pump)
            self._pumps = [t for t in self._pumps if t.is_alive()]
        pump.start()
        return ticket

    def _pump(self, ticket: ProcTicket) -> None:
        from ..frontend import solve_m_frame

        rid = str(self.replica_id)
        try:
            reply = self._rpc(solve_m_frame(ticket.request), timeout=None)
        except (TransportClosed, TransportTimeout, ProtocolError,
                ConnectionError, OSError) as e:
            ticket._finish(exception=_death_error(
                rid, f"{type(e).__name__}: {e}"))
            self._forget(ticket)
            return
        try:
            if int(np.asarray(reply["ok"])):
                if "queue_wait_s" in reply:
                    ticket.queue_wait_s = float(
                        np.asarray(reply["queue_wait_s"]))
                ticket._finish(result=_result_from_reply(reply))
            elif int(np.asarray(reply.get("shed", 0))):
                ticket._finish(exception=OverCapacityError(
                    _unpack_str(reply.get("error", np.zeros(0, np.uint8))),
                    reason=_unpack_str(reply["reason"])))
            else:
                ticket._finish(exception=RuntimeError(
                    _unpack_str(reply.get("error", np.zeros(0, np.uint8)))
                    or f"replica {rid} returned an empty error"))
        except Exception as e:  # malformed reply: treat as replica death
            ticket._finish(exception=_death_error(
                rid, f"bad reply: {type(e).__name__}: {e}"))
        self._forget(ticket)

    def _forget(self, ticket: ProcTicket) -> None:
        with self._lock:
            self._tickets.pop(id(ticket), None)

    @property
    def metrics_url(self) -> str | None:
        """The CHILD's ``/metrics`` scrape URL (its sidecar only exists
        when the child got a telemetry dir), or None."""
        if self.child_metrics_port is None:
            return None
        return f"http://{self.host}:{self.child_metrics_port}/metrics"

    # -- heartbeat ----------------------------------------------------------

    def _beat_once(self) -> dict | None:
        """One status poll; None on any failure.

        With telemetry on the poll doubles as the procs-plane clock
        channel: the request carries the parent's ``attach_clock`` stamp
        (the child's front end emits the forward ``clock_sample``), and
        the child stamps its status reply (the reverse sample emitted
        here) — bidirectional parent<->replica pairs at the heartbeat
        cadence.  Telemetry off: no stamp, byte-identical wire."""
        from ..frontend import _pack_str

        run = obs.get_run()
        frame = {"op": _pack_str("status")}
        if run is not None:
            attach_clock(frame, ORIGIN_FLEET_PARENT)
        try:
            reply = self._rpc(frame, timeout=2.0)
            ts = pop_clock(reply)
            if run is not None and ts is not None:
                run.event("clock_sample", phase="comms", src=ts[0],
                          dst=ORIGIN_FLEET_PARENT, channel="heartbeat",
                          kind="status_reply", t_send_mono=ts[1],
                          t_send_wall=ts[2])
            if not int(np.asarray(reply["ok"])):
                return None
            return json.loads(_unpack_str(reply["status"]))
        except Exception:
            return None

    def _heartbeat_loop(self) -> None:
        run = obs.get_run()
        rid = str(self.replica_id)
        if run is not None:
            # Satellite: the status-poll fields the parent already
            # fetches become per-replica labeled gauges instead of
            # liveness-only bookkeeping.
            g_queue = run.gauge("fleet_replica_queue_depth",
                                "child admission queue depth per replica")
            g_inflight = run.gauge("fleet_replica_in_flight",
                                   "in-flight requests per replica")
            g_draining = run.gauge("fleet_replica_draining",
                                   "1 while the replica is draining")
            g_accepting = run.gauge("fleet_replica_accepting",
                                    "1 while the replica accepts work")
            g_misses = run.gauge("fleet_replica_heartbeat_misses",
                                 "consecutive missed heartbeats")
        while not self._stop.wait(self.heartbeat_s):
            if self.proc.poll() is not None:
                with self._lock:
                    self._beat_misses = self.heartbeat_misses
                    closed = self._closed
                if run is not None and not closed \
                        and not self._lost_emitted:
                    # An unrequested child death (kill -9, OOM, crash):
                    # the instant lands on the REPLICA's own timeline
                    # track, and whatever the child's run directory
                    # still holds is harvested post-mortem.
                    self._lost_emitted = True
                    self._emit_process_lost(run, rid)
                continue  # dead child: keep reporting it until close()
            st = self._beat_once()
            with self._lock:
                if st is None:
                    self._beat_misses += 1
                else:
                    self._beat_misses = 0
                    self._child_status = st
                misses = self._beat_misses
                inflight = len(self._tickets)
            if run is not None and st is not None:
                tenant_inflight = sum(
                    t.get("in_flight", 0)
                    for t in st.get("tenants", {}).values())
                g_queue.set(st.get("queue_depth", 0) or 0, replica=rid)
                g_inflight.set(tenant_inflight + inflight, replica=rid)
                g_draining.set(1.0 if st.get("draining") else 0.0,
                               replica=rid)
                g_accepting.set(1.0 if st.get("accepting", True) else 0.0,
                                replica=rid)
                g_misses.set(misses, replica=rid)
            elif run is not None:
                g_misses.set(misses, replica=rid)

    def _emit_process_lost(self, run, rid: str) -> None:
        try:
            post = None
            if self.telemetry_dir:
                from ...obs import fleetobs

                post = fleetobs.harvest_run_dir(self.telemetry_dir)
            run.event("process_lost", phase="comms",
                      robot=proc_replica_actor(rid), replica=rid,
                      plane="procs", pid=self.proc.pid,
                      rc=self.proc.returncode)
            if post is not None:
                run.event("replica_postmortem", phase="fleet",
                          replica=rid, **post)
        except Exception:
            pass  # forensics are fail-open by contract

    # -- server surface (Replica/FleetRouter contract) ----------------------

    def status(self) -> dict:
        with self._lock:
            child = dict(self._child_status)
            closed = self._closed
            draining = self._draining
            misses = self._beat_misses
            inflight = len(self._tickets)
            n_requests = self._n_requests
        proc_dead = self.proc.poll() is not None
        beat_dead = misses >= self.heartbeat_misses
        accepting = (not closed and not draining and not proc_dead
                     and not beat_dead and bool(child.get("accepting", True)))
        out = dict(child)
        out.update({
            "accepting": accepting,
            "closed": closed or proc_dead,
            "draining": draining and not closed,
            "out_of_process": True,
            "child_pid": self.proc.pid,
            "child_alive": not proc_dead,
            "heartbeat_misses": misses,
            "parent_inflight": inflight,
            "parent_requests": n_requests,
        })
        out.setdefault("queue_depth", inflight)
        return out

    def drain(self) -> list[ProcTicket]:
        """Live-migration drain: stop admission, evacuate the child (its
        in-flight batch stops after the next boundary snapshot lands in
        the shared session store), and return every unanswered local
        ticket for the caller to re-admit elsewhere."""
        from ..frontend import _pack_str

        with self._lock:
            self._draining = True
            evacuated = [t for t in self._tickets.values() if not t.done()]
        if self.proc.poll() is None:
            try:
                self._rpc({"op": _pack_str("drain")}, timeout=30.0)
            except Exception:
                pass  # child died mid-drain: tickets reroute regardless
        return evacuated

    def kill(self) -> None:
        """An ACTUAL kill: ``SIGKILL`` the child process.  In-flight
        pumps watch their connections die and finish their tickets with
        the structured replica-death error."""
        with self._lock:
            self._closed = True
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait()
        run = obs.get_run()
        if run is not None and not self._lost_emitted:
            self._lost_emitted = True
            self._emit_process_lost(run, str(self.replica_id))
        self._shutdown_threads()

    def close(self, drain: bool = False) -> None:
        if drain:
            self.drain()
        with self._lock:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
        if not already and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.proc.wait()
        self._shutdown_threads()

    def _shutdown_threads(self) -> None:
        self._stop.set()
        self._monitor.join(timeout=10.0)
        with self._lock:
            pumps = list(self._pumps)
            tickets = list(self._tickets.values())
        for t in pumps:
            t.join(timeout=10.0)
        for ticket in tickets:  # pumps that never got a connection up
            ticket._finish(exception=_death_error(
                str(self.replica_id), "replica shut down"))

    def __enter__(self) -> "ProcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Child entry point
# ---------------------------------------------------------------------------

def _run_child(args) -> int:
    """The replica process: an ordinary ``SolveServer`` behind an
    ordinary ``ServeFrontend``, plus the port-file handshake.

    With ``--telemetry-dir`` the whole child runs inside its own
    ``TelemetryRun``: its statusz sidecar binds an OS-assigned port
    (reported back through the port file for the fleet aggregator to
    scrape), a ``ResourceSampler`` feeds the soak-gate series, and a
    boot span homes this stream to the replica's timeline actor."""
    import contextlib

    import jax

    jax.config.update("jax_enable_x64", True)

    boot = (time.monotonic(), time.time())
    scope = obs.run_scope(args.telemetry_dir) if args.telemetry_dir \
        else contextlib.nullcontext()
    with scope:
        from ..frontend import ServeFrontend
        from ..server import SolveServer

        run = obs.get_run()
        server = SolveServer(
            max_batch=args.max_batch, max_queue=args.max_queue,
            batch_window_s=args.batch_window,
            replica_id=args.replica_id or None,
            aot_cache_dir=args.aot_cache,
            session_store=args.session_store,
            session_every=args.session_every,
            resume_sessions=args.resume_sessions,
            metrics_port=0 if run is not None else None)
        sampler = None
        if run is not None:
            from ...obs.fleetobs import start_resource_sampler
            from ...obs.trace import emit_span

            rid = args.replica_id or "r"
            run.set_fingerprint(plane="procs", replica=rid,
                                pid=os.getpid())
            emit_span(run, "replica_boot", boot[0], boot[1],
                      time.monotonic() - boot[0], phase="serve",
                      robot=proc_replica_actor(rid), replica=rid)
            sampler = start_resource_sampler(
                run=run,
                queue_depth=lambda: server.status().get("queue_depth", 0),
                replica=rid)
        frontend = ServeFrontend(server, host=args.host, port=0)
        record = {"port": int(frontend.port), "pid": os.getpid()}
        if server.sidecar is not None:
            record["metrics_port"] = int(server.sidecar.port)
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(record, fh)
        os.replace(tmp, args.port_file)

        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        stop.wait()
        frontend.close()
        if sampler is not None:
            sampler.close()
        try:
            server.kill()  # immediate: queued work reroutes parent-side
        except Exception:
            pass
    return 0


def _build_parser():
    import argparse

    ap = argparse.ArgumentParser(
        description="Out-of-process fleet replica (child entry)")
    ap.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--port-file", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--replica-id", default="", help=argparse.SUPPRESS)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--batch-window", type=float, default=0.005)
    ap.add_argument("--aot-cache", default=None)
    ap.add_argument("--session-store", default=None)
    ap.add_argument("--session-every", type=int, default=1)
    ap.add_argument("--resume-sessions", action="store_true")
    ap.add_argument("--telemetry-dir", default="",
                    help="run the child inside its own TelemetryRun "
                         "rooted here (statusz sidecar port reported "
                         "via the port file)")
    return ap


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if not args.child or not args.port_file:
        print("this module is the fleet child entry; use --child "
              "--port-file (spawned by ProcServer)", file=sys.stderr)
        return 2
    return _run_child(args)


if __name__ == "__main__":
    sys.exit(main())
