"""Persistent AOT executable cache: the disk tier under ``ExecutableCache``.

The in-memory executable cache dies with its process, so every replica
restart and autoscale-up repays the XLA compile bill for every bucket it
has ever served (seconds per program on CPU, tens of seconds on TPU).
This module makes the compile a fleet-wide one-time cost: compiled
executables are serialized with ``jax.experimental.serialize_executable``
(the stable pickling surface under ``jax.export``) and written to a
shared directory keyed by the config fingerprint plus everything that
could invalidate the bytes — static-argument combination, backend,
jax/jaxlib versions, x64 mode, and the entry schema version.  A replica
that restarts with a warm disk deserializes and loads the executable
without ever invoking XLA, so ``serve_compile_seconds_total`` stays flat
and cold-start becomes I/O-dominated (``compile_profile`` events with
``disk_hit=True`` carry the load time for the report's cold-start split).

Durability discipline mirrors ``serve.session.SessionStore``:

* writes are atomic (temp file + fsync + rename), so a crash mid-write
  leaves a torn temp file, never a torn entry;
* every entry embeds its full identity dict and ``load`` re-validates it
  against the requested identity — a stale or hash-colliding entry is
  refused, not deserialized;
* ANY load defect (unreadable pickle, identity mismatch, deserialization
  failure) QUARANTINES the entry — renamed aside so it is never retried —
  and falls back to a fresh compile.  The cache is strictly fail-open:
  no admission path ever sees a disk-cache exception.

``AOTExecutable`` is the cache-entry wrapper (the disk-tier sibling of
``obs.profile.ProfiledExecutable``): each distinct static-argument
combination resolves once through disk-load -> AOT-compile -> disk-store,
and later calls dispatch the loaded/compiled executable with the static
kwargs stripped.  Unlike ``ProfiledExecutable`` it AOT-compiles on the
telemetry-off path too (the disk tier is a durability feature, not
telemetry) — but it constructs no obs objects and emits nothing unless a
run is live, keeping the zero-overhead fence intact.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import threading
import time

from ... import obs

#: Bump on any incompatible change to the entry payload layout.  A loader
#: finding a different version quarantines the file — running executables
#: deserialized under different framing assumptions is worse than a
#: recompile.
AOT_CACHE_SCHEMA_VERSION = 1


#: Guards the process-global compilation-cache flag toggle below.
_COMPILE_LOCK = threading.Lock()


@contextlib.contextmanager
def _self_contained_compile():
    """Serialization-safe compile scope.  An executable that jax's own
    persistent compilation cache deserialized does NOT re-serialize
    completely on the CPU backend: ``serialize_executable.serialize``
    drops the fusion symbols' object code and a later
    ``deserialize_and_load`` dies with ``Symbols not found``.  Entries
    written to THIS disk tier must therefore come from a genuine XLA
    compile, so the jax cache is disabled for the duration.  Flipping the
    flag alone is not enough: ``compilation_cache.is_cache_used`` MEMOIZES
    its verdict at the process's first compile, so the memo is reset on
    entry (cache off takes effect) and again on exit (the restored flag
    re-memoizes at the next ordinary compile).  The flag and memo are
    process-global, hence the lock; concurrent unrelated compiles merely
    miss jax's cache once."""
    import jax

    try:
        from jax.experimental.compilation_cache import (compilation_cache
                                                        as _jax_cc)
    except ImportError:  # pragma: no cover - future jax reorganisations
        _jax_cc = None

    with _COMPILE_LOCK:
        prev = bool(jax.config.jax_enable_compilation_cache)
        jax.config.update("jax_enable_compilation_cache", False)
        if _jax_cc is not None:
            _jax_cc.reset_cache()
        try:
            yield
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            if _jax_cc is not None:
                _jax_cc.reset_cache()


def _versions() -> dict:
    import jax
    import jaxlib

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "x64": bool(jax.config.jax_enable_x64),
    }


def entry_identity(fingerprint_key: str, combo: tuple) -> dict:
    """The full identity of one disk entry: everything that could make a
    serialized executable wrong to load.  ``combo`` is the sorted
    static-argument tuple the executable was lowered with."""
    ident = {
        "schema": AOT_CACHE_SCHEMA_VERSION,
        "fingerprint": str(fingerprint_key),
        "static": [[str(k), repr(v)] for k, v in combo],
    }
    ident.update(_versions())
    return ident


def _ident_digest(ident: dict) -> str:
    blob = json.dumps(ident, sort_keys=True).encode("utf-8")
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


class AOTDiskCache:
    """Directory-backed store of serialized compiled executables.

    Thread-safe and multi-process-safe by construction: entries are
    immutable once renamed into place, writes are atomic, and identity
    validation makes concurrent writers idempotent (same identity ->
    same bytes semantics).  Replicas of one fleet share a root and each
    keep their own in-memory tier above it."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.disk_hits = 0      # guarded-by: _lock
        self.disk_misses = 0    # guarded-by: _lock
        self.stores = 0         # guarded-by: _lock
        self.quarantined = 0    # guarded-by: _lock
        self.store_errors = 0   # guarded-by: _lock

    def _path(self, ident: dict) -> str:
        return os.path.join(self.root, f"aot-{_ident_digest(ident)}.bin")

    # -- reading -------------------------------------------------------------

    def load(self, ident: dict):
        """The deserialized, loaded executable for ``ident``, or None.

        None covers both a plain miss and every defect path (quarantined
        entry, version skew, unreadable file) — the caller always falls
        back to compiling.  Never raises."""
        path = self._path(ident)
        if not os.path.exists(path):
            with self._lock:
                self.disk_misses += 1
            self._obs("disk_miss")
            return None
        try:
            with open(path, "rb") as fh:
                entry = pickle.load(fh)
            if entry.get("ident") != ident:
                # A digest collision or a stale/foreign entry: the bytes
                # were compiled for a different program — refuse them.
                raise ValueError(
                    f"entry identity mismatch: {entry.get('ident')!r}")
            from jax.experimental import serialize_executable as se

            compiled = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception as e:  # any defect: quarantine, fall back
            self._quarantine(path, e)
            return None
        with self._lock:
            self.disk_hits += 1
        self._obs("disk_hit")
        return compiled

    def _quarantine(self, path: str, error: Exception) -> None:
        try:
            os.replace(path, path + ".quarantined")
        except OSError:
            pass
        with self._lock:
            self.quarantined += 1
        run = obs.get_run()
        if run is not None:
            run.counter("serve_aot_quarantined_total",
                        "corrupt/stale persisted executables set aside").inc()
            run.event("aot_entry_quarantined", phase="serve", path=path,
                      error=f"{type(error).__name__}: {error}")

    # -- writing -------------------------------------------------------------

    def store(self, ident: dict, compiled) -> bool:
        """Serialize + atomically persist one compiled executable.  Write
        failures are swallowed (the disk tier must never take a solve
        down); returns whether the entry landed."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps({"ident": ident, "payload": payload,
                                 "in_tree": in_tree, "out_tree": out_tree})
            path = self._path(ident)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except Exception as e:
            with self._lock:
                self.store_errors += 1
            run = obs.get_run()
            if run is not None:
                run.event("aot_store_failed", phase="serve",
                          error=f"{type(e).__name__}: {e}")
            return False
        with self._lock:
            self.stores += 1
        run = obs.get_run()
        if run is not None:
            run.counter("serve_aot_stores_total",
                        "compiled executables persisted to the disk "
                        "tier").inc()
        return True

    def _obs(self, outcome: str) -> None:
        run = obs.get_run()
        if run is None:
            return
        run.counter("serve_cache_requests_total",
                    "executable-cache lookups by outcome").inc(
            outcome=outcome)

    def stats(self) -> dict:
        with self._lock:
            return {"root": self.root, "disk_hits": self.disk_hits,
                    "disk_misses": self.disk_misses, "stores": self.stores,
                    "quarantined": self.quarantined,
                    "store_errors": self.store_errors}


class AOTExecutable:
    """A cache entry backed by the persistent disk tier.

    The disk-tier sibling of ``obs.profile.ProfiledExecutable``: wraps
    the jitted program the in-memory cache would otherwise store, and
    resolves each distinct static-argument combination exactly once
    through three tiers — disk load (no XLA, ``compile_profile`` event
    with ``disk_hit=True`` and the load seconds), else AOT compile
    (through ``aot_compile_profile`` when telemetry is on, so the compile
    lands in ``serve_compile_seconds_total``; a bare ``lower().compile()``
    otherwise), then a disk store so the NEXT replica skips the compile.
    Later calls dispatch the resolved executable with static kwargs
    stripped."""

    def __init__(self, jitfn, disk: AOTDiskCache, key: str, label: str,
                 static_names: tuple = (), **extra):
        self._jitfn = jitfn
        self._disk = disk
        self._key = str(key)
        self._label = str(label)
        self._static = tuple(static_names)
        self._extra = dict(extra)
        self._compiled: dict[tuple, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        combo = tuple(sorted(
            (k, kwargs[k]) for k in self._static if k in kwargs))
        with self._lock:
            compiled = self._compiled.get(combo)
        if compiled is None:
            compiled = self._obtain(combo, args, kwargs)
            with self._lock:
                compiled = self._compiled.setdefault(combo, compiled)
        dyn = {k: v for k, v in kwargs.items() if k not in self._static}
        return compiled(*args, **dyn)

    def _obtain(self, combo: tuple, args, kwargs):
        ident = entry_identity(self._key, combo)
        run = obs.get_run()
        t0 = time.monotonic()
        compiled = self._disk.load(ident)
        if compiled is not None:
            if run is not None:
                # The cold-start proof: a disk hit reports its I/O time
                # under the same event family as compiles, but touches
                # serve_compile_seconds_total NOT AT ALL — a restarted
                # replica serving only seen fingerprints keeps it at 0.
                run.event("compile_profile", phase="serve", key=self._key,
                          label=self._label, disk_hit=True,
                          load_s=time.monotonic() - t0,
                          static=dict(combo) or None, **self._extra)
            return compiled
        with _self_contained_compile():
            if run is not None:
                from ...obs.profile import aot_compile_profile

                compiled = aot_compile_profile(
                    run, self._jitfn, args, kwargs, self._key, self._label,
                    static=dict(combo) or None, disk_hit=False,
                    **self._extra)
            else:
                compiled = self._jitfn.lower(*args, **kwargs).compile()
        self._disk.store(ident, compiled)
        return compiled
