"""Session-affinity request router over a pool of solve replicas.

``FleetRouter`` is the fleet's front door: it exposes the familiar
``submit``/``solve``/``status``/``close`` surface (so ``ServeFrontend``
can sit on it unchanged) and hashes each request onto one of the
manager's ``Replica``\\ s with rendezvous (highest-random-weight)
hashing — the scheme whose remap set under pool churn is exactly the
keys owned by the departed replica, so an autoscale event does not
reshuffle every session's affinity.

Two key classes, in priority order:

* session-tagged requests hash on ``session_id`` — a live session keeps
  landing on the replica that holds its warm state and snapshot cadence;
* untagged requests hash on a cheap *bucket proxy* (quantum-rounded pose
  and measurement counts, robots, rank, dtype — computable from the raw
  ``Measurements`` without building the problem), so same-shape traffic
  coalesces onto the same replica and batch occupancy survives the
  fan-out.

``RouterTicket`` is the client future.  Migration is transparent inside
it: when the ticket's replica is drained (live migration, scale-down,
rolling restart) or dies, the router re-admits the request on the next
replica in rendezvous order and the waiter keeps waiting — ``result()``
only raises once the request truly failed (admission refusal everywhere,
or the migration cap).  Session-tagged requests re-admit onto
``resume_sessions`` replicas, which pick the solve up from the drained
replica's final boundary snapshot instead of restarting it.
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

from ... import obs
from ..server import OverCapacityError, SolveRequest

#: A request that keeps landing on dying/draining replicas is eventually
#: failed rather than bounced forever.
DEFAULT_MAX_MIGRATIONS = 8


class _Migrated(Exception):
    """Internal wake-up: the ticket's inner future was superseded by a
    re-admission on another replica.  Never escapes ``RouterTicket``."""


def _is_replica_death(e: BaseException) -> bool:
    """Failures that mean "this replica is gone", not "this request is
    bad" — the distinction between re-routing and failing the caller."""
    if isinstance(e, OverCapacityError):
        return e.reason == "closed"
    if isinstance(e, RuntimeError):
        msg = str(e)
        return "closed" in msg or "died mid-batch" in msg
    return False


def _hrw_weight(key: str, replica_id: str) -> bytes:
    return hashlib.blake2b(f"{key}|{replica_id}".encode("utf-8"),
                           digest_size=8).digest()


class RouterTicket:
    """Future for one routed request; survives replica churn.

    ``result()`` blocks through migrations: the inner per-replica ticket
    may be swapped any number of times (up to ``max_migrations``) before
    a reply lands.  ``migrations`` counts the swaps."""

    def __init__(self, router: "FleetRouter", request: SolveRequest):
        self.request = request
        self.t_submit = time.monotonic()
        self._router = router
        self._cv = threading.Condition()
        self._inner = None        # guarded-by: _cv
        self._replica = None      # guarded-by: _cv
        self._gen = 0             # guarded-by: _cv
        self._migrating = False   # guarded-by: _cv
        self._terminal = None     # guarded-by: _cv
        self.migrations = 0       # guarded-by: _cv

    def done(self) -> bool:
        with self._cv:
            if self._terminal is not None:
                return True
            if self._migrating or self._inner is None:
                return False
            inner = self._inner
        if not inner.done():
            return False
        try:
            inner.result(timeout=0)
        except BaseException as e:
            # A death/migration marker means "moving", not "done".
            return not (_is_replica_death(e) or isinstance(e, _Migrated))
        return True

    def result(self, timeout: float | None = None):
        """The ``RBCDResult`` (or raises): waits across migrations."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                while self._migrating and self._terminal is None:
                    rem = None if deadline is None \
                        else deadline - time.monotonic()
                    if rem is not None and rem <= 0:
                        raise TimeoutError(
                            "solve not finished within timeout")
                    self._cv.wait(timeout=1.0 if rem is None
                                  else min(rem, 1.0))
                if self._terminal is not None:
                    exc = self._terminal
                    self._router._done(self)
                    raise exc
                inner, gen = self._inner, self._gen
            rem = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            try:
                res = inner.result(timeout=rem)
            except _Migrated:
                continue  # inner superseded: loop picks up the new one
            except TimeoutError:
                with self._cv:
                    if gen != self._gen or self._migrating:
                        continue  # migrated right at the deadline: retry
                raise
            except (OverCapacityError, RuntimeError) as e:
                if not _is_replica_death(e):
                    self._router._done(self)
                    raise
                # The replica went away under us: re-admit and keep
                # waiting (the lazy half of failure detection — the
                # manager's monitor is the eager half; _reroute is
                # idempotent so both may fire).
                self._router._reroute(self, inner, kind="death")
                continue
            self._router._observe(inner)
            self._router._done(self)
            return res


class FleetRouter:
    """Rendezvous-hash router over a ``ReplicaManager``'s pool."""

    def __init__(self, manager, max_migrations: int = DEFAULT_MAX_MIGRATIONS,
                 quantum: int = 32):
        self.manager = manager
        self.max_migrations = int(max_migrations)
        self.quantum = max(int(quantum), 1)
        self._lock = threading.Lock()
        self._live: set = set()   # guarded-by: _lock
        self.migrations = 0       # guarded-by: _lock
        self._n_routed = 0        # guarded-by: _lock
        manager.attach_router(self)
        manager.start()

    # -- placement ----------------------------------------------------------

    def route_key(self, request: SolveRequest) -> str:
        """Affinity key: the session id when there is one, else the
        bucket proxy (cheap shape summary of the raw measurements —
        requests that would pad into the same bucket share it)."""
        if request.session_id is not None:
            return f"s|{request.session_id}"
        q = self.quantum
        n = max(int(request.meas.num_poses), 1)
        m = max(int(np.asarray(request.meas.kappa).shape[0]), 1)
        rank = request.params.r if request.params is not None else "-"
        return (f"b|{-(-n // q) * q}|{-(-m // q) * q}|"
                f"{int(request.num_robots)}|{rank}|"
                f"{np.dtype(request.dtype)}")

    def _pick(self, request: SolveRequest, exclude):
        alive = [r for r in self.manager.replicas()
                 if r not in exclude and r.alive()]
        if not alive:
            return None
        key = self.route_key(request)
        return max(alive, key=lambda r: _hrw_weight(key, r.replica_id))

    def _submit_once(self, request: SolveRequest, exclude=frozenset()):
        """Admit on the rendezvous-first alive replica, falling through
        the rendezvous order past full/closing replicas.  Raises the
        structured admission error when nobody accepts."""
        tried = set(exclude)
        while True:
            replica = self._pick(request, tried)
            if replica is None:
                raise OverCapacityError(
                    "no alive replica accepted the request",
                    reason="closed")
            try:
                return replica, replica.server.submit(request)
            except OverCapacityError as e:
                if e.reason in ("queue", "closed"):
                    tried.add(replica)
                    continue
                raise  # tenant_quota/deadline: a real admission decision
            except RuntimeError:  # "server is closed" raced the pick
                tried.add(replica)
                continue

    # -- client API ---------------------------------------------------------

    def submit(self, request: SolveRequest) -> RouterTicket:
        rt = RouterTicket(self, request)
        replica, inner = self._submit_once(request)
        with rt._cv:
            rt._inner, rt._replica = inner, replica
        with self._lock:
            self._live.add(rt)
            self._n_routed += 1
        run = obs.get_run()
        if run is not None:
            run.counter("fleet_requests_total",
                        "requests routed through the fleet router").inc(
                replica=replica.replica_id)
        return rt

    def solve(self, request: SolveRequest, timeout: float | None = None):
        return self.submit(request).result(timeout)

    def status(self) -> dict:
        replicas = []
        any_alive = False
        for r in self.manager.replicas():
            alive = r.alive()
            any_alive = any_alive or alive
            try:
                st = r.server.status()
                row = {"replica_id": r.replica_id, "alive": alive,
                       "accepting": st.get("accepting"),
                       "queue_depth": st.get("queue_depth"),
                       "requests_served": st.get("requests_served"),
                       "worker_crashes": st.get("worker_crashes"),
                       "replica": st.get("replica")}
            except Exception as e:  # a dying replica must not kill status
                row = {"replica_id": r.replica_id, "alive": False,
                       "error": f"{type(e).__name__}: {e}"}
            replicas.append(row)
        with self._lock:
            migrations = self.migrations
            routed = self._n_routed
            live = len(self._live)
        return {
            "replicas": replicas,
            "n_replicas": len(replicas),
            "migrations": migrations,
            "requests_routed": routed,
            "requests_live": live,
            # ServeFrontend/healthz compatibility: the fleet as a whole
            # is "closed" only when nothing is alive.
            "closed": not any_alive,
            "draining": False,
            "accepting": any_alive,
            "queue_depth": sum(r.get("queue_depth") or 0 for r in replicas),
        }

    def close(self) -> None:
        self.manager.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- migration ----------------------------------------------------------

    def migrate_from(self, replica) -> int:
        """Live-migrate everything off one replica: ``drain()`` it (the
        in-flight batch stops at its next boundary snapshot) and re-admit
        every evacuated ticket on its rehashed replica.  The scale-down
        and rolling-restart path; returns the number migrated."""
        # Claim the replica before it starts reading as dead, so the
        # manager's health monitor retires it quietly instead of racing
        # this drain with its own reroute_dead.
        replica.draining = True
        evacuated = replica.server.drain()
        with self._lock:
            live = list(self._live)
        by_inner = {}
        for rt in live:
            with rt._cv:
                if rt._inner is not None:
                    by_inner[id(rt._inner)] = rt
        n = 0
        for t in evacuated:
            rt = by_inner.get(id(t))
            if rt is None:
                # Not ours (submitted straight to the replica): the
                # contract-holder is whoever submitted it; shed cleanly.
                if not t.done():
                    t._finish(exception=OverCapacityError(
                        "replica drained for migration", reason="closed"))
                continue
            self._reroute(rt, t, kind="drain")
            n += 1
        return n

    def reroute_dead(self, replica) -> int:
        """Eager failure path: re-admit every live ticket stranded on a
        dead replica (the manager's monitor calls this on detection; the
        waiters' lazy path covers the gap)."""
        with self._lock:
            live = list(self._live)
        n = 0
        for rt in live:
            with rt._cv:
                if rt._replica is not replica or rt._migrating \
                        or rt._terminal is not None:
                    continue
                inner = rt._inner
            if inner.done():
                try:
                    inner.result(timeout=0)
                    continue  # completed before the death: nothing to do
                except _Migrated:
                    continue
                except BaseException as e:
                    if not _is_replica_death(e):
                        continue
            self._reroute(rt, inner, kind="death")
            n += 1
        return n

    def _reroute(self, rt: RouterTicket, failed_inner, kind: str) -> None:
        """Swap ``rt``'s inner future for a fresh admission on another
        replica.  Idempotent under races (waiter thread and monitor may
        both observe the same death): exactly one caller wins the swap,
        the rest no-op."""
        with rt._cv:
            if rt._terminal is not None or rt._migrating \
                    or rt._inner is not failed_inner:
                return
            if rt.migrations >= self.max_migrations:
                rt._terminal = OverCapacityError(
                    f"request migrated {rt.migrations} times without "
                    "completing; giving up", reason="capacity")
                rt._cv.notify_all()
                if not failed_inner.done():
                    failed_inner._finish(exception=_Migrated())
                return
            rt._migrating = True
            rt.migrations += 1
            old = rt._replica
        with self._lock:
            self.migrations += 1
        try:
            replica, inner = self._submit_once(rt.request, exclude={old})
        except (OverCapacityError, RuntimeError) as e:
            with rt._cv:
                rt._terminal = e
                rt._migrating = False
                rt._cv.notify_all()
            if not failed_inner.done():
                failed_inner._finish(exception=_Migrated())
            self._obs_migration(rt, old, None, kind, ok=False)
            return
        with rt._cv:
            rt._inner, rt._replica = inner, replica
            rt._gen += 1
            rt._migrating = False
            rt._cv.notify_all()
        if not failed_inner.done():
            # Wake waiters parked on the superseded future (drain path:
            # the evacuated ticket was never finished).
            failed_inner._finish(exception=_Migrated())
        self._obs_migration(rt, old, replica, kind, ok=True)

    # -- bookkeeping --------------------------------------------------------

    def _done(self, rt: RouterTicket) -> None:
        with self._lock:
            self._live.discard(rt)

    def _observe(self, inner) -> None:
        """Feed a completed request's queue wait to the manager's
        autoscaler (functional, not telemetry — works with obs off)."""
        wait = inner.queue_wait_s
        if wait is not None:
            self.manager.observe_queue_wait(wait)

    def _obs_migration(self, rt, old, new, kind: str, ok: bool) -> None:
        run = obs.get_run()
        if run is None:
            return
        run.counter("fleet_migrations_total",
                    "tickets re-admitted on another replica").inc(kind=kind)
        run.event("session_migrated", phase="fleet", kind=kind, ok=ok,
                  session=rt.request.session_id,
                  tenant=rt.request.tenant,
                  migrations=rt.migrations,
                  from_replica=old.replica_id if old is not None else None,
                  to_replica=new.replica_id if new is not None else None)
