"""Fleet layer: replicated solve service with session affinity.

Composes three pieces on top of the single-replica ``SolveServer``:

* ``manager.ReplicaManager`` — spawns/monitors/respawns/autoscales a
  pool of ``Replica``\\ s (each one ``SolveServer``, optionally pinned to
  its own device);
* ``router.FleetRouter`` — rendezvous-hashes session ids (and a bucket
  proxy for untagged traffic) onto the pool, and live-migrates tickets
  across drains and deaths so a replica retirement loses zero sessions;
* ``aotcache.AOTDiskCache`` / ``AOTExecutable`` — the persistent compile
  cache replicas share, making XLA compilation a fleet-wide one-time
  cost instead of a per-restart tax;
* ``procs.ProcServer`` — the out-of-process replica: the same server
  surface backed by a CHILD PROCESS speaking the packed-v2 TCP
  front-end, with heartbeat liveness and real ``kill -9`` semantics.
"""

from .aotcache import AOT_CACHE_SCHEMA_VERSION  # noqa: F401
from .aotcache import AOTDiskCache, AOTExecutable, entry_identity  # noqa: F401
from .manager import Replica, ReplicaManager  # noqa: F401
from .procs import ProcServer, ProcTicket  # noqa: F401
from .router import FleetRouter, RouterTicket  # noqa: F401
