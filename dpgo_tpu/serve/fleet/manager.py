"""Replica lifecycle: spawn, health-monitor, respawn, autoscale.

``ReplicaManager`` owns the pool of ``Replica``\\ s the router hashes
over.  Each replica is one ``SolveServer`` built by the caller's
``make_server(replica_id)`` factory — the factory decides device
placement (``SolveServer(device=...)`` pins dispatch under
``jax.default_device``), snapshot/session stores, and the shared
``aot_cache_dir`` that lets a freshly spawned replica skip XLA for every
fingerprint the fleet has already compiled.

A daemon monitor thread (joined on ``close``, so the leak-check plugin
stays green) probes each replica's ``status()["accepting"]`` every
``monitor_interval_s``:

* a replica found dead (crashed worker, external ``kill()``) is retired,
  its stranded tickets re-admitted through ``router.reroute_dead``, and a
  fresh replica spawned in its place while the pool is below
  ``min_replicas``;
* sustained queue-wait burn above ``scale_up_burn`` (measured by the same
  ``_SloTracker`` the admission shed uses — here as a functional input,
  not telemetry) spawns a replica up to ``max_replicas``; burn below
  ``scale_down_burn`` live-migrates the newest replica's sessions away
  (``router.migrate_from``) and retires it, down to ``min_replicas``.

The default ``max_replicas == min_replicas`` disables autoscaling, so
tests and fixed-size deployments get a deterministic pool.
"""

from __future__ import annotations

import threading
import time

from ... import obs
from ..server import OverCapacityError, ServeSLO, _SloTracker


class Replica:
    """One managed solve replica: an id, its server, and liveness."""

    def __init__(self, replica_id: str, server):
        self.replica_id = str(replica_id)
        self.server = server
        self.spawned_at = time.monotonic()
        #: Set by ``FleetRouter.migrate_from`` before the drain starts:
        #: the drainer owns this replica's tickets, so the health monitor
        #: must retire it WITHOUT racing a ``reroute_dead`` of its own.
        self.draining = False

    def alive(self) -> bool:
        """Liveness = the server says it is accepting work.  A crashed,
        killed, draining, or closed server all read as dead."""
        try:
            return bool(self.server.status().get("accepting"))
        except Exception:
            return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Replica({self.replica_id!r}, alive={self.alive()})"


class ReplicaManager:
    """Spawns/monitors/retires replicas; the router's source of truth."""

    def __init__(self, make_server, min_replicas: int = 1,
                 max_replicas: int | None = None,
                 monitor_interval_s: float = 0.2,
                 respawn: bool = True,
                 queue_wait_slo_s: float = 0.25,
                 scale_window_s: float = 5.0,
                 scale_up_burn: float = 1.0,
                 scale_down_burn: float = 0.05,
                 scale_cooldown_s: float = 2.0,
                 min_scale_observations: int = 8,
                 metrics_port: int | None = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        self.make_server = make_server
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas) if max_replicas is not None \
            else self.min_replicas
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.monitor_interval_s = float(monitor_interval_s)
        self.respawn = bool(respawn)
        self.queue_wait_slo_s = float(queue_wait_slo_s)
        self.scale_up_burn = float(scale_up_burn)
        self.scale_down_burn = float(scale_down_burn)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.min_scale_observations = int(min_scale_observations)
        self.metrics_port = metrics_port
        #: Fleet-level ``/metrics`` + ``/statusz`` aggregator; constructed
        #: in ``start()`` behind the telemetry fence (None when off).
        self.sidecar = None

        self._lock = threading.Lock()
        self._replicas: list[Replica] = []  # guarded-by: _lock
        self._seq = 0                       # guarded-by: _lock
        self._router = None
        self._stop = threading.Event()
        self._monitor = None
        self._started = False               # guarded-by: _lock
        self._closed = False                # guarded-by: _lock
        # Functional reuse of the burn-rate machinery (not telemetry):
        # queue wait stands in for latency, the SLO is the wait target.
        self._tracker = _SloTracker(ServeSLO(
            latency_s=self.queue_wait_slo_s, latency_target=0.5,
            window_s=float(scale_window_s)))
        self._n_waits = 0                   # guarded-by: _lock
        self._last_scale = 0.0              # guarded-by: _lock
        self.spawned = 0                    # guarded-by: _lock
        self.retired = 0                    # guarded-by: _lock
        self.respawns = 0                   # guarded-by: _lock
        self.scale_ups = 0                  # guarded-by: _lock
        self.scale_downs = 0                # guarded-by: _lock

    # -- pool ---------------------------------------------------------------

    def attach_router(self, router) -> None:
        self._router = router

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas)

    def get(self, replica_id: str) -> Replica | None:
        with self._lock:
            for r in self._replicas:
                if r.replica_id == replica_id:
                    return r
        return None

    def start(self) -> None:
        """Bring the pool to ``min_replicas`` and start the monitor.
        Idempotent."""
        with self._lock:
            if self._started:
                return
            self._started = True
        while len(self.replicas()) < self.min_replicas:
            self.spawn(reason="start")
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="dpgo-fleet-monitor", daemon=True)
        self._monitor.start()
        if self.metrics_port is not None:
            from ...obs import fleetobs
            self.sidecar = fleetobs.attach_fleet_sidecar(
                fleetobs.ReplicaFleetSource(self), port=self.metrics_port)

    def spawn(self, reason: str = "manual") -> Replica:
        with self._lock:
            if self._closed:
                raise RuntimeError("manager is closed")
            rid = f"r{self._seq}"
            self._seq += 1
        server = self.make_server(rid)
        if getattr(server, "replica_id", None) is None:
            server.replica_id = rid
        replica = Replica(rid, server)
        with self._lock:
            self._replicas.append(replica)
            self.spawned += 1
        run = obs.get_run()
        if run is not None:
            run.counter("fleet_replicas_spawned_total",
                        "replicas brought up by the manager").inc(
                reason=reason)
            run.event("replica_spawn", phase="fleet", replica=rid,
                      reason=reason, pool=len(self.replicas()))
        return replica

    def _retire(self, replica: Replica) -> None:
        with self._lock:
            try:
                self._replicas.remove(replica)
            except ValueError:
                return
            self.retired += 1

    def kill_replica(self, replica_id: str) -> bool:
        """Hard-kill one replica (chaos lever for soaks/tests): sheds its
        in-flight batch at the next boundary, retires it, re-admits the
        stranded tickets, and respawns if the pool dropped below
        ``min_replicas``."""
        replica = self.get(replica_id)
        if replica is None:
            return False
        self._retire(replica)
        replica.server.kill()
        if self._router is not None:
            self._router.reroute_dead(replica)
        with self._lock:
            need = self.respawn and not self._closed \
                and len(self._replicas) < self.min_replicas
        if need:
            with self._lock:
                self.respawns += 1
            self.spawn(reason="respawn")
        return True

    # -- autoscale input ----------------------------------------------------

    def observe_queue_wait(self, wait_s: float) -> None:
        """Router feedback: one completed request's queue wait.  Waits
        beyond ``queue_wait_slo_s`` burn the tracker's error budget."""
        with self._lock:
            self._tracker.observe_request(time.monotonic(), float(wait_s))
            self._n_waits += 1

    # -- monitor ------------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitor_interval_s):
            try:
                self._check_health()
                self._check_scale()
            except Exception as e:  # monitor must survive anything
                run = obs.get_run()
                if run is not None:
                    run.event("fleet_monitor_error", phase="fleet",
                              error=f"{type(e).__name__}: {e}")

    def _check_health(self) -> None:
        for replica in self.replicas():
            if replica.alive():
                continue
            self._retire(replica)
            if not replica.draining:
                run = obs.get_run()
                if run is not None:
                    run.counter("fleet_replica_deaths_total",
                                "replicas found dead by the monitor").inc()
                    run.event("replica_death", phase="fleet",
                              replica=replica.replica_id,
                              pool=len(self.replicas()))
                if self._router is not None:
                    self._router.reroute_dead(replica)
            with self._lock:
                need = self.respawn and not self._closed \
                    and len(self._replicas) < self.min_replicas
            if need:
                with self._lock:
                    self.respawns += 1
                self.spawn(reason="respawn")

    def _check_scale(self) -> None:
        if self.max_replicas <= self.min_replicas:
            return  # autoscaling disabled (the deterministic default)
        with self._lock:
            if self._n_waits < self.min_scale_observations:
                return
            if time.monotonic() - self._last_scale < self.scale_cooldown_s:
                return
        with self._lock:
            burn = self._tracker.burn(time.monotonic())["latency_burn"]
        n = len(self.replicas())
        if burn >= self.scale_up_burn and n < self.max_replicas:
            self._mark_scaled()
            self.spawn(reason="scale_up")
            with self._lock:
                self.scale_ups += 1
            self._obs_scale("up", burn)
        elif burn <= self.scale_down_burn and n > self.min_replicas:
            self._mark_scaled()
            self.scale_down()
            self._obs_scale("down", burn)

    def _mark_scaled(self) -> None:
        with self._lock:
            self._last_scale = time.monotonic()
            self._n_waits = 0

    def _obs_scale(self, direction: str, burn: float) -> None:
        run = obs.get_run()
        if run is not None:
            run.counter("fleet_scale_events_total",
                        "autoscaler decisions").inc(direction=direction)
            run.event("fleet_scale", phase="fleet", direction=direction,
                      burn=burn, pool=len(self.replicas()))

    def scale_down(self, replica_id: str | None = None) -> bool:
        """Retire one replica gracefully: live-migrate its sessions via
        the router's drain path, then close it.  Victim defaults to the
        newest replica (rendezvous hashing keeps the remap set minimal
        either way)."""
        with self._lock:
            if len(self._replicas) <= self.min_replicas:
                return False
            pool = list(self._replicas)
        victim = None
        if replica_id is not None:
            victim = self.get(replica_id)
        else:
            victim = max(pool, key=lambda r: r.spawned_at)
        if victim is None:
            return False
        # Retire first so the router stops hashing new work onto it,
        # then evacuate what it already holds.
        self._retire(victim)
        with self._lock:
            self.scale_downs += 1
        if self._router is not None:
            self._router.migrate_from(victim)
        else:
            for t in victim.server.drain():
                if not t.done():
                    t._finish(exception=OverCapacityError(
                        "replica retired", reason="closed"))
        victim.server.close()
        return True

    # -- shutdown -----------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self.sidecar is not None:
            try:
                self.sidecar.close()
            except Exception:
                pass
            self.sidecar = None
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        for replica in self.replicas():
            self._retire(replica)
            try:
                replica.server.close()
            except Exception:
                pass

    def __enter__(self) -> "ReplicaManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def status(self) -> dict:
        with self._lock:
            out = {"spawned": self.spawned, "retired": self.retired,
                   "respawns": self.respawns, "scale_ups": self.scale_ups,
                   "scale_downs": self.scale_downs,
                   "min_replicas": self.min_replicas,
                   "max_replicas": self.max_replicas,
                   "pool": [r.replica_id for r in self._replicas]}
            out["burn"] = self._tracker.burn(time.monotonic())
        return out
