"""The request plane: queueing, admission control, batching, SLO metrics.

``SolveServer`` is the in-process serving API (the TCP front-end in
``frontend`` is a thin shell over it).  ``submit`` performs admission
control synchronously — a bounded queue and per-tenant in-flight quotas
raise ``OverCapacityError`` immediately, so an overloaded server fails
fast instead of buffering unboundedly — and returns a ``SolveTicket``
future.  A single worker thread drains the queue: it prepares each
request (problem build, ``models.rbcd.prepare_problem``), pads it into
its shape bucket (``bucketing``), sheds requests whose deadline expired
while queued (``OverCapacityError`` with ``reason="deadline"``), groups
compatible requests, and dispatches one batched solve per group
(``runner.run_bucket``) through the fingerprint-keyed executable cache.

Warm pools: ``warm(requests)`` runs representative requests through the
full pipeline at ``max_iters=1``, populating the executable cache (and
XLA's jit caches) before traffic arrives, so the first real request of a
bucket doesn't pay compilation.

Per-tenant SLO metrics ride the ambient telemetry run (``dpgo_tpu.obs``)
when one is installed: ``serve_request`` / ``serve_batch`` /
``serve_shed`` events (the schema the report CLI's "serving" section and
``bench_serving.py`` share) plus queue-wait/latency histograms, an
occupancy gauge, and request/shed counters.  With telemetry off the
entire path constructs no obs objects — every metrics site sits behind
``obs.get_run() is not None``, same fence as the solver core.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import jax.numpy as jnp

from .. import obs
from ..config import AgentParams
from ..models.rbcd import prepare_problem
from ..types import Measurements
from .bucketing import bucket_shape_of, pad_problem
from .cache import ExecutableCache, fingerprint_key, problem_fingerprint
from .runner import run_bucket


class OverCapacityError(RuntimeError):
    """The server refused or shed this request.  ``reason`` is one of
    ``"queue"`` (bounded queue full), ``"tenant_quota"`` (per-tenant
    in-flight cap), ``"deadline"`` (shed after waiting past its deadline),
    or ``"closed"`` (server shut down with the request still queued)."""

    def __init__(self, message: str, reason: str = "capacity"):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass
class SolveRequest:
    """One tenant's problem: measurements plus solve/termination config.

    Requests whose built problems round to the same shape bucket AND agree
    on (params, dtype, max_iters, grad_norm_tol, eval_every) batch
    together; anything else dispatches separately."""

    meas: Measurements
    num_robots: int
    params: AgentParams | None = None
    tenant: str = "default"
    #: Relative deadline (seconds from submit).  A request still queued
    #: past its deadline is shed, never solved late.
    deadline_s: float | None = None
    max_iters: int | None = None
    grad_norm_tol: float = 0.1
    eval_every: int = 1
    dtype: object = jnp.float64


class SolveTicket:
    """Future for one submitted request."""

    def __init__(self, request: SolveRequest):
        self.request = request
        self.t_submit = time.monotonic()
        self.t_dispatch: float | None = None
        self.t_done: float | None = None
        self._event = threading.Event()
        self._result = None
        self._exception: BaseException | None = None
        # worker-side scratch
        self._padded = None
        self._key: str | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """The ``RBCDResult``; raises the solve's exception (including
        ``OverCapacityError`` for shed requests) or ``TimeoutError``."""
        if not self._event.wait(timeout):
            raise TimeoutError("solve not finished within timeout")
        if self._exception is not None:
            raise self._exception
        return self._result

    @property
    def queue_wait_s(self) -> float | None:
        return None if self.t_dispatch is None \
            else self.t_dispatch - self.t_submit

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def _finish(self, result=None, exception=None) -> None:
        self.t_done = time.monotonic()
        self._result = result
        self._exception = exception
        self._event.set()


class SolveServer:
    """Multi-tenant batched PGO solve server (in-process API).

    Use as a context manager; ``close()`` drains nothing — queued requests
    are shed with ``reason="closed"``."""

    def __init__(self, max_batch: int = 8, max_queue: int = 64,
                 batch_window_s: float = 0.005,
                 tenant_quota: int | None = None, quantum: int = 32,
                 init: str = "chordal"):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.batch_window_s = float(batch_window_s)
        self.tenant_quota = tenant_quota
        self.quantum = int(quantum)
        self.init = init
        self.cache = ExecutableCache()
        self._cond = threading.Condition()
        self._pending: deque[SolveTicket] = deque()
        self._inflight: dict[str, int] = {}
        self._closed = False
        run = obs.get_run()
        if run is not None:
            run.set_fingerprint(serve_max_batch=self.max_batch,
                                serve_quantum=self.quantum)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="dpgo-serve-worker")
        self._worker.start()

    # -- client API ---------------------------------------------------------

    def submit(self, request: SolveRequest) -> SolveTicket:
        """Admit a request (or raise ``OverCapacityError``) and return its
        ticket.  Admission is synchronous and cheap; problem build happens
        on the worker."""
        ticket = SolveTicket(request)
        with self._cond:
            if self._closed:
                raise RuntimeError("server is closed")
            if len(self._pending) >= self.max_queue:
                self._obs_shed(request.tenant, "queue", 0.0)
                raise OverCapacityError(
                    f"queue full ({self.max_queue} requests pending)",
                    reason="queue")
            if self.tenant_quota is not None and \
                    self._inflight.get(request.tenant, 0) >= self.tenant_quota:
                self._obs_shed(request.tenant, "tenant_quota", 0.0)
                raise OverCapacityError(
                    f"tenant {request.tenant!r} at its in-flight quota "
                    f"({self.tenant_quota})", reason="tenant_quota")
            self._inflight[request.tenant] = \
                self._inflight.get(request.tenant, 0) + 1
            self._pending.append(ticket)
            self._cond.notify_all()
        return ticket

    def solve(self, request: SolveRequest, timeout: float | None = None):
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(request).result(timeout)

    def warm(self, requests: list[SolveRequest]) -> int:
        """Warm pool: run representative requests through prepare -> pad ->
        batched dispatch at ``max_iters=1``, so their buckets' executables
        are compiled and cached before real traffic.  Returns the number
        of distinct buckets warmed."""
        groups: dict[str, list] = {}
        for req in requests:
            padded, key = self._prepare(req)
            groups.setdefault(key, []).append((padded, req))
        for members in groups.values():
            padded_list = [p for p, _ in members][:self.max_batch]
            req0 = members[0][1]
            run_bucket(padded_list, self.cache, max_iters=1,
                       grad_norm_tol=req0.grad_norm_tol,
                       eval_every=1)
        run = obs.get_run()
        if run is not None:
            run.event("serve_warm", phase="serve", buckets=len(groups),
                      requests=len(requests))
        return len(groups)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join()

    def __enter__(self) -> "SolveServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker -------------------------------------------------------------

    def _prepare(self, req: SolveRequest):
        """Problem build + bucket padding for one request; returns the
        padded problem and its full batch-compatibility key."""
        prob = prepare_problem(req.meas, req.num_robots, params=req.params,
                               dtype=req.dtype, init=None, pallas_sel=False)
        shape = bucket_shape_of(prob, quantum=self.quantum)
        padded = pad_problem(prob, shape, init=self.init)
        fp = problem_fingerprint(padded.meta, prob.params, req.dtype, shape)
        fp["termination"] = [req.max_iters or prob.params.max_num_iters,
                             req.grad_norm_tol, req.eval_every]
        return padded, fingerprint_key(fp)

    def _release(self, tickets) -> None:
        with self._cond:
            for t in tickets:
                tenant = t.request.tenant
                n = self._inflight.get(tenant, 1) - 1
                if n <= 0:
                    self._inflight.pop(tenant, None)
                else:
                    self._inflight[tenant] = n

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    leftovers = list(self._pending)
                    self._pending.clear()
                    break
                n_pending = len(self._pending)
            # Batching window: give concurrent submitters a moment to
            # coalesce before forming a batch (skip when already full).
            if n_pending < self.max_batch and self.batch_window_s > 0:
                time.sleep(self.batch_window_s)
            self._dispatch_once()
        for t in leftovers:
            t._finish(exception=OverCapacityError(
                "server closed with request still queued", reason="closed"))
        self._release(leftovers)

    def _dispatch_once(self) -> None:
        with self._cond:
            snapshot = list(self._pending)
        if not snapshot:
            return
        now = time.monotonic()
        shed, failed = [], []
        for t in snapshot:
            dl = t.request.deadline_s
            if dl is not None and (now - t.t_submit) > dl:
                shed.append(t)
                continue
            if t._padded is None:
                try:
                    t._padded, t._key = self._prepare(t.request)
                except Exception as e:  # bad request: report, don't die
                    t._finish(exception=e)
                    failed.append(t)
        for t in shed:
            waited = now - t.t_submit
            t._finish(exception=OverCapacityError(
                f"deadline ({t.request.deadline_s:.3f}s) expired after "
                f"{waited:.3f}s in queue", reason="deadline"))
            self._obs_shed(t.request.tenant, "deadline", waited)
        drop = set(shed) | set(failed)
        ready = [t for t in snapshot if t not in drop and t._padded is not None]
        batch = []
        if ready:
            lead_key = ready[0]._key
            batch = [t for t in ready if t._key == lead_key][:self.max_batch]
        with self._cond:
            for t in list(drop) + batch:
                try:
                    self._pending.remove(t)
                except ValueError:
                    pass
        self._release(list(drop))
        if batch:
            self._run_batch(batch)

    def _run_batch(self, tickets: list[SolveTicket]) -> None:
        t0 = time.monotonic()
        for t in tickets:
            t.t_dispatch = t0
        req0 = tickets[0].request
        try:
            results, info = run_bucket(
                [t._padded for t in tickets], self.cache,
                max_iters=req0.max_iters, grad_norm_tol=req0.grad_norm_tol,
                eval_every=req0.eval_every)
        except Exception as e:
            for t in tickets:
                t._finish(exception=e)
            self._release(tickets)
            return
        for t, res in zip(tickets, results):
            t._finish(result=res)
        self._release(tickets)
        self._obs_batch(tickets, results, info, time.monotonic() - t0)

    # -- telemetry (every site behind the zero-overhead fence) --------------

    def _obs_shed(self, tenant: str, reason: str, waited_s: float) -> None:
        run = obs.get_run()
        if run is None:
            return
        run.counter("serve_shed_total",
                    "requests shed by admission control").inc(
            tenant=tenant, reason=reason)
        run.event("serve_shed", phase="serve", tenant=tenant, reason=reason,
                  waited_s=waited_s)

    def _obs_batch(self, tickets, results, info, duration_s: float) -> None:
        run = obs.get_run()
        if run is None:
            return
        bucket = str(tuple(tickets[0]._padded.shape))
        run.gauge("serve_batch_occupancy",
                  "fraction of the batched executable's slots carrying "
                  "real requests").set(info["occupancy"])
        run.event("serve_batch", phase="serve", bucket=bucket,
                  size=info["size"], batch=info["batch"],
                  occupancy=info["occupancy"], rounds=info["rounds"],
                  evals=info["evals"], duration_s=duration_s,
                  cache=self.cache.stats())
        c_req = run.counter("serve_requests_total", "requests served")
        h_wait = run.histogram("serve_queue_wait_seconds",
                               "submit -> dispatch wait", unit="s")
        h_lat = run.histogram("serve_solve_latency_seconds",
                              "submit -> result latency", unit="s")
        for t, res in zip(tickets, results):
            tenant = t.request.tenant
            c_req.inc(tenant=tenant)
            h_wait.observe(t.queue_wait_s or 0.0, tenant=tenant)
            h_lat.observe(t.latency_s or 0.0, tenant=tenant)
            run.event(
                "serve_request", phase="serve", tenant=tenant, bucket=bucket,
                queue_wait_s=t.queue_wait_s, latency_s=t.latency_s,
                iterations=res.iterations, terminated_by=res.terminated_by,
                cost=res.cost_history[-1] if res.cost_history else None,
                grad_norm=res.grad_norm_history[-1]
                if res.grad_norm_history else None)
