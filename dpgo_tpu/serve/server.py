"""The request plane: queueing, admission control, batching, SLO metrics.

``SolveServer`` is the in-process serving API (the TCP front-end in
``frontend`` is a thin shell over it).  ``submit`` performs admission
control synchronously — a bounded queue and per-tenant in-flight quotas
raise ``OverCapacityError`` immediately, so an overloaded server fails
fast instead of buffering unboundedly — and returns a ``SolveTicket``
future.  A single worker thread drains the queue: it prepares each
request (problem build, ``models.rbcd.prepare_problem``), pads it into
its shape bucket (``bucketing``), sheds requests whose deadline expired
while queued (``OverCapacityError`` with ``reason="deadline"``), groups
compatible requests, and dispatches one batched solve per group
(``runner.run_bucket``) through the fingerprint-keyed executable cache.

Warm pools: ``warm(requests)`` runs representative requests through the
full pipeline at ``max_iters=1``, populating the executable cache (and
XLA's jit caches) before traffic arrives, so the first real request of a
bucket doesn't pay compilation.

Per-tenant SLO metrics ride the ambient telemetry run (``dpgo_tpu.obs``)
when one is installed: ``serve_request`` / ``serve_batch`` /
``serve_shed`` events (the schema the report CLI's "serving" section and
``bench_serving.py`` share) plus queue-wait/latency histograms, an
occupancy gauge, and request/shed counters.  On top of that sit four
operability layers, all telemetry-on only:

* **request tracing** — every request runs on one trace: ``admission``
  (submit), ``prepare``/``queue_wait`` (worker), a shared per-batch
  ``dispatch`` span with ``batch_member`` flow links in and ``reply``
  links out, and a reason-tagged ``shed`` span for requests that never
  dispatch (see ``docs/ARCHITECTURE.md`` "Serving observability");
* **live endpoints** — ``metrics_port`` starts the ``statusz`` sidecar
  (``/metrics``, ``/healthz``, ``/statusz`` from ``status()``);
* **SLO burn-rate alerting** — ``slo=ServeSLO(...)`` (or per-tenant
  dict) evaluates rolling-window latency/shed burn rates, exporting
  ``serve_slo_burn_rate`` gauges and emitting ``slo_burn`` anomalies
  through ``obs.health`` on level transitions;
* **profiling** — the executable cache wraps compiles with AOT
  cost/memory analysis (``obs.profile``), and ``profile_dir`` opens a
  ``jax.profiler`` window over the first ``profile_batches`` dispatches.

With telemetry off the entire path constructs no obs objects — every
metrics site sits behind ``obs.get_run() is not None``, same fence as
the solver core — and no sidecar thread, profiler, or SLO tracker
exists even when their knobs are set.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp

from .. import obs
from ..comms.protocol import ORIGIN_SERVE_SERVER
from ..config import AgentParams
from ..models.rbcd import prepare_problem
from ..obs import trace as obs_trace
from ..types import Measurements
from .bucketing import bucket_shape_of, pad_problem
from .cache import ExecutableCache, fingerprint_key, problem_fingerprint
from .runner import run_bucket
from .session import SessionStore


@dataclasses.dataclass(frozen=True)
class ServeSLO:
    """Per-tenant service-level objectives, evaluated as burn rates.

    A request is *good* when its submit->result latency is at most
    ``latency_s``; the latency objective demands a ``latency_target``
    fraction of good requests, leaving an error budget of
    ``1 - latency_target``.  The burn rate is the observed bad fraction
    over the rolling ``window_s`` window divided by that budget — 1.0
    means exactly consuming budget, 10x means the budget burns in a tenth
    of the window (the classic multi-window alerting vocabulary).  The
    shed objective budgets the fraction of admissions-or-sheds that were
    shed.  Crossing ``burn_warning``/``burn_critical`` emits one
    structured ``slo_burn`` anomaly event per level transition through
    ``obs.health``'s callback/policy machinery; recovery emits
    ``slo_recovered``."""

    latency_s: float = 1.0
    latency_target: float = 0.99
    shed_target: float = 0.01
    window_s: float = 60.0
    burn_warning: float = 1.0
    burn_critical: float = 10.0


class _SloTracker:
    """Rolling-window burn-rate state for one tenant.

    Pure host-side bookkeeping over event timestamps the serving metrics
    already collect; constructed only behind the telemetry fence (the
    zero-overhead boom test patches ``__init__``)."""

    def __init__(self, slo: ServeSLO):
        self.slo = slo
        self._lat: deque = deque()    # (t_mono, was_slow)
        self._shed: deque = deque()   # t_mono
        self.level: dict[str, str | None] = {"latency": None, "shed": None}

    def _trim(self, now: float) -> None:
        cutoff = now - self.slo.window_s
        for dq in (self._lat, self._shed):
            while dq:
                head = dq[0]
                t = head[0] if isinstance(head, tuple) else head
                if t >= cutoff:
                    break
                dq.popleft()

    def observe_request(self, now: float, latency_s: float) -> None:
        self._lat.append((now, latency_s > self.slo.latency_s))
        self._trim(now)

    def observe_shed(self, now: float) -> None:
        self._shed.append(now)
        self._trim(now)

    def burn(self, now: float) -> dict:
        """Current burn rates and window tallies."""
        self._trim(now)
        total = len(self._lat)
        slow = sum(1 for _, bad in self._lat if bad)
        shed = len(self._shed)
        lat_budget = max(1e-9, 1.0 - self.slo.latency_target)
        shed_budget = max(1e-9, self.slo.shed_target)
        lat_burn = (slow / total) / lat_budget if total else 0.0
        seen = total + shed
        shed_burn = (shed / seen) / shed_budget if seen else 0.0
        return {"latency_burn": lat_burn, "shed_burn": shed_burn,
                "requests": total, "slow": slow, "shed": shed,
                "window_s": self.slo.window_s}

    def classify(self, burn: float) -> str | None:
        if burn >= self.slo.burn_critical:
            return "critical"
        if burn >= self.slo.burn_warning:
            return "warning"
        return None


class OverCapacityError(RuntimeError):
    """The server refused or shed this request.  ``reason`` is one of
    ``"queue"`` (bounded queue full), ``"tenant_quota"`` (per-tenant
    in-flight cap), ``"deadline"`` (shed after waiting past its deadline),
    or ``"closed"`` (server shut down with the request still queued)."""

    def __init__(self, message: str, reason: str = "capacity"):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass
class SolveRequest:
    """One tenant's problem: measurements plus solve/termination config.

    Requests whose built problems round to the same shape bucket AND agree
    on (params, dtype, max_iters, grad_norm_tol, eval_every) batch
    together; anything else dispatches separately."""

    meas: Measurements
    num_robots: int
    params: AgentParams | None = None
    tenant: str = "default"
    #: Relative deadline (seconds from submit).  A request still queued
    #: past its deadline is shed, never solved late.
    deadline_s: float | None = None
    max_iters: int | None = None
    grad_norm_tol: float = 0.1
    eval_every: int = 1
    dtype: object = jnp.float64
    #: Wire trace context ``(trace_id, span_id, origin, t_mono, t_wall)``
    #: from ``comms.protocol.unpack_trace_entries`` — the front-end passes
    #: the client's stamped context through so the request's server-side
    #: spans join the client's trace.  None (default, and always with
    #: telemetry off) starts a fresh trace per request.
    trace_ctx: tuple | None = None
    #: Durable session identity.  When the server carries a
    #: ``SessionStore``, a session-tagged request's solver state is
    #: snapshotted on solve boundaries and, if the worker dies mid-batch,
    #: the request is re-admitted from the last snapshot and completes
    #: with ``RBCDResult.recovered = True`` instead of being lost.
    session_id: str | None = None


class SolveTicket:
    """Future for one submitted request."""

    def __init__(self, request: SolveRequest):
        self.request = request
        self.t_submit = time.monotonic()
        self.t_submit_wall = time.time()
        self.t_dispatch: float | None = None
        self.t_done: float | None = None
        self._event = threading.Event()
        self._result = None
        self._exception: BaseException | None = None
        # worker-side scratch
        self._padded = None
        self._key: str | None = None
        #: set when this request was re-admitted from a session snapshot
        #: after a worker crash; stamped onto its result as ``recovered``.
        self._recovered = False
        #: snapshot iteration this request resumed from (``serve.fleet``
        #: migration: a drained session re-admitted on another replica
        #: picks up mid-schedule); 0 = cold start.
        self._resumed_from = 0
        # tracing context (set by submit() only when telemetry is on)
        self.trace_id: int | None = None
        self.span_admission: int | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """The ``RBCDResult``; raises the solve's exception (including
        ``OverCapacityError`` for shed requests) or ``TimeoutError``."""
        if not self._event.wait(timeout):
            raise TimeoutError("solve not finished within timeout")
        if self._exception is not None:
            raise self._exception
        return self._result

    @property
    def queue_wait_s(self) -> float | None:
        return None if self.t_dispatch is None \
            else self.t_dispatch - self.t_submit

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def _finish(self, result=None, exception=None) -> None:
        self.t_done = time.monotonic()
        self._result = result
        self._exception = exception
        self._event.set()


class SolveServer:
    """Multi-tenant batched PGO solve server (in-process API).

    Use as a context manager.  ``close()`` sheds queued requests with
    ``reason="closed"``; ``close(drain=True)`` is the graceful variant
    (admission stops with structured sheds, the in-flight batch replies,
    ``/healthz`` reports ``draining`` until shutdown completes).  With a
    ``session_store``, session-tagged requests survive worker deaths: the
    supervisor re-admits them from their last snapshot and the reply
    carries ``recovered=True``."""

    def __init__(self, max_batch: int = 8, max_queue: int = 64,
                 batch_window_s: float = 0.005,
                 tenant_quota: int | None = None, quantum: int = 32,
                 init: str = "chordal",
                 slo: "ServeSLO | dict[str, ServeSLO] | None" = None,
                 metrics_port: int | None = None,
                 metrics_host: str = "127.0.0.1",
                 profile_dir: str | None = None,
                 profile_batches: int = 3,
                 verdict_every: int | None = None,
                 session_store: "SessionStore | str | None" = None,
                 session_every: int = 1,
                 worker_restarts: int = 2,
                 replica_id: str | None = None,
                 device=None,
                 resume_sessions: bool = False,
                 aot_cache_dir: str | None = None):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.batch_window_s = float(batch_window_s)
        self.tenant_quota = tenant_quota
        self.quantum = int(quantum)
        self.init = init
        #: Device-resident termination for dispatched buckets: one packed
        #: [B] verdict-vector readback per this many rounds instead of the
        #: per-eval float stack (``runner.run_bucket``'s verdict mode).
        #: Requests whose ``eval_every`` does not divide it dispatch on
        #: the legacy per-eval loop.  None = legacy everywhere.
        self.verdict_every = verdict_every
        #: One ``ServeSLO`` for every tenant, or a per-tenant dict (the
        #: ``"default"`` key, when present, covers unlisted tenants).
        self.slo = slo
        #: Crash-recovery session store (``serve.session``): session-tagged
        #: requests snapshot every ``session_every`` solve boundaries and
        #: are re-admitted from their last snapshot when the worker dies.
        #: A string is treated as the store's root directory.
        self.session_store = SessionStore(session_store) \
            if isinstance(session_store, str) else session_store
        self.session_every = max(int(session_every), 1)
        #: How many unexpected worker deaths the supervisor absorbs before
        #: giving up and shedding the queue (a crash-looping device should
        #: fail loudly, not spin).
        self.worker_restarts = max(int(worker_restarts), 0)
        #: Fleet identity (``serve.fleet``): which replica this server is,
        #: and the ``jax.Device`` its dispatches bind to (None = default
        #: device).  Identity is reported by ``status()``/``/healthz`` so
        #: the router's health poll and ``report --live`` can tell
        #: replicas apart.
        self.replica_id = replica_id
        self.device = device
        #: Fleet migration: admit session-tagged requests from their
        #: newest store snapshot (same bucket) instead of cold — the
        #: receiving half of ``drain()``.  Off by default: the
        #: single-replica crash-recovery path re-admits explicitly and
        #: must not also resume retried requests implicitly.
        self.resume_sessions = bool(resume_sessions)
        #: One ``_run_batch`` sets this with the batch still stoppable;
        #: ``drain()``/``kill()`` set it to break the in-flight batch at
        #: its next eval boundary (after the boundary snapshot lands).
        self._interrupt = threading.Event()
        disk = None
        if aot_cache_dir is not None:
            # Lazy import: fleet's router/manager import this module.
            from .fleet.aotcache import AOTDiskCache

            disk = AOTDiskCache(aot_cache_dir)
        self.cache = ExecutableCache(disk=disk)
        # One condition serializes ALL cross-thread server state: client
        # threads (submit/status/sidecar scrapes), the worker, and close.
        self._cond = threading.Condition()
        self._pending: deque[SolveTicket] = deque()   # guarded-by: _cond
        self._inflight: dict[str, int] = {}           # guarded-by: _cond
        self._closed = False                          # guarded-by: _cond
        self._draining = False                        # guarded-by: _cond
        self._terminated = False                      # guarded-by: _cond
        self._active: list[SolveTicket] = []          # guarded-by: _cond
        self._crashes = 0                             # guarded-by: _cond
        #: Live-migration mode: ``drain()`` collects interrupted and
        #: still-queued tickets here instead of finishing them, so the
        #: router can re-admit each on another replica.
        self._evacuating = False                      # guarded-by: _cond
        self._evacuated: list[SolveTicket] = []       # guarded-by: _cond
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self._pid = os.getpid()
        dev = device if device is not None else jax.devices()[0]
        self._device_info = {"platform": str(dev.platform),
                             "ordinal": int(dev.id)}
        # Plain-int liveness tallies for /statusz (server state, not obs).
        self._n_batches = 0                           # guarded-by: _cond
        self._n_requests = 0                          # guarded-by: _cond
        self._n_shed = 0                              # guarded-by: _cond
        self._last_batch: dict | None = None          # guarded-by: _cond
        self._slo_state: dict[str, _SloTracker] = {}  # guarded-by: _cond
        self.sidecar = None
        self._profiler = None
        run = obs.get_run()
        try:
            if run is not None:
                run.set_fingerprint(serve_max_batch=self.max_batch,
                                    serve_quantum=self.quantum)
                # Live endpoints and the device profiler exist only on the
                # telemetry-on path: with no run there is no registry to
                # scrape and the fence demands zero extra threads.
                if metrics_port is not None:
                    from .statusz import MetricsSidecar

                    self.sidecar = MetricsSidecar(self, run,
                                                  host=metrics_host,
                                                  port=metrics_port)
                if profile_dir is not None:
                    from ..obs.profile import ProfilerWindow

                    self._profiler = ProfilerWindow(
                        profile_dir, num_batches=profile_batches)
            self._worker = threading.Thread(target=self._supervise,
                                            daemon=True,
                                            name="dpgo-serve-worker")
            self._worker.start()
        except BaseException:
            # A half-constructed server must not strand the sidecar's
            # HTTP thread + bound socket (leakcheck-enforced contract).
            if self.sidecar is not None:
                self.sidecar.close()
            if self._profiler is not None:
                self._profiler.close()
            raise

    @property
    def metrics_url(self) -> str | None:
        """This replica's ``/metrics`` scrape URL, or None when the
        sidecar is off (no run / no ``metrics_port``) — the per-replica
        target a fleet-level aggregator merges."""
        if self.sidecar is None:
            return None
        return f"http://{self.sidecar.host}:{self.sidecar.port}/metrics"

    # -- client API ---------------------------------------------------------

    def submit(self, request: SolveRequest) -> SolveTicket:
        """Admit a request (or raise ``OverCapacityError``) and return its
        ticket.  Admission is synchronous and cheap; problem build happens
        on the worker.

        With telemetry on, admission opens the request's root ``admission``
        span: its trace id comes from the submitter's ambient span (the
        front-end's per-connection ``frontend`` span) or the wire trace
        context the client stamped (``request.trace_ctx``), so one trace
        follows the request from TCP accept to reply.  A rejected request
        closes the span tagged with the shed reason."""
        ticket = SolveTicket(request)
        run = obs.get_run()
        sp = None
        if run is not None:
            ctx = request.trace_ctx
            parent = obs_trace.current_span()
            sp = obs_trace.Span(
                run, "admission", phase="serve",
                trace_id=(ctx[0] if ctx is not None and parent is None
                          else None),
                link=ctx if parent is None else None)
            ticket.trace_id = sp.trace_id
            ticket.span_admission = sp.span_id
        try:
            with self._cond:
                if self._closed:
                    if self._draining:
                        # Graceful drain: admission stops with a structured
                        # shed (the TCP front-end turns this into a
                        # shed(reason=closed) reply, not a dropped
                        # connection).
                        self._obs_shed(request.tenant, "closed", 0.0)
                        raise OverCapacityError(
                            "server is draining: admission stopped",
                            reason="closed")
                    raise RuntimeError("server is closed")
                if len(self._pending) >= self.max_queue:
                    self._obs_shed(request.tenant, "queue", 0.0)
                    raise OverCapacityError(
                        f"queue full ({self.max_queue} requests pending)",
                        reason="queue")
                if self.tenant_quota is not None and \
                        self._inflight.get(request.tenant, 0) >= \
                        self.tenant_quota:
                    self._obs_shed(request.tenant, "tenant_quota", 0.0)
                    raise OverCapacityError(
                        f"tenant {request.tenant!r} at its in-flight quota "
                        f"({self.tenant_quota})", reason="tenant_quota")
                self._inflight[request.tenant] = \
                    self._inflight.get(request.tenant, 0) + 1
                self._pending.append(ticket)
                queue_depth = len(self._pending)
                self._cond.notify_all()
        except OverCapacityError as e:
            if sp is not None:
                sp.end(tenant=request.tenant, outcome="rejected",
                       reason=e.reason)
            raise
        except BaseException:
            if sp is not None:
                sp.end(tenant=request.tenant, outcome="error")
            raise
        if sp is not None:
            sp.end(tenant=request.tenant, outcome="queued",
                   queue_depth=queue_depth)
        return ticket

    def solve(self, request: SolveRequest, timeout: float | None = None):
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(request).result(timeout)

    def warm(self, requests: list[SolveRequest]) -> int:
        """Warm pool: run representative requests through prepare -> pad ->
        batched dispatch at ``max_iters=1``, so their buckets' executables
        are compiled and cached before real traffic.  Returns the number
        of distinct buckets warmed."""
        groups: dict[str, list] = {}
        for req in requests:
            padded, key, _ = self._prepare(req)
            groups.setdefault(key, []).append((padded, req))
        for members in groups.values():
            padded_list = [p for p, _ in members][:self.max_batch]
            req0 = members[0][1]
            run_bucket(padded_list, self.cache, max_iters=1,
                       grad_norm_tol=req0.grad_norm_tol,
                       eval_every=1)
        run = obs.get_run()
        if run is not None:
            run.event("serve_warm", phase="serve", buckets=len(groups),
                      requests=len(requests))
        return len(groups)

    def close(self, drain: bool = False) -> None:
        """Shut down.  ``drain=True`` is the graceful path: admission stops
        with structured ``OverCapacityError(reason="closed")`` sheds, the
        in-flight batch finishes and replies normally, queued requests are
        shed with the same structured reason, and ``/healthz`` reports
        ``draining`` for the whole window before going 503."""
        with self._cond:
            if self._closed:
                already = True
            else:
                already = False
                self._draining = bool(drain)
                self._closed = True
                self._cond.notify_all()
                run = obs.get_run()
                if drain and run is not None:
                    run.event("server_draining", phase="serve",
                              queued=len(self._pending))
        del already
        self._worker.join()
        with self._cond:
            if self._terminated:
                return
            self._terminated = True
        if self.sidecar is not None:
            self.sidecar.close()
        if self._profiler is not None:
            self._profiler.close()

    def drain(self) -> "list[SolveTicket]":
        """Live-migration drain (``serve.fleet``): stop admission, break
        the in-flight batch at its next eval boundary (AFTER that
        boundary's session snapshot lands), and return every unanswered
        ticket — interrupted in-flight members plus still-queued requests
        — for the caller to re-admit elsewhere.  Session-tagged tickets
        leave fresh snapshots in the store, so re-admission on a
        ``resume_sessions`` replica continues mid-schedule.  Unlike
        ``close(drain=True)``, which lets the in-flight batch COMPLETE
        and reply, this hands the work back; the server terminates either
        way."""
        queued = 0
        with self._cond:
            first = not self._closed
            if first:
                self._evacuating = True
                self._draining = True
                self._closed = True
                self._interrupt.set()
                self._cond.notify_all()
                queued = len(self._pending)
        run = obs.get_run()
        if first and run is not None:
            run.event("server_draining", phase="serve", migrate=True,
                      queued=queued, replica=self.replica_id)
        self._worker.join()
        with self._cond:
            evacuated = list(self._evacuated)
            self._evacuated = []
            term, self._terminated = self._terminated, True
        if not term:
            if self.sidecar is not None:
                self.sidecar.close()
            if self._profiler is not None:
                self._profiler.close()
        if run is not None:
            run.event("server_drained", phase="serve",
                      replica=self.replica_id, evacuated=len(evacuated))
        return evacuated

    def kill(self) -> None:
        """Hard stop — the fleet bench's chaos lever and the manager's
        last resort.  Admission stops immediately, the in-flight batch is
        interrupted at its next eval boundary and shed with
        ``reason="closed"``, queued requests shed the same way.  Session-
        tagged requests keep their boundary snapshots, so a router retry
        on another replica resumes instead of restarting."""
        with self._cond:
            if not self._closed:
                self._closed = True
                self._interrupt.set()
                self._cond.notify_all()
        self._worker.join()
        with self._cond:
            if self._terminated:
                return
            self._terminated = True
        if self.sidecar is not None:
            self.sidecar.close()
        if self._profiler is not None:
            self._profiler.close()
        run = obs.get_run()
        if run is not None:
            run.event("replica_killed", phase="serve",
                      replica=self.replica_id)

    def status(self) -> dict:
        """Live operational snapshot — the ``/statusz`` payload, shared
        with ``python -m dpgo_tpu.obs.report --live``.  Plain server
        state; safe to call with telemetry on or off."""
        with self._cond:
            queue_depth = len(self._pending)
            inflight = dict(self._inflight)
            # "closed" is the terminal state (503 on /healthz); a draining
            # server is still finishing work and reports that instead.
            closed = self._terminated
            draining = self._draining and not self._terminated
            # "accepting" is the fleet manager's liveness probe: False the
            # moment admission stops (drain begun, kill, crash-loop
            # give-up), before the terminal "closed" flips.
            accepting = not self._closed
            crashes = self._crashes
            n_requests = self._n_requests
            n_batches = self._n_batches
            n_shed = self._n_shed
            last_batch = dict(self._last_batch) if self._last_batch else None
            slo = None
            if self._slo_state:
                # Burn computation trims the trackers' rolling windows —
                # a mutation, so it stays under the lock with the rest.
                now = time.monotonic()
                slo = {t: {**trk.burn(now),
                           "level": {k: v for k, v in trk.level.items()
                                     if v is not None} or None}
                       for t, trk in sorted(self._slo_state.items())}
        tenants = {
            t: {"in_flight": n, "quota": self.tenant_quota}
            for t, n in sorted(inflight.items())
        }
        out = {
            "uptime_s": time.monotonic() - self._t0_mono,
            "closed": closed,
            "draining": draining,
            "accepting": accepting,
            # Replica identity (fleet satellite): which process/device
            # this server is, so a router health poll or report --live
            # can tell replicas apart.  replica_id is None outside a
            # fleet.
            "replica": {
                "replica_id": self.replica_id,
                "pid": self._pid,
                "start_time": self._t0_wall,
                "device": dict(self._device_info),
            },
            "worker_crashes": crashes,
            "queue_depth": queue_depth,
            "max_queue": self.max_queue,
            "max_batch": self.max_batch,
            "quantum": self.quantum,
            "tenants": tenants,
            "requests_served": n_requests,
            "batches_dispatched": n_batches,
            "requests_shed": n_shed,
            "last_batch": last_batch,
            "cache": self.cache.stats(),
        }
        if slo is not None:
            out["slo"] = slo
        return out

    def __enter__(self) -> "SolveServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker -------------------------------------------------------------

    def _dev_ctx(self):
        """The replica's device-binding scope: inside it, every array the
        prepare/dispatch path materializes commits to the bound device
        instead of the process default (no-op for an unbound server)."""
        return jax.default_device(self.device) if self.device is not None \
            else contextlib.nullcontext()

    def _prepare(self, req: SolveRequest):
        """Problem build + bucket padding for one request; returns the
        padded problem, its full batch-compatibility key, and the snapshot
        iteration it resumes from (0 = cold start).

        With ``resume_sessions`` on (the fleet migration path), a
        session-tagged request whose store carries a snapshot of the SAME
        bucket shape resumes from that exact state: ``state0`` is stamped
        and the resume point folds into the batch key, so only requests
        at the same schedule position batch together.  A shape-mismatched
        or absent snapshot falls back to a cold solve — resume is an
        optimization of correctness already guaranteed by re-solving."""
        with self._dev_ctx():
            prob = prepare_problem(req.meas, req.num_robots,
                                   params=req.params, dtype=req.dtype,
                                   init=None, pallas_sel=False)
            shape = bucket_shape_of(prob, quantum=self.quantum)
            padded = pad_problem(prob, shape, init=self.init)
        fp = problem_fingerprint(padded.meta, prob.params, req.dtype, shape)
        fp["termination"] = [req.max_iters or prob.params.max_num_iters,
                             req.grad_norm_tol, req.eval_every]
        resumed_from = 0
        if self.resume_sessions and self.session_store is not None \
                and req.session_id is not None:
            snap = self.session_store.load_newest(req.session_id)
            if snap is not None and snap.meta.get("bucket") == list(shape):
                padded = dataclasses.replace(padded, state0=snap.state)
                resumed_from = int(snap.iteration)
        if resumed_from:
            fp["resume"] = resumed_from
        return padded, fingerprint_key(fp), resumed_from

    def _release(self, tickets) -> None:
        with self._cond:
            for t in tickets:
                tenant = t.request.tenant
                n = self._inflight.get(tenant, 1) - 1
                if n <= 0:
                    self._inflight.pop(tenant, None)
                else:
                    self._inflight[tenant] = n

    def _supervise(self) -> None:
        """Worker supervisor: run the drain loop; on an unexpected worker
        death (anything escaping ``_loop`` — ``_run_batch`` already
        contains per-batch solver failures) re-admit the in-flight batch
        from session snapshots and respawn, up to ``worker_restarts``
        times.  A TaskStop-style kill therefore loses no session-tagged
        request and leaks no thread: the supervisor thread IS the next
        worker."""
        while True:
            try:
                self._loop()
                return
            except BaseException as e:  # the worker died mid-batch
                if not self._recover_from_crash(e):
                    return

    def _recover_from_crash(self, exc: BaseException) -> bool:
        """Re-admit the crashed batch (session-tagged tickets resume from
        their newest valid snapshot; the rest fail with the crash), then
        decide whether to respawn.  Returns True to run another worker
        iteration."""
        with self._cond:
            self._crashes += 1
            crashes = self._crashes
            active, self._active = self._active, []
            closed = self._closed
        run = obs.get_run()
        if run is not None:
            run.event("worker_crashed", phase="serve",
                      error=f"{type(exc).__name__}: {exc}",
                      crashes=crashes, in_flight=len(active))
        recovered, lost = [], []
        for t in active:
            snap = None
            sid = t.request.session_id
            if self.session_store is not None and sid is not None:
                snap = self.session_store.load_newest(sid)
            if snap is not None and t._padded is not None:
                t._padded = dataclasses.replace(t._padded,
                                                state0=snap.state)
                t._recovered = True
                recovered.append(t)
            else:
                lost.append(t)
        for t in lost:
            t._finish(exception=RuntimeError(
                f"solve worker died mid-batch "
                f"({type(exc).__name__}: {exc}) and no session snapshot "
                "was available to recover from"))
        self._release(lost)
        with self._cond:
            # Recovered tickets go back to the FRONT of the queue (they
            # were already dispatched once); in-flight accounting never
            # dropped them, so quotas stay consistent.
            for t in reversed(recovered):
                self._pending.appendleft(t)
            if recovered:
                self._cond.notify_all()
        if run is not None and recovered:
            run.counter("session_recoveries_total",
                        "requests re-admitted from session snapshots "
                        "after a worker crash").inc(len(recovered))
            for t in recovered:
                run.event("session_recovered", phase="serve",
                          session=t.request.session_id,
                          tenant=t.request.tenant)
        if closed or crashes > self.worker_restarts:
            # Give up: shed whatever is left so no caller blocks forever.
            with self._cond:
                leftovers = list(self._pending)
                self._pending.clear()
                self._closed = True
            for t in leftovers:
                t._finish(exception=OverCapacityError(
                    "solve worker crash-looped; server gave up",
                    reason="closed"))
            self._release(leftovers)
            return False
        return True

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    leftovers = list(self._pending)
                    self._pending.clear()
                    evacuate = self._evacuating
                    if evacuate:
                        # Migration drain: queued work is evacuated for
                        # the router to re-admit, not shed.
                        self._evacuated.extend(leftovers)
                    break
                n_pending = len(self._pending)
            # Batching window: give concurrent submitters a moment to
            # coalesce before forming a batch (skip when already full).
            if n_pending < self.max_batch and self.batch_window_s > 0:
                with obs_trace.span("coalesce", phase="serve",
                                    pending=n_pending):
                    time.sleep(self.batch_window_s)
            self._dispatch_once()
        if not evacuate:
            for t in leftovers:
                t._finish(exception=OverCapacityError(
                    "server closed with request still queued",
                    reason="closed"))
        self._release(leftovers)

    def _dispatch_once(self) -> None:
        with self._cond:
            snapshot = list(self._pending)
        if not snapshot:
            return
        now = time.monotonic()
        run = obs.get_run()
        shed, failed = [], []
        for t in snapshot:
            dl = t.request.deadline_s
            if dl is not None and (now - t.t_submit) > dl:
                shed.append(t)
                continue
            if t._padded is None:
                sp = None
                if run is not None and t.trace_id is not None:
                    sp = obs_trace.Span(run, "prepare", phase="serve",
                                        trace_id=t.trace_id,
                                        parent_id=t.span_admission)
                try:
                    with sp or obs_trace.NULL_SPAN:
                        t._padded, t._key, t._resumed_from = \
                            self._prepare(t.request)
                    if t._resumed_from:
                        # Migration resume is a recovery-from-snapshot:
                        # the reply discloses it the same way the crash
                        # path does.
                        t._recovered = True
                except Exception as e:  # bad request: report, don't die
                    t._finish(exception=e)
                    failed.append(t)
        for t in shed:
            waited = now - t.t_submit
            t._finish(exception=OverCapacityError(
                f"deadline ({t.request.deadline_s:.3f}s) expired after "
                f"{waited:.3f}s in queue", reason="deadline"))
            self._obs_shed(t.request.tenant, "deadline", waited)
            if run is not None and t.trace_id is not None:
                # The request's trace closes with a reason-tagged span
                # covering its whole queued life.
                obs_trace.emit_span(
                    run, "shed", t.t_submit, t.t_submit_wall, waited,
                    phase="serve", trace_id=t.trace_id,
                    parent_id=t.span_admission, reason="deadline",
                    tenant=t.request.tenant)
        drop = set(shed) | set(failed)
        ready = [t for t in snapshot if t not in drop and t._padded is not None]
        batch = []
        if ready:
            lead_key = ready[0]._key
            batch = [t for t in ready if t._key == lead_key][:self.max_batch]
        with self._cond:
            for t in list(drop) + batch:
                try:
                    self._pending.remove(t)
                except ValueError:
                    pass
        self._release(list(drop))
        if batch:
            self._run_batch(batch)

    def _run_batch(self, tickets: list[SolveTicket]) -> None:
        t0 = time.monotonic()
        t0_wall = time.time()
        for t in tickets:
            t.t_dispatch = t0
        req0 = tickets[0].request
        run = obs.get_run()
        dsp = None
        if run is not None:
            # One shared dispatch span per batch; the runner's
            # stack/device_dispatch/slice spans nest under it via the
            # worker thread's span stack.  Each batch mate contributes a
            # flow arrow: its queue-wait closes on its own trace, and a
            # batch_member child span here links back to its admission
            # span, so Perfetto draws N request lanes converging on the
            # one batched executable.
            dsp = obs_trace.Span(run, "dispatch", phase="serve")
            dsp.add(size=len(tickets))
            dsp.__enter__()
            for t in tickets:
                if t.trace_id is None:
                    continue
                obs_trace.emit_span(
                    run, "queue_wait", t.t_submit, t.t_submit_wall,
                    t0 - t.t_submit, phase="serve", trace_id=t.trace_id,
                    parent_id=t.span_admission, tenant=t.request.tenant)
                obs_trace.emit_span(
                    run, "batch_member", t0, t0_wall, 0.0, phase="serve",
                    tenant=t.request.tenant,
                    link=(t.trace_id, t.span_admission,
                          ORIGIN_SERVE_SERVER, t.t_submit, t.t_submit_wall))
        if self._profiler is not None:
            self._profiler.batch_begin()
        session_cb = self._session_cb(tickets)
        with self._cond:
            # The crash-recovery set: whatever the supervisor finds here
            # when the worker dies is the batch that was in flight.
            self._active = list(tickets)
        try:
            ve = self.verdict_every
            if ve is not None and ve % max(req0.eval_every, 1) != 0:
                ve = None  # incompatible cadence: legacy per-eval loop
            max_iters = req0.max_iters
            resume0 = tickets[0]._resumed_from
            if resume0:
                # Resumed sessions run their REMAINING budget: the batch
                # key folds the resume point in, so every member agrees.
                # Floored at one eval so the reply always carries a
                # history row (extra rounds only polish — monotone under
                # the plain schedule).
                base = max_iters if max_iters is not None \
                    else tickets[0]._padded.prob.params.max_num_iters
                max_iters = max(base - resume0, max(req0.eval_every, 1))
            # Per-replica device binding (serve.fleet): every array this
            # batch materializes commits to the replica's device instead
            # of the process default, so co-resident replicas don't fight
            # over one default device's queue.
            with self._dev_ctx():
                results, info = run_bucket(
                    [t._padded for t in tickets], self.cache,
                    max_iters=max_iters, grad_norm_tol=req0.grad_norm_tol,
                    eval_every=req0.eval_every, verdict_every=ve,
                    session_cb=session_cb, session_every=self.session_every,
                    should_stop=self._interrupt.is_set)
        except Exception as e:
            for t in tickets:
                t._finish(exception=e)
            self._release(tickets)
            with self._cond:
                self._active = []
            if dsp is not None:
                dsp.__exit__(type(e), e, None)
            if self._profiler is not None:
                self._profiler.batch_end()
            return
        if info.get("interrupted"):
            # drain()/kill() broke the batch at an eval boundary (the
            # boundary snapshot already landed): nobody gets a reply from
            # this partial solve.  Draining evacuates the tickets for the
            # router to re-admit elsewhere; a kill sheds them (session-
            # tagged requests resume from their snapshots on retry).
            with self._cond:
                self._active = []
                evacuating = self._evacuating
                if evacuating:
                    self._evacuated.extend(tickets)
            if not evacuating:
                for t in tickets:
                    t._finish(exception=OverCapacityError(
                        "replica killed with the batch in flight; "
                        "session-tagged requests resume from their last "
                        "snapshot", reason="closed"))
            self._release(tickets)
            if run is not None:
                run.event("batch_interrupted", phase="serve",
                          size=len(tickets), evacuating=evacuating,
                          replica=self.replica_id)
            if dsp is not None:
                dsp.add(interrupted=True)
                dsp.__exit__(None, None, None)
            if self._profiler is not None:
                self._profiler.batch_end()
            return
        with self._cond:
            self._active = []
        for t, res in zip(tickets, results):
            if t._recovered:
                res.recovered = True
            sid = t.request.session_id
            if self.session_store is not None and sid is not None:
                # The request completed; its recovery snapshots are spent.
                self.session_store.discard(sid)
            t._finish(result=res)
        self._release(tickets)
        if self._profiler is not None:
            self._profiler.batch_end()
        duration_s = time.monotonic() - t0
        if dsp is not None:
            dsp.add(rounds=info["rounds"], occupancy=info["occupancy"])
            dsp.__exit__(None, None, None)
            dispatch_ctx = (dsp.trace_id, dsp.span_id,
                            ORIGIN_SERVE_SERVER, t0, t0_wall)
            for t, res in zip(tickets, results):
                if t.trace_id is None:
                    continue
                # Reply span closes the request's trace, with a flow
                # arrow in from the shared dispatch span.  A certified
                # request's reply span carries the verdict, so the trace
                # reads decode -> admission -> dispatch -> certified
                # reply end to end.
                cert = getattr(res, "certificate", None)
                cert_attrs = {} if cert is None else {
                    "certified": bool(cert.certified),
                    "cert_lambda_min": float(cert.lambda_min)}
                obs_trace.emit_span(
                    run, "reply", t.t_done, time.time(), 0.0,
                    phase="serve", trace_id=t.trace_id,
                    parent_id=t.span_admission, tenant=t.request.tenant,
                    latency_s=t.latency_s, link=dispatch_ctx, **cert_attrs)
        with self._cond:
            self._n_batches += 1
            self._n_requests += len(tickets)
            self._last_batch = {"size": info["size"],
                                "batch": info["batch"],
                                "occupancy": info["occupancy"],
                                "rounds": info["rounds"],
                                "duration_s": duration_s}
        self._obs_batch(tickets, results, info, duration_s)

    def _session_cb(self, tickets):
        """The runner's snapshot hook for this batch: persist each
        session-tagged member's sliced state.  None when no store is
        configured or no member carries a session id (zero overhead on
        the common path)."""
        if self.session_store is None:
            return None
        tagged = [(i, t.request.session_id) for i, t in enumerate(tickets)
                  if t.request.session_id is not None]
        if not tagged:
            return None
        store = self.session_store

        def cb(iteration, states):
            for i, sid in tagged:
                t = tickets[i]
                # Snapshot sequence numbers are ABSOLUTE session
                # iterations: a resumed batch counts from zero, so its
                # resume base is added back — a later migration of the
                # same session budgets its remaining iterations right.
                # The bucket shape rides the meta so only a same-shape
                # server resumes the state (serve.fleet migration).
                store.save(sid, states[i],
                           iteration=int(iteration) + t._resumed_from,
                           meta={"tenant": t.request.tenant,
                                 "bucket": list(t._padded.shape)})
        return cb

    # -- telemetry (every site behind the zero-overhead fence) --------------

    def _slo_for(self, tenant: str) -> "ServeSLO | None":
        if self.slo is None:
            return None
        if isinstance(self.slo, ServeSLO):
            return self.slo
        return self.slo.get(tenant, self.slo.get("default"))

    def _slo_tracker(self, tenant: str) -> "_SloTracker | None":
        """The tenant's burn tracker (lazily created) — callers are
        already behind the telemetry fence."""
        slo = self._slo_for(tenant)
        if slo is None:
            return None
        with self._cond:
            trk = self._slo_state.get(tenant)
            if trk is None:
                trk = self._slo_state[tenant] = _SloTracker(slo)
        return trk

    def _slo_evaluate(self, run, tenant: str, trk: "_SloTracker") -> None:
        """Burn-rate gauges every evaluation; one ``slo_burn`` anomaly per
        level transition (through ``obs.health``'s callback/abort/dump
        machinery), one ``slo_recovered`` event on the way back down."""
        now = time.monotonic()
        # Trackers are touched by client threads (shed at admission) and
        # the worker (request completions): burn/level transitions happen
        # under the server lock so a transition is decided exactly once.
        # self._cond is reentrant (threading.Condition wraps an RLock) and
        # the registry/event locks nest strictly inside it — one order.
        with self._cond:
            burn = trk.burn(now)
            g = run.gauge("serve_slo_burn_rate",
                          "error-budget burn rate over the rolling SLO "
                          "window (1.0 = consuming exactly the budget)")
            for slo_kind, rate in (("latency", burn["latency_burn"]),
                                   ("shed", burn["shed_burn"])):
                g.set(rate, tenant=tenant, slo=slo_kind)
                level = trk.classify(rate)
                prev = trk.level[slo_kind]
                if level == prev:
                    continue
                trk.level[slo_kind] = level
                if level is not None:
                    obs.monitor_for(run).anomaly(
                        "slo_burn", severity=level, tenant=tenant,
                        slo=slo_kind, burn_rate=rate,
                        window_s=trk.slo.window_s,
                        requests=burn["requests"], slow=burn["slow"],
                        shed=burn["shed"])
                elif prev is not None:
                    run.event("slo_recovered", phase="serve", tenant=tenant,
                              slo=slo_kind, burn_rate=rate)

    def _obs_shed(self, tenant: str, reason: str, waited_s: float) -> None:
        run = obs.get_run()
        with self._cond:
            self._n_shed += 1
        if run is None:
            return
        run.counter("serve_shed_total",
                    "requests shed by admission control").inc(
            tenant=tenant, reason=reason)
        run.event("serve_shed", phase="serve", tenant=tenant, reason=reason,
                  waited_s=waited_s)
        trk = self._slo_tracker(tenant)
        if trk is not None:
            with self._cond:  # tracker windows are shared mutable state
                trk.observe_shed(time.monotonic())
            self._slo_evaluate(run, tenant, trk)

    def _obs_batch(self, tickets, results, info, duration_s: float) -> None:
        run = obs.get_run()
        if run is None:
            return
        bucket = str(tuple(tickets[0]._padded.shape))
        run.gauge("serve_batch_occupancy",
                  "fraction of the batched executable's slots carrying "
                  "real requests").set(info["occupancy"])
        run.event("serve_batch", phase="serve", bucket=bucket,
                  size=info["size"], batch=info["batch"],
                  occupancy=info["occupancy"], rounds=info["rounds"],
                  evals=info["evals"], duration_s=duration_s,
                  cache=self.cache.stats())
        c_req = run.counter("serve_requests_total", "requests served")
        h_wait = run.histogram("serve_queue_wait_seconds",
                               "submit -> dispatch wait", unit="s")
        h_lat = run.histogram("serve_solve_latency_seconds",
                              "submit -> result latency", unit="s")
        for t, res in zip(tickets, results):
            tenant = t.request.tenant
            c_req.inc(tenant=tenant)
            h_wait.observe(t.queue_wait_s or 0.0, tenant=tenant)
            h_lat.observe(t.latency_s or 0.0, tenant=tenant)
            run.event(
                "serve_request", phase="serve", tenant=tenant, bucket=bucket,
                queue_wait_s=t.queue_wait_s, latency_s=t.latency_s,
                iterations=res.iterations, terminated_by=res.terminated_by,
                cost=res.cost_history[-1] if res.cost_history else None,
                grad_norm=res.grad_norm_history[-1]
                if res.grad_norm_history else None)
            trk = self._slo_tracker(tenant)
            if trk is not None:
                with self._cond:  # tracker windows are shared mutable state
                    trk.observe_request(time.monotonic(),
                                        t.latency_s or 0.0)
                self._slo_evaluate(run, tenant, trk)
