"""The compiled-executable cache, keyed by config fingerprint.

Batched solve programs are expensive to build (XLA compilation of a
vmapped fused RBCD segment runs seconds on CPU, tens of seconds for large
buckets on TPU); the whole point of bucketing is that identical request
shapes re-dispatch the same executable.  The cache key is the canonical
config fingerprint — deliberately the same shape/dtype/schedule field set
``run_rbcd`` registers via ``TelemetryRun.set_fingerprint`` for the
regression gate (``obs/run.py``), because that canonicalization was
designed to capture exactly what makes two solves the "same program":
pose/edge/slot counts, rank, d, dtype, schedule, robust cost, selection
mode.  Two requests whose fingerprints agree reuse one executable; a
differing rank, dtype, or schedule misses and compiles its own.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from ..models.rbcd import GraphMeta, resolved_sel_mode
from ..obs.events import _jsonable


def problem_fingerprint(meta: GraphMeta, params, dtype, shape=None,
                        batch: int | None = None,
                        kind: str | None = None) -> dict:
    """Canonical (JSON-able) fingerprint of a batched solve program.

    Field names follow ``run_rbcd``'s ``set_fingerprint`` record where the
    concepts coincide (num_robots/rank/d/dtype/schedule/robust_cost/
    sel_mode), extended with the padded bucket shape, the remaining solver
    configuration (``params`` is a frozen dataclass — its repr is a stable
    canonical form), the batch width, and the program kind
    (segment/metrics/finalize)."""
    fp = {
        "solver": "serve_batch",
        "num_robots": meta.num_robots,
        "rank": meta.rank,
        "d": meta.d,
        "n_max": meta.n_max,
        "e_max": meta.e_max,
        "s_max": meta.s_max,
        "p_max": meta.p_max,
        "num_colors": meta.num_colors,
        "dtype": str(np.dtype(dtype)),
        "schedule": params.schedule.value,
        "robust_cost": params.robust.cost_type.value,
        "sel_mode": resolved_sel_mode(params),
        "params": repr(params),
    }
    if shape is not None:
        fp["bucket_shape"] = tuple(shape)
    if batch is not None:
        fp["batch"] = int(batch)
    if kind is not None:
        fp["kind"] = str(kind)
    return {k: _jsonable(v) for k, v in fp.items()}


def fingerprint_key(fp: dict) -> str:
    """Stable hashable form of a fingerprint dict."""
    return json.dumps(fp, sort_keys=True)


class ExecutableCache:
    """Fingerprint-keyed store of built executables with hit/compile
    accounting.

    ``get`` returns the cached executable for ``fp`` or invokes
    ``builder()`` exactly once and caches its result.  ``compiles`` counts
    builder invocations — the observable the bucketing tests pin: a stream
    of identical-fingerprint requests must leave it flat.

    Single-flight: concurrent ``get``\\ s on the same key run ONE builder;
    the rest wait on its completion and count as hits.  Builds still run
    outside the cache lock (builders trigger long XLA compiles, and two
    different keys must compile concurrently); per-key in-flight events
    provide the exclusion.  A builder that raises clears its in-flight
    marker so waiters (and retries) attempt the build themselves.

    ``disk`` is the optional persistent tier
    (``serve.fleet.aotcache.AOTDiskCache``): when set, the runner stores
    ``AOTExecutable`` entries that resolve through disk before compiling,
    so a fresh process with a warm disk skips XLA entirely.  The cache
    itself only carries the handle and surfaces the tier's stats; the
    tiering logic lives in the entry wrapper.
    """

    def __init__(self, disk=None):
        self.disk = disk
        self._lock = threading.Lock()
        self._entries: dict[str, object] = {}           # guarded-by: _lock
        self._building: dict[str, threading.Event] = {}  # guarded-by: _lock
        self.compiles = 0                               # guarded-by: _lock
        self.hits = 0                                   # guarded-by: _lock

    def get(self, fp: dict, builder):
        key = fingerprint_key(fp)
        while True:
            with self._lock:
                if key in self._entries:
                    self.hits += 1
                    entry = self._entries[key]
                    break
                pending = self._building.get(key)
                if pending is None:
                    pending = self._building[key] = threading.Event()
                    entry = None
                    break
            # Another thread is compiling this key: wait for it, then
            # re-check (it may have failed, in which case we build).
            pending.wait()
        if entry is not None:
            self._obs("hit")
            return entry
        try:
            built = builder()
        except BaseException:
            with self._lock:
                self._building.pop(key, None)
            pending.set()
            raise
        with self._lock:
            self._entries[key] = built
            self.compiles += 1
            self._building.pop(key, None)
        pending.set()
        self._obs("compile")
        return built

    def _obs(self, outcome: str) -> None:
        """Mirror hit/compile tallies as Prometheus counters so the live
        ``/metrics`` endpoint carries them (zero-overhead fence: resolved
        per call, nothing constructed with telemetry off)."""
        from .. import obs

        run = obs.get_run()
        if run is None:
            return
        run.counter("serve_cache_requests_total",
                    "executable-cache lookups by outcome").inc(
            outcome=outcome)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            out = {"entries": len(self._entries), "compiles": self.compiles,
                   "hits": self.hits}
        if self.disk is not None:
            out["disk"] = self.disk.stats()
        return out
