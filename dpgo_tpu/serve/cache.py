"""The compiled-executable cache, keyed by config fingerprint.

Batched solve programs are expensive to build (XLA compilation of a
vmapped fused RBCD segment runs seconds on CPU, tens of seconds for large
buckets on TPU); the whole point of bucketing is that identical request
shapes re-dispatch the same executable.  The cache key is the canonical
config fingerprint — deliberately the same shape/dtype/schedule field set
``run_rbcd`` registers via ``TelemetryRun.set_fingerprint`` for the
regression gate (``obs/run.py``), because that canonicalization was
designed to capture exactly what makes two solves the "same program":
pose/edge/slot counts, rank, d, dtype, schedule, robust cost, selection
mode.  Two requests whose fingerprints agree reuse one executable; a
differing rank, dtype, or schedule misses and compiles its own.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from ..models.rbcd import GraphMeta, resolved_sel_mode
from ..obs.events import _jsonable


def problem_fingerprint(meta: GraphMeta, params, dtype, shape=None,
                        batch: int | None = None,
                        kind: str | None = None) -> dict:
    """Canonical (JSON-able) fingerprint of a batched solve program.

    Field names follow ``run_rbcd``'s ``set_fingerprint`` record where the
    concepts coincide (num_robots/rank/d/dtype/schedule/robust_cost/
    sel_mode), extended with the padded bucket shape, the remaining solver
    configuration (``params`` is a frozen dataclass — its repr is a stable
    canonical form), the batch width, and the program kind
    (segment/metrics/finalize)."""
    fp = {
        "solver": "serve_batch",
        "num_robots": meta.num_robots,
        "rank": meta.rank,
        "d": meta.d,
        "n_max": meta.n_max,
        "e_max": meta.e_max,
        "s_max": meta.s_max,
        "p_max": meta.p_max,
        "num_colors": meta.num_colors,
        "dtype": str(np.dtype(dtype)),
        "schedule": params.schedule.value,
        "robust_cost": params.robust.cost_type.value,
        "sel_mode": resolved_sel_mode(params),
        "params": repr(params),
    }
    if shape is not None:
        fp["bucket_shape"] = tuple(shape)
    if batch is not None:
        fp["batch"] = int(batch)
    if kind is not None:
        fp["kind"] = str(kind)
    return {k: _jsonable(v) for k, v in fp.items()}


def fingerprint_key(fp: dict) -> str:
    """Stable hashable form of a fingerprint dict."""
    return json.dumps(fp, sort_keys=True)


class ExecutableCache:
    """Fingerprint-keyed store of built executables with hit/compile
    accounting.

    ``get`` returns the cached executable for ``fp`` or invokes
    ``builder()`` exactly once and caches its result.  ``compiles`` counts
    builder invocations — the observable the bucketing tests pin: a stream
    of identical-fingerprint requests must leave it flat."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, object] = {}
        self.compiles = 0
        self.hits = 0

    def get(self, fp: dict, builder):
        key = fingerprint_key(fp)
        with self._lock:
            if key in self._entries:
                self.hits += 1
                return self._entries[key]
        # Build outside the lock (builders may themselves trigger long XLA
        # compiles); a racing duplicate build is wasted work, not an error.
        built = builder()
        with self._lock:
            if key not in self._entries:
                self._entries[key] = built
                self.compiles += 1
            else:
                self.hits += 1
            return self._entries[key]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "compiles": self.compiles,
                    "hits": self.hits}
