"""Serving CLI: ``python -m dpgo_tpu.serve`` starts a TCP solve server.

::

    python -m dpgo_tpu.serve --port 9100 --max-batch 8 --max-frame-mb 64 \
        --telemetry /tmp/serve_run

Prints ``listening on HOST:PORT`` once bound (``--port 0`` = OS-assigned,
so scripts can parse the resolved port), serves until interrupted, and —
with ``--telemetry`` — writes a run directory the report CLI renders with
the per-tenant "serving" SLO section::

    python -m dpgo_tpu.obs.report /tmp/serve_run
"""

from __future__ import annotations

import argparse
import sys
import time

from .. import obs
from .frontend import ServeFrontend
from .server import ServeSLO, SolveServer


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m dpgo_tpu.serve",
                                 description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = OS-assigned, printed once bound)")
    ap.add_argument("--max-frame-mb", type=float, default=64.0,
                    help="transport frame-size cap in MiB (both directions; "
                         "oversize frames raise a clean ProtocolError)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="max problems per batched device dispatch")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="bounded admission queue length")
    ap.add_argument("--batch-window-ms", type=float, default=5.0,
                    help="coalescing window before forming a batch")
    ap.add_argument("--quantum", type=int, default=32,
                    help="shape-bucket rounding quantum (pose/edge counts)")
    ap.add_argument("--tenant-quota", type=int, default=None,
                    help="max in-flight requests per tenant")
    ap.add_argument("--wire", choices=("packed", "npz"), default="packed",
                    help="outgoing wire format (receives auto-detect)")
    ap.add_argument("--telemetry", metavar="DIR", default=None,
                    help="write a telemetry run (SLO metrics/events) here")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve live /metrics, /healthz, and /statusz on "
                         "this port (0 = OS-assigned, printed once bound; "
                         "requires --telemetry — there is no registry to "
                         "scrape without a run)")
    ap.add_argument("--slo-latency-s", type=float, default=None,
                    help="per-request latency objective: enables burn-rate "
                         "SLO alerting for every tenant")
    ap.add_argument("--profile-dir", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the first "
                         "--profile-batches batched dispatches here")
    ap.add_argument("--profile-batches", type=int, default=3)
    ap.add_argument("--session-dir", metavar="DIR", default=None,
                    help="crash-recovery session store root: session-tagged "
                         "requests snapshot their solver state on solve "
                         "boundaries and are re-admitted from the last "
                         "snapshot (reply flags recovered=1) when a worker "
                         "dies mid-batch")
    ap.add_argument("--replica-id", default=None,
                    help="identity this server reports in status()/healthz "
                         "replica blocks (fleet deployments name each "
                         "member; defaults to an anonymous singleton)")
    ap.add_argument("--aot-cache-dir", metavar="DIR", default=None,
                    help="persistent AOT executable cache root (shared "
                         "across replicas/restarts): compiled programs are "
                         "serialized here and reloaded without invoking "
                         "XLA, so a warm restart's first solve skips the "
                         "compile entirely")
    ap.add_argument("--resume-sessions", action="store_true",
                    help="with --session-dir: resume session-tagged "
                         "requests from their newest snapshot at ADMISSION "
                         "(not just after a crash) — the receiving end of "
                         "fleet live-migration")
    ap.add_argument("--drain", action="store_true",
                    help="on SIGINT, drain instead of hard-close: stop "
                         "admission with structured sheds, finish the "
                         "in-flight batch (/healthz reports draining)")
    args = ap.parse_args(argv)

    slo = ServeSLO(latency_s=args.slo_latency_s) \
        if args.slo_latency_s is not None else None
    scope = obs.run_scope(args.telemetry) if args.telemetry else None
    run = scope.__enter__() if scope else None
    try:
        server = SolveServer(max_batch=args.max_batch,
                             max_queue=args.max_queue,
                             batch_window_s=args.batch_window_ms / 1e3,
                             tenant_quota=args.tenant_quota,
                             quantum=args.quantum, slo=slo,
                             metrics_port=args.metrics_port,
                             profile_dir=args.profile_dir,
                             profile_batches=args.profile_batches,
                             session_store=args.session_dir,
                             replica_id=args.replica_id,
                             resume_sessions=args.resume_sessions,
                             aot_cache_dir=args.aot_cache_dir)
        try:
            with ServeFrontend(
                    server, host=args.host, port=args.port,
                    max_frame_bytes=int(args.max_frame_mb * 2 ** 20),
                    wire_format=args.wire) as fe:
                print(f"listening on {fe.host}:{fe.port}", flush=True)
                if server.sidecar is not None:
                    print(f"metrics on {server.sidecar.host}:"
                          f"{server.sidecar.port}", flush=True)
                elif args.metrics_port is not None:
                    print("metrics sidecar DISABLED (no --telemetry run "
                          "to scrape)", flush=True)
                if run is not None:
                    run.event("serve_listen", phase="serve", host=fe.host,
                              port=fe.port,
                              max_frame_bytes=fe.max_frame_bytes,
                              metrics_port=server.sidecar.port
                              if server.sidecar else None)
                try:
                    while True:
                        time.sleep(1.0)
                except KeyboardInterrupt:
                    print("draining" if args.drain else "shutting down",
                          flush=True)
                    # Drain while the connections are still up, so queued
                    # requests get their structured shed replies instead
                    # of a dropped socket; the frontend closes after.
                    server.close(drain=args.drain)
        finally:
            server.close(drain=args.drain)  # idempotent
    finally:
        if scope:
            scope.__exit__(None, None, None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
