"""PGO-as-a-service: the multi-tenant batched solve front-end.

The solver core is a library: one caller, one problem, one cold solve.
This package is the serving plane over it — the piece that makes
distributed certifiably-correct PGO (Tian et al., T-RO 2021) deployable
as a *shared backend* rather than a per-robot binary:

* ``bucketing`` — pads prepared problems (``models.rbcd.PreparedProblem``)
  into shape buckets so compatible requests stack into one batched array
  program.
* ``cache`` — the compiled-executable cache, keyed by the canonical config
  fingerprint (the same shape/dtype/schedule field set
  ``TelemetryRun.set_fingerprint`` records for the regression gate).
* ``runner`` — the batched dispatch: many problems per device call via
  ``vmap`` over the RBCD segment, one compiled program per bucket.
* ``server`` — the request plane: bounded queue, per-tenant quotas,
  deadline-aware shedding, warm pools, and per-tenant SLO metrics through
  ``dpgo_tpu.obs``.
* ``frontend`` — the TCP front-end over ``comms.transport.TcpTransport``
  (length-prefixed packed frames; g2o problem upload, result download).
* ``statusz`` — the live observability sidecar: ``/metrics`` (Prometheus
  scrape of the run's registry), ``/healthz``, ``/statusz`` (queue /
  tenant / cache / SLO-burn JSON, shared with ``report --live``);
  requests are traced end to end (admission -> queue -> dispatch ->
  reply spans with batch-mate flow arrows) and compiles profiled
  (``obs.profile``) — all of it only when a telemetry run is live.
* ``session`` — the crash-recovery session store: schema-versioned
  solver-state snapshots written on solve boundaries; a worker that dies
  mid-batch is respawned and session-tagged requests are re-admitted
  from their last valid snapshot (corrupt snapshots quarantined), the
  reply flagged ``recovered``.
* ``fleet`` — the scale-out layer: ``ReplicaManager`` runs N replicas
  (spawn/monitor/respawn/autoscale), ``FleetRouter`` rendezvous-hashes
  sessions onto them and live-migrates tickets across drains and deaths,
  and ``AOTDiskCache`` persists compiled executables so replica restarts
  skip XLA entirely.

Quickstart (in-process)::

    from dpgo_tpu.serve import SolveServer, SolveRequest
    with SolveServer(max_batch=8) as srv:
        tickets = [srv.submit(SolveRequest(meas, num_robots=2))
                   for meas in problems]
        results = [t.result() for t in tickets]

TCP: ``python -m dpgo_tpu.serve --port 0`` then
``serve.frontend.solve_g2o(host, port, g2o_bytes, num_robots=2)``.
"""

from .bucketing import BucketShape, bucket_shape_of, pad_problem
from .cache import ExecutableCache, problem_fingerprint
from .fleet import AOTDiskCache, FleetRouter, Replica, ReplicaManager
from .runner import run_bucket
from .server import (OverCapacityError, ServeSLO, SolveRequest, SolveServer,
                     SolveTicket)
from .session import SessionSnapshot, SessionStore

__all__ = [
    "BucketShape",
    "bucket_shape_of",
    "pad_problem",
    "ExecutableCache",
    "problem_fingerprint",
    "run_bucket",
    "OverCapacityError",
    "ServeSLO",
    "SolveRequest",
    "SolveServer",
    "SolveTicket",
    "SessionSnapshot",
    "SessionStore",
    "AOTDiskCache",
    "FleetRouter",
    "Replica",
    "ReplicaManager",
]
