"""TCP front-end: g2o problem upload / result download over the packed wire.

Reuses the deployment plane's transport stack unchanged: length-prefixed
frames (``comms.transport.TcpTransport``) carrying the v2 packed columnar
payload (``comms.protocol``), with the frame-size cap
constructor-configurable end to end (``--max-frame-mb`` on the CLI).
A request frame is an array dict — the g2o file bytes as a ``uint8``
array plus scalar config entries — and the reply carries the rounded
trajectory, cost/grad-norm histories, and termination info (or a
structured error; shed requests come back with ``shed=1`` and the
admission ``reason`` so clients can back off).

One thread per connection, sequential requests per connection; the actual
queueing/batching discipline lives in ``server.SolveServer``, which this
module only adapts to the wire.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from .. import obs
from ..comms.protocol import (DEFAULT_MAX_FRAME_BYTES, ORIGIN_SERVE_CLIENT,
                              ProtocolError, attach_clock, pack_measurements,
                              pack_trace_entries, pop_clock,
                              proc_replica_actor, unpack_measurements,
                              unpack_trace_entries)
from ..comms.transport import (TcpTransport, TransportClosed,
                               TransportTimeout, connect_tcp, listen_tcp)
from ..config import AgentParams
from ..obs import trace as obs_trace
from ..utils.g2o import read_g2o
from .server import OverCapacityError, SolveRequest, SolveServer


def _pack_str(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), np.uint8)


def _unpack_str(a) -> str:
    return bytes(np.asarray(a, np.uint8)).decode("utf-8")


def handle_request(server: SolveServer, frame: dict) -> dict:
    """One request frame -> one reply frame (in-process; the wire layer
    above is a pass-through).

    Pops the optional wire trace context the client stamped
    (``comms.protocol.unpack_trace_entries`` — old/untraced clients simply
    carry none) and, with telemetry on, wraps the request in a
    ``frontend`` span on the client's trace; ``SolveServer.submit``'s
    admission span then nests under it, so the Perfetto timeline runs
    from TCP receive to reply on one trace id."""
    ctx = unpack_trace_entries(frame)
    # Channel-level clock stamp (the procs heartbeat wire): popped
    # unconditionally so mixed telemetry-on/off peers interoperate;
    # recorded as the forward clock_sample only with a run on.
    ts = pop_clock(frame)
    run = obs.get_run()
    if run is None:
        return _handle_request(server, frame, None)
    if ts is not None:
        run.event("clock_sample", phase="comms", src=ts[0],
                  dst=proc_replica_actor(server.replica_id or "r"),
                  channel="heartbeat", kind="status_poll",
                  t_send_mono=ts[1], t_send_wall=ts[2])
    sp = obs_trace.Span(run, "frontend", phase="serve",
                        trace_id=ctx[0] if ctx is not None else None,
                        link=ctx)
    with sp:
        reply = _handle_request(server, frame, ctx)
        if "ok" in reply:
            sp.add(ok=int(np.asarray(reply["ok"])))
        return reply


def _result_reply(res, ticket=None) -> dict:
    """The success-reply vocabulary shared by the solve ops."""
    reply = {
        "ok": np.int8(1),
        "T": np.asarray(res.T),
        "cost_history": np.asarray(res.cost_history, np.float64),
        "grad_norm_history": np.asarray(res.grad_norm_history, np.float64),
        "iterations": np.int32(res.iterations),
        "terminated_by": _pack_str(res.terminated_by),
        # Crash-recovery disclosure: the solve completed from a session
        # snapshot after a worker death (serve.session).
        "recovered": np.int8(bool(getattr(res, "recovered", False))),
    }
    if ticket is not None and ticket.queue_wait_s is not None:
        # Out-of-process fleets feed the autoscaler from the REPLICA's
        # admission queue, so the wait rides the reply.
        reply["queue_wait_s"] = np.float64(ticket.queue_wait_s)
    cert = getattr(res, "certificate", None)
    if cert is not None:
        from ..models.certify import CERT_STATUS

        reply["certified"] = np.int8(bool(cert.certified))
        reply["cert_status"] = _pack_str(
            CERT_STATUS.get(cert.device_verdict, "none"))
        reply["cert_lambda_min"] = np.float64(cert.lambda_min)
        reply["cert_tol"] = np.float64(cert.tol)
    return reply


def _shed_reply(server, e: OverCapacityError) -> dict:
    reply = {"ok": np.int8(0), "shed": np.int8(1),
             "reason": _pack_str(e.reason), "error": _pack_str(str(e))}
    if e.reason == "closed":
        # Disclose a drain/shutdown shed distinctly: the client should
        # reconnect (to the fleet's next replica), not back off.
        try:
            draining = bool(server.status().get("draining"))
        except Exception:
            draining = False
        reply["draining"] = np.int8(draining)
    return reply


def _handle_solve_m(server: SolveServer, frame: dict, ctx) -> dict:
    """``solve_m``: the in-memory-measurements solve op (the out-of-
    process fleet's RPC surface).  Same reply vocabulary as ``solve``
    plus the replica-side queue wait; the request round-trips the full
    ``Measurements`` batch instead of g2o bytes."""
    try:
        meas = unpack_measurements(frame, "meas")
        if meas is None:
            raise ValueError("solve_m frame carries no 'meas' payload")
        num_robots = int(np.asarray(frame["num_robots"]))
        rank = int(np.asarray(frame["rank"])) if "rank" in frame else 5
        params = AgentParams(
            d=meas.d, r=rank, num_robots=num_robots,
            rel_change_tol=float(np.asarray(frame["rel_change_tol"]))
            if "rel_change_tol" in frame else 5e-3,
            certify_mode=_unpack_str(frame["certify_mode"])
            if "certify_mode" in frame else "off",
            certify_eta=float(np.asarray(frame["certify_eta"]))
            if "certify_eta" in frame else 1e-5)
        req = SolveRequest(
            meas=meas,
            num_robots=num_robots,
            params=params,
            tenant=_unpack_str(frame["tenant"]) if "tenant" in frame
            else "default",
            deadline_s=float(np.asarray(frame["deadline_s"]))
            if "deadline_s" in frame else None,
            max_iters=int(np.asarray(frame["max_iters"]))
            if "max_iters" in frame else None,
            grad_norm_tol=float(np.asarray(frame["grad_norm_tol"]))
            if "grad_norm_tol" in frame else 0.1,
            eval_every=int(np.asarray(frame["eval_every"]))
            if "eval_every" in frame else 1,
            trace_ctx=ctx,
            session_id=_unpack_str(frame["session"])
            if "session" in frame else None,
        )
        ticket = server.submit(req)
        res = ticket.result()
    except OverCapacityError as e:
        return _shed_reply(server, e)
    except Exception as e:
        return {"ok": np.int8(0), "error": _pack_str(f"{type(e).__name__}: {e}")}
    return _result_reply(res, ticket)


def _handle_request(server: SolveServer, frame: dict, ctx) -> dict:
    op = _unpack_str(frame["op"]) if "op" in frame else "solve"
    if op == "ping":
        return {"ok": np.int8(1)}
    if op == "status":
        # The fleet heartbeat: the replica's operational snapshot, JSON-
        # encoded (mixed scalar types) inside one uint8 frame entry.
        # With telemetry on the reply carries this replica's clock stamp
        # — the reverse leg of the heartbeat's clock_sample pair.
        try:
            reply = {"ok": np.int8(1),
                     "status": _pack_str(json.dumps(server.status(),
                                                    default=str))}
            if obs.get_run() is not None:
                attach_clock(reply,
                             proc_replica_actor(server.replica_id or "r"))
            return reply
        except Exception as e:
            return {"ok": np.int8(0),
                    "error": _pack_str(f"{type(e).__name__}: {e}")}
    if op == "drain":
        # Live-migration drain.  The evacuated tickets' WAITERS are this
        # front-end's own handler threads (blocked in solve ops); finish
        # them with the structured drain shed so every in-flight RPC
        # replies "reroute me" instead of hanging — the parent-side
        # ProcServer owns the real re-admission tickets.
        try:
            evacuated = server.drain()
        except Exception as e:
            return {"ok": np.int8(0),
                    "error": _pack_str(f"{type(e).__name__}: {e}")}
        for t in evacuated:
            if not t.done():
                t._finish(exception=OverCapacityError(
                    "evacuated: replica draining for migration",
                    reason="closed"))
        return {"ok": np.int8(1), "evacuated": np.int32(len(evacuated))}
    if op == "solve_m":
        return _handle_solve_m(server, frame, ctx)
    if op != "solve":
        return {"ok": np.int8(0), "error": _pack_str(f"unknown op {op!r}")}
    try:
        # The decode stage as its own span: g2o parse + request build,
        # so a certified request's timeline reads decode -> admission ->
        # dispatch -> certified reply with no unattributed gap.
        with obs_trace.span("decode", phase="serve",
                            bytes=int(np.asarray(frame["g2o"]).size)):
            meas = read_g2o(bytes(np.asarray(frame["g2o"], np.uint8)))
            num_robots = int(np.asarray(frame["num_robots"]))
            rank = int(np.asarray(frame["rank"])) if "rank" in frame else 5
            certify_mode = _unpack_str(frame["certify_mode"]) \
                if "certify_mode" in frame else "off"
            certify_eta = float(np.asarray(frame["certify_eta"])) \
                if "certify_eta" in frame else 1e-5
            req = SolveRequest(
                meas=meas,
                num_robots=num_robots,
                params=AgentParams(d=meas.d, r=rank, num_robots=num_robots,
                                   certify_mode=certify_mode,
                                   certify_eta=certify_eta),
                tenant=_unpack_str(frame["tenant"]) if "tenant" in frame
                else "default",
                deadline_s=float(np.asarray(frame["deadline_s"]))
                if "deadline_s" in frame else None,
                max_iters=int(np.asarray(frame["max_iters"]))
                if "max_iters" in frame else None,
                grad_norm_tol=float(np.asarray(frame["grad_norm_tol"]))
                if "grad_norm_tol" in frame else 0.1,
                eval_every=int(np.asarray(frame["eval_every"]))
                if "eval_every" in frame else 1,
                trace_ctx=ctx,
                session_id=_unpack_str(frame["session"])
                if "session" in frame else None,
            )
        res = server.submit(req).result()
    except OverCapacityError as e:
        return _shed_reply(server, e)
    except Exception as e:  # bad payload, solver failure: structured reply
        return {"ok": np.int8(0), "error": _pack_str(f"{type(e).__name__}: {e}")}
    return _result_reply(res)


class ServeFrontend:
    """TCP listener bound to a ``SolveServer``.  Binds on construction
    (``port=0`` = OS-assigned; read the resolved ``.port``), accepts on a
    daemon thread, one handler thread per connection."""

    def __init__(self, server: SolveServer, host: str = "127.0.0.1",
                 port: int = 0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 wire_format: str = "packed"):
        self.server = server
        self.max_frame_bytes = int(max_frame_bytes)
        self.wire_format = wire_format
        self._listener = listen_tcp(host, port)
        self.host, self.port = self._listener.getsockname()[:2]
        #: Each connection pairs its transport with a send lock: handler
        #: replies and ``close()``'s teardown serialize on it, so a reply
        #: for a request that was in flight when shutdown began either
        #: lands whole before the socket closes or is skipped cleanly —
        #: never interleaved with the close.
        self._transports: list[tuple[TcpTransport, threading.Lock]] = []
        self._lock = threading.Lock()
        self._closed = False
        self._accepter = threading.Thread(target=self._accept, daemon=True,
                                          name="dpgo-serve-accept")
        self._accepter.start()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            tr = TcpTransport(sock, src="serve-frontend",
                              max_frame_bytes=self.max_frame_bytes,
                              wire_format=self.wire_format)
            send_lock = threading.Lock()
            with self._lock:
                if self._closed:
                    tr.close()
                    return
                self._transports.append((tr, send_lock))
            threading.Thread(target=self._serve_conn, args=(tr, send_lock),
                             daemon=True).start()

    def _send(self, tr: TcpTransport, send_lock: threading.Lock,
              reply: dict) -> bool:
        """Send one reply under the connection's send lock.  A teardown
        that already began (``close()`` holds the lock while closing the
        socket) makes this a clean no-op instead of a write racing the
        close; returns whether the reply was delivered."""
        with send_lock:
            with self._lock:
                if self._closed:
                    return False
            tr.send(reply)
            return True

    def _serve_conn(self, tr: TcpTransport, send_lock: threading.Lock) -> None:
        while True:
            try:
                frame = tr.recv()
            except (TransportClosed, TransportTimeout):
                return
            except ProtocolError as e:
                try:
                    if not self._send(tr, send_lock, {
                            "ok": np.int8(0),
                            "error": _pack_str(f"protocol error: {e}")}):
                        return
                    continue
                except (TransportClosed, ProtocolError):
                    return
            try:
                if not self._send(tr, send_lock,
                                  handle_request(self.server, frame)):
                    return
            except ProtocolError as e:
                # Reply exceeds the frame cap: report instead of dying.
                try:
                    if not self._send(tr, send_lock, {
                            "ok": np.int8(0),
                            "error": _pack_str(f"reply too large: {e}")}):
                        return
                except (TransportClosed, ProtocolError):
                    return
            except TransportClosed:
                return

    def close(self) -> None:
        with self._lock:
            self._closed = True
            transports = list(self._transports)
        try:
            self._listener.close()
        except OSError:
            pass
        for tr, send_lock in transports:
            # Serialize with any in-flight reply: a handler mid-send
            # finishes its frame first; handlers that arrive after see
            # ``_closed`` and skip the send entirely.
            with send_lock:
                tr.close()

    def __enter__(self) -> "ServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def solve_m_frame(request) -> dict:
    """The ``solve_m`` request frame for one ``SolveRequest`` — the
    client half of ``_handle_solve_m`` (the out-of-process fleet's RPC
    encoder).  ``params`` fields beyond (d, r, rel_change_tol,
    certify_mode, certify_eta) stay at replica defaults by design: the
    fleet replicas are homogeneous and the bucket fingerprint only keys
    on what rides the wire."""
    frame = {"op": _pack_str("solve_m"),
             "num_robots": np.int32(request.num_robots),
             "tenant": _pack_str(request.tenant),
             "grad_norm_tol": np.float64(request.grad_norm_tol),
             "eval_every": np.int32(request.eval_every)}
    frame.update(pack_measurements("meas", request.meas))
    if request.params is not None:
        frame["rank"] = np.int32(request.params.r)
        frame["rel_change_tol"] = np.float64(request.params.rel_change_tol)
        if request.params.certify_mode != "off":
            frame["certify_mode"] = _pack_str(request.params.certify_mode)
            frame["certify_eta"] = np.float64(request.params.certify_eta)
    if request.max_iters is not None:
        frame["max_iters"] = np.int32(request.max_iters)
    if request.deadline_s is not None:
        frame["deadline_s"] = np.float64(request.deadline_s)
    if request.session_id is not None:
        frame["session"] = _pack_str(request.session_id)
    return frame


def solve_g2o(host: str, port: int, g2o, num_robots: int,
              tenant: str = "default", rank: int = 5,
              max_iters: int | None = None, grad_norm_tol: float = 0.1,
              eval_every: int = 1, deadline_s: float | None = None,
              timeout: float | None = None,
              max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
              wire_format: str = "packed",
              session_id: str | None = None,
              certify_mode: str = "off",
              certify_eta: float = 1e-5) -> dict:
    """Submit one g2o problem to a remote front-end and wait for the
    result.  ``g2o`` is the file's bytes or a path.  Returns a dict with
    ``ok`` plus either the result arrays (``T``, ``cost_history``,
    ``grad_norm_history``, ``iterations``, ``terminated_by``) or the
    structured error (``error``, ``shed``, ``reason``).

    ``certify_mode="device"`` requests a certified reply: the server
    folds the dual certificate into the solve's terminal epilogue and the
    reply carries ``certified`` / ``cert_status`` / ``cert_lambda_min`` /
    ``cert_tol``."""
    if isinstance(g2o, str):
        with open(g2o, "rb") as fh:
            g2o = fh.read()
    frame = {
        "op": _pack_str("solve"),
        "g2o": np.frombuffer(g2o, np.uint8),
        "num_robots": np.int32(num_robots),
        "rank": np.int32(rank),
        "tenant": _pack_str(tenant),
        "grad_norm_tol": np.float64(grad_norm_tol),
        "eval_every": np.int32(eval_every),
    }
    if max_iters is not None:
        frame["max_iters"] = np.int32(max_iters)
    if deadline_s is not None:
        frame["deadline_s"] = np.float64(deadline_s)
    if session_id is not None:
        frame["session"] = _pack_str(session_id)
    if certify_mode != "off":
        frame["certify_mode"] = _pack_str(certify_mode)
        frame["certify_eta"] = np.float64(certify_eta)
    # Request-scoped trace context: with telemetry on in the CLIENT
    # process, the whole round-trip is one span and its ids ride the
    # frame, so the server's spans join this trace (telemetry off:
    # byte-identical frames, no span).
    sp = obs_trace.start_span("solve_g2o", phase="serve")
    if sp is not None:
        frame.update(pack_trace_entries(sp.trace_id, sp.span_id,
                                        ORIGIN_SERVE_CLIENT))
    sock = connect_tcp(host, port)
    tr = TcpTransport(sock, src="serve-client",
                      max_frame_bytes=max_frame_bytes,
                      wire_format=wire_format)
    try:
        tr.send(frame)
        reply = tr.recv(timeout=timeout)
    finally:
        tr.close()
        if sp is not None:
            sp.end(host=host, port=int(port), tenant=tenant)
    out = {"ok": bool(int(np.asarray(reply["ok"])))}
    if out["ok"]:
        out["T"] = np.asarray(reply["T"])
        out["cost_history"] = np.asarray(reply["cost_history"])
        out["grad_norm_history"] = np.asarray(reply["grad_norm_history"])
        out["iterations"] = int(np.asarray(reply["iterations"]))
        out["terminated_by"] = _unpack_str(reply["terminated_by"])
        out["recovered"] = bool(int(np.asarray(reply.get("recovered", 0))))
        if "certified" in reply:
            out["certified"] = bool(int(np.asarray(reply["certified"])))
            out["cert_status"] = _unpack_str(reply["cert_status"])
            out["cert_lambda_min"] = float(np.asarray(
                reply["cert_lambda_min"]))
            out["cert_tol"] = float(np.asarray(reply["cert_tol"]))
    else:
        out["error"] = _unpack_str(reply.get("error", _pack_str("")))
        out["shed"] = bool(int(np.asarray(reply.get("shed", 0))))
        if "reason" in reply:
            out["reason"] = _unpack_str(reply["reason"])
        if "draining" in reply:
            out["draining"] = bool(int(np.asarray(reply["draining"])))
    return out
